"""Unified run timeline (ISSUE 18): merge one or more RunLedger
directories into ONE Perfetto/Chrome trace.

What lands on the timeline (``python -m ddls_tpu.telemetry.timeline
<run_dir> [<run_dir> ...] -o trace.json``, or ``scripts/
telemetry_report.py --timeline``):

* **Span tracks** — every sink ``span`` record becomes a duration slice
  on a per-name thread track; sink ``ts`` stamps are unix wall-clock at
  span END, so the slice is ``(ts - dur_s, ts)`` and multiple processes
  on one host align with no extra bookkeeping (each run dir gets its
  own pid; the manifest ``clock`` block carries the unix/perf offset
  for any perf-clock data).
* **Ring segment lifecycles** — the ring ledger's gated
  ``ring_segment`` events render as async lease→release slices per
  segment (publish as an instant inside, stalls as flagged instants on
  the stall track): the lease→publish→release ownership story from
  docs/perf_round10.md, now visible per run.
* **Cross-mesh hops** — transfer-ledger records (``sebulba.params``,
  ``sebulba.rngs``, ``stage.traj``, drain fetches) become slices with
  byte sizes in args plus Perfetto flow arrows from the hop's dispatch
  track to its destination track, so tunnel-RTT amortization is visible
  as arrow density (~116 ms per dispatch on the axon tunnel).
* **Counter tracks** — memo hit-rate (``memo_counters`` drain events)
  and ``params_age_updates`` (ring consume events) as ph "C" counters.
* **Optional device trace** — any ``jax.profiler`` capture under the
  run dir (``plugins/profile/*/*.trace.json.gz``) is folded in with a
  remapped pid, tying XLA device timelines to the same wall of spans.

This supersedes the sim-only ``scripts/trace_export.py`` view (flight
events remain exportable there; a flight JSONL passed as a run dir file
is out of scope here).
"""
from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
from typing import Any, Dict, List, Optional, Sequence

from ddls_tpu.telemetry.runlog import load_run_dir

_US = 1e6  # chrome trace timestamps are microseconds

# direction → destination track label for the flow-arrow endpoint
_DIRECTION_DEST = {
    "h2d": "device",
    "d2h": "host",
    "l2a": "actor mesh",
    "a2l": "learner mesh",
    "d2d": "device",
    # fragment frames between the learner and its actor-host processes
    # (rl/fragments.py): host memory on both ends, the wire in between
    "h2h": "remote host",
}


def _meta(pid: int, name: str, tid: Optional[int] = None) -> Dict[str, Any]:
    ev: Dict[str, Any] = {
        "name": "process_name" if tid is None else "thread_name",
        "ph": "M", "pid": pid,
        "args": {"name": name},
    }
    if tid is not None:
        ev["tid"] = tid
    return ev


class _Tids:
    """Stable per-process thread-track ids, metadata emitted once."""

    def __init__(self, pid: int, events: List[Dict[str, Any]]):
        self.pid = pid
        self.events = events
        self._ids: Dict[str, int] = {}

    def __call__(self, name: str) -> int:
        tid = self._ids.get(name)
        if tid is None:
            tid = self._ids[name] = len(self._ids) + 1
            self.events.append(_meta(self.pid, name, tid))
        return tid


def build_trace(runs: Sequence[Dict[str, Any]],
                include_device_trace: bool = True) -> Dict[str, Any]:
    """``runs`` are ``load_run_dir`` dicts; returns the Chrome trace
    document (``traceEvents`` + ``otherData``)."""
    events: List[Dict[str, Any]] = []
    # global unix origin so multi-run traces share one axis
    t0 = None
    for run in runs:
        for rec in run.get("records", ()):
            ts = rec.get("ts")
            if ts is not None:
                start = ts - float(rec.get("dur_s") or 0.0)
                t0 = start if t0 is None else min(t0, start)
        man_clock = (run.get("manifest") or {}).get("clock") or {}
        if man_clock.get("unix") is not None:
            t0 = (man_clock["unix"] if t0 is None
                  else min(t0, man_clock["unix"]))
    if t0 is None:
        t0 = 0.0

    def us(ts_unix: float) -> float:
        return max(0.0, (ts_unix - t0) * _US)

    flow_id = 0
    other: Dict[str, Any] = {"runs": []}
    for pid, run in enumerate(runs, start=1):
        man = run.get("manifest") or {}
        kind = man.get("kind", "run")
        # train ledgers carry loop_mode only in config — fold it into the
        # track label so two train runs stay distinguishable when merged
        mode = (man.get("config") or {}).get("loop_mode")
        if mode and kind.startswith("train") and mode not in kind:
            kind = "{}:{}".format(kind, mode)
        label = "{}:{}".format(
            kind,
            os.path.basename(os.path.normpath(run.get("run_dir", "?"))))
        proc = man.get("process") or {}
        if proc.get("count", 1) > 1:
            label += " (p{}/{})".format(proc.get("index", 0),
                                        proc.get("count"))
        events.append(_meta(pid, label))
        tids = _Tids(pid, events)
        other["runs"].append({
            "pid": pid, "run_dir": run.get("run_dir"),
            "kind": man.get("kind"),
            "scenario_fingerprint": man.get("scenario_fingerprint"),
            "git": man.get("git"), "devices": man.get("devices"),
        })

        ring_open: Dict[Any, float] = {}  # (segment, generation) → ts
        memo_last: Optional[Dict[str, Any]] = None
        for rec in run.get("records", ()):
            ts = rec.get("ts")
            if ts is None:
                continue
            rtype = rec.get("type")
            if rtype == "span":
                dur = float(rec.get("dur_s") or 0.0)
                events.append({
                    "name": rec.get("name", "?"), "ph": "X",
                    "pid": pid, "tid": tids(rec.get("name", "?")),
                    "ts": us(ts - dur), "dur": dur * _US,
                })
            elif rtype == "transfer":
                dur = float(rec.get("dur_s") or 0.0)
                name = rec.get("name", "?")
                direction = rec.get("direction", "?")
                tid = tids("transfer:{}".format(name))
                start = us(ts - dur)
                events.append({
                    "name": name, "ph": "X", "pid": pid, "tid": tid,
                    "ts": start, "dur": max(dur * _US, 1.0),
                    "args": {"bytes": rec.get("bytes"),
                             "direction": direction},
                })
                # flow arrow: dispatch slice → a 1 us arrival slice on
                # the direction's destination track
                flow_id += 1
                dest = _DIRECTION_DEST.get(direction, direction)
                dest_tid = tids("arrivals:{}".format(dest))
                end = us(ts)
                events.append({
                    "name": "{} → {}".format(name, dest), "ph": "s",
                    "cat": "transfer", "id": flow_id, "pid": pid,
                    "tid": tid, "ts": start + max(dur * _US, 1.0) / 2})
                events.append({
                    "name": "{} arrive".format(name), "ph": "X",
                    "pid": pid, "tid": dest_tid, "ts": end, "dur": 1.0,
                    "args": {"bytes": rec.get("bytes")},
                })
                events.append({
                    "name": "{} → {}".format(name, dest), "ph": "f",
                    "bp": "e", "cat": "transfer", "id": flow_id,
                    "pid": pid, "tid": dest_tid, "ts": end + 0.5})
            elif rtype == "event":
                kind = rec.get("kind")
                if kind == "ring_segment":
                    phase = rec.get("phase")
                    seg = rec.get("segment")
                    gen = rec.get("generation")
                    key = (seg, gen)
                    track = tids("ring seg{}".format(seg))
                    if phase == "lease":
                        ring_open[key] = ts
                        events.append({
                            "name": "seg{} g{}".format(seg, gen),
                            "ph": "b", "cat": "ring",
                            "id": "ring:{}:{}".format(seg, gen),
                            "pid": pid, "tid": track, "ts": us(ts)})
                    elif phase == "release":
                        events.append({
                            "name": "seg{} g{}".format(seg, gen),
                            "ph": "e", "cat": "ring",
                            "id": "ring:{}:{}".format(seg, gen),
                            "pid": pid, "tid": track, "ts": us(ts)})
                        ring_open.pop(key, None)
                    elif phase == "publish":
                        events.append({
                            "name": "publish seg{}".format(seg),
                            "ph": "i", "s": "t", "pid": pid,
                            "tid": track, "ts": us(ts)})
                    elif phase == "stall":
                        events.append({
                            "name": "RING STALL", "ph": "i", "s": "p",
                            "pid": pid, "tid": tids("ring stalls"),
                            "ts": us(ts),
                            "args": {"segment": seg}})
                elif kind == "memo_counters":
                    hits = rec.get("hits") or 0
                    misses = rec.get("misses") or 0
                    total = hits + misses
                    rate = (hits / total) if total else 0.0
                    memo_last = rec
                    events.append({
                        "name": "memo hit rate", "ph": "C", "pid": pid,
                        "ts": us(ts),
                        "args": {"hit_rate": round(rate, 4)}})
                elif kind == "params_age":
                    events.append({
                        "name": "params_age_updates", "ph": "C",
                        "pid": pid, "ts": us(ts),
                        "args": {"updates": rec.get("value", 0)}})
                else:
                    events.append({
                        "name": "event:{}".format(kind), "ph": "i",
                        "s": "t", "pid": pid, "tid": tids("events"),
                        "ts": us(ts),
                        "args": {k: v for k, v in rec.items()
                                 if k not in ("ts", "type", "kind")}})
        if memo_last is not None:
            other["runs"][-1]["memo_counters"] = {
                k: v for k, v in memo_last.items()
                if k not in ("ts", "type", "kind")}

        if include_device_trace:
            events.extend(_fold_device_trace(run, base_pid=1000 * pid))

    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def _fold_device_trace(run: Dict[str, Any],
                       base_pid: int) -> List[Dict[str, Any]]:
    """Fold any jax.profiler capture under the run dir in, with pids
    offset so device tracks sit beside (not inside) the host tracks.
    Device-trace timestamps are profiler-relative, not unix — Perfetto
    shows them as their own process group; correlation is by span
    structure (the one-shot capture is owned by a named span)."""
    out: List[Dict[str, Any]] = []
    run_dir = run.get("run_dir")
    if not run_dir:
        return out
    pattern = os.path.join(
        run_dir, "**", "plugins", "profile", "*", "*.trace.json.gz")
    for path in sorted(glob.glob(pattern, recursive=True))[:1]:
        try:
            with gzip.open(path, "rt") as f:
                doc = json.load(f)
        except Exception:
            continue
        for ev in doc.get("traceEvents", []):
            if "pid" in ev:
                ev = dict(ev)
                ev["pid"] = base_pid + int(ev["pid"])
            out.append(ev)
    return out


def write_timeline(run_dirs: Sequence[str], out_path: str,
                   include_device_trace: bool = True) -> Dict[str, Any]:
    runs = [load_run_dir(d) for d in run_dirs]
    doc = build_trace(runs, include_device_trace=include_device_trace)
    with open(out_path, "w") as f:
        json.dump(doc, f)
    return doc


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Merge RunLedger directories into one Perfetto "
                    "trace (open in ui.perfetto.dev or "
                    "chrome://tracing).")
    p.add_argument("run_dirs", nargs="+", help="RunLedger directories")
    p.add_argument("-o", "--out", default="timeline.json")
    p.add_argument("--no-device-trace", action="store_true",
                   help="skip folding in jax.profiler captures")
    args = p.parse_args(argv)
    for d in args.run_dirs:
        if not os.path.isdir(d):
            p.error("not a directory: {}".format(d))
    doc = write_timeline(args.run_dirs, args.out,
                         include_device_trace=not args.no_device_trace)
    n_ev = len(doc["traceEvents"])
    print("wrote {} ({} events from {} run dir{})".format(
        args.out, n_ev, len(args.run_dirs),
        "s" if len(args.run_dirs) != 1 else ""))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
