"""Episode flight recorder: structured simulator event traces (ISSUE 6).

Telemetry (metrics.py) answers "how long / how many"; this module answers
"what did the simulated cluster DO, in what order". An enabled recorder
captures each episode as an ordered stream of typed events — job
arrivals, decisions (degree + action-mask context), partitions,
placements/mounts, lookahead results (with the backend that served
them), event-clock ticks, completions and blocks — emitted from the host
tick loop (sim/cluster.py, sim/actions.py, envs/partitioning_env.py).
Traces feed three consumers:

* ``scripts/trace_diff.py`` — run one scenario through two lookahead
  backends (host / C++ / jax, or the fully-jitted episode kernels at
  decision level) and report the FIRST divergent event, turning "parity
  test failed" into "event 412: lookahead jct 3.81 vs 3.84";
* ``scripts/trace_export.py`` — Chrome-trace/Perfetto JSON, so an
  episode timeline (per-worker rows, channel rows, decision markers)
  opens in the same viewer as the jax profiler captures telemetry hooks
  up (docs/telemetry.md "jax.profiler capture");
* ``scripts/telemetry_report.py`` — a trace summary section (events by
  kind, blocks by cause, per-job lifecycle table).

The Podracer/MSRL lesson (arXiv 2104.06272, 2210.00882) applied to the
simulator itself: per-stage structured records are what make behaviour
attributable; endpoint stats only say THAT backends disagree, never
where.

Gating contract (the telemetry invariant, CLAUDE.md): the recorder is
**disabled by default** and hot paths may only touch it as::

    from ddls_tpu.telemetry import flight as _flight
    ...
    if _flight.enabled():
        _flight.emit("job_arrived", t=clock, job_idx=idx, ...)

so a disabled env step performs ONE bool check and creates zero event
objects (guard-tested in tests/test_flight.py; emits in
``ddls_tpu/sim/``/``ddls_tpu/envs/`` are statically checked by
``scripts/check_flight_gated.py``). Detail events (per-op/flow
completions inside the host lookahead engine) additionally require
``enable(detail=True)`` — they exist only where the host engine serves
the lookahead, so cross-backend diffs exclude them by default.

Event schema: every event is a plain JSON-able dict with ``seq`` (per-
recorder emission index), ``kind``, ``t`` (simulated time), plus
kind-specific fields — see EVENT_KINDS and docs/telemetry.md "Flight
recorder & trace diffing" for the full table. Worker-process traces
(``rl/rollout.py`` subprocess envs) merge into the parent recorder on
the close ack, tagged with their ``env`` index — the same transport the
telemetry counters ride.
"""
from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# the full vocabulary; emission sites are named per kind
EVENT_KINDS = (
    "job_arrived",      # cluster._get_next_job: job enters the system
    "action_decided",   # envs/partitioning_env.step: degree + mask +
                        # outcome (accepted / cause / lookahead jct)
    "partitioned",      # sim/actions.OpPartition: partitioned graph built
    "placed",           # cluster._place_ops: op -> worker commit
    "mounted",          # cluster._place_deps: dep -> channel commit
    "lookahead",        # cluster lookahead result + serving backend
    "tick",             # cluster.step event loop: clock advance
    "job_completed",    # cluster._register_completed_job
    "job_blocked",      # cluster._register_blocked_job (with cause)
    "op_completed",     # detail: host lookahead engine, per-op finish
    "flow_completed",   # detail: host lookahead engine, per-flow finish
    "worker_preempted", # cluster.step: scenario preemption window's t0
                        # crossed (t == window t0: pure (seed, spec) fn)
    "channel_degraded", # cluster.step: scenario straggler window's t0
                        # crossed (same determinism contract)
)

# kinds only the HOST lookahead engine can produce (the C++/jax engines
# return aggregates); excluded from cross-backend diffs by default
DETAIL_KINDS = ("op_completed", "flow_completed")

# payload fields that are context, not semantics: `seq` is emission
# order (differs when detail kinds are on), `backend` names which engine
# served a lookahead (host vs native IS the thing being diffed), `env`
# tags merged worker traces
DEFAULT_IGNORE_FIELDS = ("seq", "backend", "env")

# events above this count are dropped (with a tally) — a recorder left
# on across a long training run must not grow without bound
DEFAULT_MAX_EVENTS = 1_000_000


class FlightRecorder:
    """An ordered event log. The process-global instance is disabled by
    default; private instances (tests, trace scripts) are cheap."""

    __slots__ = ("enabled", "detail", "events", "max_events", "dropped",
                 "_seq")

    def __init__(self, enabled: bool = False, detail: bool = False,
                 max_events: int = DEFAULT_MAX_EVENTS):
        self.enabled = bool(enabled)
        self.detail = bool(detail)
        self.events: List[Dict[str, Any]] = []
        self.max_events = int(max_events)
        self.dropped = 0
        self._seq = 0

    def emit(self, kind: str, t: float, **fields) -> None:
        if not self.enabled:
            return
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        event = {"seq": self._seq, "kind": kind, "t": float(t), **fields}
        self._seq += 1
        self.events.append(event)

    def extend(self, events: Iterable[Dict[str, Any]],
               env_index: Optional[int] = None) -> None:
        """Merge a foreign event list (a worker process's trace) —
        events keep their own ``seq``/``t`` and gain an ``env`` tag."""
        if not self.enabled:
            return
        for e in events:
            if len(self.events) >= self.max_events:
                self.dropped += 1
                continue
            if env_index is not None:
                e = {**e, "env": int(env_index)}
            self.events.append(e)

    def drain(self) -> List[Dict[str, Any]]:
        out, self.events = self.events, []
        return out

    def reset(self) -> None:
        self.events = []
        self.dropped = 0
        self._seq = 0


_GLOBAL = FlightRecorder()


def recorder() -> FlightRecorder:
    return _GLOBAL


def enabled() -> bool:
    return _GLOBAL.enabled


def detail_enabled() -> bool:
    return _GLOBAL.enabled and _GLOBAL.detail


def enable(detail: bool = False,
           max_events: int = DEFAULT_MAX_EVENTS) -> FlightRecorder:
    _GLOBAL.detail = bool(detail)
    _GLOBAL.max_events = int(max_events)
    _GLOBAL.enabled = True
    return _GLOBAL


def disable() -> None:
    _GLOBAL.enabled = False


def emit(kind: str, t: float, **fields) -> None:
    """Gated append. Hot paths must still guard the CALL with
    ``if flight.enabled():`` so argument construction costs nothing when
    off (checked by scripts/check_flight_gated.py)."""
    _GLOBAL.emit(kind, t, **fields)


def extend(events: Iterable[Dict[str, Any]],
           env_index: Optional[int] = None) -> None:
    _GLOBAL.extend(events, env_index=env_index)


def events() -> List[Dict[str, Any]]:
    return list(_GLOBAL.events)


def drain() -> List[Dict[str, Any]]:
    return _GLOBAL.drain()


def reset() -> None:
    _GLOBAL.reset()


# ------------------------------------------------------------ persistence
def save_jsonl(path: str,
               evts: Optional[Sequence[Dict[str, Any]]] = None) -> int:
    """Write events as JSONL (``{"type": "flight", ...event}`` per line
    — the record shape scripts/telemetry_report.py summarises, so flight
    records can also ride inside a telemetry sink file). Returns the
    number of records written."""
    if evts is None:
        evts = _GLOBAL.events
    with open(path, "w") as f:
        for e in evts:
            f.write(json.dumps({"type": "flight", **e}) + "\n")
    return len(evts)


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Read flight events back from a JSONL file, tolerating interleaved
    non-flight telemetry records (span/event/snapshot lines are
    skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("type") not in (None, "flight"):
                continue
            if "kind" not in rec or rec["kind"] not in EVENT_KINDS:
                continue
            rec.pop("type", None)
            out.append(rec)
    return out


# ---------------------------------------------------------------- diffing
def comparable_events(evts: Sequence[Dict[str, Any]],
                      kinds: Optional[Sequence[str]] = None,
                      include_detail: bool = False,
                      ignore_fields: Sequence[str] = DEFAULT_IGNORE_FIELDS
                      ) -> List[Dict[str, Any]]:
    """Canonicalise a trace for cross-backend comparison: filter to the
    requested kinds (default: everything non-detail) and strip the
    context-only fields."""
    drop = set(ignore_fields)
    keep_kinds = set(kinds) if kinds is not None else None
    out = []
    for e in evts:
        kind = e.get("kind")
        if keep_kinds is not None:
            if kind not in keep_kinds:
                continue
        elif not include_detail and kind in DETAIL_KINDS:
            continue
        out.append({k: v for k, v in e.items() if k not in drop})
    return out


def _values_equal(a: Any, b: Any, rtol: float) -> bool:
    if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
            and not isinstance(a, bool) and not isinstance(b, bool):
        if a == b:
            return True
        if rtol <= 0.0:
            return False
        return abs(a - b) <= rtol * max(abs(a), abs(b), 1e-300)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return (len(a) == len(b)
                and all(_values_equal(x, y, rtol) for x, y in zip(a, b)))
    return a == b


def first_divergence(a: Sequence[Dict[str, Any]],
                     b: Sequence[Dict[str, Any]],
                     rtol: float = 0.0) -> Optional[Dict[str, Any]]:
    """First index where two CANONICALISED traces disagree (run
    ``comparable_events`` first), or None when identical.

    ``rtol``: relative tolerance for float payload fields — 0.0 demands
    bit-exactness (host vs C++); the jitted-episode decision diff passes
    the parity tests' 1e-9 (tests/test_jax_episode.py)."""
    for i, (ea, eb) in enumerate(zip(a, b)):
        if ea.get("kind") != eb.get("kind"):
            return {"index": i, "reason": "kind", "a": ea, "b": eb,
                    "fields": []}
        keys_a, keys_b = set(ea), set(eb)
        diff_fields: List[Tuple[str, Any, Any]] = []
        for k in sorted(keys_a | keys_b):
            va, vb = ea.get(k), eb.get(k)
            if k not in ea or k not in eb or not _values_equal(va, vb,
                                                               rtol):
                diff_fields.append((k, va, vb))
        if diff_fields:
            return {"index": i, "reason": "field", "a": ea, "b": eb,
                    "fields": diff_fields}
    if len(a) != len(b):
        i = min(len(a), len(b))
        return {"index": i, "reason": "length",
                "a": a[i] if i < len(a) else None,
                "b": b[i] if i < len(b) else None, "fields": []}
    return None


def format_divergence(div: Optional[Dict[str, Any]],
                      label_a: str = "A", label_b: str = "B") -> str:
    """Human-readable one-stop report of a ``first_divergence`` result:
    the event index, kind + sim-time, and the payload diff with both
    sides' full context."""
    if div is None:
        return "traces identical"
    i = div["index"]
    if div["reason"] == "length":
        longer = label_a if div["a"] is not None else label_b
        extra = div["a"] if div["a"] is not None else div["b"]
        return (f"first divergence at event #{i}: {longer} has "
                f"{extra['kind']} @ t={extra['t']:.9g} where the other "
                f"trace ended\n  {longer}: {json.dumps(extra)}")
    ea, eb = div["a"], div["b"]
    if div["reason"] == "kind":
        return (f"first divergence at event #{i}: kind "
                f"{ea['kind']} @ t={ea['t']:.9g} ({label_a}) vs "
                f"{eb['kind']} @ t={eb['t']:.9g} ({label_b})\n"
                f"  {label_a}: {json.dumps(ea)}\n"
                f"  {label_b}: {json.dumps(eb)}")
    fields = ", ".join(f"{k}: {va!r} vs {vb!r}"
                       for k, va, vb in div["fields"])
    return (f"first divergence at event #{i}: {ea['kind']} @ "
            f"t={ea['t']:.9g} — {fields}\n"
            f"  {label_a}: {json.dumps(ea)}\n"
            f"  {label_b}: {json.dumps(eb)}")


# ---------------------------------------------------------------- summary
def _iter_labeled(evts: Sequence[Dict[str, Any]]):
    """(event, job_label) pairs. The label qualifies ``job_idx`` with the
    worker ``env`` tag (merged traces) and an episode generation — a
    ``job_arrived`` that re-sees an (env, job_idx) pair starts a new
    generation, because auto-reset episodes restart indices at 0 — so
    lifecycle accounting never conflates distinct jobs that happen to
    share an index. Single-episode single-env traces keep plain
    ``"<job_idx>"`` labels."""
    gen: Dict[Tuple[Any, int], int] = {}
    for e in evts:
        ji = e.get("job_idx")
        if ji is None:
            yield e, None
            continue
        key = (e.get("env"), int(ji))
        if e.get("kind") == "job_arrived":
            gen[key] = gen.get(key, -1) + 1
        label = str(ji) if key[0] is None else f"e{key[0]}:j{ji}"
        g = gen.get(key, 0)
        if g:
            label += f"#{g}"
        yield e, label


def summarize(evts: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Trace rollup for reports: events by kind, blocks by cause, and a
    per-job lifecycle table (arrival -> decision -> placement ->
    outcome) keyed by ``_iter_labeled`` job labels, in first-appearance
    order."""
    by_kind: Dict[str, int] = {}
    blocked_by_cause: Dict[str, int] = {}
    jobs: Dict[str, Dict[str, Any]] = {}

    t_max = 0.0
    for e, label in _iter_labeled(evts):
        kind = e.get("kind", "?")
        by_kind[kind] = by_kind.get(kind, 0) + 1
        t_max = max(t_max, float(e.get("t", 0.0)))
        if label is None:
            continue
        r = jobs.setdefault(label, {})
        if kind == "job_arrived":
            r["arrived"] = e["t"]
            r["model"] = e.get("model")
        elif kind == "action_decided":
            r["decided"] = e["t"]
            r["degree"] = e.get("degree")
        elif kind == "placed":
            r["placed"] = e["t"]
            r["n_workers"] = len(e.get("workers", ()))
        elif kind == "mounted":
            r["n_channels"] = len(e.get("channels", ()))
        elif kind == "lookahead":
            r["jct"] = e.get("jct")
            r["backend"] = e.get("backend")
        elif kind == "job_completed":
            r["completed"] = e["t"]
        elif kind == "job_blocked":
            r["blocked"] = e["t"]
            cause = str(e.get("cause", "?"))
            r["cause"] = cause
            blocked_by_cause[cause] = blocked_by_cause.get(cause, 0) + 1
    return {"n_events": len(evts), "t_end": t_max, "by_kind": by_kind,
            "blocked_by_cause": blocked_by_cause, "jobs": jobs}


# -------------------------------------------------------- Perfetto export
# simulated seconds -> Chrome-trace microseconds (sim time is the
# reference's abstract unit; the scale only sets zoom level)
_TRACE_US = 1e6

_PID_WORKERS = 1
_PID_CHANNELS = 2
_PID_EVENTS = 3


def to_perfetto(evts: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome-trace/Perfetto JSON for an episode trace: one row per
    worker (jobs as duration slices), one per channel (flow mounts),
    instant markers for arrivals/decisions/blocks, and a running-jobs
    counter track from the tick events. Open in ui.perfetto.dev or
    chrome://tracing — the same viewer as the jax profiler captures
    telemetry's ``jax_trace_dir`` hook produces."""
    summary = summarize(evts)
    jobs = summary["jobs"]
    out: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": _PID_WORKERS,
         "args": {"name": "workers"}},
        {"name": "process_name", "ph": "M", "pid": _PID_CHANNELS,
         "args": {"name": "channels"}},
        {"name": "process_name", "ph": "M", "pid": _PID_EVENTS,
         "args": {"name": "episode events"}},
    ]

    worker_tid: Dict[Any, int] = {}
    channel_tid: Dict[Any, int] = {}

    def tid_for(table: Dict[Any, int], pid: int, key: Any) -> int:
        tid = table.get(key)
        if tid is None:
            tid = table[key] = len(table)
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": str(key)}})
        return tid

    # default end for jobs with no recorded outcome: the trace horizon
    horizon = summary["t_end"]

    for e, label in _iter_labeled(evts):
        kind = e.get("kind")
        ts = float(e.get("t", 0.0)) * _TRACE_US
        ji = e.get("job_idx")
        if kind == "placed":
            r = jobs.get(label, {})
            end = r.get("completed", r.get("blocked", horizon))
            dur = max(float(end) - float(e["t"]), 0.0) * _TRACE_US
            args = {"job": label, "degree": r.get("degree"),
                    "jct": r.get("jct"), "model": r.get("model")}
            for w in e.get("workers", ()):
                out.append({"name": f"job {label}", "cat": "job",
                            "ph": "X", "ts": ts, "dur": dur,
                            "pid": _PID_WORKERS,
                            "tid": tid_for(worker_tid, _PID_WORKERS, w),
                            "args": args})
        elif kind == "mounted":
            r = jobs.get(label, {})
            end = r.get("completed", r.get("blocked", horizon))
            dur = max(float(end) - float(e["t"]), 0.0) * _TRACE_US
            for c in e.get("channels", ()):
                out.append({"name": f"job {label} flows", "cat": "flow",
                            "ph": "X", "ts": ts, "dur": dur,
                            "pid": _PID_CHANNELS,
                            "tid": tid_for(channel_tid, _PID_CHANNELS,
                                           c),
                            "args": {"job": label}})
        elif kind == "action_decided":
            out.append({"name": f"decide {label} d={e.get('degree')}",
                        "cat": "decision", "ph": "i", "s": "g",
                        "ts": ts, "pid": _PID_EVENTS, "tid": 0,
                        "args": {k: e[k] for k in
                                 ("job_idx", "degree", "accepted",
                                  "cause", "jct") if k in e}})
        elif kind == "job_arrived":
            out.append({"name": f"arrive {label}", "cat": "arrival",
                        "ph": "i", "s": "g", "ts": ts,
                        "pid": _PID_EVENTS, "tid": 1,
                        "args": {"job_idx": ji,
                                 "model": e.get("model")}})
        elif kind == "job_blocked":
            out.append({"name": f"block {label}: {e.get('cause')}",
                        "cat": "block", "ph": "i", "s": "g", "ts": ts,
                        "pid": _PID_EVENTS, "tid": 2,
                        "args": {"job_idx": ji,
                                 "cause": e.get("cause")}})
        elif kind == "tick":
            out.append({"name": "jobs_running", "ph": "C", "ts": ts,
                        "pid": _PID_EVENTS,
                        "args": {"running": e.get("n_running", 0)}})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"source": "ddls_tpu flight recorder",
                          "n_flight_events": len(evts)}}
