"""Array-native computation graph for DNN training jobs.

The reference models jobs as mutable ``networkx.MultiDiGraph`` objects with
per-node/edge attribute dicts (reference: ddls/demands/jobs/job.py:42,
ddls/utils.py:400-461). Here the graph is a compact, finalisable structure:
ops and deps live in insertion-ordered tables, and ``finalize()`` caches flat
numpy index arrays (costs, adjacency, parent counts, depths) so that the
simulator's tick engine and the RL observation encoder can work on vectors
rather than attribute dicts. This is what later lets rollout state live in
fixed-size device arrays.

Terminology follows the reference: *ops* are nodes (operations of a fwd+bwd
pass), *deps* are directed edges (tensor/control dependencies).
"""
from __future__ import annotations

from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

EdgeId = Tuple[str, str]


class OpGraph:
    """A directed (possibly cyclic via mutual sync-edge pairs) op graph.

    Node attributes: ``compute`` (profiled run time on ``device_type``),
    ``memory`` (bytes resident), ``is_forward`` (pass type), and an optional
    fwd<->bwd ``counterpart`` mapping. Edge attribute: ``size`` (bytes moved).
    """

    def __init__(self, device_type: str = "A100"):
        self.device_type = device_type
        self._compute: Dict[str, float] = {}
        self._memory: Dict[str, float] = {}
        self._is_forward: Dict[str, bool] = {}
        self._counterpart: Dict[str, Optional[str]] = {}
        self._edge_size: Dict[EdgeId, float] = {}
        self._succ: Dict[str, Dict[str, None]] = {}
        self._pred: Dict[str, Dict[str, None]] = {}
        self.meta: Dict[str, object] = {}
        self._cache: Optional[dict] = None

    # ------------------------------------------------------------------ build
    def add_op(self,
               op_id: str,
               compute: float,
               memory: float,
               is_forward: bool = True,
               counterpart: Optional[str] = None) -> None:
        op_id = str(op_id)
        if op_id in self._compute:
            raise ValueError(f"op {op_id!r} already exists in graph")
        self._compute[op_id] = float(compute)
        self._memory[op_id] = float(memory)
        self._is_forward[op_id] = bool(is_forward)
        self._counterpart[op_id] = counterpart
        self._succ.setdefault(op_id, {})
        self._pred.setdefault(op_id, {})
        self._cache = None

    def add_edge(self, u: str, v: str, size: float = 0.0) -> None:
        u, v = str(u), str(v)
        if u not in self._compute or v not in self._compute:
            raise KeyError(f"edge ({u}, {v}) references an unknown op")
        self._edge_size[(u, v)] = float(size)
        self._succ[u][v] = None
        self._pred[v][u] = None
        self._cache = None

    def remove_op(self, op_id: str) -> None:
        op_id = str(op_id)
        for v in list(self._succ[op_id]):
            del self._edge_size[(op_id, v)]
            del self._pred[v][op_id]
        for u in list(self._pred[op_id]):
            del self._edge_size[(u, op_id)]
            del self._succ[u][op_id]
        for table in (self._compute, self._memory, self._is_forward,
                      self._counterpart, self._succ, self._pred):
            del table[op_id]
        self._cache = None

    def set_edge_size(self, u: str, v: str, size: float) -> None:
        if (u, v) not in self._edge_size:
            raise KeyError(f"edge ({u}, {v}) does not exist")
        self._edge_size[(u, v)] = float(size)
        self._cache = None

    def copy(self) -> "OpGraph":
        out = OpGraph(self.device_type)
        out._compute = dict(self._compute)
        out._memory = dict(self._memory)
        out._is_forward = dict(self._is_forward)
        out._counterpart = dict(self._counterpart)
        out._edge_size = dict(self._edge_size)
        out._succ = {k: dict(v) for k, v in self._succ.items()}
        out._pred = {k: dict(v) for k, v in self._pred.items()}
        out.meta = dict(self.meta)
        return out

    # ------------------------------------------------------------------ views
    @property
    def n_ops(self) -> int:
        return len(self._compute)

    @property
    def n_deps(self) -> int:
        return len(self._edge_size)

    @property
    def op_ids(self) -> List[str]:
        return list(self._compute)

    @property
    def edge_ids(self) -> List[EdgeId]:
        return list(self._edge_size)

    def has_op(self, op_id: str) -> bool:
        return str(op_id) in self._compute

    def has_edge(self, u: str, v: str) -> bool:
        return (str(u), str(v)) in self._edge_size

    def compute_cost(self, op_id: str) -> float:
        return self._compute[str(op_id)]

    def memory_cost(self, op_id: str) -> float:
        return self._memory[str(op_id)]

    def is_forward(self, op_id: str) -> bool:
        return self._is_forward[str(op_id)]

    def counterpart(self, op_id: str) -> Optional[str]:
        return self._counterpart[str(op_id)]

    def edge_size(self, u: str, v: str) -> float:
        return self._edge_size[(str(u), str(v))]

    def successors(self, op_id: str) -> List[str]:
        return list(self._succ[str(op_id)])

    def predecessors(self, op_id: str) -> List[str]:
        return list(self._pred[str(op_id)])

    def in_edges(self, op_id: str) -> List[EdgeId]:
        op_id = str(op_id)
        return [(u, op_id) for u in self._pred[op_id]]

    def out_edges(self, op_id: str) -> List[EdgeId]:
        op_id = str(op_id)
        return [(op_id, v) for v in self._succ[op_id]]

    def parents(self, op_id: str) -> List[str]:
        """Non-mutual predecessors.

        Op A is a parent of op B only if A->B exists and B->A does not: mutual
        (sync) edge pairs are treated as *children* of both endpoints so the
        backward-pass weight-sync collective cannot deadlock op readiness
        (reference: ddls/demands/jobs/job.py:508-523).
        """
        op_id = str(op_id)
        succ = self._succ[op_id]
        return [u for u in self._pred[op_id] if u not in succ]

    def forward_op_ids(self) -> List[str]:
        return [op for op, fwd in self._is_forward.items() if fwd]

    def forward_view(self) -> "OpGraph":
        """The graph restricted to forward-pass ops
        (reference: ddls/utils.py:477 get_forward_graph)."""
        out = OpGraph(self.device_type)
        for op in self.forward_op_ids():
            out.add_op(op, self._compute[op], self._memory[op],
                       is_forward=True, counterpart=self._counterpart[op])
        for (u, v), size in self._edge_size.items():
            if out.has_op(u) and out.has_op(v):
                out.add_edge(u, v, size)
        out.meta = dict(self.meta)
        return out

    # ------------------------------------------------------------ finalised arrays
    def finalize(self) -> dict:
        """Cache flat arrays keyed by stable op/edge insertion order."""
        if self._cache is not None:
            return self._cache
        op_ids = self.op_ids
        edge_ids = self.edge_ids
        op_index = {op: i for i, op in enumerate(op_ids)}
        edge_index = {e: i for i, e in enumerate(edge_ids)}

        n, m = len(op_ids), len(edge_ids)
        compute = np.array([self._compute[o] for o in op_ids], dtype=np.float64)
        memory = np.array([self._memory[o] for o in op_ids], dtype=np.float64)
        is_forward = np.array([self._is_forward[o] for o in op_ids], dtype=bool)
        edge_size = np.array([self._edge_size[e] for e in edge_ids], dtype=np.float64)
        edge_src = np.array([op_index[u] for u, _ in edge_ids], dtype=np.int64)
        edge_dst = np.array([op_index[v] for _, v in edge_ids], dtype=np.int64)

        in_edges: List[List[int]] = [[] for _ in range(n)]
        out_edges: List[List[int]] = [[] for _ in range(n)]
        for ei, (u, v) in enumerate(edge_ids):
            out_edges[op_index[u]].append(ei)
            in_edges[op_index[v]].append(ei)

        num_parents = np.array([len(self.parents(o)) for o in op_ids], dtype=np.int64)
        # an edge is "mutual" if its reverse also exists (sync-edge pair);
        # mutual edges never gate op readiness (see parents())
        edge_mutual = np.array([(v, u) in self._edge_size for u, v in edge_ids],
                               dtype=bool)
        sources = [op for op in op_ids if len(self._pred[op]) == 0]
        depth = self._bfs_depths(sources[0] if sources else None, op_index, n)

        # sorted-id ranks: the engines break priority ties to the smallest
        # op/edge id; precomputing them here (cached per graph, and graphs
        # are memoised across same-model jobs) keeps lookahead packing off
        # the per-call hot path
        op_sorted_rank = np.empty(n, dtype=np.int64)
        for r, op in enumerate(sorted(op_ids)):
            op_sorted_rank[op_index[op]] = r
        edge_sorted_rank = np.empty(m, dtype=np.int64)
        for r, e in enumerate(sorted(edge_ids)):
            edge_sorted_rank[edge_index[e]] = r

        self._cache = {
            "op_ids": op_ids,
            "edge_ids": edge_ids,
            "op_index": op_index,
            "edge_index": edge_index,
            "compute": compute,
            "memory": memory,
            "is_forward": is_forward,
            "edge_size": edge_size,
            "edge_src": edge_src,
            "edge_dst": edge_dst,
            "in_edges": in_edges,
            "out_edges": out_edges,
            "num_parents": num_parents,
            "edge_mutual": edge_mutual,
            "sources": sources,
            "depth": depth,
            "op_sorted_rank": op_sorted_rank,
            "edge_sorted_rank": edge_sorted_rank,
        }
        return self._cache

    def flow_mask(self, server_of_op) -> Tuple[np.ndarray, np.ndarray]:
        """Dense per-op server codes + per-dep flow mask.

        ``server_of_op`` is a sequence of server ids aligned with
        ``finalize()['op_ids']``. A dep is a *flow* iff its size is nonzero
        and its endpoints sit on different servers — the single definition
        shared by the dep placer, the lookahead packers, and the
        register-time run-time zeroing (which must all agree for the
        engines to stay in lockstep). Returns (scode[n_ops],
        is_flow[n_deps])."""
        arrays = self.finalize()
        server_dense: Dict[str, int] = {}
        scode = np.empty(self.n_ops, np.int64)
        for i, s in enumerate(server_of_op):
            si = server_dense.get(s)
            if si is None:
                si = server_dense.setdefault(s, len(server_dense))
            scode[i] = si
        return scode, self.flow_mask_from_codes(scode)

    def flow_mask_from_codes(self, scode) -> np.ndarray:
        """Per-dep flow mask from an already-dense per-op server-code array
        (any consistent labelling): THE flow predicate — nonzero size AND
        endpoints on different servers. Every array-path caller (dep
        placer, candidate pricing, packers, register-time zeroing) must go
        through here so the engines can never disagree on flow-ness."""
        arrays = self.finalize()
        return ((arrays["edge_size"] > 0)
                & (scode[arrays["edge_src"]] != scode[arrays["edge_dst"]]))

    def _bfs_depths(self, root: Optional[str], op_index: Dict[str, int], n: int) -> np.ndarray:
        """Shortest-path node counts from the first source op; 0 if unreachable
        (matches the reference's ``len(nx.shortest_path(...))`` with
        NetworkXNoPath -> 0, ddls/demands/jobs/job.py:23-29)."""
        depth = np.zeros(n, dtype=np.int64)
        if root is None:
            return depth
        depth[op_index[root]] = 1
        seen = {root}
        frontier = deque([(root, 1)])
        while frontier:
            node, d = frontier.popleft()
            for child in self._succ[node]:
                if child not in seen:
                    seen.add(child)
                    depth[op_index[child]] = d + 1
                    frontier.append((child, d + 1))
        return depth

    def topo_order(self) -> List[str]:
        """Kahn topological order, FIFO over insertion order (matches the
        placer's deterministic sequence, reference:
        ddls/environments/ramp_cluster/agents/placers/utils.py:100).

        In-degrees count only non-mutual parents so graphs containing
        sync-edge pairs (cycles of length 2) still order fully.
        """
        indegree = {op: len(self.parents(op)) for op in self._compute}
        queue = deque([op for op, d in indegree.items() if d == 0])
        order = list(queue)
        while queue:
            op = queue.popleft()
            for child in self._succ[op]:
                if op in self._succ.get(child, {}):
                    continue  # mutual pair: not a parent->child relation
                indegree[child] -= 1
                if indegree[child] == 0:
                    queue.append(child)
                    order.append(child)
        return order

    def __repr__(self) -> str:
        return (f"OpGraph(n_ops={self.n_ops}, n_deps={self.n_deps}, "
                f"device_type={self.device_type!r})")
