"""Synthetic PipeDream-format workload generation.

The reference's experiments load PipeDream profile graphs from disk
(``env_dev.yaml jobs_config.path_to_files``) but the dataset itself is not part
of the repo. This module synthesises families of DNN training-job profiles --
CNN-like chains with skip connections and translation-like encoder/decoder
chains -- and writes them in the exact PipeDream ``.txt`` profile format the
reader consumes, so the whole file-driven pipeline (reader -> mirror ->
Job -> generator) is exercised end to end.

Scales are chosen so the PAC-ML trade-off is non-trivial under the reference's
canonical config (interarrival 1000, 50 training steps, U(0.1, 1) max-JCT
fraction): sequential JCTs land in the hundreds-to-thousands range, and
deep partitioning buys compute speedup at the price of collective-sync
overhead through the RAMP all-reduce cost model.
"""
from __future__ import annotations

import os
import pathlib
from typing import List, Optional

import numpy as np


def _emit_node(lines: List[str], node_id: int, op_type: str, fwd: float,
               bwd: float, activation: float, parameter: float) -> None:
    lines.append(
        f"node{node_id} -- {op_type}(id={node_id}) -- "
        f"forward_compute_time={fwd:.6f}, backward_compute_time={bwd:.6f}, "
        f"activation_size={activation:.1f}, parameter_size={parameter:.1f}"
    )


def _emit_edge(lines: List[str], u: int, v: int) -> None:
    lines.append(f"node{u} -- node{v}")


def make_cnn_profile(rng: np.random.Generator,
                     n_ops: int,
                     compute_scale: float = 1.0,
                     skip_prob: float = 0.25) -> str:
    """A conv-stack-like chain with occasional skip connections."""
    lines: List[str] = []
    op_types = ["Conv2d", "BatchNorm2d", "ReLU", "MaxPool2d", "Linear"]
    for i in range(1, n_ops + 1):
        op_type = op_types[rng.integers(len(op_types))] if 1 < i < n_ops else (
            "Input" if i == 1 else "Linear")
        fwd = float(rng.uniform(0.2, 4.0)) * compute_scale
        bwd = fwd * float(rng.uniform(1.5, 2.5))
        activation = float(rng.uniform(0.05, 1.0)) * 1e9
        parameter = float(rng.uniform(0.01, 2.0)) * 1e9 if op_type in (
            "Conv2d", "Linear") else float(rng.uniform(0.001, 0.05)) * 1e9
        _emit_node(lines, i, op_type, fwd, bwd, activation, parameter)
    for i in range(1, n_ops):
        _emit_edge(lines, i, i + 1)
        if i + 2 <= n_ops and rng.random() < skip_prob:
            _emit_edge(lines, i, i + 2)
    return "\n".join(lines) + "\n"


def make_translation_profile(rng: np.random.Generator,
                             n_encoder: int,
                             n_decoder: int,
                             compute_scale: float = 1.0) -> str:
    """An encoder/decoder (GNMT-like) profile: two chains with a bridge and
    attention-style cross edges."""
    lines: List[str] = []
    n_ops = n_encoder + n_decoder
    for i in range(1, n_ops + 1):
        is_enc = i <= n_encoder
        op_type = "LSTMEnc" if is_enc else "LSTMDec"
        fwd = float(rng.uniform(0.5, 6.0)) * compute_scale
        bwd = fwd * float(rng.uniform(1.6, 2.2))
        activation = float(rng.uniform(0.1, 1.5)) * 1e9
        parameter = float(rng.uniform(0.2, 3.0)) * 1e9
        _emit_node(lines, i, op_type, fwd, bwd, activation, parameter)
    for i in range(1, n_encoder):
        _emit_edge(lines, i, i + 1)
    for i in range(n_encoder + 1, n_ops):
        _emit_edge(lines, i, i + 1)
    # bridge + attention cross edges
    _emit_edge(lines, n_encoder, n_encoder + 1)
    for i in range(n_encoder + 1, n_ops, 2):
        if i != n_encoder + 1:
            _emit_edge(lines, n_encoder, i)
    return "\n".join(lines) + "\n"


def generate_pipedream_txt_files(out_dir: str,
                                 n_cnn: int = 4,
                                 n_translation: int = 2,
                                 seed: int = 0,
                                 min_ops: int = 6,
                                 max_ops: int = 14,
                                 compute_scale: float = 1.0) -> List[str]:
    """Write a family of synthetic profiles to ``out_dir``; returns paths."""
    rng = np.random.default_rng(seed)
    pathlib.Path(out_dir).mkdir(parents=True, exist_ok=True)
    paths = []
    for i in range(n_cnn):
        n_ops = int(rng.integers(min_ops, max_ops + 1))
        path = os.path.join(out_dir, f"cnn_{i}.txt")
        with open(path, "w") as f:
            f.write(make_cnn_profile(rng, n_ops, compute_scale=compute_scale))
        paths.append(path)
    for i in range(n_translation):
        n_enc = int(rng.integers(max(3, min_ops // 2), max(4, max_ops // 2)))
        n_dec = int(rng.integers(max(3, min_ops // 2), max(4, max_ops // 2)))
        path = os.path.join(out_dir, f"translation_{i}.txt")
        with open(path, "w") as f:
            f.write(make_translation_profile(rng, n_enc, n_dec,
                                             compute_scale=compute_scale))
        paths.append(path)
    return paths
