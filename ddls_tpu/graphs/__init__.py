from ddls_tpu.graphs.op_graph import OpGraph
from ddls_tpu.graphs.readers import (
    graph_from_pipedream_txt,
    graph_from_pbtxt,
)
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

__all__ = [
    "OpGraph",
    "graph_from_pipedream_txt",
    "graph_from_pbtxt",
    "generate_pipedream_txt_files",
]
