"""Workload profile readers: PipeDream ``.txt`` and REGAL CostGraphDef ``.pbtxt``.

Produces :class:`~ddls_tpu.graphs.op_graph.OpGraph` objects holding one
forward+backward training-step graph, with the same construction semantics as
the reference (ddls/utils.py:110-476):

* the profile describes the *forward* pass; the backward pass is built by
  reflecting the forward DAG, with backward op id ``2n - (fwd - 1)`` for a
  forward op ``fwd`` in a graph of ``n`` forward ops (ddls/utils.py:342-370);
* forward and backward graphs are joined by an edge from the last forward op
  to the first backward op (ddls/utils.py:389-392);
* every edge's tensor size is the *activation* size of its producer op
  (ddls/utils.py:394-397);
* an op's ``memory_cost`` is ``activation + parameter`` size and its
  ``compute_cost`` is the profiled forward (resp. backward) time
  (ddls/utils.py:426-431).
"""
from __future__ import annotations

import json
import random
import re
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ddls_tpu.graphs.op_graph import OpGraph


# --------------------------------------------------------------------- pipedream
def _parse_pipedream_txt(path: str) -> Tuple[Dict[str, dict], List[Tuple[str, str]]]:
    """Parse node/edge lines of a PipeDream profile.

    Node line:  ``node<i> -- <OpType>(...) -- forward_compute_time=..,
    backward_compute_time=.., activation_size=.., parameter_size=..``
    Edge line:  ``node<u> -- node<v>``
    (reference parser: ddls/utils.py:278-340).
    """
    nodes: Dict[str, dict] = {}
    edges: List[Tuple[str, str]] = []
    with open(path) as f:
        for raw in f:
            raw = raw.rstrip("\n")
            if not raw.strip():
                continue
            parts = [p.split("\t")[-1] for p in raw.split(" -- ")]
            if len(parts) > 2:
                node_id = str(int(parts[0][4:]))
                stats = parts[2].split(", ")
                if len(stats) < 4:
                    raise ValueError(
                        f"{path}: malformed node line (expected 4 "
                        f"'key=value' stats): {raw!r}")
                vals = {}
                for name, field in zip(
                        ("forward", "backward", "activation", "parameter"), stats):
                    if "=" not in field:
                        raise ValueError(
                            f"{path}: malformed stat field {field!r} in "
                            f"line {raw!r}")
                    val = json.loads(field.split("=")[1].replace(";", ","))
                    if isinstance(val, list):
                        # some pipedream translation profiles list per-output
                        # activations; total = sum (reference: ddls/utils.py:322-324)
                        val = float(np.sum(val))
                    vals[name] = float(val)
                vals["op_type"] = parts[1].split("(")[0]
                nodes[node_id] = vals
            else:
                u = str(int(parts[0][4:]))
                v = str(int(parts[1][4:]))
                edges.append((u, v))
    return nodes, edges


def backward_op_id(forward_op_id, n_forward_ops: int) -> str:
    """Backward counterpart id: ``2n - (fwd - 1)``
    (reference: ddls/environments/ramp_cluster/agents/placers/utils.py:316)."""
    return str(2 * n_forward_ops - (int(forward_op_id) - 1))


def graph_from_pipedream_txt(path: str,
                             device_type: str = "A100",
                             verbose: bool = False) -> OpGraph:
    nodes, fwd_edges = _parse_pipedream_txt(path)
    n = len(nodes)

    g = OpGraph(device_type)
    # forward ops
    for op_id, vals in nodes.items():
        g.add_op(op_id,
                 compute=vals["forward"],
                 memory=vals["activation"] + vals["parameter"],
                 is_forward=True,
                 counterpart=backward_op_id(op_id, n))
    # mirrored backward ops
    for op_id, vals in nodes.items():
        g.add_op(backward_op_id(op_id, n),
                 compute=vals["backward"],
                 memory=vals["activation"] + vals["parameter"],
                 is_forward=False,
                 counterpart=op_id)

    activation = {op: vals["activation"] for op, vals in nodes.items()}
    for bop, fop in ((backward_op_id(op, n), op) for op in nodes):
        activation[bop] = nodes[fop]["activation"]

    def _add(u: str, v: str) -> None:
        g.add_edge(u, v, size=activation[u])

    for u, v in fwd_edges:
        _add(u, v)
    # reflected backward edges
    for u, v in fwd_edges:
        _add(backward_op_id(v, n), backward_op_id(u, n))
    # join last forward op to first backward op
    join_src = str(max(int(i) for i in nodes))
    join_dst = str(min(int(backward_op_id(i, n)) for i in nodes))
    _add(join_src, join_dst)

    g.meta["file_path"] = path
    g.meta["model"] = _model_name_from_path(path)
    if verbose:
        print(f"loaded {path}: {g}")
    return g


def _model_name_from_path(path: str) -> str:
    """Model tag used for memoisation keys: the file's stem, or the parent
    directory when the file is a generic ``graph.txt``
    (reference: ddls/demands/jobs/jobs_generator.py:150-155)."""
    parts = path.split("/")
    if parts[-1] == "graph.txt":
        return parts[-2]
    return re.sub(r"\.(txt|pbtxt)$", "", parts[-1])


# ----------------------------------------------------------------------- pbtxt
def _parse_pbtxt_nodes(path: str) -> List[dict]:
    """Parse CostGraphDef-style node blocks (DeepMind REGAL release format;
    reference: ddls/utils.py:110-167)."""
    out: List[dict] = []
    node: Optional[dict] = None
    with open(path) as f:
        for raw in f:
            line = raw.replace(" ", "").replace("\n", "")
            if line == "node{":
                if node is not None:
                    out.append(node)
                node = defaultdict(list)
            elif node is None or line == "}":
                continue
            elif line.startswith("id"):
                node["id"] = int(line.split(":", 1)[1])
            elif "name" in line:
                if "_SOURCE" in line:
                    node["id"] = 0
            elif "preceding_node" in line:
                node["input_info"].append(int(line.split(":", 1)[1]))
            elif "size" in line:
                node["output_info"].append(int(line.split(":", 1)[1]))
            elif "control_input" in line:
                node["control_input"].append(int(line.split(":", 1)[1]))
            elif "compute_cost" in line:
                node["compute_cost"] = int(line.split(":", 1)[1])
    if node is not None:
        out.append(node)
    return out


def graph_from_pbtxt(path: str,
                     device_type: str = "A100",
                     mirror: bool = True,
                     verbose: bool = False) -> OpGraph:
    """Build an OpGraph from a REGAL CostGraphDef profile.

    The released pbtxt files do not say which child consumes which output
    tensor, so a dependency's size is sampled among the producer's output
    sizes, preserving the released size distribution (reference hack:
    ddls/utils.py:170-198). With ``mirror=True`` the cost graph is treated as
    a forward pass and reflected into a fwd+bwd graph (the reference's pbtxt
    path never mirrors and is in fact unreachable from its JobsGenerator --
    SURVEY.md §7.5 -- so mirroring here makes pbtxt workloads actually usable
    for the partitioning MDP).
    """
    blocks = _parse_pbtxt_nodes(path)
    blocks = [b for b in blocks if isinstance(b.get("id"), int)]
    # remap ids to contiguous 1..n (the backward-mirroring arithmetic needs
    # 1-based contiguous ids; released pbtxt files may have sparse ids)
    remap = {b["id"]: str(i + 1) for i, b in enumerate(
        sorted(blocks, key=lambda b: b["id"]))}
    compute = {}
    out_sizes = {}
    data_edges: List[Tuple[str, str]] = []
    ctrl_edges: List[Tuple[str, str]] = []
    for block in blocks:
        node_id = remap[block["id"]]
        compute[node_id] = float(block.get("compute_cost", 0))
        out_sizes[node_id] = list(block.get("output_info", [])) or [0]
        for parent in block.get("input_info", []):
            if parent in remap:
                data_edges.append((remap[parent], node_id))
        for parent in block.get("control_input", []):
            if parent in remap:
                ctrl_edges.append((remap[parent], node_id))

    n = len(compute)
    g = OpGraph(device_type)
    for node_id in compute:
        mem = float(np.sum(out_sizes[node_id]))
        g.add_op(node_id, compute=compute[node_id], memory=mem,
                 is_forward=True,
                 counterpart=backward_op_id(node_id, n) if mirror else None)
    if mirror:
        for node_id in compute:
            mem = float(np.sum(out_sizes[node_id]))
            g.add_op(backward_op_id(node_id, n), compute=compute[node_id],
                     memory=mem, is_forward=False, counterpart=node_id)

    def _size_of(u: str, is_data: bool) -> float:
        return float(random.choice(out_sizes[u])) if is_data else 0.0

    seen = set()
    for edge_list, is_data in ((data_edges, True), (ctrl_edges, False)):
        for u, v in edge_list:
            if (u, v) in seen or u == v:
                continue
            seen.add((u, v))
            size = _size_of(u, is_data)
            g.add_edge(u, v, size=size)
            if mirror:
                g.add_edge(backward_op_id(v, n), backward_op_id(u, n), size=size)
    if mirror:
        join_src = str(max(int(i) for i in compute))
        join_dst = str(min(int(backward_op_id(i, n)) for i in compute))
        if not g.has_edge(join_src, join_dst):
            g.add_edge(join_src, join_dst, size=_size_of(join_src, True))

    g.meta["file_path"] = path
    g.meta["model"] = _model_name_from_path(path)
    if verbose:
        print(f"loaded {path}: {g}")
    return g


def read_graph_file(path: str, device_type: str = "A100") -> OpGraph:
    if path.endswith(".pbtxt"):
        return graph_from_pbtxt(path, device_type=device_type)
    if path.endswith(".txt"):
        return graph_from_pipedream_txt(path, device_type=device_type)
    raise ValueError(f"unsupported graph profile type: {path}")
