"""Result loaders: saved experiment/cluster logs -> pandas frames.

TPU-native counterpart of the reference's W&B run/sweep result loaders
(ddls/environments/ramp_cluster/utils.py:129-473), reading from the local
artifacts this framework writes instead of the W&B API:

* a *run dir* written by ``scripts/train_from_config.py`` /
  ``test_heuristic_from_config.py``: ``config.yaml`` +
  ``results.pkl.gz`` (or ``results.sqlite``) produced by the Logger;
* a *cluster save dir* written by ``RampClusterEnvironment.save``:
  ``reset_<i>/{steps_log,episode_stats}.{pkl,sqlite}``;
* a *sweep dir* written by ``scripts/run_sweep.py``: one run dir per
  configuration.

All loaders return plain dicts / :class:`pandas.DataFrame` so the plotting
layer and notebooks can consume them directly.
"""
from __future__ import annotations

import glob
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd


# ----------------------------------------------------------------- raw files
def _load_pickle_or_sqlite(path: Path) -> Dict[str, Any]:
    # single reader shared with the Logger so the save/load formats cannot
    # drift apart
    from ddls_tpu.train.logger import Logger

    return Logger.load(str(path))


def _find_results_file(run_dir: Path) -> Optional[Path]:
    for pattern in ("results.pkl.gz", "results.sqlite",
                    "**/results.pkl.gz", "**/results.sqlite"):
        hits = sorted(run_dir.glob(pattern))
        if hits:
            return hits[0]
    return None


def _load_yaml(path: Path) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f) or {}


@dataclass
class RunResults:
    """One experiment run: its config, its logged results, and a label."""

    name: str
    path: str
    results: Dict[str, Any]
    config: Dict[str, Any] = field(default_factory=dict)

    @property
    def kind(self) -> str:
        if "epochs" in self.results:
            return "training"
        if "heuristic_eval" in self.results:
            return "heuristic"
        if "rl_eval" in self.results:
            return "rl_eval"
        return "unknown"

    def episode_stats(self) -> Dict[str, Any]:
        """The final-episode cluster stats, whichever kind of run this is.

        * heuristic runs (test_heuristic_from_config) store them whole;
        * rl_eval runs (test_from_config) store one record per eval episode;
        * training runs (train_from_config) log only the scalar
          ``custom_metrics/*_mean`` summaries per epoch (loops.py
          _episode_summary), so the last epoch's scalars are re-mapped into
          an episode-stats-shaped dict (per-job lists are only available
          from an rl_eval run of the checkpoint).
        """
        if self.kind == "heuristic":
            return self.results["heuristic_eval"].get("episode_stats", {})
        if self.kind == "rl_eval":
            records = self.results["rl_eval"]
            return records[-1].get("episode_stats", {}) if records else {}
        if self.kind == "training":
            # scan backwards for the first epoch with usable eval stats --
            # a final epoch whose eval window finished no episode logs an
            # empty evaluation and must not shadow earlier real data
            for epoch in reversed(self.results["epochs"]):
                evaluation = epoch.get("evaluation", {})
                if evaluation.get("episode_stats"):
                    return evaluation["episode_stats"]
                flat = _flatten_scalars(evaluation)
                stats = {}
                for key, val in flat.items():
                    if key.startswith("custom_metrics/") and key.endswith(
                            "_mean"):
                        stats[key[len("custom_metrics/"):-len("_mean")]] = val
                if stats:
                    return stats
        return self.results.get("episode_stats", {})


def load_run(path: Union[str, Path],
             name: Optional[str] = None) -> RunResults:
    """Load a run dir (or a results file directly) into a RunResults."""
    path = Path(path)
    if path.is_dir():
        results_file = _find_results_file(path)
        if results_file is None:
            raise FileNotFoundError(f"no results file under {path}")
    else:
        results_file = path
        path = path.parent
    results = _load_pickle_or_sqlite(results_file)
    config: Dict[str, Any] = {}
    for candidate in (path / "config.yaml",
                      results_file.parent / "config.yaml"):
        if candidate.exists():
            config = _load_yaml(candidate)
            break
    return RunResults(name=name or path.name, path=str(path),
                      results=results, config=config)


def load_runs(paths: Union[str, Sequence[Union[str, Path]]],
              names: Optional[Sequence[str]] = None) -> List[RunResults]:
    """Load several runs; ``paths`` may be a glob pattern or a list."""
    if isinstance(paths, str):
        paths = sorted(glob.glob(paths))
    names = list(names) if names is not None else [None] * len(paths)
    if len(names) != len(paths):
        raise ValueError(f"{len(names)} names for {len(paths)} paths")
    return [load_run(p, name=n) for p, n in zip(paths, names)]


def load_cluster_save(save_dir: Union[str, Path],
                      reset: Optional[int] = None) -> Dict[str, Any]:
    """Load a RampClusterEnvironment save dir (``reset_<i>`` subdirs with
    steps_log/episode_stats in either backend)."""
    save_dir = Path(save_dir)
    resets = sorted(save_dir.glob("reset_*"),
                    key=lambda p: int(p.name.split("_")[-1]))
    if not resets:
        raise FileNotFoundError(f"no reset_* dirs under {save_dir}")
    chosen = (resets[-1] if reset is None
              else save_dir / f"reset_{reset}")
    if not chosen.is_dir():
        raise FileNotFoundError(
            f"{chosen} does not exist; available: "
            f"{[p.name for p in resets]}")
    out = {}
    for log_name in ("steps_log", "episode_stats"):
        for suffix in (".pkl", ".sqlite"):
            f = chosen / f"{log_name}{suffix}"
            if f.exists():
                out[log_name] = _load_pickle_or_sqlite(f)
                break
    return out


# -------------------------------------------------------------------- frames
def _flatten_scalars(node: Any, prefix: str = "",
                     out: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    if out is None:
        out = {}
    if isinstance(node, dict):
        for k, v in node.items():
            _flatten_scalars(v, f"{prefix}{k}/", out)
    elif isinstance(node, (int, float, np.floating, np.integer, bool)):
        out[prefix[:-1]] = float(node)
    return out


def epochs_frame(run: RunResults) -> pd.DataFrame:
    """One row per training epoch, nested scalar metrics flattened into
    '/'-joined columns (the reference's RLlib-result flattening,
    rllib_epoch_loop.py:105-230)."""
    if run.kind != "training":
        raise ValueError(f"run {run.name} has no epochs (kind={run.kind})")
    rows = [_flatten_scalars(epoch) for epoch in run.results["epochs"]]
    frame = pd.DataFrame(rows)
    frame.insert(0, "epoch", np.arange(1, len(frame) + 1))
    frame.insert(0, "run", run.name)
    return frame


def _per_job_frame(stats: Dict[str, Any], prefix: str,
                   extra: Sequence[str] = ()) -> pd.DataFrame:
    cols = {}
    for key, val in stats.items():
        if key.startswith(prefix) and isinstance(val, list):
            cols[key[len(prefix):]] = val
    for key in extra:
        if isinstance(stats.get(key), list):
            cols[key] = stats[key]
    if not cols:
        return pd.DataFrame()
    n = min(len(v) for v in cols.values())
    return pd.DataFrame({k: v[:n] for k, v in cols.items()})


def completed_jobs_frame(run: RunResults) -> pd.DataFrame:
    """Per-completed-job characteristics (the reference eval tables,
    rllib_eval_loop.py:123-158)."""
    stats = run.episode_stats()
    frame = _per_job_frame(
        stats, "jobs_completed_",
        extra=("job_completion_time", "job_completion_time_speedup",
               "job_communication_overhead_time",
               "job_computation_overhead_time"))
    if len(frame):
        frame.insert(0, "run", run.name)
    return frame


def blocked_jobs_frame(run: RunResults) -> pd.DataFrame:
    stats = run.episode_stats()
    frame = _per_job_frame(stats, "jobs_blocked_")
    if len(frame):
        frame.insert(0, "run", run.name)
    return frame


def steps_frame(source: Union[RunResults, Dict[str, Any]]) -> pd.DataFrame:
    """Per-simulator-step stats as a frame (from a run's harvested
    steps_log or a cluster save dict)."""
    if isinstance(source, RunResults):
        if source.kind == "heuristic":
            log = source.results["heuristic_eval"].get("steps_log", {})
        elif source.kind == "rl_eval":
            records = source.results["rl_eval"]
            log = records[-1].get("steps_log", {}) if records else {}
        else:
            log = source.results.get("steps_log", {})
    else:
        log = source.get("steps_log", source)
    lists = {k: v for k, v in log.items() if isinstance(v, list)}
    if not lists:
        return pd.DataFrame()
    n = min(len(v) for v in lists.values())
    return pd.DataFrame({k: v[:n] for k, v in lists.items()})


HEADLINE_METRICS = (
    "blocking_rate", "acceptance_rate", "mean_load_rate",
    "mean_cluster_throughput", "mean_demand_total_throughput",
    "mean_compute_overhead_frac", "mean_communication_overhead_frac",
    "mean_mounted_worker_utilisation_frac",
    "mean_cluster_worker_utilisation_frac",
    "num_jobs_arrived", "num_jobs_completed", "num_jobs_blocked",
)


def summary_table(runs: Sequence[RunResults]) -> pd.DataFrame:
    """Cross-run comparison of headline metrics plus mean per-job JCT and
    speedup -- the numbers behind the paper's comparison figures."""
    rows = []
    for run in runs:
        stats = run.episode_stats()
        row: Dict[str, Any] = {"run": run.name, "kind": run.kind}
        for metric in HEADLINE_METRICS:
            val = stats.get(metric)
            row[metric] = float(val) if val is not None else np.nan
        jcts = stats.get("job_completion_time") or []
        speedups = stats.get("job_completion_time_speedup") or []
        # training runs only carry the scalar means, not per-job lists
        row["mean_job_completion_time"] = (
            float(np.mean(jcts)) if jcts
            else float(stats.get("mean_job_completion_time", np.nan)))
        row["p99_job_completion_time"] = (
            float(np.percentile(jcts, 99)) if jcts else np.nan)
        row["mean_job_completion_time_speedup"] = (
            float(np.mean(speedups)) if speedups
            else float(stats.get("mean_job_completion_time_speedup",
                                 np.nan)))
        if run.kind == "heuristic":
            row["episode_return"] = run.results["heuristic_eval"].get(
                "episode_return", np.nan)
        elif run.kind == "rl_eval":
            returns = [r.get("episode", {}).get("episode_return")
                       for r in run.results["rl_eval"]]
            returns = [r for r in returns if r is not None]
            row["episode_return"] = (float(np.mean(returns))
                                     if returns else np.nan)
        elif run.kind == "training":
            returns = []
            for ep in run.results["epochs"]:
                flat = _flatten_scalars(ep)
                val = flat.get("evaluation/episode_reward_mean")
                if val is None:  # 0.0 is a legitimate reward
                    val = flat.get("episode_reward_mean")
                returns.append(val)
            returns = [r for r in returns if r is not None]
            row["episode_return"] = returns[-1] if returns else np.nan
        rows.append(row)
    return pd.DataFrame(rows)


def blocked_cause_table(runs: Sequence[RunResults]) -> pd.DataFrame:
    """Per-run counts of each blocking cause."""
    rows = []
    for run in runs:
        causes = run.episode_stats().get(
            "jobs_blocked_cause_of_unsuccessful_handling") or []
        row = {"run": run.name}
        for cause in causes:
            row[cause] = row.get(cause, 0) + 1
        rows.append(row)
    return pd.DataFrame(rows).fillna(0)
