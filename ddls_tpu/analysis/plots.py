"""Plotting: the paper's headline figures from loaded results.

Counterpart of the reference's plotting layer
(ddls/plotting/plotting.py:15-440): publication-style plot parameters,
computation-graph rendering, and the learner-vs-baseline comparison figures
(learning curves, JCT/blocking comparisons, per-job distributions) its
notebooks build. Implemented on matplotlib directly (the reference wraps
seaborn) and fed from :mod:`ddls_tpu.analysis.loaders` frames.
"""
from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Sequence, Union

import os
import sys

import matplotlib
import numpy as np

# headless default, but never hijack a backend the user already picked
# (e.g. a notebook's inline backend imports pyplot before this module)
if "matplotlib.pyplot" not in sys.modules and not os.environ.get("DISPLAY"):
    matplotlib.use("Agg")

import matplotlib.pyplot as plt  # noqa: E402

from ddls_tpu.analysis.loaders import (RunResults, blocked_cause_table,
                                       completed_jobs_frame, epochs_frame,
                                       summary_table)

# conference-style defaults (reference keeps an ICML param block,
# plotting.py:15-60)
PLOT_PARAMS = {
    "figure.figsize": (5.5, 3.4),
    "figure.dpi": 120,
    "font.size": 9,
    "axes.titlesize": 9,
    "axes.labelsize": 9,
    "legend.fontsize": 8,
    "xtick.labelsize": 8,
    "ytick.labelsize": 8,
    "axes.spines.top": False,
    "axes.spines.right": False,
    "axes.grid": True,
    "grid.alpha": 0.3,
    "savefig.bbox": "tight",
}


def apply_plot_style() -> None:
    plt.rcParams.update(PLOT_PARAMS)


def _save(fig, path: Optional[Union[str, Path]]):
    if path is not None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        fig.savefig(path)
        plt.close(fig)
    return fig


# ------------------------------------------------------------ learning curves
def plot_learning_curves(training_runs: Sequence[RunResults],
                         metric: str = "evaluation/episode_reward_mean",
                         baseline_runs: Sequence[RunResults] = (),
                         smooth: int = 1,
                         path: Optional[str] = None):
    """Learner metric vs epoch, with heuristic baselines as horizontal
    lines -- the paper's learner-vs-baseline curve."""
    apply_plot_style()
    fig, ax = plt.subplots()
    for run in training_runs:
        frame = epochs_frame(run)
        col = metric if metric in frame.columns else None
        if col is None:
            # fall back to any column whose tail matches
            tails = [c for c in frame.columns if c.endswith(metric)]
            if not tails:
                continue
            col = tails[0]
        ys = frame[col].astype(float)
        if smooth > 1:
            ys = ys.rolling(smooth, min_periods=1).mean()
        ax.plot(frame["epoch"], ys, label=run.name)
    for run in baseline_runs:
        val = run.results.get("heuristic_eval", {}).get("episode_return")
        if val is not None:
            ax.axhline(float(val), linestyle="--", linewidth=1, alpha=0.8,
                       label=f"{run.name} (heuristic)")
    ax.set_xlabel("epoch")
    ax.set_ylabel(metric)
    if ax.get_legend_handles_labels()[0]:
        ax.legend(loc="best")
    return _save(fig, path)


# --------------------------------------------------------------- comparisons
def plot_headline_comparison(runs: Sequence[RunResults],
                             metrics: Sequence[str] = (
                                 "blocking_rate", "acceptance_rate",
                                 "mean_job_completion_time_speedup",
                                 "mean_cluster_throughput"),
                             path: Optional[str] = None):
    """Grouped bar chart of headline episode metrics per run."""
    apply_plot_style()
    table = summary_table(runs)
    n = len(metrics)
    fig, axes = plt.subplots(1, n, figsize=(2.2 * n, 2.8))
    if n == 1:
        axes = [axes]
    for ax, metric in zip(axes, metrics):
        vals = table[metric].astype(float)
        ax.bar(range(len(table)), vals)
        ax.set_xticks(range(len(table)))
        ax.set_xticklabels(table["run"], rotation=45, ha="right")
        ax.set_title(metric, fontsize=8)
    fig.tight_layout()
    return _save(fig, path)


def plot_jct_cdf(runs: Sequence[RunResults],
                 speedup: bool = False,
                 path: Optional[str] = None):
    """Empirical CDF of per-job completion time (or speedup) per run."""
    apply_plot_style()
    fig, ax = plt.subplots()
    col = ("job_completion_time_speedup" if speedup
           else "job_completion_time")
    for run in runs:
        frame = completed_jobs_frame(run)
        if col not in frame.columns or not len(frame):
            continue
        xs = np.sort(frame[col].astype(float).to_numpy())
        ys = np.arange(1, len(xs) + 1) / len(xs)
        ax.step(xs, ys, where="post", label=run.name)
    ax.set_xlabel("JCT speedup vs sequential" if speedup
                  else "job completion time")
    ax.set_ylabel("CDF")
    if not speedup:
        ax.set_xscale("log")
    if ax.get_legend_handles_labels()[0]:
        ax.legend(loc="best")
    return _save(fig, path)


def plot_blocked_causes(runs: Sequence[RunResults],
                        path: Optional[str] = None):
    """Stacked bars of blocking causes per run."""
    apply_plot_style()
    table = blocked_cause_table(runs)
    causes = [c for c in table.columns if c != "run"]
    fig, ax = plt.subplots()
    bottom = np.zeros(len(table))
    for cause in causes:
        vals = table[cause].astype(float).to_numpy()
        ax.bar(range(len(table)), vals, bottom=bottom, label=cause)
        bottom += vals
    ax.set_xticks(range(len(table)))
    ax.set_xticklabels(table["run"], rotation=45, ha="right")
    ax.set_ylabel("blocked jobs")
    if causes:
        ax.legend(loc="best", fontsize=7)
    return _save(fig, path)


def plot_metric_hist(values_by_run: Dict[str, Sequence[float]],
                     xlabel: str = "",
                     bins: int = 30,
                     path: Optional[str] = None):
    """Overlaid histograms (reference's seaborn hist wrapper)."""
    apply_plot_style()
    fig, ax = plt.subplots()
    for name, values in values_by_run.items():
        ax.hist(np.asarray(values, dtype=float), bins=bins, alpha=0.5,
                label=name)
    ax.set_xlabel(xlabel)
    ax.set_ylabel("count")
    if ax.get_legend_handles_labels()[0]:
        ax.legend(loc="best")
    return _save(fig, path)


# --------------------------------------------------------- graph rendering
def render_op_graph(graph, path: Optional[str] = None,
                    color_by: str = "pass"):
    """Render a computation graph layered by dependency depth (reference
    renders via networkx/pygraphviz, plotting.py:62-130; OpGraph is
    array-native so a longest-path layering is computed directly)."""
    apply_plot_style()
    order = graph.topo_order()
    depth = {op: 0 for op in order}
    for op in order:
        for child in graph.successors(op):
            depth[child] = max(depth[child], depth[op] + 1)
    by_depth: Dict[int, List[str]] = {}
    for op, d in depth.items():
        by_depth.setdefault(d, []).append(op)
    pos = {}
    for d, ops in by_depth.items():
        for i, op in enumerate(sorted(ops, key=str)):
            pos[op] = (i - (len(ops) - 1) / 2, -d)

    fig, ax = plt.subplots(figsize=(6, max(3, 0.45 * (max(by_depth) + 1))))
    for u, v in graph.edge_ids:
        (x0, y0), (x1, y1) = pos[u], pos[v]
        ax.annotate("", xy=(x1, y1), xytext=(x0, y0),
                    arrowprops=dict(arrowstyle="->", color="0.6", lw=0.7))
    sizes = np.array([graph.compute_cost(op) for op in pos])
    smax = sizes.max() if sizes.max() > 0 else 1.0
    for op, (x, y) in pos.items():
        if color_by == "pass":
            color = ("tab:blue" if graph.is_forward(op) else "tab:orange")
        else:
            color = "tab:blue"
        size = 120 + 260 * graph.compute_cost(op) / smax
        ax.scatter([x], [y], s=size, c=color, zorder=3,
                   edgecolors="white", linewidths=0.8)
        ax.annotate(op, (x, y), ha="center", va="center", fontsize=6,
                    zorder=4)
    ax.set_axis_off()
    return _save(fig, path)


# ------------------------------------------------------------------- report
def save_comparison_report(runs: Sequence[RunResults],
                           out_dir: Union[str, Path],
                           metric: str = "evaluation/episode_reward_mean"
                           ) -> Dict[str, str]:
    """One command: all comparison artifacts (CSV + PNG) into ``out_dir``.

    This is the product of the analysis layer: the learner-vs-baseline
    curves and JCT/blocking comparisons the reference's paper notebooks
    assemble by hand.
    """
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    artifacts: Dict[str, str] = {}

    table = summary_table(runs)
    table.to_csv(out_dir / "summary.csv", index=False)
    artifacts["summary"] = str(out_dir / "summary.csv")

    causes = blocked_cause_table(runs)
    causes.to_csv(out_dir / "blocked_causes.csv", index=False)
    artifacts["blocked_causes"] = str(out_dir / "blocked_causes.csv")

    training = [r for r in runs if r.kind == "training"]
    heuristics = [r for r in runs if r.kind == "heuristic"]
    if training:
        plot_learning_curves(training, metric=metric,
                             baseline_runs=heuristics,
                             path=out_dir / "learning_curves.png")
        artifacts["learning_curves"] = str(out_dir / "learning_curves.png")
    plot_headline_comparison(runs, path=out_dir / "comparison.png")
    artifacts["comparison"] = str(out_dir / "comparison.png")
    plot_jct_cdf(runs, path=out_dir / "jct_cdf.png")
    artifacts["jct_cdf"] = str(out_dir / "jct_cdf.png")
    plot_jct_cdf(runs, speedup=True, path=out_dir / "jct_speedup_cdf.png")
    artifacts["jct_speedup_cdf"] = str(out_dir / "jct_speedup_cdf.png")
    plot_blocked_causes(runs, path=out_dir / "blocked_causes.png")
    artifacts["blocked_causes_png"] = str(out_dir / "blocked_causes.png")
    return artifacts
