"""Cross-host dataflow fragments: trajectory ring segments over sockets.

ROADMAP item 4's cross-process tier (the MSRL/MindSpeed "dataflow
fragment" shape — PAPERS.md arXiv 2210.00882, 2507.19017): actor HOSTS
run the existing deferred-fetch collector (rl/rollout.py) against their
own envs and ship each trajectory ring segment to ONE learner host as a
single framed message, so collect and update overlap across two real
processes/schedulers instead of sharing one.

Wire protocol — length-prefixed binary frames, one TCP or Unix-domain
stream per actor host, strictly request/response in submission order
(the learner's collects are serialised through the max_workers=1
pipeline executor, so a connection never carries interleaved requests):

    prefix  = struct "<4sBIQ" : MAGIC b"DF01", frame type,
              header bytes (u32), body bytes (u64)
    header  = pickled dict (control metadata, episode records, field
              table — never the obs arrays themselves)
    body    = the SEGMENT field payloads, raw bytes, concatenated in
              header["fields"] order; empty for control frames

SEGMENT bodies are scatter-gather written straight from the actor's
ring-segment slab views (``sendmsg`` over the field buffers — no
intermediate pickle/copy of obs arrays) and received straight into the
learner's OWN ``TrajRing`` segment views: the recv write is the
lease-time write, so the learner-side alias/ownership discipline is
byte-for-byte the existing ledger (rl/ring.py — note_staged's alias
probe, phase-2 update tokens, loud lease timeouts all unchanged).

Release-token topology (who frees what):

- LEARNER segment: leased before the recv, published after it; released
  by the canonical two-phase protocol train/loops.py already runs
  (note_staged / note_update) — nothing new on this side.
- ACTOR segment: published by ``RolloutCollector._collect_deferred``;
  its release token is an :class:`AckToken` armed by the driver after
  the segment frame is fully sent and set when the learner's ACK frame
  arrives — the ack IS the remote segment's phase-1 token (the socket
  send+recv is always a copy, so "staged == copied" holds by
  construction). A missing ack therefore surfaces as the ring's own
  loud lease timeout naming the ledger states, never as corruption.

Bit-exactness: a single actor host at depth 0 is pinned bit-exact vs
the in-process path (tests/test_fragments.py) because sampling is
replicated (mesh-size-invariant — no collectives), env seeds are the
learner's ``_collect_seed + i`` stream, and the rng keys ride the
PARAMS frames verbatim. Actor hosts sample on THEIR devices: on a CPU
test box both sides are the same XLA CPU backend; a TPU learner with
CPU actors trades bit-parity for the overlap (document, don't assert).

Teardown follows the shm discipline (CLAUDE.md): the learner owns the
listener socket, the actor processes, and its ring slabs — ``close()``
plus a ``weakref.finalize`` crash fallback; actors attach, never own.
SIGTERM on an actor host exits through ``finally`` so its vec-env
workers and shm slabs are reclaimed (kill test pins zero litter).
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
import traceback
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ddls_tpu import telemetry

MAGIC = b"DF01"
# magic(4) type(1) header_len(u32) body_len(u64)
_PREFIX = struct.Struct("<4sBIQ")
PREFIX_BYTES = _PREFIX.size

T_CONFIG = 1    # learner -> actor: env/model/seed build recipe
T_HELLO = 2     # actor -> learner: pid + obs field specs
T_PARAMS = 3    # learner -> actor: params snapshot + collect rng + seq
T_SEGMENT = 4   # actor -> learner: one trajectory segment (body = fields)
T_ACK = 5       # learner -> actor: segment seq consumed (phase-1 token)
T_SHUTDOWN = 6  # learner -> actor: clean exit
T_ERROR = 7     # actor -> learner: exception text (best effort)

FRAME_NAMES = {T_CONFIG: "CONFIG", T_HELLO: "HELLO", T_PARAMS: "PARAMS",
               T_SEGMENT: "SEGMENT", T_ACK: "ACK", T_SHUTDOWN: "SHUTDOWN",
               T_ERROR: "ERROR"}

# non-obs SEGMENT fields, in wire order after the obs fields
_TRAJ_FIELDS = ("actions", "logp", "values", "rewards", "dones")


# ------------------------------------------------------------------ codec
def encode_frame(ftype: int, header: Optional[dict] = None,
                 buffers: Sequence[Any] = ()) -> List[memoryview]:
    """Encode one frame as a scatter-gather buffer list (prefix+header,
    then each payload buffer verbatim — the obs arrays are never copied
    into an intermediate pickle)."""
    hdr = pickle.dumps(header if header is not None else {},
                       protocol=pickle.HIGHEST_PROTOCOL)
    views = [memoryview(b).cast("B") for b in buffers]
    body = sum(v.nbytes for v in views)
    prefix = _PREFIX.pack(MAGIC, ftype, len(hdr), body)
    return [memoryview(prefix + hdr)] + views


def frame_nbytes(parts: Sequence[memoryview]) -> int:
    return sum(p.nbytes for p in parts)


def _sendmsg_all(sock: socket.socket, parts: Sequence[memoryview]) -> int:
    """Send every buffer in ``parts`` (sendmsg scatter-gather, looping
    across partial sends); returns total bytes written."""
    pending = [p for p in parts if p.nbytes]
    total = sum(p.nbytes for p in pending)
    while pending:
        sent = sock.sendmsg(pending)
        while sent:
            if sent >= pending[0].nbytes:
                sent -= pending[0].nbytes
                pending.pop(0)
            else:
                pending[0] = pending[0][sent:]
                sent = 0
    return total


def send_frame(sock: socket.socket, ftype: int,
               header: Optional[dict] = None,
               buffers: Sequence[Any] = ()) -> int:
    return _sendmsg_all(sock, encode_frame(ftype, header, buffers))


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got = 0
    n = view.nbytes
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("socket closed mid-frame")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


def _parse_prefix(raw: bytes) -> Tuple[int, int, int]:
    magic, ftype, hdr_len, body_len = _PREFIX.unpack(raw)
    if magic != MAGIC:
        raise ValueError(f"bad frame magic {magic!r} (want {MAGIC!r}): "
                         "stream desynchronised")
    return ftype, hdr_len, body_len


def _field_view(arr: np.ndarray) -> memoryview:
    """A flat byte view of ``arr`` — zero-copy when already contiguous
    (ring-segment prefix slices are), one copy otherwise."""
    return memoryview(np.ascontiguousarray(arr)).cast("B")


def recv_frame(sock: socket.socket,
               field_sink: Optional[Callable[[str, tuple, np.dtype],
                                             Optional[np.ndarray]]] = None
               ) -> Tuple[int, dict, Dict[str, np.ndarray]]:
    """Blocking read of one frame.

    SEGMENT bodies are streamed field-by-field per the header's field
    table: ``field_sink(name, shape, dtype)`` may return a writable
    array (e.g. a learner ring-segment view — the recv IS the
    lease-time write) or None for a fresh allocation. Returns
    ``(ftype, header, fields)``; ``fields`` is empty for control
    frames (whose payload rides the header)."""
    ftype, hdr_len, body_len = _parse_prefix(_recv_exact(sock,
                                                         PREFIX_BYTES))
    header = pickle.loads(_recv_exact(sock, hdr_len)) if hdr_len else {}
    fields: Dict[str, np.ndarray] = {}
    if body_len:
        specs = header.get("fields")
        if not specs:
            raise ValueError(
                f"{FRAME_NAMES.get(ftype, ftype)} frame carries "
                f"{body_len} body bytes but no field table")
        seen = 0
        for name, shape, dtype_str in specs:
            dtype = np.dtype(dtype_str)
            dest = field_sink(name, tuple(shape), dtype) \
                if field_sink is not None else None
            if dest is None:
                dest = np.empty(tuple(shape), dtype)
            else:
                if tuple(dest.shape) != tuple(shape) or \
                        dest.dtype != dtype:
                    raise ValueError(
                        f"field {name!r}: sink shape/dtype "
                        f"{dest.shape}/{dest.dtype} != wire "
                        f"{tuple(shape)}/{dtype}")
            _recv_exact_into(sock, _writable_byte_view(dest))
            fields[name] = dest
            seen += dest.nbytes
        if seen != body_len:
            raise ValueError(f"field table sums to {seen} bytes but "
                             f"body declared {body_len}")
    return ftype, header, fields


def _writable_byte_view(arr: np.ndarray) -> memoryview:
    if not arr.flags["C_CONTIGUOUS"]:
        raise ValueError("recv destination must be C-contiguous")
    return memoryview(arr).cast("B")


class FrameAssembler:
    """Incremental frame pump (the flight-recorder LineAssembler shape):
    feed arbitrary byte chunks, get complete ``(ftype, header, body)``
    frames out — torn prefixes/headers/bodies simply wait for more
    bytes. Control-plane convenience and the codec test surface; the
    data plane streams SEGMENT bodies with :func:`recv_frame` instead
    (fields land in their destination buffers, not a joined blob)."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Tuple[int, dict, bytes]]:
        self._buf.extend(data)
        frames = []
        while True:
            if len(self._buf) < PREFIX_BYTES:
                break
            ftype, hdr_len, body_len = _parse_prefix(
                bytes(self._buf[:PREFIX_BYTES]))
            need = PREFIX_BYTES + hdr_len + body_len
            if len(self._buf) < need:
                break
            hdr = pickle.loads(bytes(
                self._buf[PREFIX_BYTES:PREFIX_BYTES + hdr_len])) \
                if hdr_len else {}
            body = bytes(self._buf[PREFIX_BYTES + hdr_len:need])
            del self._buf[:need]
            frames.append((ftype, hdr, body))
        return frames

    @property
    def pending_bytes(self) -> int:
        return len(self._buf)


# ------------------------------------------------------------- addresses
def parse_address(addr: str):
    """``unix:<path>`` -> (AF_UNIX, path); ``tcp:<host>:<port>`` ->
    (AF_INET, (host, port))."""
    if addr.startswith("unix:"):
        return socket.AF_UNIX, addr[len("unix:"):]
    if addr.startswith("tcp:"):
        host, _, port = addr[len("tcp:"):].rpartition(":")
        return socket.AF_INET, (host, int(port))
    raise ValueError(f"address must be 'unix:<path>' or "
                     f"'tcp:<host>:<port>', got {addr!r}")


def connect_address(addr: str, timeout_s: float = 30.0) -> socket.socket:
    family, target = parse_address(addr)
    deadline = time.monotonic() + timeout_s
    last_err = None
    while time.monotonic() < deadline:
        sock = socket.socket(family, socket.SOCK_STREAM)
        try:
            sock.connect(target)
            return sock
        except OSError as exc:  # listener not up yet
            last_err = exc
            sock.close()
            time.sleep(0.05)
    raise ConnectionError(f"could not connect to {addr} within "
                          f"{timeout_s}s: {last_err}")


# ----------------------------------------------------------------- tokens
class AckToken:
    """The actor-side ring release token: ``is_ready()`` flips when the
    learner's ACK frame lands (rl/ring.py's token sweep calls
    ``is_ready`` on token leaves — a plain host object is a valid
    leaf). The ack IS the remote segment's phase-1 token: the socket
    send + remote recv is always a copy, so acked == safely copied
    out of the slab, exactly the "staged tree does not alias" verdict
    of the in-process protocol."""

    __slots__ = ("_event",)

    def __init__(self):
        self._event = threading.Event()

    def set(self) -> None:
        self._event.set()

    def is_ready(self) -> bool:
        return self._event.is_set()


class _FragmentSampler:
    """The minimal learner surface ``RolloutCollector`` consumes on the
    deferred-fetch path: the algo-shared ``_sample_actions`` (PPO/
    IMPALA/PG are verbatim-identical — rl/ppo.py is the canon) plus a
    replicated obs sharding over the actor host's LOCAL mesh.
    Replicated sampling has no collectives, so its bits do not depend
    on the mesh width — the root of the cross-process parity pin."""

    def __init__(self, apply_fn):
        import jax

        from ddls_tpu.parallel.mesh import make_mesh, replicated_sharding

        self.apply_fn = apply_fn
        self.mesh = make_mesh()
        self._replicated = (replicated_sharding(self.mesh)
                            if jax.process_count() == 1 else None)

    def _sample_actions(self, params, obs, rng):
        import jax
        import jax.numpy as jnp

        logits, values = self.apply_fn(params, obs)
        actions = jax.random.categorical(rng, logits, axis=-1)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), actions[:, None],
            axis=-1)[:, 0]
        return actions, logp, values


# ------------------------------------------------------------ actor host
class ActorHostDriver:
    """Serve one learner connection: build the vec env + deferred-fetch
    collector from the CONFIG frame, then collect a segment per PARAMS
    frame and ship it as one SEGMENT frame (scatter-gather from the
    ring-segment views). Runs in ``scripts/actor_host.py``."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.vec_env = None
        self.collector = None
        self.host_index: Optional[int] = None
        self._obs_keys: Tuple[str, ...] = ()
        self._pending: Dict[int, AckToken] = {}
        self.bytes_sent = 0
        self.segments_sent = 0

    # -- build -----------------------------------------------------------
    def _build(self, cfg: dict) -> None:
        import jax

        from ddls_tpu.models.policy import batched_policy_apply
        from ddls_tpu.rl.rollout import (OBS_KEYS, ParallelVectorEnv,
                                         RolloutCollector, VectorEnv)
        from ddls_tpu.train.loops import build_policy_from_model_config
        from ddls_tpu.utils.common import get_class_from_path, \
            seed_everything

        self.host_index = int(cfg["host_index"])
        self._obs_keys = tuple(cfg.get("obs_keys") or OBS_KEYS)
        B = int(cfg["num_envs"])
        T = int(cfg["rollout_length"])
        env_cls = get_class_from_path(cfg["env_cls"])
        env_config = cfg["env_config"]
        # host 0's env seed stream is EXACTLY the learner's in-process
        # stream (_collect_seed + i) — the bit-parity pin; later hosts
        # extend it contiguously
        seeds = [int(cfg["env_seed_base"]) + i for i in range(B)]
        seed_everything(int(cfg["global_seed"]))
        if cfg.get("use_parallel_envs", True):
            self.vec_env = ParallelVectorEnv(
                env_cls, env_config, B, seeds=seeds,
                backend=cfg.get("vec_env_backend", "auto"))
        else:
            self.vec_env = VectorEnv(
                [(lambda: env_cls(**env_config)) for _ in range(B)],
                seeds=seeds)
        self.vec_env.reset()
        model = build_policy_from_model_config(int(cfg["n_actions"]),
                                               cfg.get("model_config"))
        sampler = _FragmentSampler(
            lambda p, o: batched_policy_apply(model, p, o))
        self._sampler = sampler
        self.collector = RolloutCollector(
            self.vec_env, sampler, T, deferred_fetch=True,
            # 2 segments suffice at ANY learner depth: the learner acks
            # seq k inside collect k, before PARAMS k+1 ever hits the
            # wire, so at most one actor segment is un-acked at a time
            ring_segments=int(cfg.get("actor_ring_segments", 2)))
        self.collector._needs_reset = False
        self._jax = jax

    def _hello(self) -> dict:
        from ddls_tpu.rl.shm import obs_field_specs

        specs = obs_field_specs(self.vec_env.obs[0], self._obs_keys)
        return {"pid": os.getpid(),
                "host_index": self.host_index,
                "num_envs": self.vec_env.num_envs,
                "obs_specs": {k: (tuple(shape), np.dtype(dt).str)
                              for k, (shape, dt) in specs.items()}}

    # -- serve loop ------------------------------------------------------
    def serve(self) -> None:
        try:
            ftype, cfg, _ = recv_frame(self.sock)
            if ftype != T_CONFIG:
                raise ValueError(f"expected CONFIG, got "
                                 f"{FRAME_NAMES.get(ftype, ftype)}")
            self._build(cfg)
            send_frame(self.sock, T_HELLO, self._hello())
            while True:
                ftype, header, _ = recv_frame(self.sock)
                if ftype == T_ACK:
                    token = self._pending.pop(int(header["seq"]), None)
                    if token is not None:
                        token.set()
                elif ftype == T_PARAMS:
                    self._collect_and_send(header)
                elif ftype == T_SHUTDOWN:
                    break
                else:
                    raise ValueError(
                        f"unexpected frame "
                        f"{FRAME_NAMES.get(ftype, ftype)} on actor host "
                        f"{self.host_index}")
        except (ConnectionError, BrokenPipeError, EOFError):
            # learner went away: exit quietly through finally-cleanup —
            # the learner side raises the loud error
            pass
        except BaseException as exc:
            if not isinstance(exc, SystemExit):
                try:
                    send_frame(self.sock, T_ERROR,
                               {"message": repr(exc),
                                "traceback": traceback.format_exc()})
                except OSError:
                    pass
            raise

    def _collect_and_send(self, header: dict) -> None:
        jax = self._jax
        seq = int(header["seq"])
        params = header["params"]
        if self._sampler._replicated is not None:
            params = jax.device_put(params, self._sampler._replicated)
        rng = jax.numpy.asarray(header["rng"])
        t0 = time.perf_counter()
        out = self.collector.collect(params, rng)
        wall = time.perf_counter() - t0
        traj = out["traj"]
        names, table, buffers = [], [], []
        for k in self._obs_keys:
            arr = traj["obs"][k]
            table.append((f"obs:{k}", tuple(arr.shape), arr.dtype.str))
            buffers.append(_field_view(arr))
        for name in _TRAJ_FIELDS:
            arr = np.asarray(traj[name])
            table.append((name, tuple(arr.shape), arr.dtype.str))
            buffers.append(_field_view(arr))
        lv = np.asarray(out["last_values"])
        table.append(("last_values", tuple(lv.shape), lv.dtype.str))
        buffers.append(_field_view(lv))
        seg_header = {"seq": seq, "fields": table,
                      "episodes": out["episodes"],
                      "env_steps": int(out["env_steps"]),
                      "collect_wall_s": wall,
                      "host_index": self.host_index}
        n = send_frame(self.sock, T_SEGMENT, seg_header, buffers)
        self.bytes_sent += n
        self.segments_sent += 1
        ring = out.get("ring")
        if ring is not None:
            # the ack is the phase-1 token (see module docstring); armed
            # AFTER the send completes so the slab views were fully read
            token = AckToken()
            ring.set_release_token(out["ring_segment"], token,
                                   generation=out["ring_generation"])
            self._pending[seq] = token

    def close(self) -> None:
        if self.collector is not None and hasattr(self.collector, "close"):
            try:
                self.collector.close()
            except Exception:
                pass
        if self.vec_env is not None:
            try:
                self.vec_env.close()
            except Exception:
                pass
            self.vec_env = None
        try:
            self.sock.close()
        except OSError:
            pass


# -------------------------------------------------- learner-side consumer
def _actor_host_script() -> str:
    import ddls_tpu

    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(ddls_tpu.__file__))), "scripts", "actor_host.py")


def _teardown(conns: list, procs: list, paths: list) -> None:
    """Crash-fallback teardown (weakref.finalize target — must not hold
    the LearnerFragment): close fds, escalate SIGTERM->SIGKILL, unlink
    the socket path. Mirrors rl/shm.py's parent-owned discipline."""
    for conn in conns:
        try:
            conn.close()
        except OSError:
            pass
    for proc in procs:
        if proc.poll() is None:
            proc.terminate()
    deadline = time.monotonic() + 5.0
    for proc in procs:
        if proc.poll() is None:
            try:
                proc.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
    for path in paths:
        try:
            os.unlink(path)
        except OSError:
            pass
    for path in paths:
        d = os.path.dirname(path)
        try:
            if d and d.startswith(tempfile.gettempdir()):
                os.rmdir(d)
        except OSError:
            pass


class _HostHandle:
    __slots__ = ("conn", "proc", "host_index", "pid", "segments", "acks",
                 "transit_sum", "transit_max", "bytes_recv")

    def __init__(self, conn, proc, host_index):
        self.conn = conn
        self.proc = proc
        self.host_index = host_index
        self.pid = None
        self.segments = 0
        self.acks = 0
        self.transit_sum = 0.0
        self.transit_max = 0.0
        self.bytes_recv = 0

    def describe(self) -> str:
        state = "alive"
        if self.proc is not None and self.proc.poll() is not None:
            state = f"exited rc={self.proc.returncode}"
        return f"actor host {self.host_index} (pid {self.pid}, {state})"


class LearnerFragment:
    """The learner-side collector duck-type over N actor-host
    connections (train/loops.py ``collect_transport='socket'``).

    ``collect(params, rng)`` round-robins the hosts: device_get the
    params snapshot (explicit — transfer-guard-legal) and ship it with
    the rng key as one PARAMS frame, lease a segment of the learner's
    OWN TrajRing, stream the SEGMENT frame's obs fields straight into
    that segment's views (the recv write IS the lease-time write), ACK,
    publish, and return the same out-dict shape as
    ``RolloutCollector._collect_deferred`` — so the loop's canonical
    note_staged/note_update two-phase release runs unchanged, plus
    ``segment_transit_s`` (wire+serialisation lag net of the actor's
    own collect wall time — clock-skew-free because both spans are
    single-clock durations) as ``params_age_updates``'s sibling."""

    def __init__(self, *, env_cls_path: str, env_config: dict,
                 model_config, n_actions: int, num_envs: int,
                 rollout_length: int, collect_seed: int, global_seed: int,
                 ring_segments: int, num_actor_hosts: int = 1,
                 transport: str = "unix", tcp_host: str = "127.0.0.1",
                 tcp_port: int = 0, use_parallel_envs: bool = True,
                 vec_env_backend: str = "auto",
                 actor_ring_segments: int = 2,
                 connect_timeout_s: float = 120.0,
                 recv_timeout_s: float = 300.0,
                 spawn: bool = True, actor_env: Optional[dict] = None,
                 allow_device: bool = False):
        from ddls_tpu.rl.ring import TrajRing
        from ddls_tpu.rl.rollout import OBS_KEYS

        if num_actor_hosts < 1:
            raise ValueError("num_actor_hosts must be >= 1")
        self.num_envs = int(num_envs)
        self.rollout_length = int(rollout_length)
        self._obs_keys = OBS_KEYS
        self._recv_timeout_s = float(recv_timeout_s)
        self._seq = 0
        self._rr = 0
        self._closed = False
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.segments_recv = 0
        self.env_steps_recv = 0
        self._needs_reset = False  # loops-compat; envs live on the actors

        self._sock_dir = None
        self._sock_path = None
        if transport == "unix":
            self._sock_dir = tempfile.mkdtemp(prefix="ddls_frag_")
            self._sock_path = os.path.join(self._sock_dir, "learner.sock")
            self._listener = socket.socket(socket.AF_UNIX,
                                           socket.SOCK_STREAM)
            self._listener.bind(self._sock_path)
            self.address = f"unix:{self._sock_path}"
        elif transport == "tcp":
            self._listener = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind((tcp_host, int(tcp_port)))
            host, port = self._listener.getsockname()[:2]
            self.address = f"tcp:{host}:{port}"
        else:
            raise ValueError(f"transport must be 'unix' or 'tcp', got "
                             f"{transport!r}")
        self._listener.listen(num_actor_hosts)
        self._listener.settimeout(connect_timeout_s)

        self._procs: List[subprocess.Popen] = []
        if spawn:
            script = _actor_host_script()
            child_env = dict(os.environ)
            if not allow_device:
                # CPU-subprocess gotcha (CLAUDE.md): the axon
                # sitecustomize imports jax at interpreter start, so the
                # pool var must go before the child ever runs
                child_env.pop("PALLAS_AXON_POOL_IPS", None)
            child_env.update(actor_env or {})
            argv = [sys.executable, script, "--connect", self.address]
            if allow_device:
                argv.append("--allow-device")
            for _ in range(num_actor_hosts):
                self._procs.append(subprocess.Popen(argv, env=child_env))

        self._handles: List[_HostHandle] = []
        # parent-owned lifecycle with a crash fallback, the shm
        # discipline: lists (not self) ride the finalizer
        self._final_conns: list = [self._listener]
        self._final_paths: list = ([self._sock_path]
                                   if self._sock_path else [])
        self._finalizer = weakref.finalize(
            self, _teardown, self._final_conns, self._procs,
            self._final_paths)

        config = {"env_cls": env_cls_path, "env_config": env_config,
                  "model_config": model_config, "n_actions": int(n_actions),
                  "num_envs": self.num_envs,
                  "rollout_length": self.rollout_length,
                  "global_seed": int(global_seed),
                  "use_parallel_envs": bool(use_parallel_envs),
                  "vec_env_backend": vec_env_backend,
                  "actor_ring_segments": int(actor_ring_segments),
                  "obs_keys": list(OBS_KEYS)}
        obs_specs = None
        try:
            for i in range(num_actor_hosts):
                try:
                    conn, _ = self._listener.accept()
                except socket.timeout:
                    raise RuntimeError(
                        f"actor host {i} never connected to "
                        f"{self.address} within {connect_timeout_s}s "
                        f"({self._describe_procs()})") from None
                conn.settimeout(self._recv_timeout_s)
                handle = _HostHandle(
                    conn, self._procs[i] if i < len(self._procs) else None,
                    host_index=i)
                self._final_conns.append(conn)
                cfg = dict(config)
                cfg["host_index"] = i
                # host 0 == the in-process seed stream (bit parity);
                # host j extends it by whole-host strides
                cfg["env_seed_base"] = int(collect_seed) + i * self.num_envs
                send_frame(conn, T_CONFIG, cfg)
                ftype, hello, _ = recv_frame(conn)
                if ftype == T_ERROR:
                    raise RuntimeError(
                        f"actor host {i} failed during build:\n"
                        f"{hello.get('traceback', hello.get('message'))}")
                if ftype != T_HELLO:
                    raise RuntimeError(
                        f"actor host {i}: expected HELLO, got "
                        f"{FRAME_NAMES.get(ftype, ftype)}")
                handle.pid = hello.get("pid")
                specs = {k: (tuple(s), np.dtype(d))
                         for k, (s, d) in hello["obs_specs"].items()}
                if obs_specs is None:
                    obs_specs = specs
                elif specs != obs_specs:
                    raise RuntimeError(
                        f"actor host {i} obs specs disagree with host 0: "
                        f"{specs} != {obs_specs}")
                self._handles.append(handle)

            # the learner's OWN ring: recv targets, parent-owned shm
            # slabs, canonical two-phase release — byte-for-byte the
            # in-process ledger
            missing = [k for k in OBS_KEYS if k not in obs_specs]
            if missing:
                raise RuntimeError(f"actor obs specs missing {missing}")
            self.ring = TrajRing({k: obs_specs[k] for k in OBS_KEYS},
                                 self.rollout_length + 1, self.num_envs,
                                 int(ring_segments))
        except BaseException:
            self.close()
            raise

    # -- helpers ---------------------------------------------------------
    def _describe_procs(self) -> str:
        if not self._procs:
            return "no spawned processes"
        return ", ".join(
            f"pid {p.pid}: "
            f"{'alive' if p.poll() is None else f'exited rc={p.returncode}'}"
            for p in self._procs)

    def _dead(self, handle: _HostHandle, why: str) -> RuntimeError:
        return RuntimeError(
            f"{handle.describe()} died mid-collect on {self.address}: "
            f"{why} — its trajectory segment is lost; restart the run "
            f"(fragments have no mid-epoch failover)")

    # -- the collector contract -----------------------------------------
    def collect(self, params, rng) -> Dict[str, Any]:
        import jax

        if self._closed:
            raise RuntimeError("LearnerFragment is closed")
        handle = self._handles[self._rr]
        self._rr = (self._rr + 1) % len(self._handles)
        self._seq += 1
        seq = self._seq
        T = self.rollout_length

        # explicit host fetch of the snapshot — the ONLY way params
        # leave the device here, so the steady-state transfer-guard pin
        # (tests/test_fragments.py) stays valid
        host_params = jax.device_get(params)
        rng_np = np.asarray(jax.device_get(rng))
        try:
            with telemetry.transfer("fragments.params", "h2h") as tr:
                n = send_frame(handle.conn, T_PARAMS,
                               {"seq": seq, "params": host_params,
                                "rng": rng_np})
                tr.add(host_params)
            self.bytes_sent += n
            t0 = time.perf_counter()
            seg = self.ring.lease()
            fields = self._recv_segment(handle, seg, seq)
            transit = max(
                time.perf_counter() - t0
                - float(fields["header"]["collect_wall_s"]), 0.0)
            n = send_frame(handle.conn, T_ACK, {"seq": seq})
            self.bytes_sent += n
        except (ConnectionError, BrokenPipeError, EOFError,
                socket.timeout) as exc:
            raise self._dead(handle, repr(exc)) from exc
        handle.acks += 1
        handle.transit_sum += transit
        handle.transit_max = max(handle.transit_max, transit)
        self.ring.publish(seg)
        header = fields["header"]
        if telemetry.enabled():
            hi = handle.host_index
            telemetry.inc(f"fragments.h{hi}.segments")
            telemetry.inc(f"fragments.h{hi}.acks")
            telemetry.observe(f"fragments.h{hi}.transit_s", transit)
        self.segments_recv += 1
        self.env_steps_recv += int(header["env_steps"])
        out = {
            "traj": {"obs": {k: seg.views[k][:T] for k in self._obs_keys},
                     "actions": fields["actions"],
                     "logp": fields["logp"],
                     "values": fields["values"],
                     "rewards": fields["rewards"],
                     "dones": fields["dones"]},
            "last_values": fields["last_values"],
            "episodes": header["episodes"],
            "env_steps": int(header["env_steps"]),
            "ring": self.ring,
            "ring_segment": seg,
            "ring_generation": seg.generation,
            "segment_transit_s": transit,
            "actor_host": handle.host_index,
        }
        return out

    def _recv_segment(self, handle: _HostHandle, seg, seq: int) -> dict:
        T = self.rollout_length

        def sink(name: str, shape: tuple, dtype: np.dtype):
            if name.startswith("obs:"):
                # the recv write IS the lease-time write: straight into
                # the leased segment's slab rows, no staging copy
                key = name[len("obs:"):]
                dest = seg.views[key][:T]
                return dest
            return None  # fresh per-collect allocation (host arrays)

        with telemetry.transfer("fragments.segment", "h2h") as tr:
            ftype, header, fields = recv_frame(handle.conn,
                                               field_sink=sink)
            if ftype == T_ERROR:
                raise self._dead(
                    handle, f"remote error:\n"
                    f"{header.get('traceback', header.get('message'))}")
            if ftype != T_SEGMENT:
                raise self._dead(handle,
                                 f"expected SEGMENT, got "
                                 f"{FRAME_NAMES.get(ftype, ftype)}")
            if int(header["seq"]) != seq:
                raise self._dead(handle,
                                 f"segment seq {header['seq']} != "
                                 f"expected {seq}")
            tr.add(fields)
        nbytes = sum(v.nbytes for v in fields.values())
        self.bytes_recv += nbytes
        handle.segments += 1
        handle.bytes_recv += nbytes
        fields["header"] = header
        return fields

    # -- reporting -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        per_host = {}
        for h in self._handles:
            per_host[f"h{h.host_index}"] = {
                "pid": h.pid,
                "segments": h.segments,
                "acks": h.acks,
                "bytes_recv": h.bytes_recv,
                "transit_mean_s": (h.transit_sum / h.segments
                                   if h.segments else None),
                "transit_max_s": h.transit_max,
            }
        return {
            "num_actor_hosts": len(self._handles),
            "segments": self.segments_recv,
            "bytes_sent": self.bytes_sent,
            "bytes_recv": self.bytes_recv,
            "collect_bytes_per_step": (
                (self.bytes_sent + self.bytes_recv) / self.env_steps_recv
                if self.env_steps_recv else None),
            "per_host": per_host,
        }

    # -- lifecycle -------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for handle in self._handles:
            try:
                send_frame(handle.conn, T_SHUTDOWN, {})
            except OSError:
                pass
        # grace period for the actors' own finally-cleanup (env workers,
        # shm slabs) before the finalizer's SIGTERM->SIGKILL escalation
        deadline = time.monotonic() + 10.0
        for proc in self._procs:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=max(deadline - time.monotonic(),
                                          0.1))
                except subprocess.TimeoutExpired:
                    pass
        ring = getattr(self, "ring", None)
        if ring is not None:
            ring.close()
        # finalizer does fd close + SIGTERM->SIGKILL escalation + unlink
        self._finalizer()

    def __del__(self):  # pragma: no cover - belt and braces
        try:
            self.close()
        except Exception:
            pass
