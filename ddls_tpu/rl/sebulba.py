"""Sebulba actor/learner device split over the trajectory ring.

The second Podracer architecture (arXiv 2104.06272; Anakin — the fused
single-program loop — landed in rl/fused.py): actor lanes are PINNED to
a sub-mesh of the local devices and the learner update to the
complement, so collection and update run on DISJOINT silicon and can
overlap instead of time-slicing one program. The actor half is the
fused driver's in-kernel collection (``make_segment_fn(trace_obs=True)``
+ the jitted bootstrap forward, one dispatch per segment, nothing
leaves the device); the learner half is the UNCHANGED standalone
``train_step`` jitted over the learner sub-mesh.

The actor→learner queue is a DEVICE-MODE trajectory ring
(``rl/ring.py``, slab-less segments): each collect leases a segment,
publishes it, and the existing two-phase token protocol releases it —
phase 1's token is the trajectory ``device_put`` onto the learner
sub-mesh (ready exactly when the device-to-device transfer completes;
with no host views the alias verdict is trivially "copied"), phase 2's
unconditional update-output token covers donating backends deleting
the staged buffers at dispatch. Lease backpressure bounds the in-flight
batches to the ring size, and depth-K staleness accounting
(``params_age_updates``, IMPALA's ``clip_rho_fraction`` gauge) rides
along unchanged from the round-10 ring.

Steady-state epochs are TRANSFER-FREE under
``jax.transfer_guard("disallow")``: every cross-mesh hop — params
learner→actor, per-lane rngs, trajectory actor→learner — is an
EXPLICIT ``device_put`` (the defining traffic of the split), episode
counters stay device-resident until the fused-style drain boundaries,
and the trace-obs trajectory never visits the host (the
``DevicePPOCollector`` host hop is exactly what this driver removes).

Bit-exactness vs the sequential device-collector path holds at MATCHED
partitioning (same actor mesh for collection, same learner mesh for the
update — the bootstrap forward's partitioned segment-sum accumulation
order depends on the dp width, rl/ppo_device.py): the parity driver in
tests/test_sebulba.py pins depth-0 PPO params bitwise.

Single-process only (the split partitions LOCAL devices); DQN/ES reject
loudly in train/loops.py — the same device-collection contract as the
fused loop.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ddls_tpu import telemetry
from ddls_tpu.rl.fused import EPISODE_TRACE_KEYS
from ddls_tpu.rl.ring import TrajRing


def split_meshes(actor_devices: Optional[int] = None, devices=None):
    """Partition the local devices into the actor sub-mesh and the
    learner complement: actor = first ``actor_devices`` devices
    (default: half), learner = the rest. Raises ``ValueError`` when the
    split is infeasible (< 2 devices, or an explicit count leaving
    either side empty) — callers decide whether that is a loud fallback
    (auto sizing) or a config error (explicit sizing)."""
    import jax

    from ddls_tpu.parallel.mesh import make_mesh

    devs = list(devices) if devices is not None else jax.local_devices()
    if len(devs) < 2:
        raise ValueError(
            f"sebulba needs >= 2 local devices to split (got "
            f"{len(devs)}): actor lanes and the learner update must "
            "live on disjoint sub-meshes")
    a = len(devs) // 2 if actor_devices is None else int(actor_devices)
    if not 1 <= a <= len(devs) - 1:
        raise ValueError(
            f"sebulba actor_devices={a} must leave both sub-meshes "
            f"non-empty over {len(devs)} local devices")
    return (make_mesh(devices=devs[:a]), make_mesh(devices=devs[a:]))


class SebulbaCollector:
    """Actor-side collector of the Sebulba split: ``collect(params,
    rng)`` runs one [T, B] segment batch entirely on the ACTOR sub-mesh
    and returns DEVICE trajectories for the learner to ``shard_traj``
    onto its own sub-mesh (the explicit device-to-device staging hop).

    Duck-types ``DevicePPOCollector``'s out dict, plus the ring keys
    the epoch loop's two-phase token protocol consumes
    (``ring``/``ring_segment``/``ring_generation`` — rl/rollout.py's
    shm contract) and ``ep_pending`` (the [B, T] device episode-counter
    trace, drained fused-style at sync boundaries instead of per
    collect — ``out["episodes"]`` is always empty here).

    ``memo_cfg`` follows the device-collector contract: ``"auto"``
    enables the in-kernel lookahead memo at every lane count (the
    round-12 batched probe — sim/jax_memo.py).

    ``param_layout`` names the LEARNER's partition-rule layout
    (``parallel/partition.py``); the learner→actor hop always lands
    replicated on the actor sub-mesh, so a sharded layout makes that
    ``device_put`` a gather-to-actor-layout — the transfer-ledger name
    carries the resolved layout (``sebulba.params[gather-from-fsdp]``)
    so cross-mesh byte counts stay attributable per layout."""

    def __init__(self, et, ot, model, banks: Dict, rollout_length: int,
                 actor_mesh, ring_segments: int = 2, memo_cfg="auto",
                 param_layout: str = "replicated"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddls_tpu.models.policy import batched_policy_apply
        from ddls_tpu.rl.ppo import traj_donate_argnums
        from ddls_tpu.sim.jax_env import (_kernel_obs, make_segment_fn,
                                          segment_init, vmap_segment_fn)
        from ddls_tpu.sim.jax_memo import resolve_memo_cfg

        self.et, self.ot, self.model = et, ot, model
        self.rollout_length = int(rollout_length)
        self.num_envs = int(jax.tree_util.tree_leaves(banks)[0].shape[0])
        self.mesh = actor_mesh
        self.param_layout = str(param_layout)
        # layout-attributed transfer name (telemetry_report groups the
        # ledger by name, so the gather shows up as its own row)
        self._params_hop_name = (
            "sebulba.params" if self.param_layout == "replicated"
            else f"sebulba.params[gather-from-{self.param_layout}]")
        self.memo_cfg = resolve_memo_cfg(memo_cfg, self.num_envs)
        B, T = self.num_envs, self.rollout_length
        if B % actor_mesh.shape["dp"] != 0:
            raise ValueError(
                f"num_envs {B} must divide over the actor sub-mesh dp "
                f"axis ({actor_mesh.shape['dp']})")
        self._lane = NamedSharding(actor_mesh, P("dp"))
        self._repl = NamedSharding(actor_mesh, P())
        batch_time = NamedSharding(actor_mesh, P(None, "dp"))
        batch_only = self._lane
        self.banks = jax.device_put(banks, self._lane)
        self._state = jax.vmap(
            lambda b: segment_init(et, b, self.memo_cfg))(self.banks)
        self._ep_len = np.zeros(B, np.int64)

        segment = make_segment_fn(et, ot, model, T, trace_obs=True,
                                  memo_cfg=self.memo_cfg)
        lane_segment = vmap_segment_fn(segment, B)

        def actor_round(bb, params, sim_state, lane_rngs):
            """One segment + its bootstrap forward, ONE dispatch on the
            actor sub-mesh. Mirrors rl/fused.py's one_round collection
            half exactly (trace_obs trajectory, same f64-then-f32
            casts, same jitted dp-sharded bootstrap) — the two
            ingredients of the x64 bit-parity with the sequential
            device-collector path (rl/ppo_device.py)."""
            sim_state, trace, next_fields = lane_segment(
                bb, params, sim_state, lane_rngs)

            def tb(x):
                return jnp.swapaxes(x, 0, 1)

            traj = {
                "obs": {k: tb(v) for k, v in trace["obs"].items()},
                "actions": tb(trace["action"]).astype(jnp.int32),
                "logp": tb(trace["logp"]).astype(jnp.float32),
                "values": tb(trace["value"]).astype(jnp.float32),
                "rewards": tb(trace["reward"]).astype(jnp.float32),
                "dones": tb(trace["done"]),
            }
            traj = jax.lax.with_sharding_constraint(
                traj, jax.tree_util.tree_map(lambda _: batch_time, traj))
            next_obs = jax.vmap(lambda j, f, s, o, r: _kernel_obs(
                ot, et, j, f, s, o, r))(
                next_fields["jtype"], next_fields["frac"],
                next_fields["steps"], next_fields["n_occupied"],
                next_fields["n_running"])
            _, last_values = batched_policy_apply(model, params, next_obs)
            last_values = jax.lax.with_sharding_constraint(
                last_values.astype(jnp.float32), batch_only)
            ep = {k: trace[k] for k in EPISODE_TRACE_KEYS}
            return sim_state, traj, last_values, ep

        self._actor = jax.jit(
            actor_round,
            in_shardings=(self._lane, self._repl, self._lane, self._lane),
            donate_argnums=traj_donate_argnums(2))
        # the actor→learner queue: slab-less ledger segments, one per
        # in-flight device batch (lease backpressure + the two-phase
        # release-token protocol — rl/ring.py device mode)
        self.ring = TrajRing(None, rows=T + 1, num_envs=B,
                             segments=ring_segments)

    def collect(self, params, rng) -> Dict:
        """One [T, B] segment batch on the actor sub-mesh. ``params``
        arrive committed to the LEARNER sub-mesh; the replicating
        ``device_put`` here is the explicit learner→actor hop (a real
        copy — the device sets are disjoint — so learner-side donation
        can never delete the actor's params)."""
        import jax

        seg = self.ring.lease()
        # transfer-ledger wraps (gated; NULL_SPAN + no-op add when
        # telemetry is off) around the EXISTING explicit hops — byte
        # attribution is .nbytes metadata only, transfer-guard safe
        with telemetry.transfer(self._params_hop_name, "l2a") as tr:
            params = jax.device_put(params, self._repl)
            tr.add(params)
        with telemetry.transfer("sebulba.rngs", "h2d") as tr:
            lane_rngs = jax.device_put(
                jax.random.split(rng, self.num_envs), self._lane)
            tr.add(lane_rngs)
        self._state, traj, last_values, ep = self._actor(
            self.banks, params, self._state, lane_rngs)
        self.ring.publish(seg)
        return {"traj": traj,
                "last_values": last_values,
                "env_steps": self.rollout_length * self.num_envs,
                "episodes": [],
                "ep_pending": ep,
                "ring": self.ring,
                "ring_segment": seg,
                "ring_generation": seg.generation}

    def memo_counters(self) -> Optional[Dict]:
        """Cumulative in-kernel memo counters {hits, misses, evicts,
        hit_rate}, summed over lanes (drain/reporting boundaries only —
        sim/jax_memo.py:summarize_counters); None when the memo is
        off."""
        from ddls_tpu.sim.jax_memo import summarize_counters

        if self.memo_cfg is None:
            return None
        return summarize_counters(self._state[1])

    def harvest_episodes(self, ep_trace) -> list:
        """Episode records from a FETCHED [B, T] episode-counter trace
        (the drain boundary hands host numpy arrays) — the same
        records, in the same (t, b) order and with the same host
        denominators, as ``DevicePPOCollector._harvest_episodes`` emits
        for the matching collect."""
        episodes = []
        done = np.asarray(ep_trace["done"])  # [B, T]
        B, T = done.shape
        for t in range(T):
            self._ep_len += 1
            for b in np.nonzero(done[:, t])[0]:
                blk = int(ep_trace["ep_blocked"][b, t])
                com = int(ep_trace["ep_completed"][b, t])
                arr = int(ep_trace["ep_arrived"][b, t])
                episodes.append({
                    "env_index": int(b),
                    "episode_return": float(ep_trace["ep_return"][b, t]),
                    "episode_length": int(self._ep_len[b]),
                    "num_jobs_arrived": arr,
                    "num_jobs_completed": com,
                    "num_jobs_blocked": blk,
                    "acceptance_rate": com / arr if arr else 0.0,
                    "blocking_rate": blk / arr if arr else 0.0,
                })
                self._ep_len[b] = 0
        return episodes

    def close(self) -> None:
        self.ring.close()
