"""Pure-JAX Ape-X DQN learner, sharded over a device mesh.

TPU-native replacement for the reference's RLlib ``ApexTrainer`` path
(scripts/ramp_job_partitioning_configs/algo/apex_dqn.yaml — a tuned headline
baseline per BASELINE.md). The Ray actor topology (32 sampling workers, 4
replay-buffer shards, one learner) becomes:

* B vectorised env workers with Ape-X-style per-worker epsilon-greedy
  exploration (``per_worker_epsilons``);
* one host-side prioritised replay buffer holding n-step transitions
  (workers in Ape-X compute n-step returns + initial priorities before
  pushing to replay — here the collector does, ``nstep_transitions``);
* a jitted double/dueling DQN update whose sample batch is sharded over the
  mesh's ``dp`` axis with replicated parameters, so XLA emits the gradient
  all-reduce over ICI (same scheme as ``ddls_tpu.rl.ppo``).

Tuned defaults follow the reference's apex_dqn.yaml: gamma 0.999,
lr 4.121e-7, n_step 3, batch 512, target sync every 100k sampled
transitions, prioritised replay alpha 0.9 / beta 0.1, epsilon 1 -> 0.05 over
1M steps.

Unlike the reference — which disables action masking for DQN because of an
RLlib shape bug (apex_dqn.yaml "TEMP HACK" note) — invalid actions are
masked here at *selection* time (greedy argmax and random exploration both
restricted to valid actions); the Q-network itself stays unmasked so the
dueling mean is finite.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from ddls_tpu.parallel.mesh import (place_state_tree,
                                    replicated_sharding, shard_batch)


@dataclasses.dataclass
class DQNConfig:
    lr: float = 4.121e-7
    gamma: float = 0.999
    n_step: int = 3
    train_batch_size: int = 512
    target_network_update_freq: int = 100_000  # in sampled transitions
    double_q: bool = True
    dueling: bool = True
    num_atoms: int = 1  # only 1 (non-distributional) is supported
    grad_clip: Optional[float] = 40.0
    # prioritised replay (reference replay_buffer_config)
    buffer_capacity: int = 100_000
    prioritized_replay_alpha: float = 0.9
    prioritized_replay_beta: float = 0.1
    prioritized_replay_eps: float = 1e-6
    learning_starts: int = 10_000
    # ratio of trained transitions to sampled transitions
    training_intensity: float = 1.0
    # per-worker epsilon-greedy exploration
    initial_epsilon: float = 1.0
    final_epsilon: float = 0.05
    epsilon_timesteps: int = 1_000_000

    def __post_init__(self):
        if self.num_atoms != 1:
            raise NotImplementedError(
                "distributional DQN (num_atoms > 1) is not supported; the "
                "reference's tuned config uses num_atoms 1")


class DQNTrainState(struct.PyTreeNode):
    params: Any
    target_params: Any
    opt_state: Any
    step: jnp.ndarray  # learner updates applied

    @classmethod
    def create(cls, params, tx):
        return cls(params=params,
                   target_params=jax.tree_util.tree_map(jnp.copy, params),
                   opt_state=tx.init(params),
                   step=jnp.zeros((), jnp.int32))


def per_worker_epsilons(num_envs: int, env_steps: int,
                        cfg: DQNConfig) -> np.ndarray:
    """Ape-X exploration: worker i follows the global epsilon schedule
    raised to ``1 + 7 i / (B-1)`` (Horgan et al. 2018 eq. 1 shape; the
    reference uses RLlib's PerWorkerEpsilonGreedy with initial 1 ->
    final 0.05 over 1M timesteps)."""
    frac = min(env_steps / max(cfg.epsilon_timesteps, 1), 1.0)
    base = cfg.initial_epsilon + frac * (cfg.final_epsilon
                                         - cfg.initial_epsilon)
    if num_envs == 1:
        return np.asarray([base], np.float32)
    exps = 1.0 + 7.0 * np.arange(num_envs) / (num_envs - 1)
    return (base ** exps).astype(np.float32)


def dueling_q_values(apply_out: Tuple[jnp.ndarray, jnp.ndarray],
                     dueling: bool) -> jnp.ndarray:
    """Q [N, A] from the policy net's (logits, value) heads: with dueling,
    logits act as advantages combined with the value stream
    (Q = V + A - mean A); otherwise logits are Q directly."""
    logits, values = apply_out
    if not dueling:
        return logits
    return values[:, None] + logits - logits.mean(axis=-1, keepdims=True)


def huber(x: jnp.ndarray, delta: float = 1.0) -> jnp.ndarray:
    absx = jnp.abs(x)
    return jnp.where(absx <= delta, 0.5 * x * x,
                     delta * (absx - 0.5 * delta))


# ------------------------------------------------------------------ replay
class PrioritizedReplayBuffer:
    """Host-side proportional prioritised replay over n-step transitions.

    Storage is a ring of preallocated numpy arrays (allocated from the first
    transition's tree structure). Sampling is proportional to
    ``priority**alpha`` with importance weights ``(N * p)**-beta``
    normalised by their max (Schaul et al. 2016), matching the reference's
    MultiAgentPrioritizedReplayBuffer configuration.
    """

    def __init__(self, capacity: int, alpha: float, beta: float,
                 eps: float, seed: int = 0):
        self.capacity = int(capacity)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self.rng = np.random.RandomState(seed)
        self.priorities = np.zeros(self.capacity, np.float64)
        self.storage: Optional[Dict[str, Any]] = None
        self.size = 0
        self.next_idx = 0
        self.max_priority = 1.0

    def _allocate(self, transition: Dict[str, Any]) -> None:
        def alloc(x):
            x = np.asarray(x)
            return np.zeros((self.capacity,) + x.shape, x.dtype)

        self.storage = jax.tree_util.tree_map(alloc, transition)

    def add(self, transition: Dict[str, Any]) -> None:
        if self.storage is None:
            self._allocate(transition)
        i = self.next_idx

        def write(buf, x):
            buf[i] = x
            return buf

        jax.tree_util.tree_map(write, self.storage, transition)
        self.priorities[i] = self.max_priority ** self.alpha
        self.next_idx = (i + 1) % self.capacity
        self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int) -> Tuple[Dict[str, Any], np.ndarray,
                                               np.ndarray]:
        """Returns (batch tree of [batch_size, ...], indices, IS weights)."""
        p = self.priorities[:self.size]
        probs = p / p.sum()
        idx = self.rng.choice(self.size, size=batch_size, p=probs)
        weights = (self.size * probs[idx]) ** (-self.beta)
        weights = (weights / weights.max()).astype(np.float32)
        batch = jax.tree_util.tree_map(lambda buf: buf[idx], self.storage)
        return batch, idx, weights

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        pri = np.abs(td_errors) + self.eps
        self.max_priority = max(self.max_priority, float(pri.max()))
        self.priorities[idx] = pri ** self.alpha


def nstep_transitions(steps: List[dict], n_step: int, gamma: float,
                      flush: bool) -> List[dict]:
    """Fold a per-env step list (dicts with obs/action/reward/done/next_obs)
    into n-step transitions (Ape-X workers do this before pushing to
    replay). ``steps`` is consumed from the front; with ``flush`` the tail
    is emitted with shortened horizons (episode end), otherwise it stays
    queued until enough future steps exist."""
    out = []
    limit = len(steps) if flush else len(steps) - n_step + 1
    consumed = 0
    for t in range(max(limit, 0)):
        horizon = min(n_step, len(steps) - t)
        ret, discount = 0.0, 1.0
        done = False
        for k in range(horizon):
            ret += discount * steps[t + k]["reward"]
            discount *= gamma
            if steps[t + k]["done"]:
                done = True
                horizon = k + 1
                break
        out.append({
            "obs": steps[t]["obs"],
            "action": np.int32(steps[t]["action"]),
            "reward": np.float32(ret),
            "next_obs": steps[t + horizon - 1]["next_obs"],
            # bootstrap factor: gamma^horizon, zero across episode ends
            "discount": np.float32(0.0 if done else gamma ** horizon),
        })
        consumed += 1
    del steps[:consumed]
    return out


# ----------------------------------------------------------------- learner
class ApexDQNLearner:
    """Owns the optimiser + jitted mesh-sharded DQN update.

    ``apply_fn(params, obs) -> (logits [N, A], values [N])`` — the same
    policy-net surface the PPO learner uses; for DQN the two heads combine
    into (dueling) Q-values.
    """

    def __init__(self, apply_fn: Callable, cfg: DQNConfig, mesh,
                 param_sharding: str = "replicated"):
        if param_sharding != "replicated":
            raise ValueError(
                f"param_sharding={param_sharding!r} requires the device-"
                "collection trajectory contract, which DQN does not "
                "implement (replay-buffer learner); use "
                "param_sharding='replicated' or a PPO/IMPALA/PG loop")
        self.apply_fn = apply_fn
        self.cfg = cfg
        self.mesh = mesh
        self.param_sharding = param_sharding
        chain = []
        if cfg.grad_clip is not None:
            chain.append(optax.clip_by_global_norm(cfg.grad_clip))
        chain.append(optax.adam(cfg.lr))
        self.tx = optax.chain(*chain)

        self._replicated = replicated_sharding(mesh)
        # state donated on accelerators only — on CPU donation forces the
        # jitted call to execute inline on the dispatching thread
        # (ppo.traj_donate_argnums), defeating async dispatch
        from ddls_tpu.rl.ppo import traj_donate_argnums

        self._jit_train_step = jax.jit(
            self._train_step, donate_argnums=traj_donate_argnums(0))
        self._jit_sample = jax.jit(self._sample_actions)

    # ------------------------------------------------------------- state
    def init_state(self, params) -> DQNTrainState:
        params = jax.tree_util.tree_map(jnp.copy, params)
        state = DQNTrainState.create(params, self.tx)
        # multi-host-safe placement (see parallel/mesh.py:place_state_tree)
        return place_state_tree(state, self._replicated)

    # ------------------------------------------------------------ acting
    def _masked_q(self, params, obs):
        q = dueling_q_values(self.apply_fn(params, obs), self.cfg.dueling)
        mask = obs["action_mask"].astype(bool)
        return jnp.where(mask, q, jnp.finfo(q.dtype).min)

    def _sample_actions(self, params, obs, rng, epsilons):
        """Per-env epsilon-greedy over valid actions: obs dict [B, ...],
        epsilons [B] -> actions [B]."""
        masked_q = self._masked_q(params, obs)
        greedy = jnp.argmax(masked_q, axis=-1)
        mask = obs["action_mask"].astype(jnp.float32)
        explore_rng, pick_rng = jax.random.split(rng)
        # uniform over valid actions
        rand = jax.random.categorical(pick_rng, jnp.log(mask + 1e-30),
                                      axis=-1)
        explore = (jax.random.uniform(explore_rng, greedy.shape)
                   < epsilons)
        return jnp.where(explore, rand, greedy)

    def sample_actions(self, params, obs, rng, epsilons):
        return self._jit_sample(params, obs, rng,
                                jnp.asarray(epsilons, jnp.float32))

    # ------------------------------------------------------------ update
    def _train_step(self, state: DQNTrainState,
                    batch: Dict[str, jnp.ndarray]):
        cfg = self.cfg

        def loss_fn(params):
            q = dueling_q_values(self.apply_fn(params, batch["obs"]),
                                 cfg.dueling)
            q_sel = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]

            next_mask = batch["next_obs"]["action_mask"].astype(bool)
            q_target_next = dueling_q_values(
                self.apply_fn(state.target_params, batch["next_obs"]),
                cfg.dueling)
            if cfg.double_q:
                q_online_next = dueling_q_values(
                    self.apply_fn(params, batch["next_obs"]), cfg.dueling)
                sel_src = q_online_next
            else:
                sel_src = q_target_next
            sel_src = jnp.where(next_mask, sel_src,
                                jnp.finfo(sel_src.dtype).min)
            best = jnp.argmax(sel_src, axis=-1)
            next_q = jnp.take_along_axis(q_target_next, best[:, None],
                                         axis=-1)[:, 0]
            target = batch["rewards"] + batch["discounts"] * \
                jax.lax.stop_gradient(next_q)
            td = q_sel - jax.lax.stop_gradient(target)
            loss = jnp.mean(batch["weights"] * huber(td))
            return loss, (td, q_sel)

        (loss, (td, q_sel)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)
        step = state.step + 1
        # target sync cadence is measured in sampled transitions (RLlib
        # counts env timesteps; with training_intensity 1 the two agree)
        sync_every = max(cfg.target_network_update_freq
                         // max(cfg.train_batch_size, 1), 1)
        target_params = optax.periodic_update(params, state.target_params,
                                              step, sync_every)
        state = state.replace(params=params, target_params=target_params,
                              opt_state=opt_state, step=step)
        metrics = {"loss": loss, "mean_q": jnp.mean(q_sel),
                   "mean_td_error": jnp.mean(jnp.abs(td)),
                   "max_td_error": jnp.max(jnp.abs(td))}
        return state, metrics, jnp.abs(td)

    def train_step(self, state: DQNTrainState, batch: Dict[str, Any]):
        """Jitted sharded update on a replay sample. ``batch`` leaves are
        [N, ...] host arrays; returns (state, metrics, |td| [N]) with |td|
        fetched for the replay priority update."""
        batch = shard_batch(self.mesh, batch, batch_axis=0)
        state, metrics, td = self._jit_train_step(state, batch)
        return state, metrics, np.asarray(jax.device_get(td))
