"""Fused on-device collect→update training (the Podracer/Anakin shape).

ONE jitted program runs a whole epoch: a `lax.scan` over
``updates_per_epoch`` collect→update rounds. Each round collects a
[T, B] segment with the in-kernel environment (`sim/jax_env.py
make_segment_fn`, vmapped over B job-bank lanes sharded on the mesh's
``dp`` axis) and applies the learner's scan-based update in-scan — the
gradient all-reduce over dp is emitted by XLA from the very sharding
annotations the standalone update uses. Params/opt-state/rng keys are
carried on device for the entire epoch, so the only host↔device traffic
per epoch is the ONE dispatch of the fused call: the ~116 ms tunnelled
axon round-trip (docs/perf_round4.md) is paid once per
``updates_per_epoch`` updates instead of twice per update
(PAPERS.md: arXiv 2104.06272 Podracer/Anakin; the pattern JAX-native
env suites are built for, Jumanji arXiv 2306.09884).

Parity contract: the fused program is the SAME math as the sequential
device-collector path (`rl/ppo_device.py:DevicePPOCollector` +
`PPOLearner.train_step`) — same segment kernel, same obs rebuild
(`_kernel_obs`), same f64-then-f32 cast order on the traj leaves, same
rng-split bookkeeping as `RLEpochLoop._split_rng`/`_split_collect_rng`
— pinned exactly in x64 by tests/test_fused.py's full-epoch parity
driver. Metrics and episode counters come back as DEVICE arrays
([U]-stacked metric dicts, compact [U, B, T] episode-counter traces)
and ride the existing LazyMetrics futures contract: the training loop
drains them per ``metrics_sync_interval`` epochs, never per update
(hot-path-transfer rule; the steady-state epoch passes
``jax.transfer_guard("disallow")``).

Autotuner: the axon ``remote_compile`` endpoint rejects large programs
(docs/perf_round4.md — wide-vmap episode kernels fail; few lanes x long
segments wins, and is also the documented perf preference on the
tunnel). ``autotune_fused`` therefore enumerates (lanes, segment_len)
factorisations of the requested per-update batch, ranks them by an
estimated program size (monotonic in lanes, flat in segment_len — a
scan's program does not grow with its length), probe-compiles them
smallest-first with a bounded timeout, caches the first config that
compiles keyed by workload signature + device kind
(``.probe/fused_autotune.json``), and reports failure so the caller can
fall back to ``loop_mode="pipelined"`` loudly. A successful probe warms
the very executable training reuses (jax caches per (jit, shapes)), so
probing costs nothing extra on the chosen config.

Chip ownership: fused runs own the TPU for their whole duration — hold
``.probe/tpu.lock`` via ``chip_lock`` so the probe loop never opens a
second axon client against the owned chip (the documented wedge
trigger), with ``DDLS_TPU_LOCK_OWNER=1`` exported so the run's OWN
probes are not mistaken for a second client and diverted to CPU
(bench.py ``consult_probe_state``).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

#: the tpu.lock owner handshake shared with bench.py's probe cache
LOCK_OWNER_ENV = "DDLS_TPU_LOCK_OWNER"
LOCK_FILE = "tpu.lock"
AUTOTUNE_CACHE_FILE = "fused_autotune.json"

# -------------------------------------------------------------------------
# Program-size model (ranking only — see estimate_program_bytes).
# -------------------------------------------------------------------------
#: serialized-HLO bytes per element of captured config-table constants
#: (tables are embedded in the program as literals)
_TABLE_BYTES_PER_CELL = 10.0
#: marginal serialized bytes per vmapped env lane: GSPMD/batching
#: materialises per-lane buffer shapes and layouts in the module proto
#: (round 4's observed failure mode: WIDE vmap episode kernels rejected
#: by remote_compile while narrow ones compiled)
_BYTES_PER_LANE = 24_000.0
#: fixed overhead of the epoch skeleton (scan plumbing, the scanned SGD
#: update, optimiser state threading)
_BASE_BYTES = 600_000.0


def default_probe_dir() -> str:
    """The ``.probe`` scratch dir the bench/probe tooling shares
    (CLAUDE.md TPU practicalities). Overridable via
    ``DDLS_TPU_PROBE_DIR`` for tests and relocated checkouts."""
    env = os.environ.get("DDLS_TPU_PROBE_DIR")
    if env:
        return env
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, ".probe")


# re-exported for fused-path callers; the implementation lives in the
# jax-free utils module so bench.py's probe consult (which must decide
# CPU fallback BEFORE any jax import) can use it without dragging the
# rl package's jax/flax imports in
from ddls_tpu.utils.common import lock_is_stale  # noqa: F401


class chip_lock:
    """Hold ``.probe/tpu.lock`` for the duration of a fused run.

    The documented convention (CLAUDE.md, docs/perf_round4.md): while a
    bench or training owns the chip, the lock keeps the probe loop from
    opening a second axon client — the wedge trigger. While held,
    ``DDLS_TPU_LOCK_OWNER=1`` is exported so the owner's OWN probes
    (bench.py ``consult_probe_state``) still run against the TPU instead
    of silently diverting to CPU.

    If the lock is already held by ANOTHER (live) owner, entry does not
    block or steal: ``acquired`` stays False, the env var is left alone
    (our probes then correctly treat the chip as foreign-owned), and
    exit never removes a lock we do not hold. A lock whose recorded
    owner pid is provably DEAD is stale — a hard-killed run cannot
    unlink its own file — and is reclaimed; an ``atexit`` hook
    additionally releases on interpreter exits that skip ``__exit__``.
    """

    def __init__(self, probe_dir: Optional[str] = None):
        self.probe_dir = probe_dir or default_probe_dir()
        self.path = os.path.join(self.probe_dir, LOCK_FILE)
        self.acquired = False
        self.delegated = False
        self._prev_owner_env: Optional[str] = None

    @property
    def owned(self) -> bool:
        """This process tree may use the chip: we hold the lock file
        ourselves (``acquired``) or a wrapper above us holds it and
        exported ``DDLS_TPU_LOCK_OWNER`` (``delegated``)."""
        return self.acquired or self.delegated

    def _try_acquire(self) -> bool:
        try:
            os.makedirs(self.probe_dir, exist_ok=True)
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError:
            return False
        with os.fdopen(fd, "w") as f:
            f.write(f"{os.getpid()}\n")
        return True

    def _reclaim_stale(self) -> bool:
        """Crash fallback, raced safely: reclaim a dead-owner lock only
        under an O_EXCL ``.reclaim`` sentinel, so two concurrent
        reclaimers can never both unlink-then-acquire (that TOCTOU
        would hand BOTH the chip and wedge the tunnel); the loser
        defers. A sentinel whose own writer died is itself stale and
        removed by the same pid-liveness rule."""
        guard = self.path + ".reclaim"
        try:
            fd = os.open(guard, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError:
            if lock_is_stale(guard):
                try:
                    os.unlink(guard)
                except OSError:
                    pass
            return False  # another reclaimer mid-flight: defer
        try:
            os.write(fd, f"{os.getpid()}\n".encode())
            if not lock_is_stale(self.path):  # re-check under the guard
                return False
            try:
                os.unlink(self.path)
            except OSError:
                pass
            return self._try_acquire()
        finally:
            os.close(fd)
            try:
                os.unlink(guard)
            except OSError:
                pass

    def __enter__(self) -> "chip_lock":
        if os.environ.get(LOCK_OWNER_ENV):
            # a wrapper above this process already owns the chip FOR us
            # (the documented convention: it holds the lock file and
            # exports the env var — bench.py consult_probe_state honors
            # the same handshake): delegated ownership, no file ops,
            # and exit leaves the wrapper's lock alone
            self.delegated = True
            return self
        got = self._try_acquire()
        if not got and lock_is_stale(self.path):
            got = self._reclaim_stale()
        if not got:
            return self  # live foreign owner (or unwritable dir)
        self.acquired = True
        self._prev_owner_env = os.environ.get(LOCK_OWNER_ENV)
        os.environ[LOCK_OWNER_ENV] = "1"
        import atexit

        atexit.register(self.__exit__)
        return self

    def __exit__(self, *exc) -> None:
        if not self.acquired:
            return
        import atexit

        atexit.unregister(self.__exit__)
        if self._prev_owner_env is None:
            os.environ.pop(LOCK_OWNER_ENV, None)
        else:
            os.environ[LOCK_OWNER_ENV] = self._prev_owner_env
        try:
            os.unlink(self.path)
        except OSError:
            pass
        self.acquired = False


# -------------------------------------------------------------------------
# Autotuner: candidate enumeration, size model, probe-compile, cache.
# -------------------------------------------------------------------------

@dataclasses.dataclass
class AutotuneResult:
    """The chosen fused (lanes, segment_len) config and how it was
    reached; ``probed`` records every candidate tried as
    (lanes, segment_len, ok, error)."""
    lanes: int
    segment_len: int
    estimated_bytes: int
    actual_bytes: Optional[int]
    source: str                      # "cache" | "probe" | "explicit"
    probed: List[Tuple[int, int, bool, Optional[str]]] = \
        dataclasses.field(default_factory=list)

    def as_dict(self) -> dict:
        return {"lanes": self.lanes, "segment_len": self.segment_len,
                "estimated_program_bytes": self.estimated_bytes,
                "actual_program_bytes": self.actual_bytes,
                "source": self.source,
                "probed": [{"lanes": l, "segment_len": s, "ok": ok,
                            "error": err}
                           for l, s, ok, err in self.probed]}


def table_cells(et) -> int:
    """Total elements across the episode tables' captured constants —
    the dominant static contribution to fused-program size (reads
    ``.size`` attributes only; never fetches the device arrays)."""
    return int(sum(int(np.prod(getattr(v, "shape", ()) or (1,)))
                   for v in et.tables.values()))


def memo_table_cells(et, memo_cfg) -> int:
    """Captured-constant contribution of the in-kernel lookahead memo
    (sim/jax_memo.py): the key-hash weights (1 + N + 2M u32 words) are
    embedded as program literals. The memo TABLE itself is a carried
    ARGUMENT, not a constant — it costs argument traffic and HBM, not
    serialized-program bytes. ``memo_cfg`` is the knob value ("auto" /
    MemoConfig / None); "auto" counts the cells because it turns the
    memo on at every lane count (the wide-vmap probe, ISSUE 17)."""
    if memo_cfg is None:
        return 0
    return 1 + int(et.pads.n_ops) + 2 * int(et.pads.n_deps)


def estimate_program_bytes(lanes: int, segment_len: int,
                           n_table_cells: int,
                           n_memo_cells: int = 0) -> int:
    """Estimated serialized-program size of the fused epoch.

    A RANKING model, not a measurement: calibrated coarsely against the
    round-4 observation that program size (and the axon remote_compile
    failure mode) grows with vmap WIDTH while `lax.scan` keeps it flat
    in segment length and update count. Monotonic in ``lanes``, constant
    in ``segment_len`` — exactly the "few lanes x long segments"
    preference docs/perf_round4.md measured. Probe compilation supplies
    the actual size (``AutotuneResult.actual_bytes``) for the artifact.
    """
    del segment_len  # scans do not grow the program with their length
    return int(_BASE_BYTES
               + _TABLE_BYTES_PER_CELL * (n_table_cells + n_memo_cells)
               + _BYTES_PER_LANE * lanes)


def candidate_configs(total_steps: int, dp: int,
                      max_lanes: int) -> List[Tuple[int, int]]:
    """(lanes, segment_len) factorisations of one update's
    ``total_steps`` batch, smallest-estimated-program (fewest lanes)
    first. Lanes must divide the batch, stay within ``max_lanes`` (the
    requested num_envs — more lanes than asked would change workload
    semantics upward), and divide evenly over the mesh's ``dp`` axis so
    sharded collection stays collective-free."""
    out = []
    for lanes in range(1, max_lanes + 1):
        if total_steps % lanes:
            continue
        if dp > 1 and lanes % dp:
            continue
        out.append((lanes, total_steps // lanes))
    out.sort(key=lambda ls: ls[0])
    return out


def workload_signature(et, total_steps: int, updates_per_epoch: int,
                       dp: int, max_lanes: int = 0,
                       extra: str = "", memo_cfg="auto") -> str:
    """Cache key for the autotuned config: everything the compiled
    program's size depends on — pad bounds, topology size, the
    model/degree config set, batch factorisation inputs (including the
    lane cap: a cached config must never carry more lanes than the
    current run's num_envs allows), mesh width, and the lookahead-memo
    knob (a memo-on lanes=1 program is a different program than a
    memo-off one) — hashed so a changed workload can never serve a
    stale config."""
    pads = dataclasses.asdict(et.pads)
    payload = json.dumps({
        "pads": pads, "n_srv": et.n_srv, "n_chan": et.n_chan,
        "types": list(et.types), "degrees": list(et.degrees),
        "max_action": et.max_action, "total_steps": total_steps,
        "updates_per_epoch": updates_per_epoch, "dp": dp,
        "max_lanes": max_lanes, "extra": extra,
        "memo": repr(memo_cfg)}, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


def _cache_path(probe_dir: str) -> str:
    return os.path.join(probe_dir, AUTOTUNE_CACHE_FILE)


def load_cached_config(probe_dir: str, key: str) -> Optional[dict]:
    """Best-effort read of a cached autotune decision (missing/corrupt
    cache means probe again — never an error)."""
    try:
        with open(_cache_path(probe_dir)) as f:
            return json.load(f).get(key)
    except (OSError, ValueError):
        return None


def store_cached_config(probe_dir: str, key: str, entry: dict) -> None:
    """Best-effort atomic upsert of one autotune decision."""
    path = _cache_path(probe_dir)
    try:
        os.makedirs(probe_dir, exist_ok=True)
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        data[key] = entry
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass


def _run_bounded(fn: Callable, timeout_s: float,
                 label: str) -> Tuple[bool, object, Optional[str]]:
    """Run ``fn`` on a daemon worker thread, joined with ``timeout_s``:
    an in-process axon call that wedges cannot be interrupted from
    Python (CLAUDE.md), so on timeout the thread is abandoned and the
    step reported failed. Returns (ok, value, error)."""
    import threading

    box: dict = {}

    def _work():
        try:
            box["value"] = fn()
            box["ok"] = True
        except Exception as e:  # remote_compile rejection, OOM, ...
            box["ok"] = False
            box["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_work, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return False, None, f"{label} exceeded {timeout_s:.0f}s (abandoned)"
    return bool(box.get("ok")), box.get("value"), box.get("error")


def probe_compile(build_fn: Callable[[], "FusedEpochDriver"], state,
                  timeout_s: float
                  ) -> Tuple[Optional["FusedEpochDriver"], bool,
                             Optional[int], Optional[str]]:
    """Build + compile one candidate's fused program with a bounded
    wall timeout covering BOTH steps: driver construction itself
    dispatches device work (bank device_put, the vmapped segment_init)
    that can wedge on the tunnel exactly like a compile, so it runs on
    the same bounded worker. On success the compiled executable is
    already in the jit cache — the first training epoch pays no second
    compile. Returns (driver, ok, actual_program_bytes, error).
    """
    size_box: dict = {}

    def _work():
        driver = build_fn()
        lowered = driver.lower(state)
        try:
            size_box["size"] = len(lowered.as_text())
        except Exception:
            size_box["size"] = None
        lowered.compile()
        return driver

    ok, driver, err = _run_bounded(_work, timeout_s, "compile")
    return driver, ok, size_box.get("size"), err


def autotune_fused(build_driver: Callable[[int, int],
                                          "FusedEpochDriver"],
                   state, et, total_steps: int, updates_per_epoch: int,
                   dp: int, max_lanes: int,
                   probe_dir: Optional[str] = None,
                   probe_timeout_s: float = 240.0,
                   signature_extra: str = "",
                   lanes: Optional[int] = None,
                   segment_len: Optional[int] = None,
                   memo_cfg="auto"
                   ) -> Tuple[Optional["FusedEpochDriver"],
                              AutotuneResult]:
    """Pick a compilable (lanes, segment_len) config and build its
    driver.

    Explicit ``lanes``/``segment_len`` skip probing entirely (tests,
    pinned production configs). Otherwise: cache hit → build that config
    without probing (the gate stays deterministic given the cached
    config — multi-host rule); cache miss → probe-compile candidates
    smallest-estimated-first under the caller-held chip lock, cache the
    winner. Returns (driver, result); driver is None when nothing
    compiled — the caller must fall back to ``loop_mode="pipelined"``
    LOUDLY (never silently).
    """
    probe_dir = probe_dir or default_probe_dir()
    cells = table_cells(et) + memo_table_cells(et, memo_cfg)
    if lanes is not None or segment_len is not None:
        if lanes is None or segment_len is None:
            raise ValueError("pass both lanes and segment_len (or "
                             "neither, for autotuning)")
        if lanes * segment_len != total_steps:
            raise ValueError(
                f"lanes ({lanes}) x segment_len ({segment_len}) must "
                f"equal the per-update batch ({total_steps})")
        # construction dispatches device work — bound it like a probe
        ok, driver, err = _run_bounded(
            lambda: build_driver(lanes, segment_len), probe_timeout_s,
            "driver build")
        if not ok:
            raise RuntimeError(
                f"fused driver build failed for the explicit config "
                f"(lanes={lanes}, segment_len={segment_len}): {err}")
        return driver, AutotuneResult(
            lanes=lanes, segment_len=segment_len,
            estimated_bytes=estimate_program_bytes(lanes, segment_len,
                                                   cells),
            actual_bytes=None, source="explicit")

    key = workload_signature(et, total_steps, updates_per_epoch, dp,
                             max_lanes=max_lanes, extra=signature_extra,
                             memo_cfg=memo_cfg)
    cached = load_cached_config(probe_dir, key)
    if cached is not None:
        # a hand-edited/corrupt entry is re-probed, never obeyed: the
        # cached config must satisfy every constraint the prober
        # enforces (lane cap, exact batch factorisation, dp divide)
        cl = int(cached.get("lanes", 0))
        cs = int(cached.get("segment_len", 0))
        if (cl < 1 or cl > max_lanes or cl * cs != total_steps
                or (dp > 1 and cl % dp)):
            cached = None
    if cached is not None:
        cl, cs = int(cached["lanes"]), int(cached["segment_len"])
        ok, driver, err = _run_bounded(lambda: build_driver(cl, cs),
                                       probe_timeout_s, "driver build")
        if ok:
            return driver, AutotuneResult(
                lanes=cl, segment_len=cs,
                estimated_bytes=int(cached.get("estimated_bytes", 0)),
                actual_bytes=cached.get("actual_bytes"),
                source="cache")
        # a wedged build on the cached config would wedge probing too
        return None, AutotuneResult(
            lanes=0, segment_len=0, estimated_bytes=0,
            actual_bytes=None, source="failed",
            probed=[(cl, cs, False, err)])

    probed: List[Tuple[int, int, bool, Optional[str]]] = []
    for cand_lanes, cand_seg in candidate_configs(total_steps, dp,
                                                  max_lanes):
        driver, ok, size, err = probe_compile(
            lambda cl=cand_lanes, cs=cand_seg: build_driver(cl, cs),
            state, probe_timeout_s)
        probed.append((cand_lanes, cand_seg, ok, err))
        if not ok and err and "abandoned" in err:
            # a TIMED-OUT build/compile was the smallest remaining
            # candidate (size-ranked): larger ones cannot fare better,
            # and the abandoned worker thread is still burning CPU —
            # stop probing instead of stacking more of them
            break
        if ok:
            est = estimate_program_bytes(cand_lanes, cand_seg, cells)
            store_cached_config(probe_dir, key, {
                "lanes": cand_lanes, "segment_len": cand_seg,
                "estimated_bytes": est, "actual_bytes": size})
            return driver, AutotuneResult(
                lanes=cand_lanes, segment_len=cand_seg,
                estimated_bytes=est, actual_bytes=size, source="probe",
                probed=probed)
    return None, AutotuneResult(
        lanes=0, segment_len=0, estimated_bytes=0, actual_bytes=None,
        source="failed", probed=probed)


# -------------------------------------------------------------------------
# The fused epoch driver.
# -------------------------------------------------------------------------

def horizon_bank_jobs(env, seed: int,
                      explicit: Optional[int] = None) -> int:
    """Jobs per lane bank: the explicit config when given, else sized to
    cover the sim horizon — the ONE sizing home for the device
    collector, the fused loop, and the bench (an under-sized bank ends
    in-kernel episodes early: arrival_t=inf silently truncates them).

    Sizing provisions for the SUM of interarrivals, not its mean: a
    heavy-tailed distribution can draw a lighter-than-mean bank and
    exhaust early, so a 2-sigma CLT margin on the horizon's arrival
    count rides on top of 10% slack. The process-global numpy rng the
    distributions draw from is snapshotted/restored, so sizing never
    perturbs a caller's stochastic streams."""
    if explicit:
        return int(explicit)
    msrt = float(env.max_simulation_run_time)
    if not np.isfinite(msrt):
        raise ValueError(
            "device/fused collection with an unbounded "
            "max_simulation_run_time needs an explicit "
            "algo_config device_bank_jobs")
    rng_state = np.random.get_state()
    try:
        np.random.seed(seed)
        ias = np.array([env.cluster.jobs_generator
                        .interarrival_dist.sample()
                        for _ in range(1000)], np.float64)
    finally:
        np.random.set_state(rng_state)
    mean = max(float(ias.mean()), 1e-9)
    base = msrt / mean
    return int(base * 1.1
               + 2.0 * (float(ias.std()) / mean) * np.sqrt(base)) + 10


def stacked_job_banks(et, env, n_lanes: int, n_jobs: int,
                      seed_base: int = 0) -> Dict:
    """Per-lane job banks sampled from ``env``'s own workload machinery,
    stacked along a leading lane axis. Lane i draws with seed
    ``seed_base + 7559 * i + 17`` — THE device-collection seed formula
    (one home: the training loop and the bench both build their banks
    here, so fused lanes == num_envs reproduce the device collector's
    banks bit-for-bit and the two callers can never drift)."""
    import jax.numpy as jnp

    from ddls_tpu.sim.jax_env import sample_job_bank

    banks = [sample_job_bank(et, env, n_jobs, seed_base + 7559 * i + 17)
             for i in range(n_lanes)]
    return {k: jnp.asarray(np.stack([b[k] for b in banks]))
            for k in banks[0]}


#: the compact episode-counter trace keys the fused program returns per
#: decision step (the rest of the segment trace — obs fields, actions —
#: stays INSIDE the program; only these [U, B, T] scalars ever leave)
EPISODE_TRACE_KEYS = ("done", "ep_return", "ep_blocked", "ep_completed",
                      "ep_arrived")


class FusedEpochDriver:
    """One jitted collect→update epoch over the in-kernel environment.

    Counterpart of `DevicePPOCollector` + the standalone jitted
    ``train_step``, fused: ``fused_epoch(state, rngs)`` scans
    ``updates_per_epoch`` rounds of [segment_len, num_lanes] collection
    + one update each, entirely on device. ``train_step_fn(state, traj,
    last_values, rng) -> (state, metrics)`` is the learner's UNJITTED
    update (e.g. ``PPOLearner._train_step``) so it traces into the
    epoch program; ``state_shardings`` mirrors the standalone jit's
    in/out shardings so the in-scan update partitions identically (the
    x64 parity contract).

    The simulator state is carried on device ACROSS epochs (episodes
    span epoch boundaries exactly as they span the sequential
    collector's segments); per-lane episode lengths are tracked
    host-side and consumed by ``harvest_episodes`` at drain boundaries.
    """

    def __init__(self, et, ot, model, banks: Dict, segment_len: int,
                 updates_per_epoch: int, train_step_fn: Callable,
                 state_shardings=None, mesh=None, memo_cfg="auto"):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddls_tpu.models.policy import batched_policy_apply
        from ddls_tpu.rl.ppo import traj_donate_argnums
        from ddls_tpu.sim.jax_env import (_kernel_obs, make_segment_fn,
                                          segment_init, vmap_segment_fn)
        from ddls_tpu.sim.jax_memo import resolve_memo_cfg

        self.et, self.ot, self.model = et, ot, model
        self.segment_len = int(segment_len)
        self.updates_per_epoch = int(updates_per_epoch)
        self.num_lanes = int(
            jax.tree_util.tree_leaves(banks)[0].shape[0])
        self.mesh = mesh
        self.env_steps_per_epoch = (self.updates_per_epoch
                                    * self.segment_len * self.num_lanes)
        # in-kernel lookahead memo: "auto" enables it at every lane
        # count (the batched probe masks hit lanes out of the lookahead
        # while_loop — sim/jax_memo.py, ISSUE 17); each lane carries its
        # own table, riding the carried sim state across epochs like
        # the rest of it
        self.memo_cfg = resolve_memo_cfg(memo_cfg, self.num_lanes)
        T, B, U = self.segment_len, self.num_lanes, self.updates_per_epoch
        # trace_obs: the in-scan update carry — the update consumes the
        # segment's own observations instead of re-deriving them from
        # the compact fields (a second _kernel_obs sweep over T x B
        # samples, measured ~30% of the fused epoch on CPU); same
        # _kernel_obs values either way, so parity with the sequential
        # rebuild-from-fields path is unchanged
        segment = make_segment_fn(et, ot, model, T, trace_obs=True,
                                  memo_cfg=self.memo_cfg)
        # one-lane fast path shared with DevicePPOCollector (a 1-wide
        # vmap halves the kernel's XLA:CPU throughput)
        lane_segment = vmap_segment_fn(segment, self.num_lanes)

        lane = repl = None
        if mesh is not None:
            if B % mesh.shape["dp"] != 0:
                raise ValueError(
                    f"num_lanes {B} must divide over the mesh dp axis "
                    f"({mesh.shape['dp']})")
            lane = NamedSharding(mesh, P("dp"))
            repl = NamedSharding(mesh, P())
            banks = jax.device_put(banks, lane)
            batch_time = NamedSharding(mesh, P(None, "dp"))
            batch_only = NamedSharding(mesh, P("dp"))
        self._banks = banks
        # per-lane initial sim state from each lane's OWN bank; carried
        # across fused_epoch calls like the collector's self._state
        self._state = jax.vmap(
            lambda b: segment_init(et, b, self.memo_cfg))(banks)
        self._ep_len = np.zeros(B, np.int64)

        def obs_from_fields(jtype, frac, steps, n_occ, n_run):
            return _kernel_obs(ot, et, jtype, frac, steps, n_occ, n_run)

        def traj_from_trace(trace):
            """The exact DevicePPOCollector.collect staging, traced:
            [B, T] kernel trace -> [T, B] learner traj with the same
            f64-then-f32 casts as the host path. The obs ride the trace
            (``trace_obs`` carry) — bit-equal to the host path's
            rebuild-from-fields, which vmaps the same `_kernel_obs`."""
            def tb(x):
                return jnp.swapaxes(x, 0, 1)

            return {
                "obs": {k: tb(v) for k, v in trace["obs"].items()},
                "actions": tb(trace["action"]).astype(jnp.int32),
                "logp": tb(trace["logp"]).astype(jnp.float32),
                "values": tb(trace["value"]).astype(jnp.float32),
                "rewards": tb(trace["reward"]).astype(jnp.float32),
                "dones": tb(trace["done"]),
            }

        def one_round(carry, _):
            state, sim_state, crng, urng = carry
            # rng bookkeeping mirrors RLEpochLoop._split_collect_rng /
            # _split_rng exactly: same streams, same per-round splits,
            # so fused and sequential updates consume identical keys
            crng, csub = jax.random.split(crng)
            lane_rngs = jax.random.split(csub, B)
            sim_state, trace, next_fields = lane_segment(
                self._banks, state.params, sim_state, lane_rngs)
            traj = traj_from_trace(trace)
            next_obs = jax.vmap(obs_from_fields)(
                next_fields["jtype"], next_fields["frac"],
                next_fields["steps"], next_fields["n_occupied"],
                next_fields["n_running"])
            _, last_values = batched_policy_apply(model, state.params,
                                                  next_obs)
            last_values = last_values.astype(jnp.float32)
            if mesh is not None:
                # pin the staged batch to the standalone train_step's
                # in_shardings so the in-scan update partitions (and
                # therefore rounds) identically to the sequential path
                traj = jax.lax.with_sharding_constraint(
                    traj, jax.tree_util.tree_map(
                        lambda _: batch_time, traj))
                last_values = jax.lax.with_sharding_constraint(
                    last_values, batch_only)
            urng, usub = jax.random.split(urng)
            state, metrics = train_step_fn(state, traj, last_values,
                                           usub)
            # memo trace keys stay INSIDE the program (XLA DCEs the
            # unused stacking): cumulative counters are reported from the
            # carried memo state via memo_counters() at drain boundaries
            ep = {k: trace[k] for k in EPISODE_TRACE_KEYS}
            return (state, sim_state, crng, urng), (metrics, ep)

        def epoch(state, sim_state, crng, urng):
            (state, sim_state, crng, urng), (metrics, ep) = jax.lax.scan(
                one_round, (state, sim_state, crng, urng), None,
                length=U)
            return state, sim_state, crng, urng, metrics, ep

        if mesh is not None:
            sharded_sim = jax.tree_util.tree_map(lambda _: lane,
                                                 self._state)
            # episode-counter outputs are [U, B, T]: B on axis 1
            ep_sh = NamedSharding(mesh, P(None, "dp"))
            state_sh = (state_shardings if state_shardings is not None
                        else repl)
            self._jit_epoch = jax.jit(
                epoch,
                in_shardings=(state_sh, sharded_sim, repl, repl),
                out_shardings=(state_sh, sharded_sim, repl, repl, repl,
                               ep_sh),
                donate_argnums=traj_donate_argnums(0, 1))
        else:
            self._jit_epoch = jax.jit(
                epoch, donate_argnums=traj_donate_argnums(0, 1))

    # ------------------------------------------------------------- run
    def lower(self, state):
        """Lower (trace, no compile/execute) the fused program for the
        autotuner's probe-compile and size measurement."""
        import jax

        crng = urng = jax.random.PRNGKey(0)
        return self._jit_epoch.lower(state, self._state, crng, urng)

    def fused_epoch(self, state, rngs: Tuple):
        """ONE device dispatch: ``updates_per_epoch`` collect→update
        rounds. ``rngs`` is (collect_rng, update_rng); both are split
        in-kernel with the host loop's exact bookkeeping and returned
        advanced. Returns (state, (collect_rng, update_rng),
        metrics [U]-stacked dict, episode_trace dict of [U, B, T]) —
        ALL device values; no transfer happens here (the LazyMetrics /
        episode-drain boundaries fetch later, batched).
        """
        crng, urng = rngs
        (state, self._state, crng, urng, metrics,
         ep) = self._jit_epoch(state, self._state, crng, urng)
        return state, (crng, urng), metrics, ep

    def memo_counters(self) -> Optional[Dict]:
        """Cumulative in-kernel memo counters {hits, misses, evicts,
        hit_rate} summed over lanes (drain/reporting boundaries only —
        sim/jax_memo.py:summarize_counters); None when the memo is
        off."""
        from ddls_tpu.sim.jax_memo import summarize_counters

        if self.memo_cfg is None:
            return None
        return summarize_counters(self._state[1])

    # --------------------------------------------------------- harvest
    def harvest_episodes(self, ep_trace) -> list:
        """Episode records from a FETCHED [U, B, T] episode-counter
        trace (the drain boundary hands host numpy arrays) — the same
        records, in the same (round, t, b) order, as
        ``DevicePPOCollector._harvest_episodes`` emits across U
        sequential collects, using the host denominators
        (cluster.py:1020-1023)."""
        episodes = []
        done = np.asarray(ep_trace["done"])
        U, B, T = done.shape
        for u in range(U):
            for t in range(T):
                self._ep_len += 1
                for b in np.nonzero(done[u, :, t])[0]:
                    blk = int(ep_trace["ep_blocked"][u, b, t])
                    com = int(ep_trace["ep_completed"][u, b, t])
                    arr = int(ep_trace["ep_arrived"][u, b, t])
                    episodes.append({
                        "env_index": int(b),
                        "episode_return": float(
                            ep_trace["ep_return"][u, b, t]),
                        "episode_length": int(self._ep_len[b]),
                        "num_jobs_arrived": arr,
                        "num_jobs_completed": com,
                        "num_jobs_blocked": blk,
                        "acceptance_rate": com / arr if arr else 0.0,
                        "blocking_rate": blk / arr if arr else 0.0,
                    })
                    self._ep_len[b] = 0
        return episodes
