"""Vectorised rollout collection.

Replaces RLlib's Ray rollout workers (SURVEY.md §3.1): instead of N worker
processes each owning an environment and a policy copy, one host process
steps B environment instances, stacks their padded observations into [B, ...]
arrays, and samples all B actions in a single jitted device call
(``PPOLearner.sample_actions``). The simulator itself runs per-step on the
host (its per-job heuristic placer is sequential/combinatorial — SURVEY.md
§7.4.2); the device sees only fixed-shape batched tensors.

Environments auto-reset on episode end; completed-episode returns/lengths and
the cluster's episode stats are harvested for logging, mirroring what RLlib's
callbacks collect (ddls/environments/ramp_cluster/utils.py:25-73).
"""
from __future__ import annotations

import multiprocessing as mp
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ddls_tpu import telemetry
from ddls_tpu.telemetry import flight

OBS_KEYS = ("node_features", "edge_features", "graph_features",
            "edges_src", "edges_dst", "node_split", "edge_split",
            "action_mask")


def stack_obs(obs_list: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    return {k: np.stack([np.asarray(o[k]) for o in obs_list])
            for k in OBS_KEYS}


def harvest_episode_record(env, env_index: int, episode_return: float,
                           episode_length: int) -> Dict[str, Any]:
    """Episode summary + the cluster's episode stats, mirroring what RLlib's
    callbacks collect (ddls/environments/ramp_cluster/utils.py:25-73)."""
    record = {"env_index": env_index,
              "episode_return": float(episode_return),
              "episode_length": int(episode_length)}
    cluster = getattr(env, "cluster", None)
    if cluster is not None and getattr(cluster, "episode_stats", None):
        stats = cluster.episode_stats
        for key in ("num_jobs_arrived", "num_jobs_completed",
                    "num_jobs_blocked", "blocking_rate",
                    "acceptance_rate"):
            if key in stats:
                record[key] = stats[key]
        for key in ("job_completion_time",
                    "job_completion_time_speedup"):
            vals = stats.get(key)
            if vals:
                record[f"mean_{key}"] = float(np.mean(vals))
    return record


class VectorEnv:
    """B independent environment instances with auto-reset."""

    def __init__(self, env_fns: List[Callable[[], Any]],
                 seeds: Optional[List[int]] = None):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.seeds = seeds or list(range(self.num_envs))
        self.episode_returns = np.zeros(self.num_envs)
        self.episode_lengths = np.zeros(self.num_envs, dtype=np.int64)
        self.completed_episodes: List[Dict[str, Any]] = []
        self._stacked_bufs: Optional[Dict[str, np.ndarray]] = None

    def stacked_obs(self) -> Dict[str, np.ndarray]:
        """The current obs list as one [B, ...] batch, assembled into a
        REUSED preallocated buffer (values bit-identical to
        ``stack_obs(self.obs)``; contents valid until the next
        ``stacked_obs()`` call — every current consumer copies or stages
        the batch before stepping again). The single-process half of the
        per-step obs copy tax: one allocation per run instead of one per
        step. (In-process envs have no stepping to overlap the stacking
        with — see ParallelVectorEnv for the prefetched/shm variants.)"""
        arrays = {k: [np.asarray(o[k]) for o in self.obs]
                  for k in OBS_KEYS}
        bufs = self._stacked_bufs
        if bufs is None or any(
                bufs[k].shape != (self.num_envs,) + arrays[k][0].shape
                or bufs[k].dtype != arrays[k][0].dtype for k in OBS_KEYS):
            bufs = {k: np.empty((self.num_envs,) + arrays[k][0].shape,
                                arrays[k][0].dtype) for k in OBS_KEYS}
            self._stacked_bufs = bufs
        for k in OBS_KEYS:
            np.stack(arrays[k], out=bufs[k])
        if telemetry.enabled():
            telemetry.inc("rollout.obs.bytes_stack",
                          sum(b.nbytes for b in bufs.values()))
        return bufs

    def reset(self) -> List[Dict[str, np.ndarray]]:
        self.obs = [env.reset(seed=self.seeds[i])
                    for i, env in enumerate(self.envs)]
        self.episode_returns[:] = 0.0
        self.episode_lengths[:] = 0
        return self.obs

    def step(self, actions: np.ndarray):
        return self.step_subset(range(self.num_envs), actions)

    def step_subset(self, indices, actions: np.ndarray):
        """Step only ``envs[i] for i in indices`` with ``actions`` (same
        length as ``indices``); returns (obs list for the subset, rewards,
        dones). Used by the pipelined collector to overlap device sampling
        of one env group with host stepping of the other."""
        indices = list(indices)
        rewards = np.zeros(len(indices), dtype=np.float32)
        dones = np.zeros(len(indices), dtype=bool)
        for k, i in enumerate(indices):
            env = self.envs[i]
            obs, reward, done, _ = env.step(int(actions[k]))
            rewards[k] = reward
            dones[k] = done
            self.episode_returns[i] += reward
            self.episode_lengths[i] += 1
            if done:
                self._harvest_episode(i, env)
                # fresh seed per episode so workload sampling differs
                self.seeds[i] += self.num_envs
                obs = env.reset(seed=self.seeds[i])
                self.episode_returns[i] = 0.0
                self.episode_lengths[i] = 0
            self.obs[i] = obs
        return [self.obs[i] for i in indices], rewards, dones

    def _harvest_episode(self, i: int, env) -> None:
        self.completed_episodes.append(harvest_episode_record(
            env, i, self.episode_returns[i], self.episode_lengths[i]))

    def drain_completed_episodes(self) -> List[Dict[str, Any]]:
        out, self.completed_episodes = self.completed_episodes, []
        return out

    def restart_episodes(self) -> List[Dict[str, np.ndarray]]:
        """Abandon every in-progress episode and start fresh ones on
        advanced per-env seeds. Completed-episode records are kept; the
        abandoned partial returns/lengths are dropped — used after an
        off-policy interlude (e.g. an ES eval window) so foreign-policy
        steps can never leak into training episode stats."""
        for i in range(self.num_envs):
            self.seeds[i] += self.num_envs
        self.obs = [env.reset(seed=self.seeds[i])
                    for i, env in enumerate(self.envs)]
        self.episode_returns[:] = 0.0
        self.episode_lengths[:] = 0
        return self.obs

    def close(self) -> None:
        pass


def _parallel_env_worker(conn, env_builder, env_kwargs: Dict[str, Any],
                         env_index: int, seed: int, seed_stride: int,
                         telemetry_enabled: bool = False,
                         flight_state: Optional[tuple] = None) -> None:
    """Subprocess body: owns one env, steps it on command, auto-resets.

    ``env_builder`` is a picklable callable (class or factory) receiving
    ``**env_kwargs`` — the process-parallel replacement for RLlib's Ray
    rollout workers, each of which builds its own env from the env_config
    (SURVEY.md §3.1 process-boundary note).

    ``telemetry_enabled`` mirrors the parent's telemetry switch into this
    process (spawned workers start with the global registry disabled);
    the worker's counters — the sim-layer cache hit/miss counts live
    HERE, not in the parent — ride back on the "closed" ack and are
    merged into the parent registry by ``ParallelVectorEnv.close``.
    ``flight_state`` (enabled, detail) mirrors the flight recorder the
    same way: the simulator's event trace is emitted in THIS process,
    drained on the close ack, and merged into the parent recorder tagged
    with this worker's env index.

    Shared-memory protocol (the ``shm`` backend): on ``shm_open`` the
    worker maps the parent's slabs (rl/shm.py); step commands then carry
    ``(action, dest_row)`` and the observation is written in place into
    this worker's ``[dest_row, env_index]`` slice via the masked-pad
    ``envs.obs.write_obs_into`` — the pipe reply shrinks to the
    (reward, done, record) control payload, which doubles as the ready
    flag the parent waits on before reading the slice. ``ring_open``
    upgrades the mapping to a trajectory ring (rl/ring.py): K segment
    attachments, and ``dest_row`` becomes ``(segment, row)`` — segment
    ownership (who may be written when) is entirely parent-side; the
    worker just writes where the step command points.
    """
    attachment = None
    ring_attachment = None  # set on ring_open (rl/ring.py segments)
    writer = None  # set with the attachment on shm_open/ring_open
    try:
        if telemetry_enabled:
            telemetry.enable()
        if flight_state is not None and flight_state[0]:
            flight.enable(detail=bool(flight_state[1]))
        env = env_builder(**env_kwargs)
        episode_return, episode_length = 0.0, 0
        while True:
            cmd, payload = conn.recv()
            if cmd == "reset":
                # seedless reset replays the current seed (same semantics
                # as the serial VectorEnv); "restart" advances it
                seed = payload if payload is not None else seed
                obs = env.reset(seed=seed)
                episode_return, episode_length = 0.0, 0
                conn.send(("obs", obs))
            elif cmd == "restart":
                # abandon the in-progress episode for a fresh workload
                seed += seed_stride
                obs = env.reset(seed=seed)
                episode_return, episode_length = 0.0, 0
                conn.send(("obs", obs))
            elif cmd == "shm_open":
                from ddls_tpu.envs.obs import ObsWriter
                from ddls_tpu.rl.shm import SlabAttachment

                if attachment is not None:
                    attachment.close()
                attachment = SlabAttachment(payload)
                writer = ObsWriter(
                    attachment.views["node_features"].shape[2],
                    attachment.views["edge_features"].shape[2])
                conn.send(("ok", None))
            elif cmd == "ring_open":
                from ddls_tpu.envs.obs import ObsWriter
                from ddls_tpu.rl.shm import RingAttachment

                if ring_attachment is not None:
                    ring_attachment.close()
                if attachment is not None:
                    # retire the pre-ring slab mapping (the parent
                    # unlinks it at first lease; keeping the mmap would
                    # pin the memory for the worker's lifetime) — and a
                    # stale bare-row dest after ring install now fails
                    # loudly instead of writing a retired slab
                    attachment.close()
                    attachment = None
                ring_attachment = RingAttachment(payload)
                v0 = ring_attachment.views_for(0)
                writer = ObsWriter(v0["node_features"].shape[2],
                                   v0["edge_features"].shape[2])
                conn.send(("ok", None))
            elif cmd == "step":
                if isinstance(payload, tuple):
                    action, dest_row = payload
                else:
                    action, dest_row = payload, None
                obs, reward, done, _ = env.step(int(action))
                episode_return += reward
                episode_length += 1
                record = None
                if done:
                    record = harvest_episode_record(
                        env, env_index, episode_return, episode_length)
                    seed += seed_stride
                    obs = env.reset(seed=seed)
                    episode_return, episode_length = 0.0, 0
                if isinstance(dest_row, tuple):
                    seg, row = dest_row
                    writer.write(obs, {k: v[row, env_index]
                                       for k, v in
                                       ring_attachment.views_for(
                                           seg).items()})
                    conn.send(("step", (float(reward), bool(done), record)))
                elif attachment is not None and dest_row is not None:
                    writer.write(obs, {k: v[dest_row, env_index]
                                       for k, v in
                                       attachment.views.items()})
                    conn.send(("step", (float(reward), bool(done), record)))
                else:
                    conn.send(("step",
                               (obs, float(reward), bool(done), record)))
            elif cmd == "close":
                # telemetry: counters only (cross-process histogram merge
                # is lossy, and the sim layer records nothing but
                # counters); flight: the full event trace, merged
                # parent-side with this worker's env-index tag
                counters = telemetry.snapshot().get("counters") or None
                trace = flight.drain() if flight.enabled() else None
                conn.send(("closed", {"counters": counters,
                                      "flight": trace}))
                return
    except KeyboardInterrupt:
        pass
    except Exception as e:  # surface worker crashes to the parent
        import traceback
        conn.send(("error", f"{e}\n{traceback.format_exc()}"))
    finally:
        if attachment is not None:
            attachment.close()
        if ring_attachment is not None:
            ring_attachment.close()


class _LazyObsList:
    """Sequence facade over a shm-backend env's per-env obs dicts: the
    ``step()`` return value materialises slab copies only if someone
    actually indexes/iterates it (the PPO/IMPALA hot paths ignore the
    obs return entirely — paying B copies per step there would undo the
    zero-copy win)."""

    def __init__(self, env):
        self._env = env

    def __len__(self):
        return self._env.num_envs

    def __getitem__(self, i):
        return self._env.obs[i]

    def __iter__(self):
        return iter(self._env.obs)


class ParallelVectorEnv:
    """B environment instances stepped in B subprocesses.

    Same interface as ``VectorEnv``. Env construction arguments must be
    picklable (builder callable + kwargs dict), since workers are spawned
    fresh — which also keeps the TPU runtime out of the children (only the
    parent process touches jax).

    ``backend`` selects the obs transport:

    * ``"pipe"`` (default — the seed's exact semantics): workers pickle
      the full padded obs over the control pipe every step;
    * ``"shm"``: workers write each obs once, in place, into per-field
      shared-memory slabs (rl/shm.py) and the pipe carries only the
      (reward, done, record) ready flag. ``stacked_obs()`` then returns
      VIEWS of the slab (valid until the next ``step``/``reset``;
      ``.obs`` materialises per-env copies on access), and
      ``ensure_traj_rows(T + 1)`` grows the slabs so the deferred-fetch
      collector's trajectory is the slab itself — the worker's write IS
      the traj-buffer write. Bit-identical outputs to ``pipe`` (obs,
      rewards, dones, episode-record content and order) for the same
      seeds — pinned by tests/test_shm.py;
    * ``"auto"``: ``shm`` where POSIX shared memory is usable, else
      ``pipe``.
    """

    def __init__(self, env_builder: Callable[..., Any],
                 env_kwargs: Dict[str, Any], num_envs: int,
                 seeds: Optional[List[int]] = None,
                 start_method: str = "spawn",
                 backend: str = "pipe"):
        from ddls_tpu.rl.shm import shm_available

        if backend == "auto":
            backend = "shm" if shm_available() else "pipe"
        if backend not in ("pipe", "shm"):
            raise ValueError(f"backend must be 'pipe', 'shm' or 'auto', "
                             f"got {backend!r}")
        if backend == "shm" and not shm_available():
            import warnings

            warnings.warn("POSIX shared memory unavailable; "
                          "ParallelVectorEnv falling back to the pipe "
                          "backend")
            backend = "pipe"
        self.backend = backend
        self.num_envs = num_envs
        self.seeds = seeds or list(range(num_envs))
        # opt-in (the pipelined collector sets it): full-batch step()
        # receives worker replies OUT OF ORDER as they finish and writes
        # each obs row straight into a stacked [B, ...] batch, so the
        # next sample's input assembles while slower workers still step
        # — the stacking cost rides inside the env wall instead of after
        # it. Off by default so the sequential loop keeps the seed's
        # exact cost profile for load-controlled comparisons. (The shm
        # backend subsumes it: stacked_obs IS the slab.)
        self.prefetch_stacked = False
        self._stacked_cache: Optional[Dict[str, np.ndarray]] = None
        self._stacked_bufs: Optional[Dict[str, np.ndarray]] = None
        # shm-backend state: slabs are allocated lazily at the first
        # reset (field shapes come from a real obs), row 0 holds the
        # current obs until ensure_traj_rows grows the slab — or
        # ensure_traj_ring replaces it with a K-segment trajectory ring
        # (rl/ring.py), after which _slabs tracks the ACTIVE segment's
        # slab set and _active_seg its ring index (None = single slab)
        self._slabs = None
        self._ring = None
        self._active_seg = None
        self._field_specs = None
        self._cur_row = 0
        self._obs_list: List[Dict[str, np.ndarray]] = []
        self._obs_cache: Optional[List[Dict[str, np.ndarray]]] = None
        self._extra_obs: Optional[List[Dict[str, np.ndarray]]] = None
        self._obs_nbytes = 0
        # bounded step wait: a wedged worker raises instead of hanging
        # collection forever (a DEAD worker is detected immediately via
        # pipe EOF, no timeout needed)
        self.step_timeout_s = 300.0
        self._closed = False
        ctx = mp.get_context(start_method)
        self._conns = []
        self._procs = []
        for i in range(num_envs):
            parent, child = ctx.Pipe()
            proc = ctx.Process(
                target=_parallel_env_worker,
                args=(child, env_builder, env_kwargs, i, self.seeds[i],
                      num_envs, telemetry.enabled(),
                      (flight.enabled(), flight.detail_enabled())),
                daemon=True)
            proc.start()
            child.close()
            self._conns.append(parent)
            self._procs.append(proc)
        self.completed_episodes: List[Dict[str, Any]] = []
        self._first_reset = True

    # ------------------------------------------------------------- obs views
    @property
    def obs(self) -> List[Dict[str, np.ndarray]]:
        """Per-env obs dicts. Pipe backend: the worker-sent dicts. Shm
        backend: copies materialised from the slab on access (cached
        until the next step) plus the reset-time non-slab fields
        (``action_set`` — episode-constant by the encode contract); the
        copies stay valid across later steps, so replay-style consumers
        (the DQN loop's ``prev_obs``) are safe."""
        if self._slabs is None:
            return self._obs_list
        if self._obs_cache is None:
            row = self._cur_row
            views = self._slabs.views
            extra = self._extra_obs or [{}] * self.num_envs
            self._obs_cache = [
                {**extra[i],
                 **{k: np.array(views[k][row, i]) for k in OBS_KEYS}}
                for i in range(self.num_envs)]
        return self._obs_cache

    @obs.setter
    def obs(self, value) -> None:
        self._obs_list = list(value)
        self._obs_cache = self._obs_list if self._slabs is not None else None

    def _send(self, i: int, msg) -> None:
        """Guarded dispatch: a worker that died before this command
        surfaces as a clear error instead of an unhandled
        BrokenPipeError (the kill-a-worker hardening path)."""
        try:
            self._conns[i].send(msg)
        except (BrokenPipeError, OSError):
            exitcode = self._procs[i].exitcode
            self.close()
            raise RuntimeError(
                f"env worker {i} died (exitcode {exitcode}) — cannot "
                f"dispatch {msg[0]!r}") from None

    def _recv(self, conn) -> Tuple[str, Any]:
        i = self._conns.index(conn)
        if not conn.poll(self.step_timeout_s):
            self.close()
            raise RuntimeError(
                f"env worker {i} did not reply within "
                f"{self.step_timeout_s:.0f}s (wedged worker?)")
        try:
            kind, payload = conn.recv()
        except (EOFError, ConnectionResetError, OSError):
            exitcode = self._procs[i].exitcode
            self.close()
            raise RuntimeError(
                f"env worker {i} died (exitcode {exitcode}) — pipe "
                f"closed before its reply") from None
        if kind == "error":
            self.close()
            raise RuntimeError(f"env worker failed:\n{payload}")
        return kind, payload

    def _drain_step_replies(self, on_reply) -> None:
        """One step reply per worker, consumed OUT OF ORDER as workers
        finish, under the bounded ``step_timeout_s`` deadline —
        ``on_reply(i, payload)`` handles each. The single drain loop
        shared by the shm and pipe-prefetch step paths, so the
        dead-worker (pipe EOF) and wedged-worker (deadline) handling
        can never diverge between transports."""
        from multiprocessing import connection as mp_connection

        remaining = {conn: i for i, conn in enumerate(self._conns)}
        deadline = time.monotonic() + self.step_timeout_s
        while remaining:
            ready = mp_connection.wait(
                list(remaining), timeout=max(deadline - time.monotonic(),
                                             0.0))
            if not ready:
                stuck = sorted(remaining.values())
                self.close()
                raise RuntimeError(
                    f"env workers {stuck} did not reply within "
                    f"{self.step_timeout_s:.0f}s (wedged worker?)")
            for conn in ready:
                i = remaining.pop(conn)
                try:
                    kind, payload = conn.recv()
                except (EOFError, ConnectionResetError, OSError):
                    exitcode = self._procs[i].exitcode
                    self.close()
                    raise RuntimeError(
                        f"env worker {i} died mid-step (exitcode "
                        f"{exitcode})") from None
                if kind == "error":
                    self.close()
                    raise RuntimeError(f"env worker failed:\n{payload}")
                on_reply(i, payload)

    # ---------------------------------------------------------- shm plumbing
    def _setup_slabs(self, obs: List[Dict[str, np.ndarray]]) -> None:
        """First-reset slab allocation: field shapes/dtypes come from the
        first worker's obs (all workers must agree — i.e. the env pads to
        fixed bounds); on any failure the env falls back to pipe
        permanently rather than crash training."""
        from ddls_tpu.rl import shm as shm_mod

        try:
            fields = shm_mod.obs_field_specs(obs[0], OBS_KEYS)
            for j, o in enumerate(obs[1:], start=1):
                other = shm_mod.obs_field_specs(o, OBS_KEYS)
                if other != fields:
                    raise ValueError(
                        f"env {j} obs shapes {other} differ from env 0's "
                        f"{fields} (shm needs fixed pad bounds)")
            slabs = shm_mod.SlabSet(fields, rows=1, num_envs=self.num_envs)
        except Exception as e:
            import warnings

            warnings.warn(f"shm backend unusable for this env ({e}); "
                          "falling back to pipe")
            self.backend = "pipe"
            return
        self._field_specs = fields
        self._install_slabs(slabs)
        # non-slab obs fields (action_set) are episode-constant; captured
        # at reset and reattached to materialised obs copies
        self._extra_obs = [{k: np.asarray(v) for k, v in o.items()
                            if k not in OBS_KEYS} for o in obs]

    def _install_slabs(self, slabs) -> None:
        """Broadcast the slab spec and wait for every worker's attach ack
        (after which step replies stop carrying obs payloads)."""
        with telemetry.span("rollout.shm.setup"):
            spec = slabs.spec()
            for i in range(self.num_envs):
                self._send(i, ("shm_open", spec))
            for conn in self._conns:
                self._recv(conn)
        self._slabs = slabs
        self._cur_row = 0
        self._obs_nbytes = slabs.obs_nbytes

    def _guard_ring_write(self, what: str) -> None:
        """Loud ledger guard shared by the parent-side write paths
        (reset/restart row-0 writes, full-batch stepping): writing the
        active segment while it is PUBLISHED would corrupt a batch the
        learner may still be reading. Ready release tokens are swept
        first, so a segment whose consumer already finished never
        false-positives."""
        if self._ring is None or self._active_seg is None:
            return
        self._ring.sweep()  # release anything whose token is ready
        seg = self._ring.segments[self._active_seg]
        if seg.state == "published":
            raise RuntimeError(
                f"{what} would write ring segment {seg.index}, which is "
                "PUBLISHED (owned by the learner until its release "
                "token fires) — settle the in-flight update (or release "
                "the segment) first")

    def _write_row0(self, obs: List[Dict[str, np.ndarray]]) -> None:
        self._guard_ring_write("reset/restart row-0 write")
        views = self._slabs.views
        for k in OBS_KEYS:
            for i in range(self.num_envs):
                views[k][0, i] = obs[i][k]
        self._cur_row = 0

    def ensure_traj_rows(self, rows: int) -> bool:
        """Grow the obs slabs to ``[rows, B, ...]`` so a [T, B] collector
        can treat rows ``[0:T]`` as its trajectory buffer (row t = the obs
        BEFORE step t; the final row = the bootstrap obs). Returns True
        when the slab-trajectory contract is in force. No-op (False) on
        the pipe backend."""
        if self._slabs is None:
            return False
        if self._ring is not None:
            # a ring-backed env must stay on the ring: the single-slab
            # contract would treat the ACTIVE ring segment as a private
            # slab and rewrite rows the ledger may have handed to the
            # learner (a silent fallback is exactly what the ring's
            # loud-violation contract forbids)
            raise RuntimeError(
                "ensure_traj_rows on a ring-backed env — this env's "
                "trajectory transport is the ring (ensure_traj_ring); "
                "build a separate vec env for single-slab collection")
        if self._slabs.rows >= rows:
            return True
        current = self.obs  # materialise from the OLD slab first
        old = self._slabs
        try:
            from ddls_tpu.rl.shm import SlabSet

            slabs = SlabSet(self._field_specs, rows=rows,
                            num_envs=self.num_envs)
        except Exception as e:
            import warnings

            warnings.warn(f"could not grow shm slabs to {rows} rows "
                          f"({e}); keeping per-step slab")
            return False
        self._install_slabs(slabs)
        self._write_row0(current)
        self._obs_cache = current
        old.close()
        return True

    def rebase_row0(self) -> None:
        """Move the current obs to slab row 0 (one [B, ...] copy per
        field, once per segment) so the next T steps write rows 1..T."""
        if self._slabs is None or self._cur_row == 0:
            return
        views = self._slabs.views
        for k in OBS_KEYS:
            views[k][0] = views[k][self._cur_row]
        self._cur_row = 0
        self._obs_cache = None

    # ------------------------------------------------------ trajectory ring
    @property
    def traj_ring(self):
        """The installed trajectory ring (rl/ring.py), or None."""
        return self._ring

    def ensure_traj_ring(self, rows: int, segments: int):
        """Install (or return) a ``segments``-way trajectory ring of
        ``[rows, B, ...]`` slabs (rl/ring.py) — the multi-segment
        generalisation of ``ensure_traj_rows``. Returns the ring, or
        None on the pipe backend / allocation failure (callers fall
        back to the single-slab path). Idempotent while the requested
        shape fits the installed ring."""
        if self._slabs is None:
            return None
        if self._ring is not None:
            if (self._ring.rows >= rows
                    and len(self._ring.segments) >= segments):
                return self._ring
            # a silent fallback here would route collection onto the
            # single-slab path while the active slab is still a ring
            # segment the learner may own — ledger-violating writes,
            # exactly what the contract promises can't happen. Loud by
            # design (as ring-lease timeouts are).
            raise RuntimeError(
                f"trajectory ring shape change mid-run: installed "
                f"[{self._ring.rows} rows x "
                f"{len(self._ring.segments)} segments], requested "
                f"[{rows} x {segments}] — build a fresh vec env for a "
                "different rollout length or pipeline depth")
        try:
            from ddls_tpu.rl.ring import TrajRing

            ring = TrajRing(self._field_specs, rows=rows,
                            num_envs=self.num_envs, segments=segments)
        except Exception as e:
            import warnings

            warnings.warn(f"could not allocate a {segments}-segment "
                          f"trajectory ring ({e}); keeping the single "
                          "slab")
            return None
        with telemetry.span("rollout.ring.setup"):
            specs = ring.specs()
            for i in range(self.num_envs):
                self._send(i, ("ring_open", specs))
            for conn in self._conns:
                self._recv(conn)
        self._ring = ring
        return ring

    def begin_ring_segment(self, segment) -> None:
        """Point collection at a freshly-leased ring segment: the
        current obs (the previous segment's bootstrap row — or the
        pre-ring slab's current row on the first lease) is copied into
        the new segment's row 0, the one [B, ...]-per-field copy that
        ``rebase_row0`` pays on the single slab. The previous segment
        is only READ here, which every ledger state permits."""
        prev, prev_row = self._slabs, self._cur_row
        views = segment.views
        if prev is not segment.slabs or prev_row != 0:
            for k in OBS_KEYS:
                views[k][0] = prev.views[k][prev_row]
        if self._active_seg is None and prev is not segment.slabs:
            # first lease: the pre-ring current-obs slab is retired (its
            # unlink frees the name now; workers' live mappings die with
            # them — they will only ever be pointed at ring segments)
            prev.close()
        self._slabs = segment.slabs
        self._active_seg = segment.index
        self._cur_row = 0
        self._obs_cache = None
        self._stacked_cache = None

    def traj_obs_views(self, T: int) -> Dict[str, np.ndarray]:
        """Slab rows [0:T] as the trajectory obs — zero-copy views, valid
        until the next ``rebase_row0``/``reset`` overwrites row 0 (i.e.
        until the next collect segment begins)."""
        return {k: self._slabs.views[k][:T] for k in OBS_KEYS}

    def reset(self) -> List[Dict[str, np.ndarray]]:
        # seeds live worker-side (advanced on every auto-reset); only the
        # first reset pins them, later resets continue each worker's sequence
        payload = self.seeds if self._first_reset else [None] * self.num_envs
        self._first_reset = False
        self._stacked_cache = None
        for i, seed in enumerate(payload):
            self._send(i, ("reset", seed))
        obs = [self._recv(conn)[1] for conn in self._conns]
        self.obs = obs
        if self.backend == "shm" and self._slabs is None:
            self._setup_slabs(obs)
        if self._slabs is not None:
            self._write_row0(obs)
            self._obs_cache = obs
        if not self._obs_nbytes:
            # per-env obs bytes (the unit of the bytes-copied counters),
            # valid for both transports once shapes are known
            self._obs_nbytes = sum(int(np.asarray(obs[0][k]).nbytes)
                                   for k in OBS_KEYS)
        return self.obs

    def stacked_obs(self) -> Dict[str, np.ndarray]:
        """The current obs as one [B, ...] batch. Shm backend: VIEWS of
        the slab row the workers wrote in place — no copy at all (valid
        until the next ``step``/``reset``). Pipe backend with
        ``prefetch_stacked``: the batch was already assembled inside the
        previous ``step()`` as worker replies arrived (bit-identical to
        ``stack_obs(self.obs)``, measured earlier)."""
        if self._slabs is not None:
            row = self._cur_row
            return {k: self._slabs.views[k][row] for k in OBS_KEYS}
        if self._stacked_cache is not None:
            return self._stacked_cache
        stacked = stack_obs(self.obs)
        if telemetry.enabled():
            telemetry.inc("rollout.obs.bytes_stack",
                          sum(v.nbytes for v in stacked.values()))
        return stacked

    def step(self, actions: np.ndarray):
        if self._slabs is not None:
            return self._step_shm(actions)
        if self.prefetch_stacked:
            return self._step_prefetch(actions)
        return self.step_subset(range(self.num_envs), actions)

    def _step_shm(self, actions: np.ndarray):
        """Full-batch step over the slab transport: obs rows are written
        worker-side (each write is the ONLY materialisation of that obs),
        replies carry (reward, done, record) and arrive out of order —
        the reply is the per-worker ready flag; episode records flush in
        env-index order, matching the pipe paths bit-for-bit."""
        if self._ring is not None and self._active_seg is None:
            # workers retired their pre-ring slab mapping at ring_open;
            # stepping before the first begin_ring_segment would write
            # nowhere the parent reads — surface it, loudly
            raise RuntimeError(
                "trajectory ring installed but no segment is active — "
                "lease a segment and call begin_ring_segment() before "
                "stepping")
        # stepping outside the lease cycle (a direct vec.step() between
        # collects) must not rewrite a learner-owned segment either
        self._guard_ring_write("step")
        R = self._slabs.rows
        dest = self._cur_row if R == 1 else min(self._cur_row + 1, R - 1)
        payload_dest = (dest if self._active_seg is None
                        else (self._active_seg, dest))
        for i in range(self.num_envs):
            self._send(i, ("step", (int(actions[i]), payload_dest)))
        B = self.num_envs
        rewards = np.zeros(B, dtype=np.float32)
        dones = np.zeros(B, dtype=bool)
        records: Dict[int, dict] = {}

        def on_reply(i, payload):
            reward, done, record = payload
            rewards[i] = reward
            dones[i] = done
            if record is not None:
                records[i] = record

        self._drain_step_replies(on_reply)
        self._cur_row = dest
        self._obs_cache = None
        self.completed_episodes.extend(records[i] for i in sorted(records))
        if telemetry.enabled():
            telemetry.inc("rollout.ipc.replies", B)
            telemetry.inc("rollout.obs.bytes_slab", self._obs_nbytes * B)
        return _LazyObsList(self), rewards, dones

    def _step_prefetch(self, actions: np.ndarray):
        """Full-batch step with out-of-order reply handling: each worker's
        obs row lands in a fresh stacked batch the moment it arrives, so
        stacking overlaps the stragglers' env stepping. Outputs (obs,
        rewards, dones, episode-record order) are bit-identical to the
        in-order path — records are flushed in env-index order."""
        for i in range(self.num_envs):
            self._send(i, ("step", int(actions[i])))
        B = self.num_envs
        rewards = np.zeros(B, dtype=np.float32)
        dones = np.zeros(B, dtype=bool)
        records: Dict[int, dict] = {}
        state = {"stacked": None}

        def on_reply(i, payload):
            obs, reward, done, record = payload
            self.obs[i] = obs
            stacked = state["stacked"]
            if stacked is None:
                # reuse the previous step's assembly buffers (valid-
                # until-next-step contract, same as stacked_obs)
                stacked = self._stacked_bufs
                if stacked is None or any(
                        stacked[k].shape[1:] != np.asarray(obs[k]).shape
                        or stacked[k].dtype != np.asarray(obs[k]).dtype
                        for k in OBS_KEYS):
                    stacked = {
                        k: np.empty((B,) + np.asarray(obs[k]).shape,
                                    np.asarray(obs[k]).dtype)
                        for k in OBS_KEYS}
                self._stacked_bufs = state["stacked"] = stacked
            for k in OBS_KEYS:
                stacked[k][i] = obs[k]
            rewards[i] = reward
            dones[i] = done
            if record is not None:
                records[i] = record

        self._drain_step_replies(on_reply)
        self.completed_episodes.extend(
            records[i] for i in sorted(records))
        self._stacked_cache = state["stacked"]
        if telemetry.enabled():
            telemetry.inc("rollout.ipc.replies", B)
            telemetry.inc("rollout.obs.bytes_pipe", self._obs_nbytes * B)
            telemetry.inc("rollout.obs.bytes_stack", self._obs_nbytes * B)
        return list(self.obs), rewards, dones

    def step_subset(self, indices, actions: np.ndarray):
        """Step only the workers in ``indices``; see VectorEnv.step_subset.
        On the shm backend a partial subset rides the pipe (obs payload)
        and the parent refreshes the CURRENT slab row in place — subset
        stepping is the split-batch pipelined collector's path, which
        never runs under the slab-trajectory contract."""
        indices = list(indices)
        self._stacked_cache = None
        for k, i in enumerate(indices):
            self._send(i, ("step", int(actions[k])))
        rewards = np.zeros(len(indices), dtype=np.float32)
        dones = np.zeros(len(indices), dtype=bool)
        for k, i in enumerate(indices):
            _, (obs, reward, done, record) = self._recv(self._conns[i])
            if self._slabs is not None:
                views = self._slabs.views
                for key in OBS_KEYS:
                    views[key][self._cur_row, i] = obs[key]
                self._obs_cache = None
            else:
                self.obs[i] = obs
            rewards[k] = reward
            dones[k] = done
            if record is not None:
                self.completed_episodes.append(record)
        if telemetry.enabled():
            telemetry.inc("rollout.ipc.replies", len(indices))
            telemetry.inc("rollout.obs.bytes_pipe",
                          self._obs_nbytes * len(indices))
        return [self.obs[i] for i in indices], rewards, dones

    def drain_completed_episodes(self) -> List[Dict[str, Any]]:
        out, self.completed_episodes = self.completed_episodes, []
        return out

    def restart_episodes(self) -> List[Dict[str, np.ndarray]]:
        """See VectorEnv.restart_episodes: workers advance their own seeds
        on the dedicated restart command and drop partial accumulators."""
        if self._first_reset:
            return self.reset()
        self._stacked_cache = None
        for i in range(self.num_envs):
            self._send(i, ("restart", None))
        obs = [self._recv(conn)[1] for conn in self._conns]
        self.obs = obs
        if self._slabs is not None:
            self._write_row0(obs)
            self._obs_cache = obs
        return self.obs

    def close(self) -> None:
        """Idempotent shutdown: close acks drained under one shared
        deadline, workers join-escalated (join -> terminate -> kill) so a
        wedged worker can never hang teardown, and the shm slabs are
        unlinked last (their finalizer covers paths that never reach
        here)."""
        if self._closed:
            return
        self._closed = True
        for conn in self._conns:
            try:
                conn.send(("close", None))
            except (BrokenPipeError, OSError):
                pass
        # drain to each worker's "closed" ack (stale step replies may sit
        # ahead of it when closing after a worker error) and merge the
        # worker's telemetry counters into this process's registry. One
        # SHARED 2 s deadline across all conns: a wedged worker must not
        # serially cost 2 s per env on the failure-path teardown (the
        # join/terminate below still reaps it). With the flight recorder
        # on, the ack carries each worker's full event trace — give the
        # drain real room so a long run's traces are not silently cut
        # off mid-merge by the teardown budget
        deadline = time.monotonic() + (30.0 if flight.enabled() else 2.0)
        for i, conn in enumerate(self._conns):
            try:
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not conn.poll(remaining):
                        break
                    kind, payload = conn.recv()
                    if kind == "closed":
                        payload = payload or {}
                        counters = payload.get("counters")
                        if counters and telemetry.enabled():
                            for name, value in counters.items():
                                telemetry.inc(name, int(value))
                        trace = payload.get("flight")
                        if trace and flight.enabled():
                            flight.extend(trace, env_index=i)
                        break
            except (EOFError, BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=5)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=2)
            if proc.is_alive():  # terminate ignored (blocked in syscall)
                proc.kill()
                proc.join(timeout=1)
        if self._ring is not None:
            if self._active_seg is None and self._slabs is not None:
                # ring installed but never leased: the pre-ring slab
                # was not yet retired by begin_ring_segment — unlink it
                # here (the parent-unlinks-on-close contract)
                self._slabs.close()
            # unlink every ring segment (after the first lease,
            # self._slabs is one of them)
            self._ring.close()
            self._ring = None
            self._slabs = None
        if self._slabs is not None:
            self._slabs.close()
            self._slabs = None


class RolloutCollector:
    """Collects [T, B] trajectory batches for the PPO learner.

    With ``pipeline=True`` (default for an even batch of >= 2 envs) the envs
    are split into two groups and collection interleaves them: while the host
    steps group A's simulators, the device is already computing group B's
    action batch (jax dispatch is asynchronous), so the per-step device
    round-trip — significant under a tunnelled TPU — is hidden behind env
    stepping instead of serialised with it.
    """

    def __init__(self, vec_env: VectorEnv, learner, rollout_length: int,
                 pipeline: Optional[bool] = None,
                 deferred_fetch: bool = False,
                 ring_segments: Optional[int] = None):
        self.vec_env = vec_env
        self.learner = learner
        self.rollout_length = rollout_length
        B = vec_env.num_envs
        # trajectory-ring sizing (rl/ring.py): on a shm vec env the
        # deferred collector leases one [T+1, B, ...] segment per
        # collect instead of rewriting the single slab, which deletes
        # the per-segment bulk defensive copy (the PR 4 aliasing
        # hazard is handled by segment ownership: a leased segment is
        # not rewritten until its release token reports the staged
        # batch consumed). None resolves to the double-buffer minimum
        # (2) for deferred fetch; 0 forces the legacy single slab +
        # bulk copy; the depth-K pipelined loop passes depth + 2.
        if ring_segments is None:
            ring_segments = 2 if deferred_fetch else 0
        self.ring_segments = int(ring_segments)
        # deferred_fetch (the pipelined loop mode, train/loops.py): one
        # jitted program per step (rng split folded in), actions are the
        # ONLY per-step device fetch (logp/values stay device futures,
        # drained in one device_get at segment end), obs rows are copied
        # into preallocated [T, B, ...] traj buffers while the forward
        # is in flight, and every transfer is explicit
        # (device_put/device_get — pinned by the transfer-guard test).
        # Bit-identical outputs to the plain path; only the
        # dispatch/fetch schedule changes.
        self.deferred_fetch = bool(deferred_fetch)
        self._jit_step_fn = None
        # explicit staging target for the stacked obs: the learner's
        # replicated mesh sharding (where its params live), so the jitted
        # sample needs no implicit device-to-device reshard — a bare
        # device_put would commit to ONE device and trip the
        # transfer-guard pin (and a real reshard) on multi-device meshes.
        # MULTI-PROCESS: never — each process's obs are ITS OWN shard of
        # the collection, and a device_put onto the global mesh would
        # fabricate a "replicated" global array from process-divergent
        # data (mismatched collectives downstream: gloo size errors).
        # There the batch rides into the jit as host arrays, exactly as
        # the pre-round-6 collector did.
        self._obs_sharding = (getattr(learner, "_replicated", None)
                              if jax.process_count() == 1 else None)
        if self.deferred_fetch:
            pipeline = False  # deferred path has its own schedule
            if getattr(vec_env, "prefetch_stacked", None) is False:
                vec_env.prefetch_stacked = True
        if pipeline is None and (B < 2 or B % 2
                                 or jax.default_backend() == "cpu"):
            # overlap only exists when sampling runs on an accelerator; on a
            # CPU backend the device IS the host, and two half-batch calls
            # just double the sampling overhead
            pipeline = False
        # pipeline=None: decide adaptively after timing the first collect.
        # Per step, pipelined cost ~ 2*max(sample, env/2) vs non-pipelined
        # sample + env, so splitting wins exactly when sampling is cheaper
        # than env stepping — under a high-latency tunnelled TPU with fast
        # host envs, pipelining *doubles* the dominant round-trip count.
        self.pipeline = pipeline
        self._needs_reset = True

    def _step_program(self):
        """One jitted program per rollout step: rng split + sampling fused,
        so the host dispatches once instead of paying a separate
        ~ms-scale ``jax.random.split`` dispatch per step. The split tree
        is IDENTICAL to the plain path's host-side
        ``rng, step_rng = split(rng)`` followed by sampling with
        ``step_rng`` — same bits out."""
        if self._jit_step_fn is None:
            sample = self.learner._sample_actions

            def step_fn(params, obs, rng):
                rng, step_rng = jax.random.split(rng)
                actions, logp, values = sample(params, obs, step_rng)
                return rng, actions, logp, values

            self._jit_step_fn = jax.jit(step_fn)
        return self._jit_step_fn

    def _collect_deferred(self, params, rng) -> Dict[str, Any]:
        """Deferred-fetch collection (see __init__); [T, B] outputs
        bit-identical to the plain path.

        On a shm-backend vec env the workers' in-place writes ARE the
        trajectory buffer (row t = the obs before step t, row T = the
        bootstrap obs). With ``ring_segments >= 2`` (the default for
        deferred fetch) each collect leases one segment of a
        K-segment trajectory ring (rl/ring.py) and returns ZERO-COPY
        views of its rows: segment ownership — a published segment is
        not rewritten until its release token reports the staged batch
        consumed — replaces the bulk defensive copy the single slab
        needed. That copy was a correctness requirement there: jax's
        CPU client ZERO-COPY ALIASES page-aligned host buffers (shm
        mmaps are page-aligned) when a device_put/jit input needs no
        layout change — measured on a 1-device mesh — so single-slab
        views staged into the async update would be silently rewritten
        by the next segment's worker writes (``ring_segments=0`` keeps
        that legacy path: slab + bulk copy). The per-step sample inputs
        stay views on every path because each step's
        ``device_get(actions)`` completes the forward before any row it
        read is rewritten."""
        T, B = self.rollout_length, self.vec_env.num_envs
        step_fn = self._step_program()
        ring = segment = None
        if self.ring_segments >= 2:
            ensure_ring = getattr(self.vec_env, "ensure_traj_ring", None)
            if ensure_ring is not None:
                ring = ensure_ring(T + 1, self.ring_segments)
        if ring is not None:
            # lease the next free segment (counts a stall + blocks on
            # the oldest published segment's release token when the
            # learner is behind); its row 0 receives the bootstrap obs
            segment = ring.lease()
            self.vec_env.begin_ring_segment(segment)
            use_slab = True
        else:
            ensure = getattr(self.vec_env, "ensure_traj_rows", None)
            use_slab = bool(ensure is not None and ensure(T + 1))
            if use_slab:
                # carry the previous segment's bootstrap obs into row 0
                self.vec_env.rebase_row0()
        if self._obs_sharding is not None:
            # the epoch's incoming key was split outside the mesh; place
            # it next to the params explicitly (after step 0 the key is
            # step_fn's own replicated output and stays put)
            rng = jax.device_put(rng, self._obs_sharding)
        act_buf = np.zeros((T, B), dtype=np.int32)
        rew_buf = np.zeros((T, B), dtype=np.float32)
        done_buf = np.zeros((T, B), dtype=bool)
        traj_obs: Optional[Dict[str, np.ndarray]] = None
        logp_refs: List[Any] = [None] * T
        val_refs: List[Any] = [None] * T
        for t in range(T):
            batched = self.vec_env.stacked_obs()
            staged = (jax.device_put(batched, self._obs_sharding)
                      if self._obs_sharding is not None else batched)
            rng, actions, logp, values = step_fn(params, staged, rng)
            if not use_slab:
                if traj_obs is None:
                    traj_obs = {k: np.empty((T,) + batched[k].shape,
                                            batched[k].dtype)
                                for k in OBS_KEYS}
                # the copy into the traj buffers runs while the device is
                # still computing this step's forward
                for k in OBS_KEYS:
                    traj_obs[k][t] = batched[k]
                if telemetry.enabled():
                    telemetry.inc("rollout.obs.bytes_traj_copy",
                                  sum(np.asarray(batched[k]).nbytes
                                      for k in OBS_KEYS))
            actions = jax.device_get(actions)
            act_buf[t] = actions
            logp_refs[t] = logp
            val_refs[t] = values
            _, rewards, dones = self.vec_env.step(actions)
            rew_buf[t] = rewards
            done_buf[t] = dones
        if segment is not None:
            # ring path: the trajectory IS the leased segment's rows —
            # zero-copy views, safe without the bulk defensive copy
            # because the segment is not rewritten until its release
            # token (attached by the caller once the staged batch is
            # provably consumed) reports ready
            traj_obs = dict(self.vec_env.traj_obs_views(T))
        elif use_slab:
            # single-slab path: one bulk memcpy of the worker-written
            # slab rows into a fresh buffer (see docstring: staging
            # must never alias the reused slab); np.array allocates +
            # copies in one call
            views = self.vec_env.traj_obs_views(T)
            traj_obs = {k: np.array(v) for k, v in views.items()}
            if telemetry.enabled():
                telemetry.inc("rollout.obs.bytes_traj_copy",
                              sum(v.nbytes for v in traj_obs.values()))
        final = self.vec_env.stacked_obs()
        final_staged = (jax.device_put(final, self._obs_sharding)
                        if self._obs_sharding is not None else final)
        rng, _, _, last_values = step_fn(params, final_staged, rng)
        # ONE drain for every deferred future (all long since ready —
        # this is a batch of buffer copies, not a wait). It also blocks
        # on the bootstrap forward, so the staged `final` (possibly an
        # alias of the segment's bootstrap row) is consumed before the
        # segment is handed over.
        logp_host, val_host, last_host = jax.device_get(
            (logp_refs, val_refs, last_values))
        out = {
            "traj": {"obs": traj_obs, "actions": act_buf,
                     "logp": np.stack(logp_host).astype(np.float32),
                     "values": np.stack(val_host).astype(np.float32),
                     "rewards": rew_buf, "dones": done_buf},
            "last_values": np.asarray(last_host, np.float32),
            "episodes": self.vec_env.drain_completed_episodes(),
            "env_steps": T * B,
        }
        if segment is not None:
            # ownership passes to the learner; the caller MUST run the
            # two-phase token protocol (ring.note_staged/note_update —
            # train/loops.py and bench.py are the models), quoting the
            # generation so a late token can't release a recycled
            # segment
            ring.publish(segment)
            out["ring"] = ring
            out["ring_segment"] = segment
            out["ring_generation"] = segment.generation
        return out

    def collect(self, params, rng) -> Dict[str, Any]:
        """Run rollout_length steps in every env; returns a trajectory dict
        of [T, B, ...] host arrays plus bootstrap values [B]."""
        T, B = self.rollout_length, self.vec_env.num_envs
        if self._needs_reset:
            self.vec_env.reset()
            self._needs_reset = False
        if self.deferred_fetch:
            return self._collect_deferred(params, rng)
        if self.pipeline and B >= 2 and B % 2 == 0:
            return self._collect_pipelined(params, rng)

        obs_buf: List[Dict[str, np.ndarray]] = []
        act_buf = np.zeros((T, B), dtype=np.int32)
        logp_buf = np.zeros((T, B), dtype=np.float32)
        val_buf = np.zeros((T, B), dtype=np.float32)
        rew_buf = np.zeros((T, B), dtype=np.float32)
        done_buf = np.zeros((T, B), dtype=bool)

        measure = self.pipeline is None and B >= 2 and B % 2 == 0
        sample_time = env_time = 0.0
        for t in range(T):
            batched = stack_obs(self.vec_env.obs)
            rng, step_rng = jax.random.split(rng)
            # t == 0 pays jit trace+compile for sample_actions; excluding
            # it keeps the measurement at steady-state cost
            timing = measure and t > 0
            t0 = time.perf_counter() if timing else 0.0
            actions, logp, values = self.learner.sample_actions(
                params, batched, step_rng)
            actions = np.asarray(actions)
            if timing:
                sample_time += time.perf_counter() - t0
            obs_buf.append(batched)
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(values)
            t0 = time.perf_counter() if timing else 0.0
            _, rewards, dones = self.vec_env.step(actions)
            if timing:
                env_time += time.perf_counter() - t0
            rew_buf[t] = rewards
            done_buf[t] = dones
        if measure and T > 1:
            # see __init__: split-batch overlap wins iff sampling (device
            # round-trip incl. dispatch+fetch) is cheaper than env stepping
            self.pipeline = sample_time < env_time

        final = stack_obs(self.vec_env.obs)
        rng, val_rng = jax.random.split(rng)
        _, _, last_values = self.learner.sample_actions(params, final,
                                                        val_rng)

        traj_obs = {k: np.stack([o[k] for o in obs_buf])
                    for k in OBS_KEYS}
        return {
            "traj": {"obs": traj_obs, "actions": act_buf, "logp": logp_buf,
                     "values": val_buf, "rewards": rew_buf,
                     "dones": done_buf},
            "last_values": np.asarray(last_values),
            "episodes": self.vec_env.drain_completed_episodes(),
            "env_steps": T * B,
        }

    def _collect_pipelined(self, params, rng) -> Dict[str, Any]:
        """Two-group interleaved collection (see class docstring).

        Device-dispatch order per step t: sample(G0, t), sample(G1, t),
        sample(G0, t+1), ... — each half's host env stepping overlaps the
        other half's device sampling.
        """
        T, B = self.rollout_length, self.vec_env.num_envs
        H = B // 2
        groups = [list(range(H)), list(range(H, B))]

        obs_buf: List[List[Dict[str, np.ndarray]]] = [[], []]
        act_buf = np.zeros((T, B), dtype=np.int32)
        logp_buf = np.zeros((T, B), dtype=np.float32)
        val_buf = np.zeros((T, B), dtype=np.float32)
        rew_buf = np.zeros((T, B), dtype=np.float32)
        done_buf = np.zeros((T, B), dtype=bool)
        last_values = [None, None]

        def sample(g, step_rng):
            batched = stack_obs([self.vec_env.obs[i] for i in groups[g]])
            return batched, self.learner.sample_actions(params, batched,
                                                        step_rng)

        cols = [slice(0, H), slice(H, B)]
        rng, r0 = jax.random.split(rng)
        pending = [sample(0, r0), None]
        for t in range(T):
            rng, r1 = jax.random.split(rng)
            pending[1] = sample(1, r1)
            for g in (0, 1):
                batched, (actions, logp, values) = pending[g]
                actions = np.asarray(actions)  # blocks on this half only
                obs_buf[g].append(batched)
                act_buf[t, cols[g]] = actions
                logp_buf[t, cols[g]] = np.asarray(logp)
                val_buf[t, cols[g]] = np.asarray(values)
                # host steps this half while the device runs the other half's
                # (already dispatched) sampling
                _, rewards, dones = self.vec_env.step_subset(groups[g],
                                                             actions)
                rew_buf[t, cols[g]] = rewards
                done_buf[t, cols[g]] = dones
                if g == 0:
                    rng, rnext = jax.random.split(rng)
                    pending[0] = sample(0, rnext)
                    if t + 1 == T:
                        last_values[0] = pending[0][1][2]
        # group 1 bootstrap: dispatched after group 0's
        rng, rlast = jax.random.split(rng)
        last_values[1] = sample(1, rlast)[1][2]

        traj_obs = {
            k: np.concatenate(
                [np.stack([o[k] for o in obs_buf[0]]),
                 np.stack([o[k] for o in obs_buf[1]])], axis=1)
            for k in OBS_KEYS}
        return {
            "traj": {"obs": traj_obs, "actions": act_buf, "logp": logp_buf,
                     "values": val_buf, "rewards": rew_buf,
                     "dones": done_buf},
            "last_values": np.concatenate([np.asarray(last_values[0]),
                                           np.asarray(last_values[1])]),
            "episodes": self.vec_env.drain_completed_episodes(),
            "env_steps": T * B,
        }
