"""Vectorised rollout collection.

Replaces RLlib's Ray rollout workers (SURVEY.md §3.1): instead of N worker
processes each owning an environment and a policy copy, one host process
steps B environment instances, stacks their padded observations into [B, ...]
arrays, and samples all B actions in a single jitted device call
(``PPOLearner.sample_actions``). The simulator itself runs per-step on the
host (its per-job heuristic placer is sequential/combinatorial — SURVEY.md
§7.4.2); the device sees only fixed-shape batched tensors.

Environments auto-reset on episode end; completed-episode returns/lengths and
the cluster's episode stats are harvested for logging, mirroring what RLlib's
callbacks collect (ddls/environments/ramp_cluster/utils.py:25-73).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

OBS_KEYS = ("node_features", "edge_features", "graph_features",
            "edges_src", "edges_dst", "node_split", "edge_split",
            "action_mask")


def stack_obs(obs_list: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    return {k: np.stack([np.asarray(o[k]) for o in obs_list])
            for k in OBS_KEYS}


class VectorEnv:
    """B independent environment instances with auto-reset."""

    def __init__(self, env_fns: List[Callable[[], Any]],
                 seeds: Optional[List[int]] = None):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.seeds = seeds or list(range(self.num_envs))
        self.episode_returns = np.zeros(self.num_envs)
        self.episode_lengths = np.zeros(self.num_envs, dtype=np.int64)
        self.completed_episodes: List[Dict[str, Any]] = []

    def reset(self) -> List[Dict[str, np.ndarray]]:
        self.obs = [env.reset(seed=self.seeds[i])
                    for i, env in enumerate(self.envs)]
        self.episode_returns[:] = 0.0
        self.episode_lengths[:] = 0
        return self.obs

    def step(self, actions: np.ndarray):
        rewards = np.zeros(self.num_envs, dtype=np.float32)
        dones = np.zeros(self.num_envs, dtype=bool)
        for i, env in enumerate(self.envs):
            obs, reward, done, _ = env.step(int(actions[i]))
            rewards[i] = reward
            dones[i] = done
            self.episode_returns[i] += reward
            self.episode_lengths[i] += 1
            if done:
                self._harvest_episode(i, env)
                # fresh seed per episode so workload sampling differs
                self.seeds[i] += self.num_envs
                obs = env.reset(seed=self.seeds[i])
                self.episode_returns[i] = 0.0
                self.episode_lengths[i] = 0
            self.obs[i] = obs
        return self.obs, rewards, dones

    def _harvest_episode(self, i: int, env) -> None:
        record = {"env_index": i,
                  "episode_return": float(self.episode_returns[i]),
                  "episode_length": int(self.episode_lengths[i])}
        cluster = getattr(env, "cluster", None)
        if cluster is not None and getattr(cluster, "episode_stats", None):
            stats = cluster.episode_stats
            for key in ("num_jobs_arrived", "num_jobs_completed",
                        "num_jobs_blocked", "blocking_rate",
                        "acceptance_rate"):
                if key in stats:
                    record[key] = stats[key]
            for key in ("job_completion_time",
                        "job_completion_time_speedup"):
                vals = stats.get(key)
                if vals:
                    record[f"mean_{key}"] = float(np.mean(vals))
        self.completed_episodes.append(record)

    def drain_completed_episodes(self) -> List[Dict[str, Any]]:
        out, self.completed_episodes = self.completed_episodes, []
        return out


class RolloutCollector:
    """Collects [T, B] trajectory batches for the PPO learner."""

    def __init__(self, vec_env: VectorEnv, learner, rollout_length: int):
        self.vec_env = vec_env
        self.learner = learner
        self.rollout_length = rollout_length
        self._needs_reset = True

    def collect(self, params, rng) -> Dict[str, Any]:
        """Run rollout_length steps in every env; returns a trajectory dict
        of [T, B, ...] host arrays plus bootstrap values [B]."""
        T, B = self.rollout_length, self.vec_env.num_envs
        if self._needs_reset:
            self.vec_env.reset()
            self._needs_reset = False

        obs_buf: List[Dict[str, np.ndarray]] = []
        act_buf = np.zeros((T, B), dtype=np.int32)
        logp_buf = np.zeros((T, B), dtype=np.float32)
        val_buf = np.zeros((T, B), dtype=np.float32)
        rew_buf = np.zeros((T, B), dtype=np.float32)
        done_buf = np.zeros((T, B), dtype=bool)

        for t in range(T):
            batched = stack_obs(self.vec_env.obs)
            rng, step_rng = jax.random.split(rng)
            actions, logp, values = self.learner.sample_actions(
                params, batched, step_rng)
            actions = np.asarray(actions)
            obs_buf.append(batched)
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(values)
            _, rewards, dones = self.vec_env.step(actions)
            rew_buf[t] = rewards
            done_buf[t] = dones

        final = stack_obs(self.vec_env.obs)
        rng, val_rng = jax.random.split(rng)
        _, _, last_values = self.learner.sample_actions(params, final,
                                                        val_rng)

        traj_obs = {k: np.stack([o[k] for o in obs_buf])
                    for k in OBS_KEYS}
        return {
            "traj": {"obs": traj_obs, "actions": act_buf, "logp": logp_buf,
                     "values": val_buf, "rewards": rew_buf,
                     "dones": done_buf},
            "last_values": np.asarray(last_values),
            "episodes": self.vec_env.drain_completed_episodes(),
            "env_steps": T * B,
        }
