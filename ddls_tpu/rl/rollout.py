"""Vectorised rollout collection.

Replaces RLlib's Ray rollout workers (SURVEY.md §3.1): instead of N worker
processes each owning an environment and a policy copy, one host process
steps B environment instances, stacks their padded observations into [B, ...]
arrays, and samples all B actions in a single jitted device call
(``PPOLearner.sample_actions``). The simulator itself runs per-step on the
host (its per-job heuristic placer is sequential/combinatorial — SURVEY.md
§7.4.2); the device sees only fixed-shape batched tensors.

Environments auto-reset on episode end; completed-episode returns/lengths and
the cluster's episode stats are harvested for logging, mirroring what RLlib's
callbacks collect (ddls/environments/ramp_cluster/utils.py:25-73).
"""
from __future__ import annotations

import multiprocessing as mp
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

OBS_KEYS = ("node_features", "edge_features", "graph_features",
            "edges_src", "edges_dst", "node_split", "edge_split",
            "action_mask")


def stack_obs(obs_list: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    return {k: np.stack([np.asarray(o[k]) for o in obs_list])
            for k in OBS_KEYS}


def harvest_episode_record(env, env_index: int, episode_return: float,
                           episode_length: int) -> Dict[str, Any]:
    """Episode summary + the cluster's episode stats, mirroring what RLlib's
    callbacks collect (ddls/environments/ramp_cluster/utils.py:25-73)."""
    record = {"env_index": env_index,
              "episode_return": float(episode_return),
              "episode_length": int(episode_length)}
    cluster = getattr(env, "cluster", None)
    if cluster is not None and getattr(cluster, "episode_stats", None):
        stats = cluster.episode_stats
        for key in ("num_jobs_arrived", "num_jobs_completed",
                    "num_jobs_blocked", "blocking_rate",
                    "acceptance_rate"):
            if key in stats:
                record[key] = stats[key]
        for key in ("job_completion_time",
                    "job_completion_time_speedup"):
            vals = stats.get(key)
            if vals:
                record[f"mean_{key}"] = float(np.mean(vals))
    return record


class VectorEnv:
    """B independent environment instances with auto-reset."""

    def __init__(self, env_fns: List[Callable[[], Any]],
                 seeds: Optional[List[int]] = None):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.seeds = seeds or list(range(self.num_envs))
        self.episode_returns = np.zeros(self.num_envs)
        self.episode_lengths = np.zeros(self.num_envs, dtype=np.int64)
        self.completed_episodes: List[Dict[str, Any]] = []

    def reset(self) -> List[Dict[str, np.ndarray]]:
        self.obs = [env.reset(seed=self.seeds[i])
                    for i, env in enumerate(self.envs)]
        self.episode_returns[:] = 0.0
        self.episode_lengths[:] = 0
        return self.obs

    def step(self, actions: np.ndarray):
        rewards = np.zeros(self.num_envs, dtype=np.float32)
        dones = np.zeros(self.num_envs, dtype=bool)
        for i, env in enumerate(self.envs):
            obs, reward, done, _ = env.step(int(actions[i]))
            rewards[i] = reward
            dones[i] = done
            self.episode_returns[i] += reward
            self.episode_lengths[i] += 1
            if done:
                self._harvest_episode(i, env)
                # fresh seed per episode so workload sampling differs
                self.seeds[i] += self.num_envs
                obs = env.reset(seed=self.seeds[i])
                self.episode_returns[i] = 0.0
                self.episode_lengths[i] = 0
            self.obs[i] = obs
        return self.obs, rewards, dones

    def _harvest_episode(self, i: int, env) -> None:
        self.completed_episodes.append(harvest_episode_record(
            env, i, self.episode_returns[i], self.episode_lengths[i]))

    def drain_completed_episodes(self) -> List[Dict[str, Any]]:
        out, self.completed_episodes = self.completed_episodes, []
        return out

    def close(self) -> None:
        pass


def _parallel_env_worker(conn, env_builder, env_kwargs: Dict[str, Any],
                         env_index: int, seed: int, seed_stride: int) -> None:
    """Subprocess body: owns one env, steps it on command, auto-resets.

    ``env_builder`` is a picklable callable (class or factory) receiving
    ``**env_kwargs`` — the process-parallel replacement for RLlib's Ray
    rollout workers, each of which builds its own env from the env_config
    (SURVEY.md §3.1 process-boundary note).
    """
    try:
        env = env_builder(**env_kwargs)
        episode_return, episode_length = 0.0, 0
        while True:
            cmd, payload = conn.recv()
            if cmd == "reset":
                seed = payload if payload is not None else seed
                obs = env.reset(seed=seed)
                episode_return, episode_length = 0.0, 0
                conn.send(("obs", obs))
            elif cmd == "step":
                obs, reward, done, _ = env.step(int(payload))
                episode_return += reward
                episode_length += 1
                record = None
                if done:
                    record = harvest_episode_record(
                        env, env_index, episode_return, episode_length)
                    seed += seed_stride
                    obs = env.reset(seed=seed)
                    episode_return, episode_length = 0.0, 0
                conn.send(("step", (obs, float(reward), bool(done), record)))
            elif cmd == "close":
                conn.send(("closed", None))
                return
    except KeyboardInterrupt:
        pass
    except Exception as e:  # surface worker crashes to the parent
        import traceback
        conn.send(("error", f"{e}\n{traceback.format_exc()}"))


class ParallelVectorEnv:
    """B environment instances stepped in B subprocesses.

    Same interface as ``VectorEnv``. Env construction arguments must be
    picklable (builder callable + kwargs dict), since workers are spawned
    fresh — which also keeps the TPU runtime out of the children (only the
    parent process touches jax).
    """

    def __init__(self, env_builder: Callable[..., Any],
                 env_kwargs: Dict[str, Any], num_envs: int,
                 seeds: Optional[List[int]] = None,
                 start_method: str = "spawn"):
        self.num_envs = num_envs
        self.seeds = seeds or list(range(num_envs))
        ctx = mp.get_context(start_method)
        self._conns = []
        self._procs = []
        for i in range(num_envs):
            parent, child = ctx.Pipe()
            proc = ctx.Process(
                target=_parallel_env_worker,
                args=(child, env_builder, env_kwargs, i, self.seeds[i],
                      num_envs),
                daemon=True)
            proc.start()
            child.close()
            self._conns.append(parent)
            self._procs.append(proc)
        self.completed_episodes: List[Dict[str, Any]] = []
        self.obs: List[Dict[str, np.ndarray]] = []
        self._first_reset = True

    def _recv(self, conn) -> Tuple[str, Any]:
        kind, payload = conn.recv()
        if kind == "error":
            self.close()
            raise RuntimeError(f"env worker failed:\n{payload}")
        return kind, payload

    def reset(self) -> List[Dict[str, np.ndarray]]:
        # seeds live worker-side (advanced on every auto-reset); only the
        # first reset pins them, later resets continue each worker's sequence
        payload = self.seeds if self._first_reset else [None] * self.num_envs
        self._first_reset = False
        for conn, seed in zip(self._conns, payload):
            conn.send(("reset", seed))
        self.obs = [self._recv(conn)[1] for conn in self._conns]
        return self.obs

    def step(self, actions: np.ndarray):
        for conn, action in zip(self._conns, actions):
            conn.send(("step", int(action)))
        rewards = np.zeros(self.num_envs, dtype=np.float32)
        dones = np.zeros(self.num_envs, dtype=bool)
        for i, conn in enumerate(self._conns):
            _, (obs, reward, done, record) = self._recv(conn)
            self.obs[i] = obs
            rewards[i] = reward
            dones[i] = done
            if record is not None:
                self.completed_episodes.append(record)
        return self.obs, rewards, dones

    def drain_completed_episodes(self) -> List[Dict[str, Any]]:
        out, self.completed_episodes = self.completed_episodes, []
        return out

    def close(self) -> None:
        for conn in self._conns:
            try:
                conn.send(("close", None))
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=5)
            if proc.is_alive():
                proc.terminate()


class RolloutCollector:
    """Collects [T, B] trajectory batches for the PPO learner."""

    def __init__(self, vec_env: VectorEnv, learner, rollout_length: int):
        self.vec_env = vec_env
        self.learner = learner
        self.rollout_length = rollout_length
        self._needs_reset = True

    def collect(self, params, rng) -> Dict[str, Any]:
        """Run rollout_length steps in every env; returns a trajectory dict
        of [T, B, ...] host arrays plus bootstrap values [B]."""
        T, B = self.rollout_length, self.vec_env.num_envs
        if self._needs_reset:
            self.vec_env.reset()
            self._needs_reset = False

        obs_buf: List[Dict[str, np.ndarray]] = []
        act_buf = np.zeros((T, B), dtype=np.int32)
        logp_buf = np.zeros((T, B), dtype=np.float32)
        val_buf = np.zeros((T, B), dtype=np.float32)
        rew_buf = np.zeros((T, B), dtype=np.float32)
        done_buf = np.zeros((T, B), dtype=bool)

        for t in range(T):
            batched = stack_obs(self.vec_env.obs)
            rng, step_rng = jax.random.split(rng)
            actions, logp, values = self.learner.sample_actions(
                params, batched, step_rng)
            actions = np.asarray(actions)
            obs_buf.append(batched)
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(values)
            _, rewards, dones = self.vec_env.step(actions)
            rew_buf[t] = rewards
            done_buf[t] = dones

        final = stack_obs(self.vec_env.obs)
        rng, val_rng = jax.random.split(rng)
        _, _, last_values = self.learner.sample_actions(params, final,
                                                        val_rng)

        traj_obs = {k: np.stack([o[k] for o in obs_buf])
                    for k in OBS_KEYS}
        return {
            "traj": {"obs": traj_obs, "actions": act_buf, "logp": logp_buf,
                     "values": val_buf, "rewards": rew_buf,
                     "dones": done_buf},
            "last_values": np.asarray(last_values),
            "episodes": self.vec_env.drain_completed_episodes(),
            "env_steps": T * B,
        }
