"""Vectorised rollout collection.

Replaces RLlib's Ray rollout workers (SURVEY.md §3.1): instead of N worker
processes each owning an environment and a policy copy, one host process
steps B environment instances, stacks their padded observations into [B, ...]
arrays, and samples all B actions in a single jitted device call
(``PPOLearner.sample_actions``). The simulator itself runs per-step on the
host (its per-job heuristic placer is sequential/combinatorial — SURVEY.md
§7.4.2); the device sees only fixed-shape batched tensors.

Environments auto-reset on episode end; completed-episode returns/lengths and
the cluster's episode stats are harvested for logging, mirroring what RLlib's
callbacks collect (ddls/environments/ramp_cluster/utils.py:25-73).
"""
from __future__ import annotations

import multiprocessing as mp
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ddls_tpu import telemetry

OBS_KEYS = ("node_features", "edge_features", "graph_features",
            "edges_src", "edges_dst", "node_split", "edge_split",
            "action_mask")


def stack_obs(obs_list: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    return {k: np.stack([np.asarray(o[k]) for o in obs_list])
            for k in OBS_KEYS}


def harvest_episode_record(env, env_index: int, episode_return: float,
                           episode_length: int) -> Dict[str, Any]:
    """Episode summary + the cluster's episode stats, mirroring what RLlib's
    callbacks collect (ddls/environments/ramp_cluster/utils.py:25-73)."""
    record = {"env_index": env_index,
              "episode_return": float(episode_return),
              "episode_length": int(episode_length)}
    cluster = getattr(env, "cluster", None)
    if cluster is not None and getattr(cluster, "episode_stats", None):
        stats = cluster.episode_stats
        for key in ("num_jobs_arrived", "num_jobs_completed",
                    "num_jobs_blocked", "blocking_rate",
                    "acceptance_rate"):
            if key in stats:
                record[key] = stats[key]
        for key in ("job_completion_time",
                    "job_completion_time_speedup"):
            vals = stats.get(key)
            if vals:
                record[f"mean_{key}"] = float(np.mean(vals))
    return record


class VectorEnv:
    """B independent environment instances with auto-reset."""

    def __init__(self, env_fns: List[Callable[[], Any]],
                 seeds: Optional[List[int]] = None):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.seeds = seeds or list(range(self.num_envs))
        self.episode_returns = np.zeros(self.num_envs)
        self.episode_lengths = np.zeros(self.num_envs, dtype=np.int64)
        self.completed_episodes: List[Dict[str, Any]] = []

    def stacked_obs(self) -> Dict[str, np.ndarray]:
        """The current obs list as one [B, ...] batch (in-process envs
        have no stepping to overlap the stacking with — see
        ParallelVectorEnv.stacked_obs for the prefetched variant)."""
        return stack_obs(self.obs)

    def reset(self) -> List[Dict[str, np.ndarray]]:
        self.obs = [env.reset(seed=self.seeds[i])
                    for i, env in enumerate(self.envs)]
        self.episode_returns[:] = 0.0
        self.episode_lengths[:] = 0
        return self.obs

    def step(self, actions: np.ndarray):
        return self.step_subset(range(self.num_envs), actions)

    def step_subset(self, indices, actions: np.ndarray):
        """Step only ``envs[i] for i in indices`` with ``actions`` (same
        length as ``indices``); returns (obs list for the subset, rewards,
        dones). Used by the pipelined collector to overlap device sampling
        of one env group with host stepping of the other."""
        indices = list(indices)
        rewards = np.zeros(len(indices), dtype=np.float32)
        dones = np.zeros(len(indices), dtype=bool)
        for k, i in enumerate(indices):
            env = self.envs[i]
            obs, reward, done, _ = env.step(int(actions[k]))
            rewards[k] = reward
            dones[k] = done
            self.episode_returns[i] += reward
            self.episode_lengths[i] += 1
            if done:
                self._harvest_episode(i, env)
                # fresh seed per episode so workload sampling differs
                self.seeds[i] += self.num_envs
                obs = env.reset(seed=self.seeds[i])
                self.episode_returns[i] = 0.0
                self.episode_lengths[i] = 0
            self.obs[i] = obs
        return [self.obs[i] for i in indices], rewards, dones

    def _harvest_episode(self, i: int, env) -> None:
        self.completed_episodes.append(harvest_episode_record(
            env, i, self.episode_returns[i], self.episode_lengths[i]))

    def drain_completed_episodes(self) -> List[Dict[str, Any]]:
        out, self.completed_episodes = self.completed_episodes, []
        return out

    def restart_episodes(self) -> List[Dict[str, np.ndarray]]:
        """Abandon every in-progress episode and start fresh ones on
        advanced per-env seeds. Completed-episode records are kept; the
        abandoned partial returns/lengths are dropped — used after an
        off-policy interlude (e.g. an ES eval window) so foreign-policy
        steps can never leak into training episode stats."""
        for i in range(self.num_envs):
            self.seeds[i] += self.num_envs
        self.obs = [env.reset(seed=self.seeds[i])
                    for i, env in enumerate(self.envs)]
        self.episode_returns[:] = 0.0
        self.episode_lengths[:] = 0
        return self.obs

    def close(self) -> None:
        pass


def _parallel_env_worker(conn, env_builder, env_kwargs: Dict[str, Any],
                         env_index: int, seed: int, seed_stride: int,
                         telemetry_enabled: bool = False) -> None:
    """Subprocess body: owns one env, steps it on command, auto-resets.

    ``env_builder`` is a picklable callable (class or factory) receiving
    ``**env_kwargs`` — the process-parallel replacement for RLlib's Ray
    rollout workers, each of which builds its own env from the env_config
    (SURVEY.md §3.1 process-boundary note).

    ``telemetry_enabled`` mirrors the parent's telemetry switch into this
    process (spawned workers start with the global registry disabled);
    the worker's counters — the sim-layer cache hit/miss counts live
    HERE, not in the parent — ride back on the "closed" ack and are
    merged into the parent registry by ``ParallelVectorEnv.close``.
    """
    try:
        if telemetry_enabled:
            telemetry.enable()
        env = env_builder(**env_kwargs)
        episode_return, episode_length = 0.0, 0
        while True:
            cmd, payload = conn.recv()
            if cmd == "reset":
                # seedless reset replays the current seed (same semantics
                # as the serial VectorEnv); "restart" advances it
                seed = payload if payload is not None else seed
                obs = env.reset(seed=seed)
                episode_return, episode_length = 0.0, 0
                conn.send(("obs", obs))
            elif cmd == "restart":
                # abandon the in-progress episode for a fresh workload
                seed += seed_stride
                obs = env.reset(seed=seed)
                episode_return, episode_length = 0.0, 0
                conn.send(("obs", obs))
            elif cmd == "step":
                obs, reward, done, _ = env.step(int(payload))
                episode_return += reward
                episode_length += 1
                record = None
                if done:
                    record = harvest_episode_record(
                        env, env_index, episode_return, episode_length)
                    seed += seed_stride
                    obs = env.reset(seed=seed)
                    episode_return, episode_length = 0.0, 0
                conn.send(("step", (obs, float(reward), bool(done), record)))
            elif cmd == "close":
                # counters only: cross-process histogram merge is lossy,
                # and the sim layer records nothing but counters
                counters = telemetry.snapshot().get("counters") or None
                conn.send(("closed", counters))
                return
    except KeyboardInterrupt:
        pass
    except Exception as e:  # surface worker crashes to the parent
        import traceback
        conn.send(("error", f"{e}\n{traceback.format_exc()}"))


class ParallelVectorEnv:
    """B environment instances stepped in B subprocesses.

    Same interface as ``VectorEnv``. Env construction arguments must be
    picklable (builder callable + kwargs dict), since workers are spawned
    fresh — which also keeps the TPU runtime out of the children (only the
    parent process touches jax).
    """

    def __init__(self, env_builder: Callable[..., Any],
                 env_kwargs: Dict[str, Any], num_envs: int,
                 seeds: Optional[List[int]] = None,
                 start_method: str = "spawn"):
        self.num_envs = num_envs
        self.seeds = seeds or list(range(num_envs))
        # opt-in (the pipelined collector sets it): full-batch step()
        # receives worker replies OUT OF ORDER as they finish and writes
        # each obs row straight into a stacked [B, ...] batch, so the
        # next sample's input assembles while slower workers still step
        # — the stacking cost rides inside the env wall instead of after
        # it. Off by default so the sequential loop keeps the seed's
        # exact cost profile for load-controlled comparisons.
        self.prefetch_stacked = False
        self._stacked_cache: Optional[Dict[str, np.ndarray]] = None
        ctx = mp.get_context(start_method)
        self._conns = []
        self._procs = []
        for i in range(num_envs):
            parent, child = ctx.Pipe()
            proc = ctx.Process(
                target=_parallel_env_worker,
                args=(child, env_builder, env_kwargs, i, self.seeds[i],
                      num_envs, telemetry.enabled()),
                daemon=True)
            proc.start()
            child.close()
            self._conns.append(parent)
            self._procs.append(proc)
        self.completed_episodes: List[Dict[str, Any]] = []
        self.obs: List[Dict[str, np.ndarray]] = []
        self._first_reset = True

    def _recv(self, conn) -> Tuple[str, Any]:
        kind, payload = conn.recv()
        if kind == "error":
            self.close()
            raise RuntimeError(f"env worker failed:\n{payload}")
        return kind, payload

    def reset(self) -> List[Dict[str, np.ndarray]]:
        # seeds live worker-side (advanced on every auto-reset); only the
        # first reset pins them, later resets continue each worker's sequence
        payload = self.seeds if self._first_reset else [None] * self.num_envs
        self._first_reset = False
        self._stacked_cache = None
        for conn, seed in zip(self._conns, payload):
            conn.send(("reset", seed))
        self.obs = [self._recv(conn)[1] for conn in self._conns]
        return self.obs

    def stacked_obs(self) -> Dict[str, np.ndarray]:
        """The current obs as one [B, ...] batch; with
        ``prefetch_stacked`` the batch was already assembled inside the
        previous ``step()`` as worker replies arrived (bit-identical to
        ``stack_obs(self.obs)``, measured earlier)."""
        if self._stacked_cache is not None:
            return self._stacked_cache
        return stack_obs(self.obs)

    def step(self, actions: np.ndarray):
        if self.prefetch_stacked:
            return self._step_prefetch(actions)
        return self.step_subset(range(self.num_envs), actions)

    def _step_prefetch(self, actions: np.ndarray):
        """Full-batch step with out-of-order reply handling: each worker's
        obs row lands in a fresh stacked batch the moment it arrives, so
        stacking overlaps the stragglers' env stepping. Outputs (obs,
        rewards, dones, episode-record order) are bit-identical to the
        in-order path — records are flushed in env-index order."""
        from multiprocessing import connection as mp_connection

        for i, conn in enumerate(self._conns):
            conn.send(("step", int(actions[i])))
        B = self.num_envs
        rewards = np.zeros(B, dtype=np.float32)
        dones = np.zeros(B, dtype=bool)
        stacked: Optional[Dict[str, np.ndarray]] = None
        records: Dict[int, dict] = {}
        remaining = {conn: i for i, conn in enumerate(self._conns)}
        while remaining:
            for conn in mp_connection.wait(list(remaining)):
                i = remaining.pop(conn)
                kind, payload = conn.recv()
                if kind == "error":
                    self.close()
                    raise RuntimeError(f"env worker failed:\n{payload}")
                obs, reward, done, record = payload
                self.obs[i] = obs
                if stacked is None:
                    stacked = {
                        k: np.empty((B,) + np.asarray(obs[k]).shape,
                                    np.asarray(obs[k]).dtype)
                        for k in OBS_KEYS}
                for k in OBS_KEYS:
                    stacked[k][i] = obs[k]
                rewards[i] = reward
                dones[i] = done
                if record is not None:
                    records[i] = record
        self.completed_episodes.extend(
            records[i] for i in sorted(records))
        self._stacked_cache = stacked
        return list(self.obs), rewards, dones

    def step_subset(self, indices, actions: np.ndarray):
        """Step only the workers in ``indices``; see VectorEnv.step_subset."""
        indices = list(indices)
        self._stacked_cache = None
        for k, i in enumerate(indices):
            self._conns[i].send(("step", int(actions[k])))
        rewards = np.zeros(len(indices), dtype=np.float32)
        dones = np.zeros(len(indices), dtype=bool)
        for k, i in enumerate(indices):
            _, (obs, reward, done, record) = self._recv(self._conns[i])
            self.obs[i] = obs
            rewards[k] = reward
            dones[k] = done
            if record is not None:
                self.completed_episodes.append(record)
        return [self.obs[i] for i in indices], rewards, dones

    def drain_completed_episodes(self) -> List[Dict[str, Any]]:
        out, self.completed_episodes = self.completed_episodes, []
        return out

    def restart_episodes(self) -> List[Dict[str, np.ndarray]]:
        """See VectorEnv.restart_episodes: workers advance their own seeds
        on the dedicated restart command and drop partial accumulators."""
        if self._first_reset:
            return self.reset()
        self._stacked_cache = None
        for conn in self._conns:
            conn.send(("restart", None))
        self.obs = [self._recv(conn)[1] for conn in self._conns]
        return self.obs

    def close(self) -> None:
        for conn in self._conns:
            try:
                conn.send(("close", None))
            except (BrokenPipeError, OSError):
                pass
        # drain to each worker's "closed" ack (stale step replies may sit
        # ahead of it when closing after a worker error) and merge the
        # worker's telemetry counters into this process's registry. One
        # SHARED 2 s deadline across all conns: a wedged worker must not
        # serially cost 2 s per env on the failure-path teardown (the
        # join/terminate below still reaps it)
        deadline = time.monotonic() + 2.0
        for conn in self._conns:
            try:
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not conn.poll(remaining):
                        break
                    kind, payload = conn.recv()
                    if kind == "closed":
                        if payload and telemetry.enabled():
                            for name, value in payload.items():
                                telemetry.inc(name, int(value))
                        break
            except (EOFError, BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=5)
            if proc.is_alive():
                proc.terminate()


class RolloutCollector:
    """Collects [T, B] trajectory batches for the PPO learner.

    With ``pipeline=True`` (default for an even batch of >= 2 envs) the envs
    are split into two groups and collection interleaves them: while the host
    steps group A's simulators, the device is already computing group B's
    action batch (jax dispatch is asynchronous), so the per-step device
    round-trip — significant under a tunnelled TPU — is hidden behind env
    stepping instead of serialised with it.
    """

    def __init__(self, vec_env: VectorEnv, learner, rollout_length: int,
                 pipeline: Optional[bool] = None,
                 deferred_fetch: bool = False):
        self.vec_env = vec_env
        self.learner = learner
        self.rollout_length = rollout_length
        B = vec_env.num_envs
        # deferred_fetch (the pipelined loop mode, train/loops.py): one
        # jitted program per step (rng split folded in), actions are the
        # ONLY per-step device fetch (logp/values stay device futures,
        # drained in one device_get at segment end), obs rows are copied
        # into preallocated [T, B, ...] traj buffers while the forward
        # is in flight, and every transfer is explicit
        # (device_put/device_get — pinned by the transfer-guard test).
        # Bit-identical outputs to the plain path; only the
        # dispatch/fetch schedule changes.
        self.deferred_fetch = bool(deferred_fetch)
        self._jit_step_fn = None
        # explicit staging target for the stacked obs: the learner's
        # replicated mesh sharding (where its params live), so the jitted
        # sample needs no implicit device-to-device reshard — a bare
        # device_put would commit to ONE device and trip the
        # transfer-guard pin (and a real reshard) on multi-device meshes.
        # MULTI-PROCESS: never — each process's obs are ITS OWN shard of
        # the collection, and a device_put onto the global mesh would
        # fabricate a "replicated" global array from process-divergent
        # data (mismatched collectives downstream: gloo size errors).
        # There the batch rides into the jit as host arrays, exactly as
        # the pre-round-6 collector did.
        self._obs_sharding = (getattr(learner, "_replicated", None)
                              if jax.process_count() == 1 else None)
        if self.deferred_fetch:
            pipeline = False  # deferred path has its own schedule
            if getattr(vec_env, "prefetch_stacked", None) is False:
                vec_env.prefetch_stacked = True
        if pipeline is None and (B < 2 or B % 2
                                 or jax.default_backend() == "cpu"):
            # overlap only exists when sampling runs on an accelerator; on a
            # CPU backend the device IS the host, and two half-batch calls
            # just double the sampling overhead
            pipeline = False
        # pipeline=None: decide adaptively after timing the first collect.
        # Per step, pipelined cost ~ 2*max(sample, env/2) vs non-pipelined
        # sample + env, so splitting wins exactly when sampling is cheaper
        # than env stepping — under a high-latency tunnelled TPU with fast
        # host envs, pipelining *doubles* the dominant round-trip count.
        self.pipeline = pipeline
        self._needs_reset = True

    def _step_program(self):
        """One jitted program per rollout step: rng split + sampling fused,
        so the host dispatches once instead of paying a separate
        ~ms-scale ``jax.random.split`` dispatch per step. The split tree
        is IDENTICAL to the plain path's host-side
        ``rng, step_rng = split(rng)`` followed by sampling with
        ``step_rng`` — same bits out."""
        if self._jit_step_fn is None:
            sample = self.learner._sample_actions

            def step_fn(params, obs, rng):
                rng, step_rng = jax.random.split(rng)
                actions, logp, values = sample(params, obs, step_rng)
                return rng, actions, logp, values

            self._jit_step_fn = jax.jit(step_fn)
        return self._jit_step_fn

    def _collect_deferred(self, params, rng) -> Dict[str, Any]:
        """Deferred-fetch collection (see __init__); [T, B] outputs
        bit-identical to the plain path."""
        T, B = self.rollout_length, self.vec_env.num_envs
        step_fn = self._step_program()
        if self._obs_sharding is not None:
            # the epoch's incoming key was split outside the mesh; place
            # it next to the params explicitly (after step 0 the key is
            # step_fn's own replicated output and stays put)
            rng = jax.device_put(rng, self._obs_sharding)
        act_buf = np.zeros((T, B), dtype=np.int32)
        rew_buf = np.zeros((T, B), dtype=np.float32)
        done_buf = np.zeros((T, B), dtype=bool)
        traj_obs: Optional[Dict[str, np.ndarray]] = None
        logp_refs: List[Any] = [None] * T
        val_refs: List[Any] = [None] * T
        for t in range(T):
            batched = self.vec_env.stacked_obs()
            staged = (jax.device_put(batched, self._obs_sharding)
                      if self._obs_sharding is not None else batched)
            rng, actions, logp, values = step_fn(params, staged, rng)
            if traj_obs is None:
                traj_obs = {k: np.empty((T,) + batched[k].shape,
                                        batched[k].dtype)
                            for k in OBS_KEYS}
            # the copy into the traj buffers runs while the device is
            # still computing this step's forward
            for k in OBS_KEYS:
                traj_obs[k][t] = batched[k]
            actions = jax.device_get(actions)
            act_buf[t] = actions
            logp_refs[t] = logp
            val_refs[t] = values
            _, rewards, dones = self.vec_env.step(actions)
            rew_buf[t] = rewards
            done_buf[t] = dones
        final = self.vec_env.stacked_obs()
        final_staged = (jax.device_put(final, self._obs_sharding)
                        if self._obs_sharding is not None else final)
        rng, _, _, last_values = step_fn(params, final_staged, rng)
        # ONE drain for every deferred future (all long since ready —
        # this is a batch of buffer copies, not a wait)
        logp_host, val_host, last_host = jax.device_get(
            (logp_refs, val_refs, last_values))
        return {
            "traj": {"obs": traj_obs, "actions": act_buf,
                     "logp": np.stack(logp_host).astype(np.float32),
                     "values": np.stack(val_host).astype(np.float32),
                     "rewards": rew_buf, "dones": done_buf},
            "last_values": np.asarray(last_host, np.float32),
            "episodes": self.vec_env.drain_completed_episodes(),
            "env_steps": T * B,
        }

    def collect(self, params, rng) -> Dict[str, Any]:
        """Run rollout_length steps in every env; returns a trajectory dict
        of [T, B, ...] host arrays plus bootstrap values [B]."""
        T, B = self.rollout_length, self.vec_env.num_envs
        if self._needs_reset:
            self.vec_env.reset()
            self._needs_reset = False
        if self.deferred_fetch:
            return self._collect_deferred(params, rng)
        if self.pipeline and B >= 2 and B % 2 == 0:
            return self._collect_pipelined(params, rng)

        obs_buf: List[Dict[str, np.ndarray]] = []
        act_buf = np.zeros((T, B), dtype=np.int32)
        logp_buf = np.zeros((T, B), dtype=np.float32)
        val_buf = np.zeros((T, B), dtype=np.float32)
        rew_buf = np.zeros((T, B), dtype=np.float32)
        done_buf = np.zeros((T, B), dtype=bool)

        measure = self.pipeline is None and B >= 2 and B % 2 == 0
        sample_time = env_time = 0.0
        for t in range(T):
            batched = stack_obs(self.vec_env.obs)
            rng, step_rng = jax.random.split(rng)
            # t == 0 pays jit trace+compile for sample_actions; excluding
            # it keeps the measurement at steady-state cost
            timing = measure and t > 0
            t0 = time.perf_counter() if timing else 0.0
            actions, logp, values = self.learner.sample_actions(
                params, batched, step_rng)
            actions = np.asarray(actions)
            if timing:
                sample_time += time.perf_counter() - t0
            obs_buf.append(batched)
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(values)
            t0 = time.perf_counter() if timing else 0.0
            _, rewards, dones = self.vec_env.step(actions)
            if timing:
                env_time += time.perf_counter() - t0
            rew_buf[t] = rewards
            done_buf[t] = dones
        if measure and T > 1:
            # see __init__: split-batch overlap wins iff sampling (device
            # round-trip incl. dispatch+fetch) is cheaper than env stepping
            self.pipeline = sample_time < env_time

        final = stack_obs(self.vec_env.obs)
        rng, val_rng = jax.random.split(rng)
        _, _, last_values = self.learner.sample_actions(params, final,
                                                        val_rng)

        traj_obs = {k: np.stack([o[k] for o in obs_buf])
                    for k in OBS_KEYS}
        return {
            "traj": {"obs": traj_obs, "actions": act_buf, "logp": logp_buf,
                     "values": val_buf, "rewards": rew_buf,
                     "dones": done_buf},
            "last_values": np.asarray(last_values),
            "episodes": self.vec_env.drain_completed_episodes(),
            "env_steps": T * B,
        }

    def _collect_pipelined(self, params, rng) -> Dict[str, Any]:
        """Two-group interleaved collection (see class docstring).

        Device-dispatch order per step t: sample(G0, t), sample(G1, t),
        sample(G0, t+1), ... — each half's host env stepping overlaps the
        other half's device sampling.
        """
        T, B = self.rollout_length, self.vec_env.num_envs
        H = B // 2
        groups = [list(range(H)), list(range(H, B))]

        obs_buf: List[List[Dict[str, np.ndarray]]] = [[], []]
        act_buf = np.zeros((T, B), dtype=np.int32)
        logp_buf = np.zeros((T, B), dtype=np.float32)
        val_buf = np.zeros((T, B), dtype=np.float32)
        rew_buf = np.zeros((T, B), dtype=np.float32)
        done_buf = np.zeros((T, B), dtype=bool)
        last_values = [None, None]

        def sample(g, step_rng):
            batched = stack_obs([self.vec_env.obs[i] for i in groups[g]])
            return batched, self.learner.sample_actions(params, batched,
                                                        step_rng)

        cols = [slice(0, H), slice(H, B)]
        rng, r0 = jax.random.split(rng)
        pending = [sample(0, r0), None]
        for t in range(T):
            rng, r1 = jax.random.split(rng)
            pending[1] = sample(1, r1)
            for g in (0, 1):
                batched, (actions, logp, values) = pending[g]
                actions = np.asarray(actions)  # blocks on this half only
                obs_buf[g].append(batched)
                act_buf[t, cols[g]] = actions
                logp_buf[t, cols[g]] = np.asarray(logp)
                val_buf[t, cols[g]] = np.asarray(values)
                # host steps this half while the device runs the other half's
                # (already dispatched) sampling
                _, rewards, dones = self.vec_env.step_subset(groups[g],
                                                             actions)
                rew_buf[t, cols[g]] = rewards
                done_buf[t, cols[g]] = dones
                if g == 0:
                    rng, rnext = jax.random.split(rng)
                    pending[0] = sample(0, rnext)
                    if t + 1 == T:
                        last_values[0] = pending[0][1][2]
        # group 1 bootstrap: dispatched after group 0's
        rng, rlast = jax.random.split(rng)
        last_values[1] = sample(1, rlast)[1][2]

        traj_obs = {
            k: np.concatenate(
                [np.stack([o[k] for o in obs_buf[0]]),
                 np.stack([o[k] for o in obs_buf[1]])], axis=1)
            for k in OBS_KEYS}
        return {
            "traj": {"obs": traj_obs, "actions": act_buf, "logp": logp_buf,
                     "values": val_buf, "rewards": rew_buf,
                     "dones": done_buf},
            "last_values": np.concatenate([np.asarray(last_values[0]),
                                           np.asarray(last_values[1])]),
            "episodes": self.vec_env.drain_completed_episodes(),
            "env_steps": T * B,
        }
