from ddls_tpu.rl.ppo import PPOConfig, PPOLearner, compute_gae
from ddls_tpu.rl.rollout import RolloutCollector, VectorEnv

__all__ = ["PPOConfig", "PPOLearner", "compute_gae", "RolloutCollector",
           "VectorEnv"]
