from ddls_tpu.rl.dqn import (ApexDQNLearner, DQNConfig,
                             PrioritizedReplayBuffer, nstep_transitions,
                             per_worker_epsilons)
from ddls_tpu.rl.ppo import PPOConfig, PPOLearner, compute_gae
from ddls_tpu.rl.ring import TrajRing
from ddls_tpu.rl.rollout import ParallelVectorEnv, RolloutCollector, VectorEnv

__all__ = ["ApexDQNLearner", "DQNConfig", "PrioritizedReplayBuffer",
           "nstep_transitions", "per_worker_epsilons",
           "PPOConfig", "PPOLearner", "compute_gae", "ParallelVectorEnv",
           "RolloutCollector", "TrajRing", "VectorEnv"]
