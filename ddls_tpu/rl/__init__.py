from ddls_tpu.rl.ppo import PPOConfig, PPOLearner, compute_gae
from ddls_tpu.rl.rollout import ParallelVectorEnv, RolloutCollector, VectorEnv

__all__ = ["PPOConfig", "PPOLearner", "compute_gae", "ParallelVectorEnv",
           "RolloutCollector", "VectorEnv"]
