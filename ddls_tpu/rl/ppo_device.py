"""PPO with ON-DEVICE rollout collection.

The collection half of PPO-on-device (§5.8): fixed-length [T, B]
segments are produced by `sim/jax_env.py:make_segment_fn` — the entire
environment (placement, pricing, lookahead, event clock, observation,
policy forward, sampling) runs inside one jitted scan per env, vmapped
over B job banks, with episodes resetting in-kernel. The host
reconstructs the exact observations from the compact trace
(`rebuild_obs_batch` — bit-equal to what the kernel's policy forward
saw) and feeds the EXISTING `PPOLearner.shard_traj`/`train_step`.

Under the tunnelled TPU this replaces T×B host→device round trips per
collect (~116 ms each) with ONE dispatch.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class DevicePPOCollector:
    """Drop-in counterpart of `rl/rollout.py:RolloutCollector` whose envs
    live on device. ``banks`` is a dict of stacked job-bank arrays with a
    leading B axis (same shapes per bank).

    With ``mesh`` (a 1-D+ ``jax.sharding.Mesh`` with a ``dp`` axis), the
    lane axis is SHARDED over the mesh's dp devices: each device runs its
    own lanes' episodes inside the one jitted dispatch (the vmapped scan
    is embarrassingly parallel over lanes, so XLA partitions it with no
    collectives). This is the pod collection shape — the update already
    shards its batch over the same mesh, so without it a multi-chip
    slice would collect on one chip and update on all. Requires
    ``num_envs`` divisible by the dp axis size.

    ``params_shardings`` (optional, mesh mode only) is the sharding tree
    the learner keeps its params in (``parallel/partition.py`` — fsdp/tp
    layouts); the collector's jitted forwards declare it as the params
    in_sharding so sharded params enter the in-scan forward as-is (XLA
    inserts the layout's gathers INSIDE the program) instead of being
    implicitly replicated at dispatch. Default keeps today's replicated
    in_sharding — bit-identical programs.

    ``memo_cfg`` wires the in-kernel lookahead memo (sim/jax_memo.py):
    ``"auto"`` (default) enables it at EVERY lane count — the batched
    probe masks hit lanes out of the lookahead while_loop, so the
    vmapped lanes hit their own per-lane tables too (ISSUE 17). Memo
    hit/miss counters ride the per-collect trace and
    ``memo_counters()`` exposes the cumulative totals summed over lanes
    (drain boundaries only)."""

    def __init__(self, et, ot, model, banks: Dict, rollout_length: int,
                 mesh=None, memo_cfg="auto", params_shardings=None):
        import jax
        import jax.numpy as jnp

        from ddls_tpu.rl.ppo import traj_donate_argnums
        from ddls_tpu.sim.jax_env import (make_segment_fn, segment_init,
                                          vmap_segment_fn)
        from ddls_tpu.sim.jax_memo import resolve_memo_cfg

        self.et, self.ot, self.model = et, ot, model
        self.rollout_length = rollout_length
        self.num_envs = int(jax.tree_util.tree_leaves(banks)[0].shape[0])
        self.mesh = mesh
        self.memo_cfg = resolve_memo_cfg(memo_cfg, self.num_envs)
        segment = make_segment_fn(et, ot, model, rollout_length,
                                  memo_cfg=self.memo_cfg)
        lane_segment = vmap_segment_fn(segment, self.num_envs)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            if self.num_envs % mesh.shape["dp"] != 0:
                raise ValueError(
                    f"num_envs {self.num_envs} must divide over the "
                    f"mesh dp axis ({mesh.shape['dp']})")
            lane = NamedSharding(mesh, P("dp"))
            repl = NamedSharding(mesh, P())
            # fsdp/tp params enter with the learner's layout declared, so
            # dispatch never implicitly replicates them (the gathers live
            # inside the compiled program instead)
            p_sh = repl if params_shardings is None else params_shardings
            banks = jax.device_put(banks, lane)
            # rngs/state arrive as host (or mismatched) arrays; jit's
            # explicit in_shardings reshards them on dispatch. The env
            # state (argnum 2) is donated on accelerator backends: each
            # collect replaces it with the returned state, so the old
            # buffers can back the new ones in place instead of doubling
            # the per-lane sim state (CPU donation disabled — it forces
            # inline execution of the jitted call, ppo.traj_donate_argnums)
            self._vseg = jax.jit(
                lane_segment,
                in_shardings=(lane, p_sh, lane, lane),
                out_shardings=(lane, lane, lane),
                donate_argnums=traj_donate_argnums(2))
        else:
            if params_shardings is not None:
                raise ValueError(
                    "params_shardings requires a mesh: the sharded-params "
                    "layouts only exist on a device mesh")
            self._vseg = jax.jit(lane_segment,
                                 donate_argnums=traj_donate_argnums(2))
        self.banks = banks
        # jitted bootstrap-value forward: one compiled dispatch per
        # collect instead of an eager op-by-op chain — and the SAME
        # compiled math as the fused epoch's in-scan bootstrap
        # (rl/fused.py), whose x64 parity pin requires the two paths to
        # round identically. Two ingredients of that bit-equality:
        # jitted not eager (eager fuses nothing and differs at the last
        # f32 ulp), and the same PARTITIONING — under a mesh the fused
        # bootstrap consumes lane-sharded obs, so the standalone one
        # must shard its batch axis identically or the partitioned
        # segment-sum accumulation order diverges
        from ddls_tpu.models.policy import batched_policy_apply

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._jit_apply = jax.jit(
                lambda p, o: batched_policy_apply(model, p, o),
                in_shardings=(p_sh if params_shardings is not None
                              else NamedSharding(mesh, P()),
                              NamedSharding(mesh, P("dp"))))
        else:
            self._jit_apply = jax.jit(
                lambda p, o: batched_policy_apply(model, p, o))
        # per-env initial state from each env's OWN bank (arrival clocks
        # differ across banks)
        self._state = jax.vmap(
            lambda b: segment_init(et, b, self.memo_cfg))(banks)
        # per-lane decision count of the in-flight episode (episodes span
        # segment boundaries; the kernel's counters reset in-kernel at
        # done, so length is tracked here)
        self._ep_len = np.zeros(self.num_envs, np.int64)

    def collect(self, params, rng) -> Dict:
        """One [T, B] segment batch; returns the PPOLearner traj dict
        plus bootstrap values."""
        import jax

        from ddls_tpu.sim.jax_env import rebuild_obs_batch

        rngs = jax.random.split(rng, self.num_envs)
        self._state, trace, next_fields = self._vseg(
            self.banks, params, self._state, rngs)
        trace = {k: np.asarray(v) for k, v in trace.items()}
        # kernel trace is [B, T]; the learner wants [T, B]
        trace = {k: np.swapaxes(v, 0, 1) for k, v in trace.items()}
        obs = rebuild_obs_batch(self.et, self.ot, trace)
        traj = {
            "obs": obs,
            "actions": trace["action"].astype(np.int32),
            "logp": trace["logp"].astype(np.float32),
            "values": trace["value"].astype(np.float32),
            "rewards": trace["reward"].astype(np.float32),
            "dones": trace["done"].astype(bool),
        }
        next_obs = rebuild_obs_batch(self.et, self.ot, {
            k: np.asarray(v) for k, v in next_fields.items()})
        next_obs = {k: np.asarray(v) for k, v in next_obs.items()}
        if self.mesh is not None and jax.process_count() > 1:
            # multi-process jax rejects numpy inputs against the jit's
            # non-trivial (dp-sharded) in_shardings even on this fully-
            # addressable LOCAL mesh — stage explicitly first (device_put
            # to a local sharding is collective-free; same program, same
            # bits as the single-process path)
            from jax.sharding import NamedSharding, PartitionSpec as P

            next_obs = jax.device_put(
                next_obs, NamedSharding(self.mesh, P("dp")))
        _, last_values = self._jit_apply(params, next_obs)
        return {"traj": traj,
                "last_values": np.asarray(last_values, np.float32),
                "env_steps": self.rollout_length * self.num_envs,
                "episodes": self._harvest_episodes(trace)}

    def memo_counters(self) -> Optional[Dict]:
        """Cumulative in-kernel memo counters {hits, misses, evicts,
        hit_rate}, summed over lanes (drain/reporting boundaries only —
        sim/jax_memo.py:summarize_counters); None when the memo is
        off."""
        from ddls_tpu.sim.jax_memo import summarize_counters

        if self.memo_cfg is None:
            return None
        return summarize_counters(self._state[1])

    def _harvest_episodes(self, trace) -> list:
        """Episode records at done boundaries, from the traced in-kernel
        counters — the device counterpart of
        `rollout.py:harvest_episode_record`, using the HOST denominators:
        ``acceptance_rate`` = completed/arrived and ``blocking_rate`` =
        blocked/arrived where arrived counts every job that entered the
        queue, decided or not (cluster.py:1020-1023; the kernel traces
        the arrival pointer as ``ep_arrived``), so device- and
        host-collected runs log comparable rates."""
        episodes = []
        done = trace["done"]  # [T, B] after the caller's swap
        T, B = done.shape
        for t in range(T):
            self._ep_len += 1
            for b in np.nonzero(done[t])[0]:
                blk = int(trace["ep_blocked"][t, b])
                com = int(trace["ep_completed"][t, b])
                arr = int(trace["ep_arrived"][t, b])
                episodes.append({
                    "env_index": int(b),
                    "episode_return": float(trace["ep_return"][t, b]),
                    "episode_length": int(self._ep_len[b]),
                    "num_jobs_arrived": arr,
                    "num_jobs_completed": com,
                    "num_jobs_blocked": blk,
                    "acceptance_rate": com / arr if arr else 0.0,
                    "blocking_rate": blk / arr if arr else 0.0,
                })
                self._ep_len[b] = 0
        return episodes
