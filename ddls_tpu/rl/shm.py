"""Shared-memory observation slabs for zero-copy rollout collection.

The pipe backend of ``ParallelVectorEnv`` materialises every padded
observation three times per step: the worker pickles it over a pipe, the
parent unpickles and ``stack_obs``-copies it into a fresh ``[B, ...]``
batch, and the deferred-fetch collector copies that again into the
``[T, B, ...]`` trajectory buffer. This module provides the slab layer of
the shm backend: the parent allocates one POSIX shared-memory segment per
observation field shaped ``[rows, B, *field]`` (``rows = 1`` for plain
stepping, ``rows = T + 1`` for the deferred-fetch collector, whose
trajectory IS slab rows ``[0:T]``), workers map the same segments and
write their ``[row, i]`` slice in place, and only small control payloads
(actions in, reward/done/episode-record out) ride the pipes. This is the
host-side obs-transfer tax that arXiv 2012.04210 identifies as the
dominant non-sim cost in CPU-actor/accelerator-learner stacks.

Ownership contract (CLAUDE.md invariant):

* the PARENT owns every segment's lifecycle — it creates, unlinks on
  ``close()``, and carries a ``weakref.finalize`` fallback so an
  interrupted run (KeyboardInterrupt mid-collect, a crashed test) leaves
  no ``/dev/shm`` litter;
* WORKERS attach without resource-tracker registration (CPython < 3.12
  registers every by-name attach, and the tracker would unlink the
  parent's live segment when the worker exits) and only ever write their
  own ``[row, env_index]`` slice, between receiving a step command and
  sending the reply — the reply on the pipe is the per-worker ready
  flag; the parent reads a slice only after that flag;
* above the slab, the trajectory RING (rl/ring.py) adds a per-segment
  ledger — the collector owns a segment from lease to publish, the
  learner from publish to release, and release (token-driven) is the
  only point a segment becomes writable again. Workers are oblivious:
  a ring just means K slab attachments and a ``(segment, row)`` write
  destination instead of a bare row.

``scripts/check_shm_unlink.py`` (tier-1) enforces that every
``SharedMemory(create=True)`` in the package keeps the paired
unlink/finalizer.
"""
from __future__ import annotations

import weakref
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

try:  # pragma: no cover - the import exists on every supported CPython
    from multiprocessing import resource_tracker, shared_memory
except ImportError:  # pragma: no cover
    shared_memory = None
    resource_tracker = None


@dataclass(frozen=True)
class SlabField:
    """Picklable descriptor of one field's slab, sent to workers over the
    control pipe so they can map the same segment by name."""
    key: str
    shm_name: str
    shape: Tuple[int, ...]  # full slab shape: (rows, num_envs, *field)
    dtype: str


_AVAILABLE: bool | None = None


def shm_available() -> bool:
    """Whether POSIX shared memory is usable here (``/dev/shm`` mounted,
    not blocked by the sandbox). Probed once per process with a tiny
    create+unlink round trip."""
    global _AVAILABLE
    if _AVAILABLE is None:
        if shared_memory is None:
            _AVAILABLE = False
        else:
            try:
                seg = shared_memory.SharedMemory(create=True, size=16)
            except (OSError, ValueError):
                _AVAILABLE = False
            else:
                seg.close()
                seg.unlink()
                _AVAILABLE = True
    return _AVAILABLE


def obs_field_specs(obs: Dict[str, np.ndarray],
                    keys: Sequence[str]) -> Dict[str, Tuple[Tuple[int, ...],
                                                            np.dtype]]:
    """(shape, dtype) template per field from one encoded observation —
    the slab layout source. Fixed shapes are a backend requirement: an
    unpadded env (no ``pad_obs_kwargs``) cannot ride slabs."""
    out = {}
    for k in keys:
        arr = np.asarray(obs[k])
        out[k] = (tuple(arr.shape), arr.dtype)
    return out


def _release_segments(segments: List) -> None:
    """Close + unlink every segment; the single cleanup path shared by
    ``SlabSet.close`` and its finalizer. A still-exported numpy view pins
    the local mapping (``BufferError``) but never the name — unlink still
    removes the ``/dev/shm`` entry and the memory frees when the last map
    dies."""
    for seg in segments:
        try:
            seg.close()
        except BufferError:
            pass
        except OSError:
            pass
        try:
            seg.unlink()
        except (FileNotFoundError, OSError):
            pass


class SlabSet:
    """Parent-side owner of the per-field shared-memory slabs.

    ``views[key]`` is a ``[rows, num_envs, *field]`` ndarray over the
    segment. ``close()`` unlinks; a ``weakref.finalize`` covers every
    other exit path (leak-proofing is part of the backend contract).
    """

    def __init__(self, fields: Dict[str, Tuple[Tuple[int, ...], np.dtype]],
                 rows: int, num_envs: int):
        if shared_memory is None:
            raise RuntimeError("multiprocessing.shared_memory unavailable")
        self.rows = int(rows)
        self.num_envs = int(num_envs)
        self.fields = dict(fields)
        self._segments: Dict[str, object] = {}
        self.views: Dict[str, np.ndarray] = {}
        created: List = []
        try:
            for key, (shape, dtype) in fields.items():
                full = (self.rows, self.num_envs) + tuple(shape)
                nbytes = int(np.prod(full)) * np.dtype(dtype).itemsize
                seg = shared_memory.SharedMemory(create=True,
                                                 size=max(nbytes, 1))
                created.append(seg)
                self._segments[key] = seg
                view = np.ndarray(full, dtype=np.dtype(dtype),
                                  buffer=seg.buf)
                view.fill(0)
                self.views[key] = view
        except Exception:
            _release_segments(created)
            raise
        self._finalizer = weakref.finalize(self, _release_segments, created)

    @property
    def obs_nbytes(self) -> int:
        """Bytes of ONE environment's observation (all fields) — the
        per-env-step unit for the bytes-copied telemetry counters."""
        return sum(int(np.prod(shape)) * np.dtype(dtype).itemsize
                   for shape, dtype in self.fields.values())

    def spec(self) -> List[SlabField]:
        return [SlabField(key=key, shm_name=self._segments[key].name,
                          shape=tuple(self.views[key].shape),
                          dtype=np.dtype(dtype).str)
                for key, (_, dtype) in self.fields.items()]

    def segment_names(self) -> List[str]:
        return [seg.name for seg in self._segments.values()]

    def close(self) -> None:
        """Unlink every segment (idempotent; the finalizer runs at most
        once). Views are dropped first so the munmap can proceed unless a
        caller still holds one — in which case unlink alone suffices."""
        self.views = {}
        self._finalizer()


def _attach_untracked(name: str):
    """Attach an existing segment WITHOUT resource-tracker registration:
    the tracker is shared with the parent under the spawn context, so a
    worker-side register/unregister pair would delete the PARENT's
    registration (and a by-name attach left registered would unlink the
    parent's live segment when the worker exits). CPython 3.13 exposes
    ``track=False`` for exactly this; earlier versions need the register
    hook silenced around the constructor."""
    try:
        return shared_memory.SharedMemory(name=name, track=False)
    except TypeError:  # Python < 3.13: no track kwarg
        original = resource_tracker.register
        resource_tracker.register = lambda *a, **k: None
        try:
            return shared_memory.SharedMemory(name=name)
        finally:
            resource_tracker.register = original


class SlabAttachment:
    """Worker-side mapping of the parent's slabs (attach by name, never
    create, never unlink)."""

    def __init__(self, fields: Sequence[SlabField]):
        if shared_memory is None:
            raise RuntimeError("multiprocessing.shared_memory unavailable")
        self._segments: List = []
        self.views: Dict[str, np.ndarray] = {}
        for f in fields:
            seg = _attach_untracked(f.shm_name)
            self._segments.append(seg)
            self.views[f.key] = np.ndarray(tuple(f.shape),
                                           dtype=np.dtype(f.dtype),
                                           buffer=seg.buf)

    def close(self) -> None:
        self.views = {}
        for seg in self._segments:
            try:
                seg.close()
            except BufferError:
                pass
        self._segments = []


class RingAttachment:
    """Worker-side mapping of a trajectory ring's K segments (attach by
    name, never create, never unlink — one ``SlabAttachment`` per ring
    segment). ``views_for(seg)`` selects the segment a ``(seg, row)``
    step destination addresses."""

    def __init__(self, segment_specs: Sequence[Sequence[SlabField]]):
        self.segments: List[SlabAttachment] = []
        try:
            for spec in segment_specs:
                self.segments.append(SlabAttachment(spec))
        except Exception:
            self.close()
            raise

    def views_for(self, seg: int) -> Dict[str, np.ndarray]:
        return self.segments[seg].views

    def close(self) -> None:
        for att in self.segments:
            att.close()
        self.segments = []
