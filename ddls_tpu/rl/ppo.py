"""Pure-JAX PPO learner, sharded over a device mesh.

This replaces the reference's RLlib ``PPOTrainer`` (SURVEY.md §2.7,
ddls/loops/rllib_epoch_loop.py:81): same algorithm — GAE, clipped surrogate
with adaptive-KL penalty, clipped value loss, entropy bonus, minibatched SGD
epochs — but as a single jitted SPMD program. The trajectory batch is sharded
over the mesh's ``dp`` axis and parameters are replicated, so XLA emits the
gradient all-reduce over ICI from the sharding annotations (the TPU-native
equivalent of RLlib's learner/worker gradient sync).

Tuned defaults follow the reference's PPO hyperparameters
(scripts/ramp_job_partitioning_configs/algo/ppo.yaml via BASELINE.md): lr
2.785e-4, gamma 0.997, clip 0.18, entropy 0.003, train batch 4000, SGD
minibatch 128, 50 SGD iters.

Everything under ``train_step`` is traced once: the SGD-epoch and minibatch
loops are ``lax.scan``s, so the whole update is one XLA computation per
compile — no per-minibatch dispatch from Python.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from ddls_tpu.parallel.mesh import (place_state_tree,
                                    replicated_sharding, shard_batch)


def traj_donate_argnums(state_argnum: int, *traj_argnums: int):
    """Donation plan for a jitted train step: on accelerator backends the
    state AND the staged trajectory/last_values buffers (shard_traj's
    device_put) are donated — the batch is consumed exactly once, so the
    staging copy of the largest arrays in the loop (the [T, B, ...] obs)
    disappears instead of outliving the update, and the state updates in
    place. Callers must treat shard_traj output as moved-from after
    train_step there.

    On CPU donation is DISABLED entirely (round 6, measured in
    docs/perf_round6.md): XLA:CPU cannot alias the staged batch into the
    update's outputs anyway ('donated buffers were not usable'), and —
    the load-bearing part — a donated jitted call EXECUTES INLINE on the
    dispatching thread instead of dispatching asynchronously, which
    serialises the update against all host work and defeats the
    pipelined loop's overlap. Bit-identical numerics either way.
    """
    import jax

    if jax.default_backend() == "cpu":
        return ()
    return (state_argnum,) + tuple(traj_argnums)


@dataclasses.dataclass
class PPOConfig:
    lr: float = 2.785e-4
    gamma: float = 0.997
    gae_lambda: float = 1.0
    clip_param: float = 0.18
    vf_clip_param: float = 10.0
    vf_loss_coeff: float = 1.0
    entropy_coeff: float = 0.003
    kl_coeff: float = 0.2
    kl_target: float = 0.01
    num_sgd_iter: int = 50
    sgd_minibatch_size: int = 128
    # consumed by the epoch loop, which sizes rollouts so that
    # rollout_length x num_envs == train_batch_size (the learner itself
    # takes whatever [T, B] batch it is handed)
    train_batch_size: int = 4000
    grad_clip: Optional[float] = None
    normalize_advantages: bool = True


class TrainState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    kl_coeff: jnp.ndarray
    step: jnp.ndarray

    @classmethod
    def create(cls, params, tx, kl_coeff: float):
        return cls(params=params, opt_state=tx.init(params),
                   kl_coeff=jnp.asarray(kl_coeff, jnp.float32),
                   step=jnp.zeros((), jnp.int32))


def categorical_entropy(logits: jnp.ndarray) -> jnp.ndarray:
    """Entropy of softmax(logits); safe for -inf-masked logits."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    return -jnp.sum(jnp.where(p > 0, p * logp, 0.0), axis=-1)


def compute_gae(rewards: jnp.ndarray, values: jnp.ndarray,
                dones: jnp.ndarray, last_values: jnp.ndarray,
                gamma: float, lam: float
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Generalised advantage estimation over [T, B] arrays.

    ``dones[t]`` marks that the episode ended at step t (no bootstrap
    across it). Returns (advantages, value_targets), both [T, B].
    """
    next_values = jnp.concatenate([values[1:], last_values[None]], axis=0)
    not_done = 1.0 - dones.astype(jnp.float32)
    deltas = rewards + gamma * next_values * not_done - values

    def scan_fn(carry, x):
        delta, nd = x
        adv = delta + gamma * lam * nd * carry
        return adv, adv

    _, advs = jax.lax.scan(scan_fn, jnp.zeros_like(last_values),
                           (deltas, not_done), reverse=True)
    return advs, advs + values


def ppo_loss(params, apply_fn: Callable, batch: Dict[str, jnp.ndarray],
             kl_coeff: jnp.ndarray, cfg: PPOConfig):
    """Clipped-surrogate PPO loss with KL penalty on one minibatch.

    ``batch``: obs (dict of [N, ...]), actions [N], old_logp [N],
    old_values [N], advantages [N], value_targets [N].
    """
    logits, values = apply_fn(params, batch["obs"])
    # invalid actions arrive already finfo.min-masked in the logits
    # (GNNPolicy), so the softmax family here needs no extra masking
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    logp = jnp.take_along_axis(
        logp_all, batch["actions"][:, None].astype(jnp.int32),
        axis=-1)[:, 0]

    ratio = jnp.exp(logp - batch["old_logp"])
    advs = batch["advantages"]
    surr = jnp.minimum(
        ratio * advs,
        jnp.clip(ratio, 1.0 - cfg.clip_param, 1.0 + cfg.clip_param) * advs)
    policy_loss = -jnp.mean(surr)

    # sample-estimated KL(old || new), as RLlib's PPO uses for its
    # adaptive penalty
    kl = jnp.mean(batch["old_logp"] - logp)

    vf_err = (values - batch["value_targets"]) ** 2
    vf_clipped = batch["old_values"] + jnp.clip(
        values - batch["old_values"], -cfg.vf_clip_param, cfg.vf_clip_param)
    vf_err_clipped = (vf_clipped - batch["value_targets"]) ** 2
    vf_loss = 0.5 * jnp.mean(jnp.maximum(vf_err, vf_err_clipped))

    entropy = jnp.mean(categorical_entropy(logits))

    total = (policy_loss + kl_coeff * kl + cfg.vf_loss_coeff * vf_loss
             - cfg.entropy_coeff * entropy)
    metrics = {"policy_loss": policy_loss, "vf_loss": vf_loss, "kl": kl,
               "entropy": entropy, "total_loss": total,
               "clip_frac": jnp.mean(
                   (jnp.abs(ratio - 1.0) > cfg.clip_param).astype(
                       jnp.float32))}
    return total, metrics


class PPOLearner:
    """Owns the optimiser + jitted, mesh-sharded ``train_step``.

    ``apply_fn(params, obs) -> (logits [N, A], values [N])`` must accept a
    dict of batched observation arrays (see
    ``ddls_tpu.models.policy.batched_policy_apply``).
    """

    def __init__(self, apply_fn: Callable, cfg: PPOConfig, mesh,
                 shard_params_axis: str | None = None,
                 param_sharding: str = "replicated"):
        self.apply_fn = apply_fn
        self.cfg = cfg
        self.mesh = mesh
        # optional tensor parallelism: name a second mesh axis (e.g. "mp")
        # and eligible dense kernels are sharded over their output-feature
        # dim (parallel/mesh.py mp_tree_shardings); XLA emits the tp
        # collectives from the annotations. None = replicate (the default
        # 1-D dp plan; the policy net is small enough that dp alone is
        # usually right — SURVEY §2.10 MP row)
        self.shard_params_axis = shard_params_axis
        # declarative layout from the partition-rule table
        # (parallel/partition.py): "replicated" keeps today's exact
        # sharding objects (bit-identical jit programs); "fsdp"/"tp"
        # assign PartitionSpecs by regex over param-tree paths
        from ddls_tpu.parallel import partition as _partition

        _partition.validate_layout(param_sharding)
        if param_sharding != "replicated":
            if shard_params_axis is not None:
                raise ValueError(
                    "pass either param_sharding or the legacy "
                    "shard_params_axis, not both")
            _partition.validate_mesh_for_layout(mesh, param_sharding)
        self.param_sharding = param_sharding
        self._partition = _partition
        chain = []
        if cfg.grad_clip is not None:
            chain.append(optax.clip_by_global_norm(cfg.grad_clip))
        chain.append(optax.adam(cfg.lr))
        self.tx = optax.chain(*chain)

        self._replicated = replicated_sharding(mesh)
        self._batch_time = NamedSharding(mesh, P(None, "dp"))
        self._batch_only = NamedSharding(mesh, P("dp"))
        self._jit_train_step = None  # built per state layout in init_state
        self._jit_cache = {}  # state-layout key -> compiled jit wrapper
        self._jit_sample = jax.jit(self._sample_actions)

    def _state_shardings(self, state):
        """Sharding tree for a TrainState: replicated, rule-table sharded
        (partition.state_shardings — regex over paths, so params and their
        adam moments get identical specs via suffix matching), or
        tp-sharded by the legacy shape-based rule."""
        if self.param_sharding != "replicated":
            return self._partition.state_shardings(
                self.mesh, state, self.param_sharding)
        if self.shard_params_axis is None:
            return self._replicated
        from ddls_tpu.parallel.mesh import mp_tree_shardings

        return mp_tree_shardings(self.mesh, state,
                                 axis_name=self.shard_params_axis)

    # ------------------------------------------------------------- state
    def init_state(self, params) -> TrainState:
        # copy params: train_step donates its input state, and device_put
        # alone can alias the caller's arrays (which donation would delete)
        params = jax.tree_util.tree_map(jnp.copy, params)
        state = TrainState.create(params, self.tx, self.cfg.kl_coeff)
        shardings = self._state_shardings(state)
        # memoise the jit wrapper per state layout: a fresh jax.jit object
        # has an empty executable cache, so rebuilding it on every
        # init_state would recompile the scanned SGD update even when the
        # layout is unchanged (e.g. re-initialising params between trials)
        key = (jax.tree_util.tree_structure(state),
               tuple(str(getattr(s, "spec", s)) for s in
                     jax.tree_util.tree_leaves(shardings)))
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(
                self._train_step,
                in_shardings=(shardings, self._batch_time,
                              self._batch_only, self._replicated),
                out_shardings=(shardings, self._replicated),
                donate_argnums=traj_donate_argnums(0, 1, 2))
        self._jit_train_step = self._jit_cache[key]
        # multi-host-safe placement: device_put onto a global sharding
        # would run jax's per-leaf assert_equal broadcasts (gloo-
        # colliding under process skew); the state is process-identical
        # by the multi-host seed rules, so each process contributes its
        # copy collective-free (parallel/mesh.py:place_state_tree)
        return place_state_tree(state, shardings)

    # ------------------------------------------------------------ acting
    def _sample_actions(self, params, obs, rng):
        logits, values = self.apply_fn(params, obs)
        actions = jax.random.categorical(rng, logits, axis=-1)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), actions[:, None],
            axis=-1)[:, 0]
        return actions, logp, values

    def sample_actions(self, params, obs, rng):
        """Batched action sampling: dict of [B, ...] -> (actions [B],
        logp [B], values [B])."""
        return self._jit_sample(params, obs, rng)

    # ----------------------------------------------------------- update
    def _minibatch_step(self, state, mb):
        grad_fn = jax.value_and_grad(ppo_loss, has_aux=True)
        (_, metrics), grads = grad_fn(state.params, self.apply_fn, mb,
                                      state.kl_coeff, self.cfg)
        updates, opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)
        state = state.replace(params=params, opt_state=opt_state,
                              step=state.step + 1)
        return state, metrics

    def _train_step(self, state: TrainState, traj: Dict[str, jnp.ndarray],
                    last_values: jnp.ndarray, rng: jnp.ndarray):
        """One PPO update on a [T, B] trajectory batch.

        GAE -> flatten to [N] -> num_sgd_iter epochs of shuffled
        minibatches (both loops are lax.scans). N must be divisible by
        sgd_minibatch_size x 1; the trailing remainder of each shuffled
        epoch is dropped, as in standard JAX PPO implementations.
        """
        cfg = self.cfg
        advs, targets = compute_gae(traj["rewards"], traj["values"],
                                    traj["dones"], last_values,
                                    cfg.gamma, cfg.gae_lambda)
        if cfg.normalize_advantages:
            advs = (advs - advs.mean()) / (advs.std() + 1e-8)

        T, B = traj["rewards"].shape
        n = T * B
        D = self.mesh.shape["dp"]  # static; B % D enforced by shard_batch
        n_loc = n // D

        # [T, B, ...] -> [D, n_loc, ...] with the D axis sharded over dp.
        # Transpose-then-reshape only relabels the sharded B axis (B ->
        # (D, B/D)), so this flattening needs no cross-device movement.
        def to_rows(x):
            x = jnp.swapaxes(x, 0, 1)  # [B, T, ...]
            return x.reshape((D, n_loc) + x.shape[2:])

        flat = {
            "obs": jax.tree_util.tree_map(to_rows, traj["obs"]),
            "actions": to_rows(traj["actions"]),
            "old_logp": to_rows(traj["logp"]),
            "old_values": to_rows(traj["values"]),
            "advantages": to_rows(advs),
            "value_targets": to_rows(targets),
        }
        # each minibatch takes mb_loc samples from every device's shard, so
        # shuffling happens per shard (a batched local gather) rather than
        # as a global permutation that would all-gather the whole batch
        # across ICI every SGD epoch; with per-epoch reshuffles this
        # stratified scheme is statistically equivalent minibatch SGD
        mb_loc = max(min(cfg.sgd_minibatch_size, n) // D, 1)
        num_mb = n_loc // mb_loc

        def epoch(state, erng):
            perms = jax.vmap(lambda k: jax.random.permutation(k, n_loc))(
                jax.random.split(erng, D))

            def shuffle(x):
                # drop the remainder of each shard so the minibatch grid is
                # exact (num_mb * mb_loc <= n_loc)
                x = jax.vmap(lambda row, p: row[p[:num_mb * mb_loc]])(x, perms)
                x = x.reshape((D, num_mb, mb_loc) + x.shape[2:])
                x = jnp.swapaxes(x, 0, 1)  # [num_mb, D, mb_loc, ...]
                return x.reshape((num_mb, D * mb_loc) + x.shape[3:])

            mbs = jax.tree_util.tree_map(shuffle, flat)
            state, ms = jax.lax.scan(self._minibatch_step, state, mbs)
            # mean over the epoch's minibatches, so the KL driving the
            # adaptive coefficient is a batch-wide estimate (as in RLlib),
            # not one arbitrary minibatch
            return state, jax.tree_util.tree_map(jnp.mean, ms)

        state, metrics_per_epoch = jax.lax.scan(
            epoch, state, jax.random.split(rng, cfg.num_sgd_iter))
        metrics = jax.tree_util.tree_map(lambda m: m[-1], metrics_per_epoch)

        # RLlib-style adaptive KL coefficient update
        kl = metrics["kl"]
        kl_coeff = jnp.where(
            kl > 2.0 * cfg.kl_target, state.kl_coeff * 1.5,
            jnp.where(kl < 0.5 * cfg.kl_target, state.kl_coeff * 0.5,
                      state.kl_coeff))
        state = state.replace(kl_coeff=kl_coeff)
        metrics["kl_coeff"] = kl_coeff
        return state, metrics

    def train_step(self, state: TrainState, traj: Dict[str, jnp.ndarray],
                   last_values, rng):
        """Jitted sharded update. ``traj`` leaves are [T, B, ...] with the
        B axis sharded over the mesh's dp axis (see shard_traj)."""
        if self._jit_train_step is None:
            raise RuntimeError("call init_state() before train_step(): the "
                               "update is compiled for the state's layout")
        return self._jit_train_step(state, traj, last_values, rng)

    def shard_traj(self, traj: Dict[str, Any], last_values):
        """Place a host trajectory on the mesh: [T, B, ...] leaves sharded
        over B; last_values [B] sharded over its only axis."""
        traj = shard_batch(self.mesh, traj, batch_axis=1)
        last_values = shard_batch(self.mesh, last_values, batch_axis=0)
        return traj, last_values
