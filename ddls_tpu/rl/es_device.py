"""Evolution strategies trained ENTIRELY on device via jitted episodes.

The §5.8 end-state for one algorithm family: the fitness of every
population member is a full environment episode run inside jit
(`sim/jax_env.py:make_policy_episode_fn` — placement, pricing, lookahead,
event clock, observation, policy forward, sampling all in one `lax.scan`),
vmapped over the antithetic population. One device dispatch evaluates the
whole generation; the ES gradient estimate and parameter update
(`rl/es.py:ESLearner`) are jitted too, so a training generation never
touches a host simulator. Under the tunnelled TPU this is the difference
between ~9 host-driven decisions/s and population-parallel episodes per
dispatch.

The host keeps only the outer generation loop and job-bank sampling
(workload arrivals are data, not computation).
"""
from __future__ import annotations

from typing import Callable, Dict

import numpy as np


def make_generation_fn(episode_fn: Callable, learner):
    """(state, stacked_params, eps, bank, rng) -> (new_state, fitness).

    ``episode_fn`` from `make_policy_episode_fn`; ``stacked_params``/
    ``eps`` from `ESLearner.perturb`. Every population member rolls one
    full episode on the SAME job bank, and each antithetic pair shares
    one action-sampling key, so within-pair fitness differences are pure
    policy effects (common random numbers)."""
    import jax

    def generation(state, stacked_params, eps, bank, rng):
        import jax.numpy as jnp

        pop = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        # common random numbers WITHIN each antithetic pair: the +eps and
        # -eps members share one action-sampling key (perturb stacks
        # [plus, minus], es.py:110-117), so their fitness difference is a
        # pure policy effect, not sampling noise
        half_rngs = jax.random.split(rng, pop // 2)
        rngs = jnp.concatenate([half_rngs, half_rngs])
        out = jax.vmap(episode_fn, in_axes=(None, 0, 0))(
            bank, stacked_params, rngs)
        fitness = out["ret"]
        new_state, metrics = learner.update(state, eps, fitness)
        return new_state, fitness

    return jax.jit(generation)


def train_es_on_device(et, ot, model, learner, params,
                       sample_bank: Callable[[int], Dict],
                       n_generations: int,
                       seed: int = 0,
                       verbose: bool = False):
    """Outer ES loop: everything inside a generation is one jitted
    program. Returns (final_params, history)."""
    import jax

    from ddls_tpu.sim.jax_env import make_policy_episode_fn

    # wide memo ON (the make_policy_episode_fn default): the generation
    # vmaps the episode over the population and the batched probe masks
    # hit lanes out of the lookahead while_loop — every population
    # member carries its own table and hits its cache (ISSUE 17)
    episode_fn = make_policy_episode_fn(et, ot, model)
    generation_fn = make_generation_fn(episode_fn, learner)
    state = learner.init_state(params)
    rng = jax.random.PRNGKey(seed)
    history = []
    for g in range(n_generations):
        rng, r_perturb, r_run = jax.random.split(rng, 3)
        stacked, eps = learner.perturb(state.params, r_perturb)
        bank = sample_bank(g)
        state, fitness = generation_fn(state, stacked, eps, bank, r_run)
        fit = np.asarray(fitness)
        history.append({"generation": g, "fitness_mean": float(fit.mean()),
                        "fitness_max": float(fit.max()),
                        "fitness_min": float(fit.min())})
        if verbose:
            print(f"generation {g}: fitness mean {fit.mean():.2f} "
                  f"max {fit.max():.2f}", flush=True)
    return state.params, history
