"""Vanilla policy gradient (REINFORCE) learner on the mesh.

Replaces the reference's RLlib ``PGTrainer``
(scripts/ramp_job_partitioning_configs/algo/pg.yaml): loss is the plain
score-function estimator ``-mean(logp * G)`` with discounted reward-to-go
returns and no critic (the policy network's value head is simply unused),
matching RLlib's PG semantics. One jitted update per collected batch,
trajectories sharded over the mesh's ``dp`` axis.

Episodes in this MDP terminate inside the rollout window (the env
auto-resets), so reward-to-go is computed with the bootstrap cut at every
``done`` and a zero tail for the truncated remainder -- the small
truncation bias is inherent to PG without a value function.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from ddls_tpu.parallel.mesh import (place_state_tree,
                                    replicated_sharding, shard_batch)


@dataclasses.dataclass
class PGConfig:
    lr: float = 4e-4  # RLlib PG default
    gamma: float = 0.99
    grad_clip: Optional[float] = None
    train_batch_size: int = 200


class PGState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    step: jnp.ndarray

    @classmethod
    def create(cls, params, tx):
        return cls(params=params, opt_state=tx.init(params),
                   step=jnp.zeros((), jnp.int32))


def reward_to_go(rewards: jnp.ndarray, dones: jnp.ndarray,
                 gamma: float) -> jnp.ndarray:
    """Discounted reward-to-go over [T, B], cut at episode boundaries."""
    not_done = 1.0 - dones.astype(jnp.float32)

    def scan_fn(carry, x):
        r, nd = x
        g = r + gamma * nd * carry
        return g, g

    _, returns = jax.lax.scan(scan_fn, jnp.zeros(rewards.shape[1]),
                              (rewards, not_done), reverse=True)
    return returns


class PGLearner:
    """Collector-compatible REINFORCE learner (same interface as
    PPOLearner: ``sample_actions`` / ``shard_traj`` / ``train_step``)."""

    def __init__(self, apply_fn: Callable, cfg: PGConfig, mesh,
                 param_sharding: str = "replicated"):
        self.apply_fn = apply_fn
        self.cfg = cfg
        self.mesh = mesh
        from ddls_tpu.parallel import partition as _partition

        _partition.validate_layout(param_sharding)
        if param_sharding != "replicated":
            _partition.validate_mesh_for_layout(mesh, param_sharding)
        self.param_sharding = param_sharding
        self._partition = _partition
        chain = []
        if cfg.grad_clip is not None:
            chain.append(optax.clip_by_global_norm(cfg.grad_clip))
        chain.append(optax.adam(cfg.lr))
        self.tx = optax.chain(*chain)

        self._replicated = replicated_sharding(mesh)
        self._batch_time = NamedSharding(mesh, P(None, "dp"))
        self._batch_only = NamedSharding(mesh, P("dp"))
        # traj/last_values donated too on accelerator backends (see
        # ppo.traj_donate_argnums): the staged batch is single-use, so
        # its buffers need not outlive the update
        from ddls_tpu.rl.ppo import traj_donate_argnums

        self._donate = traj_donate_argnums(0, 1, 2)
        # replicated jit built eagerly as before — bit-identical default
        self._jit_train_step = jax.jit(
            self._train_step,
            in_shardings=(self._replicated, self._batch_time,
                          self._batch_only),
            out_shardings=(self._replicated, self._replicated),
            donate_argnums=self._donate)
        self._jit_cache = {}
        self._jit_sample = jax.jit(self._sample_actions)

    def _state_shardings(self, state):
        if self.param_sharding == "replicated":
            return self._replicated
        return self._partition.state_shardings(
            self.mesh, state, self.param_sharding)

    def init_state(self, params) -> PGState:
        params = jax.tree_util.tree_map(jnp.copy, params)
        state = PGState.create(params, self.tx)
        shardings = self._state_shardings(state)
        if self.param_sharding != "replicated":
            key = (jax.tree_util.tree_structure(state),
                   tuple(str(getattr(s, "spec", s)) for s in
                         jax.tree_util.tree_leaves(shardings)))
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(
                    self._train_step,
                    in_shardings=(shardings, self._batch_time,
                                  self._batch_only),
                    out_shardings=(shardings, self._replicated),
                    donate_argnums=self._donate)
            self._jit_train_step = self._jit_cache[key]
        # multi-host-safe placement (see parallel/mesh.py:place_state_tree)
        return place_state_tree(state, shardings)

    def _sample_actions(self, params, obs, rng):
        logits, values = self.apply_fn(params, obs)
        actions = jax.random.categorical(rng, logits, axis=-1)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), actions[:, None],
            axis=-1)[:, 0]
        return actions, logp, values

    def sample_actions(self, params, obs, rng):
        return self._jit_sample(params, obs, rng)

    def _loss(self, params, traj, returns):
        T, B = traj["rewards"].shape
        flat_obs = jax.tree_util.tree_map(
            lambda x: x.reshape((T * B,) + x.shape[2:]), traj["obs"])
        logits, _ = self.apply_fn(params, flat_obs)
        logp_all = jax.nn.log_softmax(logits.reshape(T, B, -1), axis=-1)
        logp = jnp.take_along_axis(
            logp_all, traj["actions"][..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        policy_loss = -jnp.mean(logp * returns)
        metrics = {"policy_loss": policy_loss,
                   "total_loss": policy_loss,
                   "mean_return_to_go": jnp.mean(returns)}
        return policy_loss, metrics

    def _train_step(self, state: PGState, traj, last_values):
        returns = reward_to_go(traj["rewards"], traj["dones"],
                               self.cfg.gamma)
        grad_fn = jax.value_and_grad(self._loss, has_aux=True)
        (_, metrics), grads = grad_fn(state.params, traj, returns)
        updates, opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)
        state = state.replace(params=params, opt_state=opt_state,
                              step=state.step + 1)
        return state, metrics

    def train_step(self, state, traj, last_values, rng=None):
        return self._jit_train_step(state, traj, last_values)

    def shard_traj(self, traj: Dict[str, Any], last_values):
        traj = shard_batch(self.mesh, traj, batch_axis=1)
        last_values = shard_batch(self.mesh, last_values, batch_axis=0)
        return traj, last_values
