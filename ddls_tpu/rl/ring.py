"""Multi-segment shared-memory trajectory ring: decoupled actor→learner
dataflow within one host (ROADMAP item 4; the MSRL "dataflow fragments"
shape of arXiv 2210.00882 at single-host scale).

The PR 4 slab (rl/shm.py) gave the deferred-fetch collector a zero-copy
trajectory — worker obs writes land directly in the ``[T+1, B, ...]``
slab rows — but ONE slab rewritten in place forces a bulk defensive copy
of every segment before the asynchronously-executing update may read it:
jax's CPU client zero-copy aliases page-aligned host buffers (shm mmaps
are) into ``device_put`` results whenever no layout change is needed, so
slab views staged into the update would be silently rewritten by the
next segment's worker writes (docs/perf_round7.md). This module replaces
the single slab with a ring of K independently-owned segments so the
copy becomes unnecessary: a segment is not rewritten until it is
RELEASED, and release happens only after whatever staged from it has
been consumed.

Ownership ledger (extending the CLAUDE.md slab contract one level up —
workers still own only their ``[row, env_index]`` slice between a step
command and its pipe reply):

* ``free``      — nobody reads or writes; the only state a lease may
  take a segment from;
* ``leased``    — the COLLECTOR owns it: worker step writes target its
  rows, the collector reads them back as trajectory views;
* ``published`` — the LEARNER owns it: the collector is done, the rows
  are (or are about to be) staged into the update; nobody writes.

``release`` — the transition back to ``free`` — is driven by a
*release token*: any object with jax's ``is_ready()`` protocol (a
staged device array, an update-output metric). The token is chosen per
segment by the ALIAS VERDICT, probed once per segment at its first
staging (``staged_aliases``: does the device-put result share the
segment's host memory?):

* no alias (host→device copy, or the strided shards of a multi-device
  mesh): the staged buffers are independent the moment the copy
  completes — the phase-1 token is the staged tree itself;
* alias (e.g. any 1-device CPU mesh): the update reads the segment's
  own bytes — only an output of the consuming update can mark them
  consumed (donation never bites here: donation is disabled on CPU,
  the only backend where host aliasing exists — rl/ppo.py
  traj_donate_argnums).

Phase 2 (``note_update``) attaches an update-output token
UNCONDITIONALLY after the update dispatch: on donating backends the
update deletes a phase-1 staging token's buffers at dispatch — before
the queued host→device transfer necessarily finished reading the
segment — so a deleted token reads not-ready and waits for this
replacement rather than releasing early.

``lease()`` sweeps ready tokens non-blockingly; when every segment is
unreleased it counts a STALL and polls token readiness under a hard
``timeout_s`` deadline (never ``block_until_ready`` — a wedged update
must surface as the timeout error, not an unbounded hang). All
counters ride the gated telemetry API (one bool check when disabled —
CLAUDE.md hot-path contract).

Segment lifecycle/unlink safety is delegated to ``SlabSet`` (each
segment carries its own ``weakref.finalize`` crash fallback), so an
interrupted run leaves no ``/dev/shm`` litter; the lint engine's
``shm-unlink`` rule covers the creates in rl/shm.py.

DEVICE MODE (round 12, rl/sebulba.py): ``TrajRing(fields=None, ...)``
builds SLAB-LESS segments — no shm, ``views`` empty — for the Sebulba
actor→learner device queue, where a "segment" is one in-flight
device-resident batch rather than host memory. The ledger, the lease
backpressure, and the two-phase token protocol carry over UNCHANGED:
with no host views the alias probe trivially verdicts "copied"
(``staged_aliases`` over zero address ranges), so the phase-1 token is
the tree ``device_put`` onto the learner sub-mesh — ready exactly when
the device-to-device transfer completes — and phase 2's unconditional
update-output token still covers donating backends deleting the staged
buffers at dispatch. Worker-attach surfaces (``specs``,
``segment_names``) reject loudly in this mode.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ddls_tpu import telemetry
from ddls_tpu.rl.shm import SlabSet

#: occupancy histogram bucket bounds (occupied segment count at lease
#: time); rings beyond 8 segments land in the overflow bucket
OCCUPANCY_BUCKETS = tuple(range(9))


def _token_ready(token: Any) -> bool:
    """Non-blocking readiness of a release token (a pytree of jax arrays
    or anything exposing ``is_ready``). A DELETED leaf (a staged buffer
    donated into the update) counts as NOT ready: donation deletes at
    dispatch, not at consumption — the queued host→device transfer may
    still be reading the segment's bytes — so a deleted staging token
    must wait to be REPLACED by the update-output token
    (``note_update``), which is ready only after the consumer ran."""
    import jax

    for leaf in jax.tree_util.tree_leaves(token):
        ready = getattr(leaf, "is_ready", None)
        if ready is None:
            continue
        try:
            if not ready():
                return False
        except RuntimeError:
            return False  # deleted: unusable as a marker — see docstring
    return True


def staged_aliases(staged, views: Dict[str, np.ndarray]) -> bool:
    """Whether any leaf of the staged (device) tree shares memory with
    the segment's host slab views — the per-segment alias verdict.

    Primary probe: each addressable shard's ``unsafe_buffer_pointer``
    against the views' host address ranges (no transfer, works under
    ``jax.transfer_guard``). Fallback: ``np.shares_memory`` on the
    shard's host export. Any probe failure returns True — the
    conservative verdict only delays release until the update's token,
    it can never corrupt data."""
    import jax

    ranges: List[Tuple[int, int]] = []
    for v in views.values():
        base = v.__array_interface__["data"][0]
        ranges.append((base, base + v.nbytes))

    def hits(ptr: int) -> bool:
        return any(lo <= ptr < hi for lo, hi in ranges)

    for leaf in jax.tree_util.tree_leaves(staged):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            return True
        for shard in shards:
            try:
                if hits(shard.data.unsafe_buffer_pointer()):
                    return True
            except Exception:
                try:
                    host = np.asarray(shard.data)
                except Exception:
                    return True
                if any(np.shares_memory(host, v) for v in views.values()):
                    return True
    return False


class RingSegment:
    """One ``[rows, B, ...]`` slab plus its ledger entry. ``slabs=None``
    is a DEVICE-MODE segment (see module docstring): pure ledger entry
    for one in-flight device batch, no host memory, empty ``views``."""

    __slots__ = ("index", "slabs", "state", "release_token", "aliased",
                 "generation")

    def __init__(self, index: int, slabs: Optional[SlabSet]):
        self.index = index
        self.slabs = slabs
        self.state = "free"
        self.release_token: Any = None
        # alias verdict: None until the first staging probes it
        self.aliased: Optional[bool] = None
        # lease counter: token calls carry the generation they belong
        # to, so a SLOW consumer's late token can never release a
        # segment that was already recycled for a newer batch
        self.generation = 0

    @property
    def views(self) -> Dict[str, np.ndarray]:
        return self.slabs.views if self.slabs is not None else {}


class TrajRing:
    """K independently-owned trajectory segments with the ledger above.

    Thread contract: ``lease``/``publish`` run on the collecting thread
    (the main thread at ``pipeline_depth=0``, the background collection
    thread otherwise); ``set_release_token`` may run on either (staging
    tokens on the collector thread, update tokens on the main thread).
    One condition variable serialises the ledger.
    """

    def __init__(self,
                 fields: Optional[Dict[str, Tuple[Tuple[int, ...],
                                                  np.dtype]]],
                 rows: int, num_envs: int, segments: int):
        if segments < 2:
            raise ValueError(
                f"a trajectory ring needs >= 2 segments, got {segments}")
        self.rows = int(rows)
        self.num_envs = int(num_envs)
        # fields=None: device mode — slab-less ledger-only segments
        self.fields = dict(fields) if fields is not None else None
        self.segments: List[RingSegment] = []
        try:
            for i in range(segments):
                self.segments.append(RingSegment(
                    i, None if fields is None
                    else SlabSet(fields, rows=rows, num_envs=num_envs)))
        except Exception:
            self.close()
            raise
        self._cond = threading.Condition()
        self._next = 0  # round-robin lease cursor
        # ledger counters (host ints; fetched once at reporting
        # boundaries — bench's `ring` block, telemetry_report's section)
        self.leases = 0
        self.stalls = 0
        self.publishes = 0
        self.releases = 0
        # exact occupancy histogram: occupied-segment count at each
        # lease, index = occupancy (the bench/report artifact)
        self.occupancy_counts = [0] * (segments + 1)
        self._params_age_sum = 0
        self._params_age_n = 0

    # ------------------------------------------------------------- ledger
    def _sweep_locked(self) -> None:
        for seg in self.segments:
            if seg.state == "published" and seg.release_token is not None:
                if _token_ready(seg.release_token):
                    self._release_locked(seg)

    def _release_locked(self, seg: RingSegment) -> None:
        seg.state = "free"
        seg.release_token = None
        self.releases += 1
        if telemetry.enabled():
            telemetry.inc("rollout.ring.release")
            telemetry.record_event("ring_segment", phase="release",
                                   segment=seg.index,
                                   generation=seg.generation)
        self._cond.notify_all()

    def _next_free_locked(self) -> Optional[RingSegment]:
        K = len(self.segments)
        for off in range(K):
            seg = self.segments[(self._next + off) % K]
            if seg.state == "free":
                self._next = (seg.index + 1) % K
                return seg
        return None

    def lease(self, timeout_s: float = 300.0) -> RingSegment:
        """Claim the next free segment for collection, waiting (and
        counting a stall) while every segment is leased/published —
        token readiness is POLLED under the hard ``timeout_s``
        deadline, so a lost or never-ready release token turns into an
        error instead of a silent hang (same discipline as the vec
        env's ``step_timeout_s``)."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            self._sweep_locked()
            occupied = sum(1 for s in self.segments if s.state != "free")
            self.occupancy_counts[occupied] += 1
            if telemetry.enabled():
                telemetry.observe("rollout.ring.occupancy", occupied,
                                  buckets=OCCUPANCY_BUCKETS)
            seg = self._next_free_locked()
            if seg is None:
                self.stalls += 1
                if telemetry.enabled():
                    telemetry.inc("rollout.ring.stall")
                    telemetry.record_event("ring_segment", phase="stall",
                                           segment=None,
                                           occupied=occupied)
            while seg is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    states = [(s.index, s.state,
                               s.release_token is not None)
                              for s in self.segments]
                    raise RuntimeError(
                        f"trajectory ring lease timed out after "
                        f"{timeout_s:.0f}s — no segment released "
                        f"(ledger: {states}); a published segment's "
                        "release token was never set or never became "
                        "ready")
                # bounded poll: wait for a release/token notification
                # (or the next readiness check) and re-sweep. Polling —
                # not jax.block_until_ready — keeps the deadline REAL:
                # an update that never completes (the documented wedge
                # mode of the tunnelled TPU) surfaces as the timeout
                # error above instead of an unbounded silent hang.
                self._cond.wait(timeout=min(remaining, 0.05))
                self._sweep_locked()
                seg = self._next_free_locked()
            seg.state = "leased"
            seg.release_token = None
            seg.generation += 1
            self.leases += 1
            if telemetry.enabled():
                telemetry.inc("rollout.ring.lease")
                telemetry.record_event("ring_segment", phase="lease",
                                       segment=seg.index,
                                       generation=seg.generation)
            return seg

    def publish(self, seg: RingSegment) -> None:
        """Collection done: ownership passes to the learner. The segment
        stays unwritable until its release token reports ready."""
        with self._cond:
            if seg.state != "leased":
                raise RuntimeError(
                    f"publish on segment {seg.index} in state "
                    f"{seg.state!r} (must be leased)")
            seg.state = "published"
            self.publishes += 1
            if telemetry.enabled():
                telemetry.inc("rollout.ring.publish")
                telemetry.record_event("ring_segment", phase="publish",
                                       segment=seg.index,
                                       generation=seg.generation)
            self._cond.notify_all()

    def set_release_token(self, seg: RingSegment, token: Any,
                          generation: Optional[int] = None) -> None:
        """Attach the consumption marker that turns this published
        segment free once ready (staged tree when staging copied, an
        update output when staging aliased the segment). ``generation``
        — when the caller knows which lease its batch came from — makes
        a LATE token harmless: it no-ops if the segment was already
        released and re-leased for a newer batch."""
        with self._cond:
            if seg.state != "published":
                return  # already released (or re-leased): nothing to do
            if generation is not None and seg.generation != generation:
                return  # stale consumer: this token's batch is long gone
            seg.release_token = token
            self._cond.notify_all()

    def sweep(self) -> None:
        """Release every published segment whose token is ready (the
        same pass a lease performs) — for callers that need the ledger
        current without leasing (e.g. the vec env's reset guard)."""
        with self._cond:
            self._sweep_locked()

    def release(self, seg: RingSegment) -> None:
        """Immediate explicit release (teardown/tests); the normal path
        is token-driven via the lease-time sweep."""
        with self._cond:
            if seg.state == "free":
                return
            self._release_locked(seg)

    # ------------------------------------------- consumer token protocol
    # The ONE authoritative implementation of the two-phase handoff
    # (train/loops.py and bench.py both call these — the verdict/token
    # choice must never fork between consumers).
    def note_staged(self, seg: RingSegment, staged_tree,
                    generation: Optional[int] = None) -> None:
        """Phase 1, at staging time: probe the alias verdict ONCE per
        segment (cached — the steady state stays probe-free), and when
        staging COPIED the segment's bytes, attach the staged tree as
        the release token (free the moment the copies land). Pass the
        batch's ``ring_generation`` so a slow consumer can never token
        a recycled segment."""
        if seg.aliased is None:
            seg.aliased = staged_aliases(staged_tree, seg.views)
        if not seg.aliased:
            self.set_release_token(seg, staged_tree,
                                   generation=generation)

    def note_update(self, seg: RingSegment, update_output,
                    generation: Optional[int] = None) -> None:
        """Phase 2, after the update dispatch — UNCONDITIONAL: for an
        alias-verdict segment the update output is the earliest safe
        release marker; for a copy-verdict segment it REPLACES a phase-1
        staging token whose buffers the update may have donated-and-
        deleted (a deleted token reads not-ready forever — see
        ``_token_ready``). A segment the phase-1 token already released
        — or one re-leased past this batch's ``generation`` — is a
        no-op."""
        self.set_release_token(seg, update_output, generation=generation)

    # ------------------------------------------------------------ metrics
    def observe_params_age(self, age: int) -> None:
        """Record one consumed batch's params age (updates between its
        collection params snapshot and its consumption) — the V-trace
        staleness the ring asks IMPALA to absorb."""
        self._params_age_sum += int(age)
        self._params_age_n += 1
        if telemetry.enabled():
            telemetry.observe("rollout.ring.params_age_updates", int(age),
                              buckets=OCCUPANCY_BUCKETS)
            telemetry.record_event("params_age", value=int(age))

    def stats(self) -> Dict[str, Any]:
        """Ledger counters as one host-side dict (no device fetch):
        the bench JSON `ring` block / report section payload."""
        with self._cond:
            return {
                "segments": len(self.segments),
                "rows": self.rows,
                "leases": self.leases,
                "stalls": self.stalls,
                "publishes": self.publishes,
                "releases": self.releases,
                "occupancy_counts": list(self.occupancy_counts),
                "mean_params_age": (
                    self._params_age_sum / self._params_age_n
                    if self._params_age_n else None),
                "aliased_segments": [bool(s.aliased) for s in self.segments
                                     if s.aliased is not None],
            }

    # ---------------------------------------------------------- lifecycle
    def specs(self) -> List[list]:
        """Per-segment slab specs for the workers' ring attach."""
        if self.fields is None:
            raise RuntimeError(
                "device-mode trajectory ring has no slabs: worker "
                "attach (specs) is a shm-ring surface only")
        return [seg.slabs.spec() for seg in self.segments]

    def segment_names(self) -> List[str]:
        if self.fields is None:
            raise RuntimeError(
                "device-mode trajectory ring has no slabs: worker "
                "attach (segment_names) is a shm-ring surface only")
        return [name for seg in self.segments
                for name in seg.slabs.segment_names()]

    def close(self) -> None:
        """Unlink every segment (idempotent); each SlabSet's own
        ``weakref.finalize`` covers paths that never reach here."""
        for seg in self.segments:
            if seg.slabs is not None:
                seg.slabs.close()
