"""Pure-JAX IMPALA learner: V-trace off-policy actor-critic on a mesh.

Replaces the reference's RLlib ``ImpalaTrainer``
(scripts/ramp_job_partitioning_configs/algo/impala.yaml;
rllib_epoch_loop.py:34 trains it through the same epoch loop as PPO). The
reference's IMPALA decouples actors from the learner with Ray queues; here
the decoupling is *statistical* first — the vectorised collector's
sampling policy lags the learner, and V-trace importance weighting
(Espeholt et al. 2018, arXiv 1802.01561) corrects exactly that lag — and,
since the depth-K pipelined loop (train/loops.py ``pipeline_depth``, the
rl/ring.py trajectory ring), infrastructural too: up to K collected
batches ride ahead of the learner, each arriving ``params_age_updates``
updates stale, the behavior logp travelling in the traj. The update
itself is one jitted SPMD program: trajectories sharded over the mesh's
``dp`` axis, parameters replicated, gradient all-reduce emitted by XLA.
The ``mean_rho`` / ``clip_rho_fraction`` metrics make the absorbed
staleness visible: rho drifting from 1 (and the clip engaging) is the
signature of batches collected too many updates behind the target
policy.

Config surface follows the reference's impala.yaml: vtrace rho/pg-rho clips
1.0, ``vtrace_drop_last_ts``, grad_clip 40, adam (``opt_type: adam``),
vf_loss_coeff 0.5, entropy_coeff 0.01.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from ddls_tpu.parallel.mesh import (place_state_tree,
                                    replicated_sharding, shard_batch)


@dataclasses.dataclass
class ImpalaConfig:
    lr: float = 5e-4
    gamma: float = 0.99
    vtrace_clip_rho_threshold: float = 1.0
    vtrace_clip_pg_rho_threshold: float = 1.0
    vtrace_drop_last_ts: bool = True
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: Optional[float] = 40.0
    opt_type: str = "adam"
    # rmsprop branch (reference impala.yaml decay/momentum/epsilon)
    decay: float = 0.99
    momentum: float = 0.0
    epsilon: float = 0.1
    train_batch_size: int = 500


class ImpalaState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    step: jnp.ndarray

    @classmethod
    def create(cls, params, tx):
        return cls(params=params, opt_state=tx.init(params),
                   step=jnp.zeros((), jnp.int32))


def vtrace(behavior_logp: jnp.ndarray,
           target_logp: jnp.ndarray,
           rewards: jnp.ndarray,
           values: jnp.ndarray,
           dones: jnp.ndarray,
           last_values: jnp.ndarray,
           gamma: float,
           clip_rho: float = 1.0,
           clip_pg_rho: float = 1.0
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """V-trace targets and policy-gradient advantages over [T, B] arrays.

    Returns (vs, pg_advantages), both [T, B]:

        rho_t  = min(clip_rho, pi/mu);  c_t = min(1, pi/mu)
        delta_t = rho_t (r_t + gamma V(x_{t+1}) - V(x_t))
        vs_t   = V(x_t) + delta_t + gamma c_t (vs_{t+1} - V(x_{t+1}))
        adv_t  = min(clip_pg_rho, pi/mu) (r_t + gamma vs_{t+1} - V(x_t))

    ``dones[t]`` cuts the bootstrap across episode ends.
    """
    rho = jnp.exp(target_logp - behavior_logp)
    clipped_rho = jnp.minimum(clip_rho, rho)
    cs = jnp.minimum(1.0, rho)
    not_done = 1.0 - dones.astype(jnp.float32)

    next_values = jnp.concatenate([values[1:], last_values[None]], axis=0)
    deltas = clipped_rho * (
        rewards + gamma * next_values * not_done - values)

    def scan_fn(carry, x):
        delta, c, nd = x
        acc = delta + gamma * c * nd * carry
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(last_values), (deltas, cs, not_done),
        reverse=True)
    vs = values + vs_minus_v

    next_vs = jnp.concatenate([vs[1:], last_values[None]], axis=0)
    pg_adv = jnp.minimum(clip_pg_rho, rho) * (
        rewards + gamma * next_vs * not_done - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner:
    """Jitted mesh-sharded V-trace update; collector-compatible interface
    (``sample_actions`` / ``shard_traj`` / ``train_step``, as PPOLearner)."""

    def __init__(self, apply_fn: Callable, cfg: ImpalaConfig, mesh,
                 param_sharding: str = "replicated"):
        self.apply_fn = apply_fn
        self.cfg = cfg
        self.mesh = mesh
        from ddls_tpu.parallel import partition as _partition

        _partition.validate_layout(param_sharding)
        if param_sharding != "replicated":
            _partition.validate_mesh_for_layout(mesh, param_sharding)
        self.param_sharding = param_sharding
        self._partition = _partition
        chain = []
        if cfg.grad_clip is not None:
            chain.append(optax.clip_by_global_norm(cfg.grad_clip))
        if cfg.opt_type == "rmsprop":
            chain.append(optax.rmsprop(cfg.lr, decay=cfg.decay,
                                       momentum=cfg.momentum,
                                       eps=cfg.epsilon))
        else:
            chain.append(optax.adam(cfg.lr))
        self.tx = optax.chain(*chain)

        self._replicated = replicated_sharding(mesh)
        self._batch_time = NamedSharding(mesh, P(None, "dp"))
        self._batch_only = NamedSharding(mesh, P("dp"))
        # traj/last_values donated too on accelerator backends (see
        # ppo.traj_donate_argnums): the staged batch is single-use, so
        # its buffers need not outlive the update
        from ddls_tpu.rl.ppo import traj_donate_argnums

        self._donate = traj_donate_argnums(0, 1, 2)
        # the replicated jit is built eagerly, exactly as before the
        # partition engine existed — same object, same program, so the
        # default layout stays bit-identical
        self._jit_train_step = jax.jit(
            self._train_step,
            in_shardings=(self._replicated, self._batch_time,
                          self._batch_only),
            out_shardings=(self._replicated, self._replicated),
            donate_argnums=self._donate)
        self._jit_cache = {}
        self._jit_sample = jax.jit(self._sample_actions)

    def _state_shardings(self, state):
        if self.param_sharding == "replicated":
            return self._replicated
        return self._partition.state_shardings(
            self.mesh, state, self.param_sharding)

    def init_state(self, params) -> ImpalaState:
        params = jax.tree_util.tree_map(jnp.copy, params)
        state = ImpalaState.create(params, self.tx)
        shardings = self._state_shardings(state)
        if self.param_sharding != "replicated":
            key = (jax.tree_util.tree_structure(state),
                   tuple(str(getattr(s, "spec", s)) for s in
                         jax.tree_util.tree_leaves(shardings)))
            if key not in self._jit_cache:
                self._jit_cache[key] = jax.jit(
                    self._train_step,
                    in_shardings=(shardings, self._batch_time,
                                  self._batch_only),
                    out_shardings=(shardings, self._replicated),
                    donate_argnums=self._donate)
            self._jit_train_step = self._jit_cache[key]
        # multi-host-safe placement (see parallel/mesh.py:place_state_tree)
        return place_state_tree(state, shardings)

    # ------------------------------------------------------------ acting
    def _sample_actions(self, params, obs, rng):
        logits, values = self.apply_fn(params, obs)
        actions = jax.random.categorical(rng, logits, axis=-1)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), actions[:, None],
            axis=-1)[:, 0]
        return actions, logp, values

    def sample_actions(self, params, obs, rng):
        return self._jit_sample(params, obs, rng)

    # ------------------------------------------------------------ update
    def _loss(self, params, traj, last_values):
        cfg = self.cfg
        T, B = traj["rewards"].shape

        def flat_apply(obs):
            flat = jax.tree_util.tree_map(
                lambda x: x.reshape((T * B,) + x.shape[2:]), obs)
            logits, values = self.apply_fn(params, flat)
            return (logits.reshape(T, B, -1), values.reshape(T, B))

        logits, values = flat_apply(traj["obs"])
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        target_logp = jnp.take_along_axis(
            logp_all, traj["actions"][..., None].astype(jnp.int32),
            axis=-1)[..., 0]

        vs, pg_adv = vtrace(
            traj["logp"], target_logp, traj["rewards"], values,
            traj["dones"], last_values, cfg.gamma,
            cfg.vtrace_clip_rho_threshold,
            cfg.vtrace_clip_pg_rho_threshold)

        if cfg.vtrace_drop_last_ts:
            # the reference drops the last timestep, whose bootstrap uses
            # values from the stale behavior policy (impala.yaml)
            sl = slice(None, -1)
        else:
            sl = slice(None)
        policy_loss = -jnp.mean(target_logp[sl] * pg_adv[sl])
        vf_loss = 0.5 * jnp.mean((values[sl] - vs[sl]) ** 2)
        logp_masked = jnp.where(jnp.isfinite(logp_all), logp_all, 0.0)
        entropy = -jnp.mean(jnp.sum(
            jnp.exp(logp_all[sl]) * logp_masked[sl], axis=-1))

        total = (policy_loss + cfg.vf_loss_coeff * vf_loss
                 - cfg.entropy_coeff * entropy)
        rho_all = jnp.exp(target_logp[sl] - traj["logp"][sl])
        metrics = {"policy_loss": policy_loss, "vf_loss": vf_loss,
                   "entropy": entropy, "total_loss": total,
                   "mean_rho": jnp.mean(rho_all),
                   # fraction of importance weights the rho clip truncated
                   # — the staleness-absorption gauge for the depth-K
                   # pipelined loop (0 on-policy; rising values mean the
                   # behavior policy is falling behind the target)
                   "clip_rho_fraction": jnp.mean(
                       (rho_all > cfg.vtrace_clip_rho_threshold)
                       .astype(jnp.float32))}
        return total, metrics

    def _train_step(self, state: ImpalaState, traj, last_values):
        grad_fn = jax.value_and_grad(self._loss, has_aux=True)
        (_, metrics), grads = grad_fn(state.params, traj, last_values)
        updates, opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)
        state = state.replace(params=params, opt_state=opt_state,
                              step=state.step + 1)
        return state, metrics

    def train_step(self, state, traj, last_values, rng=None):
        return self._jit_train_step(state, traj, last_values)

    def shard_traj(self, traj: Dict[str, Any], last_values):
        traj = shard_batch(self.mesh, traj, batch_axis=1)
        last_values = shard_batch(self.mesh, last_values, batch_axis=0)
        return traj, last_values
