"""Evolution Strategies learner: population-batched parameter search.

Replaces the reference's RLlib ``ESTrainer``
(scripts/ramp_job_partitioning_configs/algo/es.yaml): antithetic Gaussian
parameter perturbations, centered-rank fitness shaping, and an Adam step on
the score-function gradient estimate (Salimans et al. 2017,
arXiv 1703.03864). Where RLlib evaluates population members on separate Ray
workers with a shared noise table, the TPU-native design batches the
*population itself*: perturbed parameter sets are stacked along a leading
population axis on device, every vectorised env runs one member, and a
single vmapped forward computes all members' actions per step -- the
population dimension rides the MXU instead of a worker pool.

Fitness is the return of a fixed-length interaction window per member
(auto-resetting envs), rather than exactly-one-episode-per-worker; set
``rollout_length`` to the env's episode length to recover whole-episode
fitness.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct


@dataclasses.dataclass
class ESConfig:
    # reference es.yaml surface
    stepsize: float = 0.01
    noise_stdev: float = 0.02
    l2_coeff: float = 0.005
    episodes_per_batch: int = 1000
    report_length: int = 10
    # probability that an epoch also evaluates the UNPERTURBED mean params
    # (reported as eval_fitness_mean, never folded into the gradient) —
    # RLlib's eval_prob marks whole worker rollouts as eval rollouts; here
    # the unit of evaluation is an epoch's interaction window
    eval_prob: float = 0.03
    # exploration noise on the policy's action logits during fitness
    # rollouts. RLlib's action_noise_std perturbs continuous actions
    # directly; the discrete-action analogue is Gaussian logit noise ahead
    # of the argmax (0 = deterministic greedy, the old behaviour)
    action_noise_std: float = 0.01
    train_batch_size: int = 2000


class ESState(struct.PyTreeNode):
    params: Any
    opt_state: Any
    step: jnp.ndarray

    @classmethod
    def create(cls, params, tx):
        return cls(params=params, opt_state=tx.init(params),
                   step=jnp.zeros((), jnp.int32))


def centered_ranks(fitness: jnp.ndarray) -> jnp.ndarray:
    """Map fitness values to centered ranks in [-0.5, 0.5] (the reference
    trainer's rank shaping; robust to fitness scale)."""
    n = fitness.shape[0]
    ranks = jnp.argsort(jnp.argsort(fitness))
    return ranks.astype(jnp.float32) / jnp.maximum(n - 1, 1) - 0.5


class ESLearner:
    """Population-batched ES with a collector-free interface.

    ``apply_fn(params, obs_batch) -> (logits [N, A], values [N])`` as for
    the gradient learners; the value head is unused.
    """

    def __init__(self, apply_fn: Callable, cfg: ESConfig, mesh,
                 population: int, param_sharding: str = "replicated"):
        if param_sharding != "replicated":
            raise ValueError(
                f"param_sharding={param_sharding!r} requires the device-"
                "collection trajectory contract, which ES does not "
                "implement (population perturbation learner); use "
                "param_sharding='replicated' or a PPO/IMPALA/PG loop")
        if population % 2 != 0:
            raise ValueError(
                f"ES population must be even (antithetic pairs), got "
                f"{population}")
        self.param_sharding = param_sharding
        self.apply_fn = apply_fn
        self.cfg = cfg
        self.mesh = mesh
        self.population = population
        self.tx = optax.adam(cfg.stepsize)

        self._jit_perturb = jax.jit(self._perturb)
        self._jit_pop_actions = jax.jit(self._pop_actions)
        # state donated on accelerators only (see ppo.traj_donate_argnums:
        # CPU donation forces inline execution of the jitted call)
        from ddls_tpu.rl.ppo import traj_donate_argnums

        self._jit_update = jax.jit(self._update,
                                   donate_argnums=traj_donate_argnums(0))

    def init_state(self, params) -> ESState:
        params = jax.tree_util.tree_map(jnp.copy, params)
        return ESState.create(params, self.tx)

    # -------------------------------------------------------- population
    def _perturb(self, params, rng) -> Tuple[Any, Any]:
        """Antithetic population: eps for P/2 members, mirrored for the
        rest. Returns (stacked_params [P, ...], eps [P/2, ...])."""
        half = self.population // 2
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        eps_leaves = [
            jax.random.normal(k, (half,) + leaf.shape, leaf.dtype)
            for k, leaf in zip(keys, leaves)]
        eps = jax.tree_util.tree_unflatten(treedef, eps_leaves)

        def stack(leaf, e):
            plus = leaf[None] + self.cfg.noise_stdev * e
            minus = leaf[None] - self.cfg.noise_stdev * e
            return jnp.concatenate([plus, minus], axis=0)

        stacked = jax.tree_util.tree_map(stack, params, eps)
        return stacked, eps

    def perturb(self, params, rng):
        return self._jit_perturb(params, rng)

    def _pop_actions(self, stacked_params, obs, rng, noise_std):
        """Action for each member on its own env: obs leaves are [P, ...];
        one vmapped forward covers the population. ``noise_std`` Gaussian
        noise lands on the logits before the argmax (discrete analogue of
        RLlib's action-space noise; a traced scalar so 0.0 and >0 share one
        compiled kernel). Masked logits sit at -inf or at GNNPolicy's
        finfo.min clamp (models/policy.py:93-97) — either way ~1e38 below
        any valid logit, an offset Gaussian noise cannot bridge, so noise
        never unmasks an invalid action."""

        def one(member_params, member_obs, member_rng):
            batched = jax.tree_util.tree_map(lambda x: x[None], member_obs)
            logits, _ = self.apply_fn(member_params, batched)
            logits = logits[0]
            logits = logits + noise_std * jax.random.normal(
                member_rng, logits.shape, logits.dtype)
            return jnp.argmax(logits, axis=-1)

        keys = jax.random.split(rng, self.population)
        return jax.vmap(one)(stacked_params, obs, keys)

    def pop_actions(self, stacked_params, obs, rng=None, noise_std=None):
        if rng is None:
            # deterministic-greedy convenience path (the pre-noise API):
            # without a caller rng there is no honest randomness, so noise
            # is off — and asking for noise without an rng is an error,
            # not a silent override
            if noise_std:
                raise ValueError(
                    "pop_actions(noise_std > 0) needs an rng; without one "
                    "the same frozen noise pattern would repeat every call")
            rng = jax.random.PRNGKey(0)
            noise_std = 0.0
        if noise_std is None:
            noise_std = self.cfg.action_noise_std
        return self._jit_pop_actions(stacked_params, obs, rng,
                                     jnp.float32(noise_std))

    # ------------------------------------------------------------ update
    def _update(self, state: ESState, eps, fitness):
        """Adam step on the ES gradient estimate with rank shaping and L2
        decay: g = -1/(P sigma) sum_i w_i eps_i + l2 * theta."""
        cfg = self.cfg
        weights = centered_ranks(fitness)
        half = self.population // 2
        # antithetic pair weight: w_plus - w_minus per eps sample
        pair_w = weights[:half] - weights[half:]

        def grad_leaf(theta, e):
            # e: [P/2, ...]; tensordot over the population axis
            g = -jnp.tensordot(pair_w, e, axes=1) / (
                self.population * cfg.noise_stdev)
            return g + cfg.l2_coeff * theta

        grads = jax.tree_util.tree_map(grad_leaf, state.params, eps)
        updates, opt_state = self.tx.update(grads, state.opt_state,
                                            state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {"fitness_mean": jnp.mean(fitness),
                   "fitness_max": jnp.max(fitness),
                   "fitness_std": jnp.std(fitness),
                   "grad_norm": optax.global_norm(grads)}
        return state.replace(params=params, opt_state=opt_state,
                             step=state.step + 1), metrics

    def update(self, state, eps, fitness):
        return self._jit_update(state, eps, jnp.asarray(fitness,
                                                        jnp.float32))

    # --------------------------------------------------------- evaluation
    def evaluate_population(self, stacked_params, vec_env, window: int,
                            rng=None, noise_std=None) -> np.ndarray:
        """Run every env for ``window`` steps, env i driven by member i;
        returns summed rewards [P]. ``rng`` seeds the per-step action
        noise (``noise_std``, default cfg.action_noise_std)."""
        from ddls_tpu.rl.rollout import stack_obs

        if rng is None:
            rng = jax.random.PRNGKey(0)
        fitness = np.zeros(self.population, dtype=np.float64)
        for _ in range(window):
            rng, sub = jax.random.split(rng)
            obs = stack_obs(vec_env.obs)
            actions = np.asarray(self.pop_actions(stacked_params, obs, sub,
                                                  noise_std=noise_std))
            _, rewards, _ = vec_env.step(actions)
            fitness += rewards
        return fitness

    def evaluate_mean_params(self, params, vec_env, window: int,
                             rng=None) -> float:
        """Fitness of the UNPERTURBED params (cfg.eval_prob hook): every
        env runs the same mean parameters, noise-free; returns the mean
        summed reward across envs."""
        stacked = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (self.population,) + x.shape),
            params)
        fitness = self.evaluate_population(stacked, vec_env, window, rng,
                                           noise_std=0.0)
        return float(np.mean(fitness))
