"""L5 tests: PAC-ML env, observation encoding, rewards, baseline actors."""
import numpy as np
import pytest

from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.envs.baselines import (AcceptableJCT, MaxParallelism,
                                     NoParallelism, RandomActor)
from ddls_tpu.envs.obs import GRAPH_FEATURE_DIM


def _make_env(dataset_dir, reward="job_acceptance", reward_kwargs=None,
              steps=50, interarrival=1000.0, replication=3,
              sampling="remove", max_parts=8):
    return RampJobPartitioningEnvironment(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": interarrival},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.1, "max_val": 1.0, "decimals": 2},
            "replication_factor": replication,
            "job_sampling_mode": sampling,
            "num_training_steps": steps},
        max_partitions_per_op=max_parts,
        min_op_run_time_quantum=0.01,
        reward_function=reward,
        reward_function_kwargs=reward_kwargs or {"fail_reward": -1,
                                                 "success_reward": 1},
        max_simulation_run_time=1e5,
        pad_obs_kwargs={"max_nodes": 150},
        apply_action_mask=True)


def test_obs_shapes_and_mask(dataset_dir):
    env = _make_env(dataset_dir)
    obs = env.reset(seed=0)
    max_e = (150 * 149) // 2
    assert obs["node_features"].shape == (150, 5)
    assert obs["edge_features"].shape == (max_e, 2)
    assert obs["edges_src"].shape == (max_e,)
    assert obs["graph_features"].shape == (GRAPH_FEATURE_DIM + 9,)
    assert obs["action_mask"][0] == 1  # 0 always valid
    # odd actions > 1 invalid
    for a in (3, 5, 7):
        assert obs["action_mask"][a] == 0
    assert obs["node_features"].min() >= 0
    assert obs["node_features"].max() <= 1
    assert np.all(np.isfinite(obs["graph_features"]))
    # node_split matches the queued job's op count
    job = list(env.cluster.job_queue.jobs.values())[0]
    assert obs["node_split"][0] == job.graph.n_ops
    assert obs["edge_split"][0] == job.graph.n_deps


def test_full_episode_with_acceptable_jct(dataset_dir):
    env = _make_env(dataset_dir)
    obs = env.reset(seed=0)
    actor = AcceptableJCT()
    total_reward, steps = 0.0, 0
    done = False
    while not done and steps < 100:
        job = list(env.cluster.job_queue.jobs.values())[0]
        action = actor.compute_action(obs, job_to_place=job)
        obs, reward, done, info = env.step(action)
        total_reward += reward
        steps += 1
    assert done
    e = env.cluster.episode_stats
    assert e["num_jobs_arrived"] == (e["num_jobs_completed"]
                                     + e["num_jobs_blocked"])
    # job_acceptance reward: +1/-1 per decision
    assert total_reward == (e["num_jobs_completed"] - e["num_jobs_blocked"])


def test_invalid_action_raises_or_falls_back(dataset_dir):
    env = _make_env(dataset_dir)
    obs = env.reset(seed=0)
    with pytest.raises(ValueError):
        env.step(3)  # odd -> invalid under mask
    env.apply_action_mask = False
    obs, reward, done, info = env.step(3)  # falls back to 0 (don't place)
    assert reward == -1  # job blocked


def test_action_zero_blocks_job(dataset_dir):
    env = _make_env(dataset_dir)
    env.reset(seed=0)
    n_blocked_before = env.cluster.episode_stats["num_jobs_blocked"]
    obs, reward, done, info = env.step(0)
    assert env.cluster.episode_stats["num_jobs_blocked"] == n_blocked_before + 1
    assert reward == -1


def test_baseline_ordering(dataset_dir):
    """Sanity: AcceptableJCT should accept at least as many jobs as
    NoParallelism under tight SLAs (the paper's qualitative ordering)."""
    results = {}
    for actor_cls in (NoParallelism, AcceptableJCT, MaxParallelism):
        env = _make_env(dataset_dir, replication=4)
        obs = env.reset(seed=42)
        actor = actor_cls()
        done, steps = False, 0
        while not done and steps < 150:
            job = list(env.cluster.job_queue.jobs.values())[0]
            action = actor.compute_action(obs, job_to_place=job)
            obs, _, done, _ = env.step(action)
            steps += 1
        results[actor_cls.name] = (
            env.cluster.episode_stats["acceptance_rate"])
    assert results["acceptable_jct"] >= results["no_parallelism"]


def test_lookahead_jct_reward(dataset_dir):
    env = _make_env(dataset_dir, reward="lookahead_job_completion_time",
                    reward_kwargs={
                        "fail_reward": "job_sequential_completion_time",
                        "fail_reward_factor": 10, "sign": -1,
                        "normaliser": "job_sequential_completion_time_times_fail_reward_factor"})
    obs = env.reset(seed=0)
    # blocked job (action 0): reward = -(seq*10)/(seq*10) = -1
    obs, reward, done, info = env.step(0)
    assert reward == pytest.approx(-1.0)
    # placed job: reward = -(jct/(seq*10)) in (-1, 0)
    if not done:
        valid = obs["action_set"][obs["action_mask"].astype(bool)]
        obs, reward, done, info = env.step(int(valid[-1]))
        if reward != pytest.approx(-1.0):
            assert -1.0 < reward < 0.0


def _jct_env(dataset_dir, interarrival, sim_end, steps=40):
    return RampJobPartitioningEnvironment(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "max_files": 1,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": interarrival},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 1.0},
            "replication_factor": 2,
            "job_sampling_mode": "remove",
            "num_training_steps": steps},
        max_partitions_per_op=8,
        min_op_run_time_quantum=0.01,
        reward_function="multi_objective_jct_blocking",
        reward_function_kwargs={"sign": -1, "blocking_weight": 1},
        max_simulation_run_time=sim_end,
        pad_obs_kwargs={"max_nodes": 150, "max_edges": 512},
        apply_action_mask=True)


def test_jct_reward_survives_episode_end_sweep(dataset_dir):
    """When the episode ends during the AUTO-steps (after the placed-job
    bookkeeping), cluster finalisation sweeps the still-running placed job
    into jobs_blocked; JCT rewards must fall back to the env's
    pre-auto-step stash instead of raising (regression: round-4 JCT
    training crashed on 'placed job idx ... is neither running nor
    completed').

    Timeline engineered with a probed JCT T: job A placed at 0 (completes
    at T), job B arrives at 0.6T and is placed; B's cluster step ends at
    A's completion (T), the auto-steps then hit sim_end = 1.3T with B
    still running -> B is swept while still in placed_job_idxs."""
    probe = _jct_env(dataset_dir, interarrival=1e9, sim_end=1e12)
    probe.reset(seed=0)
    probe.step(1)
    ji = probe.last_job_arrived_job_idx
    probed = (probe.cluster.jobs_running.get(ji)
              or probe.cluster.jobs_completed.get(ji))
    T = probed.details["lookahead_job_completion_time"]

    env = _jct_env(dataset_dir, interarrival=0.6 * T, sim_end=1.3 * T)
    obs = env.reset(seed=0)
    obs, r1, done, info = env.step(1)       # job A placed
    assert not done
    obs, r2, done, info = env.step(1)       # job B placed, then swept
    assert done
    ji = env.last_job_arrived_job_idx
    assert ji in env.placed_job_idxs        # B passed every gate
    assert ji not in env.cluster.jobs_running
    assert ji not in env.cluster.jobs_completed
    assert ji in env.cluster.jobs_blocked   # swept by finalisation
    assert env.last_placed_job is not None
    expected = -(env.last_placed_job.details[
        "lookahead_job_completion_time"]
        / env.last_placed_job.seq_completion_time)
    assert r2 == pytest.approx(expected)


def test_fixed_degree_packing_actor():
    """The round-5 extracted rule actor: plays its degree iff valid,
    declines otherwise (docs/results_round5/rule_extraction.md)."""
    from ddls_tpu.envs.baselines import FixedDegreePacking

    actor = FixedDegreePacking(degree=8)
    obs = {"action_set": np.arange(17, dtype=np.int32),
           "action_mask": np.zeros(17, dtype=np.int32)}
    obs["action_mask"][[0, 1, 2, 4, 8]] = 1
    assert actor.compute_action(obs) == 8
    obs["action_mask"][8] = 0
    assert actor.compute_action(obs) == 0
    assert FixedDegreePacking(degree=4).compute_action(obs) == 4


def test_adaptive_degree_packing_static_target():
    """The d*(scale, load) law's geometry snap (round 5,
    docs/results_round5/degree_map.md): degrees must tile the group
    structure; snapping is by STATIC geometry, never by current
    occupancy (a busy cluster declines rather than shrink the degree)."""
    from ddls_tpu.envs.baselines import AdaptiveDegreePacking

    actor = AdaptiveDegreePacking()
    # 6x6x2 topology: group = 12; target 16 must snap to 12 (one whole
    # group), not 14 (tiles nothing) — the measured out-of-sample win
    assert actor._static_target(16, 12, 16, (6, 6, 2)) == 12
    # 4x4x2: group = 8; 16 = two whole groups, allowed
    assert actor._static_target(16, 8, 16, (4, 4, 2)) == 16
    # 8x8x2: group = 16; 16 fits within one group
    assert actor._static_target(16, 16, 16, (8, 8, 2)) == 16
    # target capped by the action-space max
    assert actor._static_target(32, 8, 16, (4, 4, 2)) == 16


def test_adaptive_degree_packing_jct_objective():
    """Objective-aware tier shift (docs/results_round5/degree_map.md):
    under the JCT reward family the heavy-load target is 8, not 4; the
    group-tiling geometry is objective-independent."""
    from ddls_tpu.envs.baselines import AdaptiveDegreePacking

    assert AdaptiveDegreePacking(objective="jct").heavy_degree == 8
    assert AdaptiveDegreePacking().heavy_degree == 4
    # explicit heavy_degree wins (the d=4-under-JCT ablation must stay
    # expressible)
    assert AdaptiveDegreePacking(heavy_degree=4,
                                 objective="jct").heavy_degree == 4
    with pytest.raises(ValueError):
        AdaptiveDegreePacking(objective="latency")
