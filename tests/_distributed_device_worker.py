"""Worker for the 2-process multi-host x device_collector test
(VERDICT r4 item 6).

Each process joins a global gloo mesh and runs TWO full epochs of PPO
whose collection happens entirely in the jitted env
(`algo_config.device_collector: true`): per-process job banks (the
collect seed is process-distinct, so banks and in-kernel episode
histories genuinely diverge), per-process segment rngs, in-kernel
episode resets — the new deterministic-gate hazard class — while the
replicated parameters of the sharded update must end BIT-identical on
every process.

Prints machine-checkable lines: BANKS <sha1>, PARAMS <sha1>.
"""
import hashlib
import sys

sys.path.insert(0, sys.argv[4] if len(sys.argv) > 4 else ".")

from ddls_tpu.parallel import initialize_distributed


def main() -> int:
    coordinator, num_processes, process_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
    initialize_distributed(coordinator_address=coordinator,
                           num_processes=num_processes,
                           process_id=process_id, platform="cpu")
    import jax
    import numpy as np

    from ddls_tpu.train.loops import RLEpochLoop

    env_config = {
        "topology_config": {"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        "node_config": {"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        "jobs_config": {
            # identical synthetic dataset on every process (env CONFIG is
            # process-identical); bank CONTENTS diverge via the
            # process-distinct collect seed
            "synthetic": {"n_cnn": 1, "n_translation": 1, "seed": 6,
                          "min_ops": 6, "max_ops": 8},
            "path_to_files": None,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 40.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.1, "max_val": 0.6, "decimals": 2},
            "replication_factor": 20,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 20},
        "max_partitions_per_op": 4,
        "min_op_run_time_quantum": 0.01,
        "reward_function": "job_acceptance",
        "max_simulation_run_time": 2e3,
        "pad_obs_kwargs": {"max_nodes": 32, "max_edges": 64},
    }
    model = {"fcnet_hiddens": [16], "custom_model_config": {
        "out_features_msg": 4, "out_features_hidden": 8,
        "out_features_node": 4, "out_features_graph": 4}}
    algo_config = {"lr": 1e-3, "num_sgd_iter": 2,
                   "sgd_minibatch_size": 8, "train_batch_size": 16,
                   "device_collector": True}

    loop = RLEpochLoop(
        path_to_env_cls="ddls_tpu.envs.partitioning_env."
                        "RampJobPartitioningEnvironment",
        env_config=env_config, model=model, algo_config=algo_config,
        num_envs=2, rollout_length=8, use_parallel_envs=False,
        evaluation_interval=None, seed=0)
    for _ in range(2):
        results = loop.run()
    assert results["epoch_counter"] == 2, results

    # process-divergence evidence: the per-process job banks must differ
    # (the whole point of process-distinct collect seeds)
    hb = hashlib.sha1()
    for leaf in jax.tree_util.tree_leaves(
            jax.device_get(loop.collector.banks)):
        hb.update(np.ascontiguousarray(leaf).tobytes())
    print(f"BANKS process={process_id} digest={hb.hexdigest()}",
          flush=True)

    # parameters must be BIT-identical across processes
    h = hashlib.sha1()
    for leaf in jax.tree_util.tree_leaves(
            jax.device_get(loop.state.params)):
        h.update(np.ascontiguousarray(leaf).tobytes())
    print(f"PARAMS process={process_id} digest={h.hexdigest()}",
          flush=True)
    loop.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
