"""L5 tests for the placement-shaping MDP: action table, mask semantics,
episode runs with shaper baselines, and RL training on the env."""
import numpy as np
import pytest

from ddls_tpu.envs import RampJobPlacementShapingEnvironment
from ddls_tpu.envs.baselines import (FirstFitShaper, LastFitShaper,
                                     RandomShaper)
from ddls_tpu.envs.shaping_obs import shape_action_table


def _env_config(dataset_dir, max_parts_obs=4):
    return dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 1000.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.5, "max_val": 1.0, "decimals": 2},
            "replication_factor": 4,
            "job_sampling_mode": "remove",
            "num_training_steps": 50,
            "max_partitions_per_op_in_observation": max_parts_obs},
        op_partitioner="sip_ml_op_partitioner",
        op_partitioner_kwargs={"min_op_run_time_quantum": 0.01},
        reward_function="job_acceptance",
        reward_function_kwargs={"fail_reward": -1, "success_reward": 1},
        max_simulation_run_time=1e5,
        pad_obs_kwargs={"max_nodes": 64, "max_edges": 256},
        apply_action_mask=True)


def _make_env(dataset_dir, max_parts_obs=4, **kwargs):
    cfg = _env_config(dataset_dir, max_parts_obs)
    cfg.update(kwargs)
    return RampJobPlacementShapingEnvironment(**cfg)


def test_shape_action_table_order(dataset_dir):
    env = _make_env(dataset_dir)
    table = shape_action_table(env.cluster.topology)
    assert table[0] is None
    assert table[1] == (1, 1, 1)
    assert table[2] == (1, 1, 2)
    assert table[3] == (1, 2, 1)
    assert table[8] == (2, 2, 2)
    assert len(table) == 2 * 2 * 2 + 1
    assert env.action_space.n == 9


def test_mask_respects_partition_degree_and_free_workers(dataset_dir):
    env = _make_env(dataset_dir, max_parts_obs=4)
    obs = env.reset(seed=0)
    assert obs["action_mask"][0] == 1
    job_id = next(iter(env.op_partition.partitioned_jobs))
    degree = env.op_partition.job_id_to_max_partition_degree[job_id]
    for action, shape in env.action_to_shape.items():
        if shape is None:
            continue
        c, r, s = shape
        if c * r * s < degree:
            assert obs["action_mask"][action] == 0, (action, shape, degree)
    # obs encodes the partitioned job (more ops than the original)
    pjob = env.op_partition.partitioned_jobs[job_id]
    assert obs["node_split"][0] == pjob.graph.n_ops


def test_invalid_action_raises_then_falls_back(dataset_dir):
    env = _make_env(dataset_dir, max_parts_obs=4)
    obs = env.reset(seed=0)
    invalid = int(np.argmin(obs["action_mask"]))
    if obs["action_mask"][invalid] == 0:
        with pytest.raises(ValueError):
            env.step(invalid)
        env.apply_action_mask = False
        _, reward, _, _ = env.step(invalid)  # falls back to 0 (don't place)
        assert reward == -1


@pytest.mark.parametrize("actor_cls", [FirstFitShaper, LastFitShaper,
                                       RandomShaper])
def test_full_episode_with_shapers(dataset_dir, actor_cls):
    env = _make_env(dataset_dir)
    obs = env.reset(seed=0)
    actor = actor_cls()
    done, steps, total = False, 0, 0.0
    while not done and steps < 60:
        obs, reward, done, _ = env.step(actor.compute_action(obs))
        total += reward
        steps += 1
    assert done
    e = env.cluster.episode_stats
    assert e["num_jobs_arrived"] == (e["num_jobs_completed"]
                                     + e["num_jobs_blocked"])


def test_last_fit_outperforms_first_fit(dataset_dir):
    """Biggest-shape-first should accept at least as many jobs as
    smallest-shape-first (whose tiny meta-blocks often admit no valid
    symmetric sub-block for split ops)."""
    returns = {}
    for actor_cls in (FirstFitShaper, LastFitShaper):
        env = _make_env(dataset_dir)
        obs = env.reset(seed=0)
        actor = actor_cls()
        done, steps, total = False, 0, 0.0
        while not done and steps < 60:
            obs, reward, done, _ = env.step(actor.compute_action(obs))
            total += reward
            steps += 1
        returns[actor_cls.name] = total
    assert returns["last_fit"] >= returns["first_fit"]


def test_rl_training_on_shaping_env(dataset_dir):
    """BASELINE.json config 4: GNN policy + PPO on the shaping env."""
    from ddls_tpu.train import RLEpochLoop

    loop = RLEpochLoop(
        path_to_env_cls=("ddls_tpu.envs.placement_shaping_env."
                         "RampJobPlacementShapingEnvironment"),
        env_config=_env_config(dataset_dir),
        num_envs=2, rollout_length=4, n_devices=2,
        evaluation_interval=None, seed=0,
        algo_config={"train_batch_size": 8, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 2},
        model={"fcnet_hiddens": [16],
               "custom_model_config": {"out_features_msg": 4,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}})
    results = loop.run()
    assert np.isfinite(results["learner"]["total_loss"])
    loop.close()
