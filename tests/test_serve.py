"""Serving subsystem tests (ddls_tpu/serve, ISSUE 1).

The load-bearing pin is BATCHING NEVER CHANGES AN ANSWER: every bucket
runs one fixed-shape XLA program (``flat_batched`` at ``max_batch`` rows,
partial flushes padded with replica rows), and at a fixed program a
request's output rows depend only on its own data — XLA tiles by shape,
not by data — so a request served in a full mixed batch is bit-equal to
the same request served alone. Full bit-equality to the *differently
shaped* single-graph ``__call__`` program is NOT pinnable (XLA retiles
per shape and reassociates f32 sums — the same caveat
tests/test_models.py pins for flat_batched vs vmap); across programs the
pin is masked-pattern equality + 1e-5 closeness + identical argmax
decisions.

Also pinned: deadline flushes of partial batches, saturation/dead-device
degradation to the FixedDegreePacking fallback (answers agree with the
checkpoint-extracted rule; no request is ever dropped), the
``serve_policy.py --selftest`` front end, and the ``bench.py --mode
serve`` JSON contract.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_ACTIONS = 9
BUCKETS = [(8, 12), (16, 28)]
MAX_BATCH = 4


def _rand_obs(rng, n, m, max_nodes, max_edges, mask_valid=(0, 1, 2, 4, 8)):
    node_features = np.zeros((max_nodes, 5), np.float32)
    node_features[:n] = rng.uniform(0, 1, (n, 5))
    edge_features = np.zeros((max_edges, 2), np.float32)
    edge_features[:m] = rng.uniform(0, 1, (m, 2))
    src = np.zeros(max_edges, np.int32)
    dst = np.zeros(max_edges, np.int32)
    src[:m] = rng.integers(0, n, m)
    dst[:m] = rng.integers(0, n, m)
    mask = np.zeros(N_ACTIONS, np.int32)
    mask[list(mask_valid)] = 1
    return {
        "action_set": np.arange(N_ACTIONS, dtype=np.int32),
        "action_mask": mask,
        "node_features": node_features,
        "edge_features": edge_features,
        "graph_features": rng.uniform(0, 1, (17 + N_ACTIONS,)).astype(
            np.float32),
        "edges_src": src,
        "edges_dst": dst,
        "node_split": np.array([n], np.int32),
        "edge_split": np.array([m], np.int32),
    }


@pytest.fixture(scope="module")
def model_params():
    from ddls_tpu.models.policy import GNNPolicy

    model = GNNPolicy(n_actions=N_ACTIONS, out_features_msg=4,
                      out_features_hidden=8, out_features_node=4,
                      out_features_graph=4, fcnet_hiddens=(16,))
    obs = _rand_obs(np.random.default_rng(0), 6, 8, *BUCKETS[-1])
    params = model.init(jax.random.PRNGKey(0),
                        jax.tree_util.tree_map(np.asarray, obs))
    return model, params


def _make_server(model_params, clock=None, **kwargs):
    from ddls_tpu.serve import PolicyServer

    model, params = model_params
    defaults = dict(buckets=BUCKETS, max_batch=MAX_BATCH, deadline_s=0.01)
    defaults.update(kwargs)
    if clock is not None:
        defaults["clock"] = clock
    return PolicyServer(model, params, **defaults)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# --------------------------------------------------------------- bucketing
class TestBucketing:
    def test_default_buckets_halving_ladder(self):
        from ddls_tpu.serve import default_buckets

        b = default_buckets(32, 60, n_buckets=3)
        assert b[-1] == (32, 60)
        assert b == sorted(set(b))
        assert len(b) == 3
        # edges default to the fully-connected bound
        assert default_buckets(8)[-1] == (8, 28)

    def test_smallest_fit_and_pad(self):
        from ddls_tpu.serve import BucketOverflowError, ObsBucketer

        bk = ObsBucketer(BUCKETS)
        obs = _rand_obs(np.random.default_rng(1), 5, 6, 20, 40)
        idx, padded = bk.bucket_obs(obs)
        assert idx == 0
        assert padded["node_features"].shape == (8, 5)
        assert padded["edge_features"].shape == (12, 2)
        # real rows untouched, pad rows zero
        np.testing.assert_array_equal(padded["node_features"][:5],
                                      obs["node_features"][:5])
        np.testing.assert_array_equal(padded["node_features"][5:], 0.0)
        np.testing.assert_array_equal(padded["edges_src"][:6],
                                      obs["edges_src"][:6])
        # both dimensions must fit: 5 nodes but 20 edges -> second bucket
        assert bk.bucket_index(5, 20) == 1
        with pytest.raises(BucketOverflowError):
            bk.bucket_index(17, 4)

    def test_repad_is_forward_invariant(self, model_params):
        """pad_obs_to only moves the dead masked region; the single-graph
        forward over the re-padded obs matches the original to padding
        tolerance (the perf_round2 invariant serving relies on)."""
        from ddls_tpu.envs.obs import pad_obs_to

        model, params = model_params
        obs = _rand_obs(np.random.default_rng(2), 6, 9, 20, 40)
        lo_a, va_a = model.apply(params,
                                 jax.tree_util.tree_map(np.asarray, obs))
        re = pad_obs_to(obs, 16, 28)
        lo_b, va_b = model.apply(params,
                                 jax.tree_util.tree_map(np.asarray, re))
        np.testing.assert_allclose(
            np.where(np.isfinite(lo_a), lo_a, 0.0),
            np.where(np.isfinite(lo_b), lo_b, 0.0), atol=1e-5)
        np.testing.assert_allclose(va_a, va_b, atol=1e-5)


# -------------------------------------------------------------- microbatch
class TestMicrobatch:
    def _req(self, rid, bucket, t):
        from ddls_tpu.serve import PendingRequest

        return PendingRequest(request_id=rid, bucket_idx=bucket, obs={},
                              enqueue_time=t)

    def test_full_batch_flushes_immediately(self):
        from ddls_tpu.serve import MicrobatchEngine

        eng = MicrobatchEngine(2, max_batch=3, deadline_s=10.0)
        for i in range(3):
            eng.submit(self._req(i, 0, 0.0))
        batches = eng.due_batches(now=0.0)
        assert len(batches) == 1 and batches[0][0] == 0
        assert [r.request_id for r in batches[0][1]] == [0, 1, 2]
        assert eng.queued() == 0

    def test_deadline_flushes_partial_and_never_mixes_buckets(self):
        from ddls_tpu.serve import MicrobatchEngine

        eng = MicrobatchEngine(2, max_batch=4, deadline_s=0.01)
        eng.submit(self._req(0, 0, 0.0))
        eng.submit(self._req(1, 1, 0.0))
        assert eng.due_batches(now=0.005) == []
        assert eng.next_deadline() == pytest.approx(0.01)
        batches = eng.due_batches(now=0.011)
        assert sorted(b[0] for b in batches) == [0, 1]
        assert all(len(b[1]) == 1 for b in batches)

    def test_force_drains(self):
        from ddls_tpu.serve import MicrobatchEngine

        eng = MicrobatchEngine(1, max_batch=4, deadline_s=100.0)
        eng.submit(self._req(0, 0, 0.0))
        assert eng.due_batches(now=0.0) == []
        assert len(eng.due_batches(now=0.0, force=True)) == 1

    def test_next_deadline_reports_full_batch_due_now(self):
        """A queue already holding a full batch is due immediately:
        next_deadline must report a time not in the future (the head's
        enqueue time), or a caller that sleeps to it would delay a
        flush-on-fill by up to deadline_s — defeating the fill half of
        flush-on-fill-or-deadline."""
        from ddls_tpu.serve import MicrobatchEngine

        eng = MicrobatchEngine(2, max_batch=2, deadline_s=10.0)
        eng.submit(self._req(0, 0, 1.0))
        assert eng.next_deadline() == pytest.approx(11.0)  # partial
        eng.submit(self._req(1, 0, 2.0))                   # now full
        assert eng.next_deadline() == pytest.approx(1.0)   # due already
        eng.due_batches(now=2.0)
        assert eng.next_deadline() is None


# ------------------------------------------------------------ bit-equality
class TestBatchedForwardParity:
    @pytest.mark.parametrize("bucket", list(range(len(BUCKETS))))
    def test_batched_bit_equal_to_unbatched(self, model_params, bucket):
        """THE serving pin (ISSUE 1 acceptance): for every bucket size, a
        request's logits/value from a full mixed batch are bit-equal to
        serving it unbatched through the same program — batching can
        never change an answer."""
        from ddls_tpu.serve import BucketForward

        model, params = model_params
        bn, be = BUCKETS[bucket]
        rng = np.random.default_rng(10 + bucket)
        reqs = [_rand_obs(rng, int(rng.integers(2, bn + 1)),
                          int(rng.integers(1, be + 1)), bn, be)
                for _ in range(MAX_BATCH)]
        bf = BucketForward(model, params, max_batch=MAX_BATCH)
        lo_batch, va_batch = bf.forward(reqs)
        for i, req in enumerate(reqs):
            lo_solo, va_solo = bf.forward([req])
            np.testing.assert_array_equal(lo_batch[i], lo_solo[0])
            np.testing.assert_array_equal(va_batch[i], va_solo[0])

    @pytest.mark.parametrize("bucket", list(range(len(BUCKETS))))
    def test_agrees_with_single_graph_forward(self, model_params, bucket):
        """Across programs (fixed-batch vs the single-graph ``__call__``)
        XLA retiles, so the pin is: identical masked(-inf) pattern,
        1e-5-close finite logits/values, identical argmax decision."""
        model, params = model_params
        from ddls_tpu.serve import BucketForward

        bn, be = BUCKETS[bucket]
        rng = np.random.default_rng(20 + bucket)
        reqs = [_rand_obs(rng, int(rng.integers(2, bn + 1)),
                          int(rng.integers(1, be + 1)), bn, be)
                for _ in range(MAX_BATCH)]
        bf = BucketForward(model, params, max_batch=MAX_BATCH)
        lo_batch, va_batch = bf.forward(reqs)
        for i, req in enumerate(reqs):
            lo_s, va_s = model.apply(
                params, jax.tree_util.tree_map(np.asarray, req))
            lo_s, va_s = np.asarray(lo_s), np.asarray(va_s)
            np.testing.assert_array_equal(np.isfinite(lo_batch[i]),
                                          np.isfinite(lo_s))
            np.testing.assert_allclose(
                np.where(np.isfinite(lo_batch[i]), lo_batch[i], 0.0),
                np.where(np.isfinite(lo_s), lo_s, 0.0), atol=1e-5)
            np.testing.assert_allclose(va_batch[i], va_s, atol=1e-5)
            assert int(np.argmax(lo_batch[i])) == int(np.argmax(lo_s))

    def test_each_bucket_compiles_exactly_once(self, model_params):
        server = _make_server(model_params, clock=_FakeClock())
        rng = np.random.default_rng(3)
        for t in range(10):
            bn, be = BUCKETS[t % 2]
            server.submit(_rand_obs(rng, bn - 1, be - 2, bn, be), now=0.0)
        server.drain(now=0.0)
        assert server.stats.n_compiles == len(BUCKETS)

    def test_server_batched_decisions_match_serve_one(self, model_params):
        rng = np.random.default_rng(4)
        bn, be = BUCKETS[0]
        reqs = [_rand_obs(rng, int(rng.integers(2, bn + 1)),
                          int(rng.integers(1, be + 1)), bn, be)
                for _ in range(MAX_BATCH)]
        clock = _FakeClock()
        server = _make_server(model_params, clock=clock)
        for o in reqs:
            server.submit(o, now=0.0)
        batched = {r.request_id: r.action for r in server.poll(now=0.0)}
        assert len(batched) == MAX_BATCH
        solo_server = _make_server(model_params, clock=_FakeClock())
        for i, o in enumerate(reqs):
            assert solo_server.serve_one(o).action == batched[i]


# ------------------------------------------------------- deadlines/fallback
class TestServerBehaviour:
    def test_deadline_flush_fires_under_partial_batch(self, model_params):
        clock = _FakeClock()
        server = _make_server(model_params, clock=clock, deadline_s=0.01)
        rng = np.random.default_rng(5)
        bn, be = BUCKETS[1]
        for _ in range(MAX_BATCH - 1):
            server.submit(_rand_obs(rng, 10, 14, bn, be), now=0.0)
        assert server.poll(now=0.005) == []          # not due yet
        out = server.poll(now=0.012)                 # deadline expired
        assert len(out) == MAX_BATCH - 1
        assert all(r.source == "policy" and r.batch_fill == MAX_BATCH - 1
                   for r in out)
        assert list(server.stats.occupancies) == [
            pytest.approx((MAX_BATCH - 1) / MAX_BATCH)]
        # latency = deadline wait under the injected clock
        assert all(r.latency_s == pytest.approx(0.012) for r in out)

    def test_saturation_falls_back_without_dropping(self, model_params):
        from ddls_tpu.envs.baselines import FixedDegreePacking

        clock = _FakeClock()
        server = _make_server(model_params, clock=clock, max_queue=4,
                              deadline_s=100.0,
                              fallback=FixedDegreePacking(degree=4))
        rng = np.random.default_rng(6)
        bn, be = BUCKETS[0]
        reqs = [_rand_obs(rng, 5, 6, bn, be) for _ in range(10)]
        ids = [server.submit(o, now=0.0) for o in reqs]
        # the first 4 queued; 5..10 answered immediately from the heuristic
        immediate = server.poll(now=0.0)
        fallback = [r for r in immediate if r.source == "fallback"]
        assert len(fallback) == 6
        assert all(r.reason == "saturated" for r in fallback)
        rule = FixedDegreePacking(degree=4)
        assert all(r.action == rule.compute_action(reqs[r.request_id])
                   for r in fallback)
        # nothing dropped: drain answers the queued remainder
        rest = server.drain(now=0.0)
        answered = {r.request_id for r in immediate} | {
            r.request_id for r in rest}
        assert answered == set(ids)
        assert server.stats.summary()["fallback_rate"] == pytest.approx(0.6)

    def test_dead_backend_degrades_to_heuristic(self, model_params):
        """The wedged-tunnel scenario: the batched forward raising flips
        the server into degraded mode; every request (in-flight and
        later) is answered by FixedDegreePacking at the extracted degree,
        none dropped."""
        from ddls_tpu.envs.baselines import FixedDegreePacking
        from ddls_tpu.serve import DEFAULT_FALLBACK_DEGREE

        def broken_apply(params, obs):
            raise RuntimeError("tunnel wedged")

        clock = _FakeClock()
        server = _make_server(model_params, clock=clock,
                              apply_fn=broken_apply,
                              fallback=FixedDegreePacking(degree=4))
        assert DEFAULT_FALLBACK_DEGREE == 8  # the rule_extraction degree
        rng = np.random.default_rng(7)
        bn, be = BUCKETS[0]
        reqs = [_rand_obs(rng, 5, 6, bn, be) for _ in range(MAX_BATCH + 2)]
        for o in reqs:
            server.submit(o, now=0.0)
        out = server.drain(now=0.0)
        assert len(out) == MAX_BATCH + 2
        assert all(r.source == "fallback" for r in out)
        assert server.degraded
        rule = FixedDegreePacking(degree=4)
        assert all(r.action == rule.compute_action(reqs[r.request_id])
                   for r in out)
        # later submits short-circuit to the heuristic (fallback latency
        # completes at the CLOCK's now — advance it to the submit time)
        clock.t = 1.0
        rid = server.submit(reqs[0], now=1.0)
        out2 = server.poll(now=1.0)
        assert [r.request_id for r in out2] == [rid]
        assert out2[0].reason == "degraded"

    def test_serve_one_matches_id_with_prior_queue(self, model_params):
        """serve_one must return ITS request's response even when the
        forced drain also resolves earlier-queued requests — those stay
        pending for the next poll, none dropped."""
        clock = _FakeClock()
        server = _make_server(model_params, clock=clock, deadline_s=100.0)
        rng = np.random.default_rng(9)
        bn, be = BUCKETS[0]
        first = _rand_obs(rng, 5, 6, bn, be)
        second = _rand_obs(rng, 6, 7, bn, be)
        rid_first = server.submit(first, now=0.0)   # queues (partial batch)
        resp = server.serve_one(second)
        assert resp.request_id != rid_first
        solo = _make_server(model_params, clock=_FakeClock())
        assert resp.action == solo.serve_one(second).action
        # the first request's answer was resolved by the drain and is
        # waiting on the next poll
        rest = server.poll(now=0.0)
        assert [r.request_id for r in rest] == [rid_first]

    def test_oversized_graph_falls_back(self, model_params):
        clock = _FakeClock()
        server = _make_server(model_params, clock=clock)
        big = _rand_obs(np.random.default_rng(8), 20, 24, 24, 30)
        server.submit(big, now=0.0)
        out = server.poll(now=0.0)
        assert len(out) == 1 and out[0].reason == "overflow"

    def test_malformed_obs_rejected_at_submit_not_batch(self, model_params):
        """A bad request errors to ITS caller at submit (missing keys,
        wrong per-row feature width, graph/mask width disagreeing with the
        server's model, action_set the fallback needs absent or ragged)
        and never reaches a batch — co-queued well-formed requests still
        get policy answers."""
        clock = _FakeClock()
        server = _make_server(model_params, clock=clock)
        rng = np.random.default_rng(11)
        bn, be = BUCKETS[0]
        good = _rand_obs(rng, 5, 6, bn, be)
        rid = server.submit(good, now=0.0)

        # every fallback path reads action_set (envs/baselines.py) — a
        # request without it must be rejected up front, not crash poll()
        # the day the backend degrades
        missing = {k: v for k, v in good.items() if k != "action_set"}
        with pytest.raises(ValueError, match="missing"):
            server.submit(missing, now=0.0)

        bad_width = dict(good)
        bad_width["node_features"] = np.zeros((bn, 4), np.float32)
        with pytest.raises(ValueError, match="node_features"):
            server.submit(bad_width, now=0.0)

        bad_graph = dict(good)
        bad_graph["graph_features"] = np.zeros(3, np.float32)
        with pytest.raises(ValueError, match="graph_features"):
            server.submit(bad_graph, now=0.0)

        bad_set = dict(good)
        bad_set["action_set"] = np.arange(3, dtype=np.int32)
        with pytest.raises(ValueError, match="action_set"):
            server.submit(bad_set, now=0.0)

        out = server.drain(now=0.0)
        assert [r.request_id for r in out] == [rid]
        assert out[0].source == "policy"
        # rejected submits are not counted as served requests
        assert server.stats.n_requests == 1

    def test_inconsistent_splits_rejected_at_submit(self, model_params):
        """node_split/edge_split must agree with the rows actually
        present: an inflated split would make the repad zero-fill
        phantom "real" rows (served as a garbage policy answer), a
        negative one silently truncates real rows, and short
        edges_src/edges_dst would index garbage — all data errors owed
        to the submitting caller."""
        clock = _FakeClock()
        server = _make_server(model_params, clock=clock)
        good = _rand_obs(np.random.default_rng(13), 5, 6, *BUCKETS[0])

        inflated = dict(good)
        inflated["node_split"] = np.array(
            [int(np.asarray(good["node_features"]).shape[0]) + 3],
            np.int32)
        with pytest.raises(ValueError, match="node_split"):
            server.submit(inflated, now=0.0)

        negative = dict(good)
        negative["edge_split"] = np.array([-2], np.int32)
        with pytest.raises(ValueError, match="edge_split"):
            server.submit(negative, now=0.0)

        short_src = dict(good)
        short_src["edges_src"] = np.asarray(good["edges_src"])[:2]
        with pytest.raises(ValueError, match="edges_src"):
            server.submit(short_src, now=0.0)

        # a REAL edge endpoint outside this graph's real nodes would
        # escape its slot in the flat-batched mega-graph and scatter
        # into a CO-BATCHED graph's embedding — the one way a request
        # could break "batching never changes an answer"
        n_real = int(np.asarray(good["node_split"]).reshape(-1)[0])
        out_of_range = dict(good)
        dst = np.asarray(good["edges_dst"]).copy()
        dst[0] = n_real  # >= node_split: points past this graph
        out_of_range["edges_dst"] = dst
        with pytest.raises(ValueError, match="edges_dst"):
            server.submit(out_of_range, now=0.0)

        negative_src = dict(good)
        src = np.asarray(good["edges_src"]).copy()
        src[0] = -1
        negative_src["edges_src"] = src
        with pytest.raises(ValueError, match="edges_src"):
            server.submit(negative_src, now=0.0)

        # the well-formed obs still serves; nothing latched
        resp = server.serve_one(good)
        assert resp.source == "policy"
        assert not server.degraded

    def test_checkpoint_graph_feature_dim_probe(self):
        """The startup pairing guard reads the trained graph width off a
        restored param tree (attribute names frozen by the shipped
        checkpoints) and returns None for unrecognised shapes instead of
        raising."""
        from ddls_tpu.serve import checkpoint_graph_feature_dim

        tree = {"params": {"graph_module": {"Dense_0": {
            "kernel": np.zeros((34, 8), np.float32)}}}}
        assert checkpoint_graph_feature_dim(tree) == 34
        assert checkpoint_graph_feature_dim({}) is None
        assert checkpoint_graph_feature_dim({"params": {}}) is None
        assert checkpoint_graph_feature_dim(None) is None

    def test_width_contract_seeded_by_model_not_first_request(
            self, model_params):
        """The action width comes from the model itself and the graph
        width from the constructor where given — a wrong-width FIRST
        request is rejected instead of poisoning the contract (or, worse,
        passing submit and latching degraded when the forward fails on a
        healthy backend). A rejected request commits no pins."""
        clock = _FakeClock()
        good = _rand_obs(np.random.default_rng(12), 5, 6, *BUCKETS[0])
        gdim = int(good["graph_features"].shape[0])
        server = _make_server(model_params, clock=clock,
                              graph_feature_dim=gdim)

        wrong_mask = dict(good)
        wrong_mask["action_mask"] = np.ones(N_ACTIONS + 3, np.int32)
        with pytest.raises(ValueError, match="action_mask"):
            server.submit(wrong_mask, now=0.0)

        wrong_graph = dict(good)
        wrong_graph["graph_features"] = np.zeros(gdim + 9, np.float32)
        with pytest.raises(ValueError, match="graph_features"):
            server.submit(wrong_graph, now=0.0)

        # the correct client still serves; nothing was pinned wrong,
        # nothing latched
        resp = server.serve_one(good)
        assert resp.source == "policy"
        assert not server.degraded


# --------------------------------------------------------------- baselines
def test_adaptive_degree_packing_reads_cluster_arrival_counter():
    """ADVICE r5 item 2: rho comes from the cluster's arrival-demand
    counter (blocked arrivals included), not per-decision accumulation —
    and carries no cross-episode state on that path."""
    from ddls_tpu.envs.baselines import AdaptiveDegreePacking

    class _Stopwatch:
        def __init__(self, t):
            self._t = t

        def time(self):
            return self._t

    class _Topo:
        num_workers = 32
        shape = (4, 4, 2)

    class _Cluster:
        def __init__(self, now, arrived, seq_sum):
            self.stopwatch = _Stopwatch(now)
            self.num_jobs_arrived = arrived
            self.sum_arrived_seq_completion_time = seq_sum
            self.topology = _Topo()

    class _Env:
        def __init__(self, cluster):
            self.cluster = cluster

    class _Job:
        seq_completion_time = 1000.0

    actor = AdaptiveDegreePacking()
    # heavy overload entirely from BLOCKED arrivals: worker-seconds that
    # never reach a decision step still push rho into the heavy tier
    env = _Env(_Cluster(now=100.0, arrived=10, seq_sum=32 * 100.0 * 2.0))
    assert actor._rho(env, _Job()) == pytest.approx(2.0)
    # stateless across calls: same inputs, same estimate (the old
    # accumulator would have doubled it)
    assert actor._rho(env, _Job()) == pytest.approx(2.0)
    # light load
    env2 = _Env(_Cluster(now=100.0, arrived=10, seq_sum=32 * 100.0 * 0.1))
    assert actor._rho(env2, _Job()) == pytest.approx(0.1)
    # warmup guard unchanged
    env3 = _Env(_Cluster(now=0.0, arrived=10, seq_sum=50.0))
    assert np.isnan(actor._rho(env3, _Job()))
    # explicit episode-reset hook exists and clears legacy state
    actor._seq_sum = 123.0
    actor.reset()
    assert actor._seq_sum == 0.0


def test_cluster_accumulates_arrived_seq_completion_time(dataset_dir):
    from ddls_tpu.envs import RampJobPartitioningEnvironment

    env = RampJobPartitioningEnvironment(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2, "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={"path_to_files": dataset_dir,
                     "job_interarrival_time_dist": {
                         "_target_": "ddls_tpu.demands.distributions.Fixed",
                         "val": 100.0},
                     "max_acceptable_job_completion_time_frac_dist": {
                         "_target_":
                             "ddls_tpu.demands.distributions.Uniform",
                         "min_val": 0.5, "max_val": 1.0, "decimals": 2},
                     "replication_factor": 3,
                     "job_sampling_mode": "remove_and_repeat",
                     "num_training_steps": 10},
        max_partitions_per_op=4, min_op_run_time_quantum=0.01,
        reward_function="job_acceptance", max_simulation_run_time=2e3,
        pad_obs_kwargs={"max_nodes": 16, "max_edges": 32})
    obs = env.reset(seed=0)
    c = env.cluster
    assert c.sum_arrived_seq_completion_time > 0.0
    first = c.sum_arrived_seq_completion_time
    assert first == pytest.approx(
        list(c.job_queue.jobs.values())[0].seq_completion_time)
    done, steps = False, 0
    while not done and steps < 6:
        valid = np.flatnonzero(np.asarray(obs["action_mask"]))
        obs, _, done, _ = env.step(int(valid[0]))
        steps += 1
    assert c.sum_arrived_seq_completion_time >= first
    assert c.num_jobs_arrived >= 1
    # reset zeroes the counter with the rest of the cluster
    env.reset(seed=1)
    assert env.cluster.sum_arrived_seq_completion_time == pytest.approx(
        list(env.cluster.job_queue.jobs.values())[0].seq_completion_time)


# ------------------------------------------------------------ front ends
def test_line_assembler_handles_bursts():
    """The stdin pump selects on the raw fd, and select() fires once per
    CHUNK — a burst of N lines arriving in one read must all be handled
    before the loop returns to select (a buffered readline() would
    strand lines 2..N in Python's buffer while select blocks on the
    drained fd: interactive-client deadlock)."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from serve_policy import LineAssembler
    finally:
        sys.path.pop(0)

    la = LineAssembler()
    # one chunk, three complete lines + one partial
    assert la.feed(b'{"id": 1}\n{"id": 2}\n{"id": 3}\n{"id"') == [
        '{"id": 1}', '{"id": 2}', '{"id": 3}']
    # the partial completes across chunks
    assert la.feed(b': 4}\n') == ['{"id": 4}']
    assert la.flush() == []
    # unterminated final line surfaces at EOF flush
    assert la.feed(b'{"id": 5}') == []
    assert la.flush() == ['{"id": 5}']
    assert la.flush() == []


def test_serve_policy_selftest_script():
    """CI satellite: the stdin/JSON driver's --selftest smoke runs on CPU
    (no TPU probe) and reports ok."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_policy.py"),
         "--selftest", "--selftest-requests", "12", "--max-batch", "4",
         "--degree", "8"],
        capture_output=True, text=True, timeout=600, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["selftest"] == "ok"
    assert payload["n_requests"] == 12
    assert payload["n_fallback_saturated"] > 0


def test_bench_serve_smoke(capsys):
    """Acceptance: `bench.py --mode serve` emits one JSON line with
    decisions/sec, p50/p99 latency, batch occupancy and fallback rate on
    the CPU smoke path."""
    import bench

    rc = bench.main(["--mode", "serve", "--serve-requests", "48",
                     "--serve-rps", "400", "--serve-max-batch", "4",
                     "--probe-timeout", "120"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert rc == 0, payload
    assert payload["metric"] == "serve_decisions_per_sec"
    assert payload["value"] > 0
    assert payload["p50_latency_ms"] is not None
    assert payload["p99_latency_ms"] >= payload["p50_latency_ms"]
    assert 0.0 < payload["batch_occupancy"] <= 1.0
    assert 0.0 <= payload["fallback_rate"] <= 1.0
    assert payload["num_requests"] == 48
    assert payload["n_compiles"] <= len(payload["buckets"])
    # ISSUE 3 acceptance: the JSON line carries a telemetry section whose
    # histogram-derived p50/p99 agree with the existing latency fields
    # (same trailing window; the top-level fields are rounded to 3 dp)
    tele = payload["telemetry"]
    assert "bench.run" in tele["spans"]
    lat = tele["serve"]["histograms"]["serve.latency_s"]
    assert lat["count"] == 48
    assert lat["p50"] * 1e3 == pytest.approx(payload["p50_latency_ms"],
                                             abs=5e-4)
    assert lat["p99"] * 1e3 == pytest.approx(payload["p99_latency_ms"],
                                             abs=5e-4)
    serve_counters = tele["serve"]["counters"]
    assert serve_counters["serve.requests"] == 48
    assert sum(v for k, v in serve_counters.items()
               if k.startswith("serve.flush_cause.")) == \
        serve_counters["serve.flushes"]


def test_bench_pad_bounds_cache_fingerprints_dataset(tmp_path):
    """ADVICE r5 item 4: regenerating the dataset at the same path must
    invalidate the cached pad bounds."""
    import bench
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    d = str(tmp_path / "ds")
    os.makedirs(d)
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=0, seed=0,
                                 min_ops=4, max_ops=6)
    b1 = bench._dataset_pad_bounds(d)
    for f in os.listdir(d):
        os.remove(os.path.join(d, f))
    generate_pipedream_txt_files(d, n_cnn=2, n_translation=1, seed=1,
                                 min_ops=10, max_ops=14)
    b2 = bench._dataset_pad_bounds(d)
    assert b2["max_nodes"] >= 10
    assert b2 != b1
