"""W&B logging paths, exercised with a fake wandb module (the real one is
optional and not installed in this image): the epoch loop's flattening
logger (reference counterpart: rllib_epoch_loop.py:105-230 W&B results
flattening) and the heuristic EvalLoop's episode metrics."""
import tempfile

import numpy as np
import pytest


class FakeWandb:
    def __init__(self):
        self.logged = []

    def log(self, payload):
        assert isinstance(payload, dict)
        self.logged.append(payload)


@pytest.fixture(scope="module")
def dataset_dir():
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    d = tempfile.mkdtemp(prefix="wandb_log_")
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=2)
    return d


def _env_config(dataset_dir):
    return dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 4,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 2},
        max_partitions_per_op=4,
        reward_function="job_acceptance",
        max_simulation_run_time=5e4,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})


def test_epoch_loop_flattens_results_to_wandb(dataset_dir):
    from ddls_tpu.train import make_epoch_loop

    fake = FakeWandb()
    loop = make_epoch_loop(
        "ppo",
        path_to_env_cls=("ddls_tpu.envs.partitioning_env."
                         "RampJobPartitioningEnvironment"),
        env_config=_env_config(dataset_dir),
        model={"fcnet_hiddens": [8],
               "custom_model_config": {"out_features_msg": 4,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}},
        algo_config={"lr": 1e-3, "train_batch_size": 8, "num_sgd_iter": 2,
                     "sgd_minibatch_size": 8},
        num_envs=2, rollout_length=4, n_devices=2,
        use_parallel_envs=False, evaluation_interval=None,
        seed=0, wandb=fake)
    results = loop.run()
    loop.log(results)
    loop.close()

    assert len(fake.logged) == 1
    flat = fake.logged[0]
    # nested dicts flattened to slash paths; every value a python float
    assert "learner/total_loss" in flat
    assert "env_steps_this_iter" in flat
    assert all(isinstance(v, float) for v in flat.values())
    # non-scalar leaves (lists, strings) are dropped, not crashed on
    assert not any(isinstance(v, (list, str)) for v in flat.values())


def test_eval_loop_logs_episode_metrics_to_wandb(dataset_dir):
    from ddls_tpu.envs import RampJobPartitioningEnvironment
    from ddls_tpu.envs.baselines import MaxParallelism
    from ddls_tpu.train.loops import EvalLoop

    fake = FakeWandb()
    loop = EvalLoop(env=RampJobPartitioningEnvironment(
                        **_env_config(dataset_dir)),
                    actor=MaxParallelism(), wandb=fake)
    results = loop.run(seed=0, max_steps=6)
    assert np.isfinite(results["episode_return"])
    assert len(fake.logged) == 1
    assert fake.logged[0]["eval/episode_return"] == pytest.approx(
        results["episode_return"])
    assert fake.logged[0]["eval/episode_length"] == results["episode_length"]
