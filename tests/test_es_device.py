"""ES trained entirely on device: population fitness = jitted policy
episodes (no host simulator in the training loop). Mechanics are asserted
hard (shapes, finiteness, fitness ordering, parameter movement); learning
progress is reported, not asserted (3 generations of a tiny config is not
a convergence test)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
from ddls_tpu.models.policy import GNNPolicy
from ddls_tpu.parallel.mesh import make_mesh
from ddls_tpu.rl.es import ESConfig, ESLearner
from ddls_tpu.rl.es_device import train_es_on_device
from ddls_tpu.sim.jax_env import (build_episode_tables, build_job_bank,
                                  build_obs_tables)


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("es_device_jobs"))
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=4)
    env = RampJobPartitioningEnvironment(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2, "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={"path_to_files": d,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 60.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.2, "max_val": 1.0, "decimals": 2},
            "replication_factor": 10,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 10},
        max_partitions_per_op=4, min_op_run_time_quantum=0.01,
        reward_function="job_acceptance", max_simulation_run_time=1.5e3,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})
    obs = env.reset(seed=0)
    et = build_episode_tables(env)
    ot = build_obs_tables(env, et)
    model = GNNPolicy(n_actions=5, out_features_msg=4,
                      out_features_hidden=8, out_features_node=4,
                      out_features_graph=4, fcnet_hiddens=(16,))
    params = model.init(jax.random.PRNGKey(1),
                        jax.tree_util.tree_map(jnp.asarray, obs))
    return env, et, ot, model, params


def test_es_generations_run_fully_on_device(setup):
    env, et, ot, model, params = setup
    learner = ESLearner(lambda p, o: model.apply(p, o),
                        ESConfig(stepsize=0.02, noise_stdev=0.05),
                        make_mesh(1), population=8)

    def sample_bank(gen):
        r = np.random.RandomState(100 + gen)
        J = 26
        recs = [{"model": et.types[int(r.randint(0, len(et.types)))],
                 "num_training_steps": 10,
                 "sla_frac": round(float(r.uniform(0.2, 1.0)), 2),
                 "time_arrived": 60.0 * i} for i in range(J)]
        return {k: jnp.asarray(v)
                for k, v in build_job_bank(et, recs).items()}

    final_params, history = train_es_on_device(
        et, ot, model, learner, params, sample_bank, n_generations=3,
        seed=0)

    assert len(history) == 3
    for h in history:
        assert np.isfinite(h["fitness_mean"])
        assert h["fitness_min"] <= h["fitness_mean"] <= h["fitness_max"]
    # parameters moved under the ES update
    moved = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        params, final_params)
    assert max(jax.tree_util.tree_leaves(moved)) > 0
    print("fitness trajectory:",
          [round(h["fitness_mean"], 2) for h in history])
