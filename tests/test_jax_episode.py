"""Full-episode parity: the jitted canonical-RAMP episode
(sim/jax_env.py make_episode_fn) replays a host episode's action sequence
and must reproduce every decision — reward, acceptance, blocked cause,
decision time, lookahead JCT — plus the final counters.

Runs under JAX_ENABLE_X64=1 in a subprocess (process-global flag), the
same isolation pattern as tests/test_jax_pricing.py."""
import os
import subprocess
import sys

DRIVER = r"""
import numpy as np
import jax
import jax.numpy as jnp

assert jax.config.read("jax_enable_x64")

import tempfile
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.sim.jax_env import (build_episode_tables, build_job_bank,
                                  make_episode_fn, CAUSE_ACCEPTED,
                                  CAUSE_NOT_HANDLED, CAUSE_OP_PLACEMENT,
                                  CAUSE_DEP_PLACEMENT, CAUSE_SLA)

d = tempfile.mkdtemp(prefix="jax_episode_")
generate_pipedream_txt_files(d, n_cnn=2, n_translation=1, seed=5)
env = RampJobPartitioningEnvironment(
    topology_config={"type": "ramp", "kwargs": {
        "num_communication_groups": 4,
        "num_racks_per_communication_group": 4,
        "num_servers_per_rack": 2, "num_channels": 1,
        "total_node_bandwidth": 1.6e12,
        "intra_gpu_propagation_latency": 50e-9,
        "worker_io_latency": 100e-9}},
    node_config={"type_1": {"num_nodes": 32, "workers_config": [
        {"num_workers": 1, "worker": "A100"}]}},
    jobs_config={"path_to_files": d,
        "job_interarrival_time_dist": {
            "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 40.0},
        "max_acceptable_job_completion_time_frac_dist": {
            "_target_": "ddls_tpu.demands.distributions.Uniform",
            "min_val": 0.1, "max_val": 1.0, "decimals": 2},
        "replication_factor": 40, "job_sampling_mode": "remove_and_repeat",
        "num_training_steps": 20},
    max_partitions_per_op=8, min_op_run_time_quantum=0.01,
    reward_function="job_acceptance", max_simulation_run_time=5e3,
    pad_obs_kwargs={"max_nodes": 150, "max_edges": 512})

CAUSE_BY_STR = {
    "not_handled": CAUSE_NOT_HANDLED,
    "op_partition": CAUSE_OP_PLACEMENT,   # never expected here
    "op_placement": CAUSE_OP_PLACEMENT,
    "dep_placement": CAUSE_DEP_PLACEMENT,
    "max_acceptable_job_completion_time_exceeded": CAUSE_SLA,
    "job_queue_full": -99,                # cannot occur in this MDP
}

# ---- host episode with a mixed action policy, recording everything
obs = env.reset(seed=17)
rng = np.random.RandomState(23)
arrivals = []   # one record per arrived job, in arrival order
decisions = []  # (action, reward, accepted, cause_code, t, jct)
seen_idx = set()

def record_arrival(job):
    arrivals.append({"model": job.details["model"],
                     "num_training_steps": job.num_training_steps,
                     "sla_frac": job.max_acceptable_jct_frac,
                     "time_arrived": job.details["time_arrived"]})

done = False
while not done:
    job = next(iter(env.cluster.job_queue.jobs.values()))
    ji = env.cluster.job_id_to_job_idx[job.job_id]
    if ji not in seen_idx:
        assert ji == len(arrivals), (ji, len(arrivals))
        seen_idx.add(ji)
        record_arrival(job)
    t_dec = env.cluster.stopwatch.time()
    valid = np.nonzero(np.asarray(obs["action_mask"]))[0]
    # mix: mostly aggressive degrees (exercises placement failures +
    # SLA blocks), some zeros (not_handled), some moderate
    r = rng.rand()
    if r < 0.15:
        action = 0
    elif r < 0.55:
        action = int(valid[-1])
    else:
        action = int(rng.choice(valid))
    n_causes_before = len(env.cluster.episode_stats[
        "jobs_blocked_cause_of_unsuccessful_handling"])
    obs, reward, done, info = env.step(action)
    accepted = ji in env.cluster.jobs_running or ji in env.cluster.jobs_completed
    if accepted:
        pj = (env.cluster.jobs_running.get(ji)
              or env.cluster.jobs_completed.get(ji))
        jct = pj.details["lookahead_job_completion_time"]
        cause = CAUSE_ACCEPTED
    else:
        jct = 0.0
        # the decided job's cause is the FIRST one appended this step
        # (episode finalisation may append later simulation_ended entries)
        causes = env.cluster.episode_stats[
            "jobs_blocked_cause_of_unsuccessful_handling"]
        cause = CAUSE_BY_STR[causes[n_causes_before]]
    decisions.append((action, reward, accepted, cause, t_dec, jct))

# jobs that arrived but were never decided (episode ended) are not in
# `arrivals` via the decision loop only if queued at done; record all
# remaining arrivals the cluster saw so the bank covers them
n_arrived = env.cluster.num_jobs_arrived
host = {
    "accepted": int(sum(1 for d in decisions if d[2])),
    "blocked": int(sum(1 for d in decisions if not d[2])),
    "completed": int(len(env.cluster.jobs_completed)),
    "ret": float(sum(d[1] for d in decisions)),
}
print(f"host episode: {len(decisions)} decisions, {n_arrived} arrivals, "
      f"accepted {host['accepted']} blocked {host['blocked']} "
      f"completed {host['completed']}")

# bank needs EVERY arrival (the last one may still sit in the queue)
for ji in range(len(arrivals), n_arrived):
    j = (env.cluster.jobs_running.get(ji) or env.cluster.jobs_completed.get(ji)
         or env.cluster.jobs_blocked.get(ji)
         or env.cluster.job_queue.jobs.get(env.cluster.job_idx_to_job_id[ji]))
    assert j is not None, f"arrival {ji} untracked"
    record_arrival(j.original_job if j.original_job is not j else j)

# ---- jitted replay
et = build_episode_tables(env)
bank = build_job_bank(et, arrivals)
episode_fn = make_episode_fn(et)
actions = jnp.asarray([d[0] for d in decisions], jnp.int32)
out = episode_fn({k: jnp.asarray(v) for k, v in bank.items()}, actions)
reward_tr, accept_tr, cause_tr, jct_tr, t_tr, has_job_tr = (
    np.asarray(x) for x in out["trace"])

assert has_job_tr.all(), "replay ran out of queued jobs before the host did"
n_bad = 0
for i, (action, reward, accepted, cause, t_dec, jct) in enumerate(decisions):
    ok = (bool(accept_tr[i]) == accepted and int(cause_tr[i]) == cause
          and reward_tr[i] == reward
          and abs(t_tr[i] - t_dec) <= 1e-9 * max(t_dec, 1.0)
          and (not accepted or abs(jct_tr[i] - jct) <= 1e-9 * jct))
    if not ok:
        n_bad += 1
        if n_bad <= 5:
            print(f"DECISION {i} action {action}: host "
                  f"(acc={accepted}, cause={cause}, r={reward}, "
                  f"t={t_dec}, jct={jct}) vs kernel "
                  f"(acc={bool(accept_tr[i])}, cause={int(cause_tr[i])}, "
                  f"r={reward_tr[i]}, t={t_tr[i]}, jct={jct_tr[i]})")
assert n_bad == 0, f"{n_bad} of {len(decisions)} decisions diverged"
assert int(out["accepted"]) == host["accepted"]
assert int(out["blocked"]) == host["blocked"]
assert int(out["completed"]) == host["completed"]
assert abs(float(out["ret"]) - host["ret"]) < 1e-9

# ---- episode-record parity vs the host cluster's finalised stats:
# arrivals (the device collectors' rate denominator) and num_jobs_blocked
# INCLUDING the host finalisation that blocks jobs still running at
# simulation end (cluster.py:1010-1013)
er = env.cluster.episode_stats
assert int(out["arrived"]) == n_arrived == er["num_jobs_arrived"], (
    int(out["arrived"]), n_arrived, er["num_jobs_arrived"])
assert int(out["blocked_total"]) == er["num_jobs_blocked"], (
    int(out["blocked_total"]), int(out["blocked"]), er["num_jobs_blocked"])
still = int(out["blocked_total"]) - int(out["blocked"])
print(f"EPISODE_PARITY_OK decisions={len(decisions)} "
      f"still_running_at_end={still}")
"""


def test_full_episode_parity_x64():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    res = subprocess.run([sys.executable, "-c", DRIVER], env=env,
                         capture_output=True, text=True, timeout=1800)
    assert res.returncode == 0, (res.stdout[-4000:], res.stderr[-4000:])
    assert "EPISODE_PARITY_OK" in res.stdout, res.stdout[-2000:]
