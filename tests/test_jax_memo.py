"""In-kernel lookahead memo (sim/jax_memo.py, ISSUE 13 + 17).

Unit level: a forced hash collision must MISS (bitwise residual compare)
and recompute — never serve the colliding entry; eviction is
deterministic round-robin; the canonical grouping matches the host's
``np.unique``-based canonicalisation (cluster.py:468-476).

Kernel level: a memo-enabled segment is BITWISE identical to a memo-off
segment (traces, bootstrap fields) — the hit==recompute contract — AT
EVERY VMAP WIDTH (lanes 1, 2 and 8 — the wide batched probe, ISSUE 17),
the table persists across in-kernel episode resets exactly like the
host ``lookahead_cache`` persists across ``reset()`` (misses stop
growing once the first episode has populated the table), per-lane
counters drain independently, and the hit rate on a repeated-placement
episode is strictly positive. The x64 leg of the hit==recompute
contract rides the EXISTING full-episode parity suites
(test_jax_episode / test_jax_policy_episode run the episode kernels
with the memo enabled by default and pin them against the host
simulator exactly).

Loop level: a lanes=1 fused epoch loop resolves the memo ON by default,
stays transfer-free in steady state under ``jax.transfer_guard``, and
reports counters at the drain boundary only; multi-lane collectors
resolve the memo ON too (resolve_memo_cfg "auto" at every width).
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


# ============================================================ unit level
class _EtStub:
    """Minimal et for memo_init: pads + the dtype-bearing table."""

    def __init__(self, n_ops=4, n_deps=6):
        import types

        self.pads = types.SimpleNamespace(n_ops=n_ops, n_deps=n_deps)
        self.tables = {"dep_size": np.zeros(n_deps, np.float32)}


def _key(seed, n_ops=4, n_deps=6):
    import jax.numpy as jnp

    r = np.random.RandomState(seed)
    groups = jnp.asarray(r.randint(0, 3, n_ops), jnp.int32)
    times = jnp.asarray(r.rand(n_deps), jnp.float32)
    return jnp.int32(0), groups, times


def _probe(memo, key, value):
    from ddls_tpu.sim.jax_memo import memo_lookahead

    import jax.numpy as jnp

    # compute takes the probe's hit flag (the wide-probe mask the real
    # caller threads into jax_lookahead's while_loop cond); a plain
    # value ignores it
    (t, ok), memo = memo_lookahead(
        memo, *key, lambda skip: (jnp.float32(value), jnp.bool_(True)))
    return float(t), memo


def test_forced_hash_collision_recomputes_never_serves_colliding_entry():
    from ddls_tpu.sim.jax_memo import MemoConfig, memo_init

    et = _EtStub()
    # ONE set, ONE way: every distinct key collides by construction
    memo = memo_init(et, MemoConfig(n_sets=1, n_ways=1))
    a, b = _key(1), _key(2)
    t, memo = _probe(memo, a, 1.5)      # miss: insert A
    assert t == 1.5
    t, memo = _probe(memo, b, 2.5)      # collides with A's set/way
    assert t == 2.5, "collision served the colliding entry's value"
    assert int(memo["misses"]) == 2 and int(memo["hits"]) == 0
    assert int(memo["evicts"]) == 1     # B evicted A (1-way set)
    t, memo = _probe(memo, b, 9.5)      # B now resident: hit serves 2.5
    assert t == 2.5
    assert int(memo["hits"]) == 1
    t, memo = _probe(memo, a, 7.25)     # A was evicted: recompute
    assert t == 7.25


def test_eviction_is_deterministic_round_robin():
    import jax

    from ddls_tpu.sim.jax_memo import MemoConfig, memo_init

    et = _EtStub()
    keys = [_key(s) for s in (1, 2, 3)]

    def drive():
        memo = memo_init(et, MemoConfig(n_sets=1, n_ways=2))
        for i, k in enumerate(keys):
            _, memo = _probe(memo, k, float(i))
        return memo

    m1, m2 = drive(), drive()
    # identical decision stream -> bit-identical table (incl. rr state)
    for k in m1:
        assert np.array_equal(np.asarray(m1[k]), np.asarray(m2[k])), k
    # key 3 evicted way 0 (round-robin): key 1 misses, keys 2/3 hit
    memo = m1
    t, memo = _probe(memo, keys[1], 8.0)
    assert t == 1.0  # hit: stored value
    t, memo = _probe(memo, keys[2], 8.0)
    assert t == 2.0  # hit: stored value
    t, memo = _probe(memo, keys[0], 8.0)
    assert t == 8.0  # evicted: recompute
    del jax


def test_zero_vs_negative_zero_times_never_alias():
    import jax.numpy as jnp

    from ddls_tpu.sim.jax_memo import MemoConfig, memo_init

    et = _EtStub()
    memo = memo_init(et, MemoConfig(n_sets=1, n_ways=2))
    cfg, groups, _ = _key(1)
    tz = jnp.zeros(6, jnp.float32)
    t, memo = _probe(memo, (cfg, groups, tz), 1.0)
    # -0.0 == 0.0 under float ==, but the probe compares BIT patterns
    t, memo = _probe(memo, (cfg, groups, -tz), 2.0)
    assert t == 2.0 and int(memo["hits"]) == 0


def test_canonical_groups_matches_host_canonicalisation():
    import jax.numpy as jnp

    from ddls_tpu.sim.jax_memo import canonical_groups

    r = np.random.RandomState(7)
    for _ in range(20):
        n = int(r.randint(1, 12))
        sc = r.randint(0, 5, n)
        n_valid = int(r.randint(1, n + 1))
        valid = np.zeros(n, bool)
        valid[:n_valid] = True
        # the host's vectorised first-appearance renumbering
        # (cluster.py:468-476) over the valid prefix
        _, first_idx, inv = np.unique(sc[:n_valid], return_index=True,
                                      return_inverse=True)
        rank = np.argsort(np.argsort(first_idx))
        want = np.full(n, -1, np.int32)
        want[:n_valid] = rank[inv]
        got = np.asarray(canonical_groups(jnp.asarray(sc, jnp.int32),
                                          jnp.asarray(valid)))
        assert np.array_equal(got, want), (sc, valid, got, want)


def test_memo_knob_rejected_loudly_without_device_collection():
    """Forcing the knob on a host-collection loop must fail before any
    env construction (the loud-rejection convention: a silent no-op
    would let a memo-off run masquerade as memo-on in comparisons)."""
    from ddls_tpu.train import make_epoch_loop

    with pytest.raises(ValueError, match="use_jax_lookahead_memo"):
        make_epoch_loop("ppo", path_to_env_cls=ENV_CLS, env_config={},
                        algo_config={"use_jax_lookahead_memo": True})


def test_resolve_memo_cfg_knob():
    from ddls_tpu.sim.jax_memo import MemoConfig, resolve_memo_cfg

    assert resolve_memo_cfg("auto", 1) == MemoConfig()
    # ISSUE 17: "auto" enables the memo at EVERY lane count — the
    # batched probe masks hit lanes out of the lookahead while_loop
    assert resolve_memo_cfg("auto", 8) == MemoConfig()
    assert resolve_memo_cfg(None, 1) is None
    assert resolve_memo_cfg(None, 8) is None
    cfg = MemoConfig(n_sets=4, n_ways=1)
    assert resolve_memo_cfg(cfg, 8) is cfg
    with pytest.raises(ValueError, match="memo_cfg"):
        resolve_memo_cfg(True, 1)
    with pytest.raises(ValueError, match="n_lanes"):
        resolve_memo_cfg("auto", 0)


# ========================================================== kernel level
ENV_CLS = "ddls_tpu.envs.partitioning_env.RampJobPartitioningEnvironment"

_TINY_MODEL = {"fcnet_hiddens": [16],
               "custom_model_config": {"out_features_msg": 4,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}}


@pytest.fixture(scope="module")
def memo_env(tmp_path_factory):
    """Small canonical env + tables + tiny policy, shared by the kernel-
    and loop-level tests (one dataset, one table build)."""
    import jax
    import jax.numpy as jnp

    from ddls_tpu.envs import RampJobPartitioningEnvironment
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
    from ddls_tpu.models.policy import GNNPolicy
    from ddls_tpu.sim.jax_env import (build_episode_tables,
                                      build_job_bank, build_obs_tables)

    d = str(tmp_path_factory.mktemp("memo_jobs"))
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=9,
                                 min_ops=4, max_ops=6)
    env_config = dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2, "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={"path_to_files": d,
                     "job_interarrival_time_dist": {
                         "_target_":
                             "ddls_tpu.demands.distributions.Fixed",
                         "val": 60.0},
                     "max_acceptable_job_completion_time_frac_dist": {
                         "_target_":
                             "ddls_tpu.demands.distributions.Uniform",
                         "min_val": 0.2, "max_val": 1.0, "decimals": 2},
                     "replication_factor": 10,
                     "job_sampling_mode": "remove_and_repeat",
                     "num_training_steps": 10},
        max_partitions_per_op=4, min_op_run_time_quantum=0.01,
        reward_function="job_acceptance", max_simulation_run_time=6e2,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})
    env = RampJobPartitioningEnvironment(**env_config)
    obs0 = env.reset(seed=0)
    et = build_episode_tables(env)
    ot = build_obs_tables(env, et)
    model = GNNPolicy(n_actions=5, out_features_msg=4,
                      out_features_hidden=8, out_features_node=4,
                      out_features_graph=4, fcnet_hiddens=(16,))
    params = model.init(jax.random.PRNGKey(0),
                        jax.tree_util.tree_map(jnp.asarray, obs0))
    r = np.random.RandomState(0)
    recs = [{"model": et.types[int(r.randint(0, len(et.types)))],
             "num_training_steps": 10,
             "sla_frac": round(float(r.uniform(0.2, 1.0)), 2),
             "time_arrived": 60.0 * i} for i in range(12)]
    bank = {k: jnp.asarray(v)
            for k, v in build_job_bank(et, recs).items()}
    return {"dataset": d, "env": env, "env_config": env_config,
            "et": et, "ot": ot, "model": model, "params": params,
            "bank": bank}


def test_segment_memo_bitwise_parity_and_cross_reset_persistence(
        memo_env):
    """The load-bearing kernel pin: memo-on == memo-off BITWISE across
    three carried segments spanning multiple in-kernel episode resets;
    the memo persists across those resets (misses FREEZE once the first
    episode populated the table — the host lookahead_cache contract),
    and the repeated-placement hit rate is > 0."""
    import jax

    from ddls_tpu.sim.jax_env import make_segment_fn, segment_init
    from ddls_tpu.sim.jax_memo import MemoConfig

    et, ot = memo_env["et"], memo_env["ot"]
    model, params, bank = (memo_env["model"], memo_env["params"],
                           memo_env["bank"])
    mc = MemoConfig(n_sets=16, n_ways=2)
    seg_on = make_segment_fn(et, ot, model, 24, memo_cfg=mc)
    seg_off = make_segment_fn(et, ot, model, 24)
    st_on = segment_init(et, bank, mc)
    st_off = segment_init(et, bank)
    rng = jax.random.PRNGKey(7)
    dones = 0
    miss_curve, hit_curve = [], []
    for _ in range(3):
        rng, sub = jax.random.split(rng)
        st_on, tr_on, nf_on = seg_on(bank, params, st_on, sub)
        st_off, tr_off, nf_off = seg_off(bank, params, st_off, sub)
        for k in tr_off:  # identical actions/rewards/counters/fields
            assert np.array_equal(np.asarray(tr_on[k]),
                                  np.asarray(tr_off[k])), k
        for k in nf_off:  # identical bootstrap fields
            assert np.array_equal(np.asarray(nf_on[k]),
                                  np.asarray(nf_off[k])), k
        dones += int(np.asarray(tr_on["done"]).sum())
        miss_curve.append(int(np.asarray(tr_on["memo_misses"])[-1]))
        hit_curve.append(int(np.asarray(tr_on["memo_hits"])[-1]))
    assert dones >= 2, "horizon must complete episodes for this pin"
    # cross-reset persistence: every episode after the first replays
    # bank placements already in the table — misses stop growing
    assert miss_curve[1] == miss_curve[0] == miss_curve[2], miss_curve
    # repeated-placement hit rate > 0 (ISSUE 13 satellite)
    assert hit_curve[-1] > 0
    assert hit_curve[-1] / (hit_curve[-1] + miss_curve[-1]) > 0.5


def _lane_banks(memo_env, n_lanes):
    """``n_lanes`` DISTINCT job banks (different sla/type streams per
    lane) stacked on a leading lane axis — distinct lanes make the wide
    probe's per-lane tables genuinely diverge."""
    import jax.numpy as jnp

    from ddls_tpu.sim.jax_env import build_job_bank

    et = memo_env["et"]
    banks = []
    for lane in range(n_lanes):
        r = np.random.RandomState(100 + lane)
        recs = [{"model": et.types[int(r.randint(0, len(et.types)))],
                 "num_training_steps": 10,
                 "sla_frac": round(float(r.uniform(0.2, 1.0)), 2),
                 "time_arrived": 60.0 * i} for i in range(12)]
        banks.append({k: jnp.asarray(v)
                      for k, v in build_job_bank(et, recs).items()})
    return {k: jnp.stack([b[k] for b in banks]) for k in banks[0]}


@pytest.mark.parametrize("n_lanes", [2, 8])
def test_vmapped_segment_memo_bitwise_parity_and_per_lane_drain(
        memo_env, n_lanes):
    """The ISSUE 17 load-bearing pin: memo-on == memo-off BITWISE under
    a multi-lane vmap (the batched probe serves stored bits to hit
    lanes and masked miss lanes iterate under their own cond), across
    carried segments spanning in-kernel episode resets; each lane's
    table persists across ITS resets (per-lane misses freeze once that
    lane's first episode populated its table), per-lane counters drain
    independently, and the lane-summed summary matches their total."""
    import jax

    from ddls_tpu.sim.jax_env import (make_segment_fn, segment_init,
                                      vmap_segment_fn)
    from ddls_tpu.sim.jax_memo import MemoConfig, summarize_counters

    et, ot = memo_env["et"], memo_env["ot"]
    model, params = memo_env["model"], memo_env["params"]
    banks = _lane_banks(memo_env, n_lanes)
    mc = MemoConfig(n_sets=16, n_ways=2)
    seg_on = vmap_segment_fn(
        make_segment_fn(et, ot, model, 24, memo_cfg=mc), n_lanes)
    seg_off = vmap_segment_fn(
        make_segment_fn(et, ot, model, 24), n_lanes)
    st_on = jax.vmap(lambda b: segment_init(et, b, mc))(banks)
    st_off = jax.vmap(lambda b: segment_init(et, b))(banks)
    rng = jax.random.PRNGKey(11)
    dones = np.zeros(n_lanes, np.int64)
    miss_curve, hit_curve = [], []
    for _ in range(3):
        rng, sub = jax.random.split(rng)
        lane_rngs = jax.random.split(sub, n_lanes)
        st_on, tr_on, nf_on = seg_on(banks, params, st_on, lane_rngs)
        st_off, tr_off, nf_off = seg_off(banks, params, st_off,
                                         lane_rngs)
        for k in tr_off:  # identical actions/rewards/counters/fields
            assert np.array_equal(np.asarray(tr_on[k]),
                                  np.asarray(tr_off[k])), k
        for k in nf_off:  # identical bootstrap fields
            assert np.array_equal(np.asarray(nf_on[k]),
                                  np.asarray(nf_off[k])), k
        dones += np.asarray(tr_on["done"]).sum(axis=-1)
        # per-lane cumulative counters ride the trace: [B, T], last step
        miss_curve.append(np.asarray(tr_on["memo_misses"])[:, -1])
        hit_curve.append(np.asarray(tr_on["memo_hits"])[:, -1])
    assert (dones >= 2).all(), ("every lane must complete episodes for "
                                f"the cross-reset pin, got {dones}")
    # cross-reset persistence PER LANE: by the third segment every lane
    # has completed (and re-entered) episodes, and its replays serve
    # from the table it populated BEFORE the in-kernel resets — misses
    # freeze in the steady tail (lanes whose first episode spans the
    # first segment boundary may add a miss in segment 2, never later)
    assert np.array_equal(miss_curve[2], miss_curve[1]), miss_curve
    # every lane hits its own cache (distinct banks, distinct tables)
    assert (hit_curve[-1] > 0).all(), hit_curve[-1]
    # distinct banks produce genuinely per-lane counter streams
    if n_lanes > 1:
        assert len({int(h) for h in hit_curve[-1]}
                   | {int(m) for m in miss_curve[-1]}) > 1
    # the lane-summed reporting summary == sum of per-lane finals
    summary = summarize_counters(st_on[1])
    assert summary["hits"] == int(hit_curve[-1].sum())
    assert summary["misses"] == int(miss_curve[-1].sum())
    assert 0.0 < summary["hit_rate"] <= 1.0


def test_device_collector_resolves_memo_by_lanes_and_reports(memo_env):
    """num_envs=1 -> memo auto-ON with counters at the drain boundary;
    num_envs>1 -> ALSO auto-ON (the wide batched probe, ISSUE 17) with
    counters summed over lanes."""
    import jax

    from ddls_tpu.rl.ppo_device import DevicePPOCollector

    et, ot = memo_env["et"], memo_env["ot"]
    model, params, bank = (memo_env["model"], memo_env["params"],
                           memo_env["bank"])
    one = {k: v[None] for k, v in bank.items()}
    col = DevicePPOCollector(et, ot, model, one, rollout_length=24)
    assert col.memo_cfg is not None
    for seed in (3, 4):
        out = col.collect(params, jax.random.PRNGKey(seed))
    assert out["traj"]["actions"].shape == (24, 1)
    counters = col.memo_counters()
    assert counters is not None and counters["hits"] > 0
    assert 0.0 < counters["hit_rate"] <= 1.0
    # one probe per decision whose action enters the heavy path
    # (action-0 decisions skip eval_cfg entirely), never more
    assert 0 < (counters["hits"] + counters["misses"]) <= 48

    two = _lane_banks(memo_env, 2)
    col2 = DevicePPOCollector(et, ot, model, two, rollout_length=24)
    assert col2.memo_cfg is not None, (
        "auto must resolve the memo ON at every lane count (ISSUE 17)")
    for seed in (5, 6):
        col2.collect(params, jax.random.PRNGKey(seed))
    c2 = col2.memo_counters()
    assert c2 is not None and c2["hits"] > 0
    # lane-summed probe count: ≤ one per heavy-path decision per lane
    assert 0 < (c2["hits"] + c2["misses"]) <= 2 * 48


def test_fused_lanes1_memo_on_transfer_free_then_reports(memo_env,
                                                         monkeypatch):
    """The fused loop at lanes=1 (the axon-preferred shape) resolves the
    memo ON, its steady-state epoch stays transfer-free under
    ``jax.transfer_guard`` (ISSUE 13 acceptance), and the bench-facing
    counters surface only at the reporting boundary."""
    import jax

    from ddls_tpu.train import make_epoch_loop

    monkeypatch.setenv("DDLS_TPU_PROBE_DIR", os.path.join(
        memo_env["dataset"], "probe"))
    loop = make_epoch_loop(
        "ppo",
        path_to_env_cls=ENV_CLS,
        env_config=memo_env["env_config"],
        model=_TINY_MODEL,
        algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 1, "num_workers": 1},
        num_envs=1, rollout_length=16, n_devices=1,
        use_parallel_envs=False, evaluation_interval=None, seed=0,
        loop_mode="fused", updates_per_epoch=1,
        metrics_sync_interval=3,
        fused_config={"lanes": 1, "segment_len": 16})
    try:
        assert loop.fused is not None, "fused build fell back"
        assert loop.fused.memo_cfg is not None, (
            "lanes=1 fused must resolve the memo ON by default")
        loop.run()  # warm: compile + first-use constant transfers
        with jax.transfer_guard("disallow"):
            loop.run()  # steady state: memo table stays on device
        r3 = loop.run()  # drain boundary
        assert np.isfinite(r3["learner"]["total_loss"])
        counters = loop.fused.memo_counters()
        assert counters is not None
        # one probe per heavy-path decision across 3 epochs x 16 steps
        assert 0 < counters["hits"] + counters["misses"] <= 3 * 16
        assert counters["hits"] > 0
    finally:
        loop.close()
