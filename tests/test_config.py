"""Config-system tests: group composition, overrides, _target_
instantiation, and the shipped config trees."""
import os

import pytest

from ddls_tpu.config import (get_by_dotted_path, instantiate, load_config,
                             save_config, set_by_dotted_path)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = os.path.join(REPO, "scripts", "ramp_job_partitioning_configs")


def _write(tmp_path, rel, text):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(text)
    return path


def test_group_composition_and_overrides(tmp_path):
    _write(tmp_path, "root.yaml", """
defaults:
    - grp: a
top:
    x: 1
    bw: 1.6e12
""")
    _write(tmp_path, "grp/a.yaml", "val: 1\nname: a\n")
    _write(tmp_path, "grp/b.yaml", "val: 2\nname: b\n")

    cfg = load_config(str(tmp_path), "root")
    assert cfg["grp"] == {"val": 1, "name": "a"}
    # scientific notation without signed exponent parses as float
    assert cfg["top"]["bw"] == pytest.approx(1.6e12)

    cfg = load_config(str(tmp_path), "root",
                      overrides=["grp=b", "top.x=5", "top.new.deep=hi"])
    assert cfg["grp"]["name"] == "b"
    assert cfg["top"]["x"] == 5
    assert cfg["top"]["new"]["deep"] == "hi"


def test_instantiate_nested_targets():
    obj = instantiate({
        "_target_": "ddls_tpu.demands.distributions.Fixed",
        "val": 7})
    assert obj.sample() == 7
    # reference-repo class paths map onto ddls_tpu equivalents
    obj = instantiate({
        "_target_": "ddls.distributions.fixed.Fixed", "val": 3})
    assert obj.sample() == 3


def test_dotted_path_helpers():
    cfg = {}
    set_by_dotted_path(cfg, "a.b.c", 4)
    assert get_by_dotted_path(cfg, "a.b.c") == 4
    assert get_by_dotted_path(cfg, "a.b.missing", "dflt") == "dflt"


def test_save_round_trip(tmp_path):
    cfg = {"a": {"b": [1, 2]}, "c": 1.5}
    save_config(cfg, str(tmp_path / "out.yaml"))
    back = load_config(str(tmp_path), "out")
    assert back == cfg


def test_shipped_training_config_composes():
    cfg = load_config(CONFIGS, "rllib_config")
    assert cfg["algo"]["algo_config"]["gamma"] == pytest.approx(0.997)
    assert cfg["env_config"]["topology_config"]["kwargs"][
        "total_node_bandwidth"] == pytest.approx(1.6e12)
    assert cfg["model"]["custom_model_config"]["out_features_msg"] == 32
    assert cfg["epoch_loop"]["_target_"].endswith("RLEpochLoop")
    # algo group re-selection keeps composing
    cfg2 = load_config(CONFIGS, "rllib_config",
                       overrides=["launcher.num_epochs=3"])
    assert cfg2["launcher"]["num_epochs"] == 3


def test_shipped_heuristic_config_composes():
    cfg = load_config(CONFIGS, "heuristic_config")
    loop_cfg = cfg["eval_loop"]
    assert loop_cfg["_target_"].endswith("EvalLoop")
    assert loop_cfg["actor"]["_target_"].endswith("AcceptableJCT")
    assert loop_cfg["env"]["max_partitions_per_op"] == 16


def test_all_shipped_env_configs_cap_edge_padding():
    """Every shipped env/heuristic config must set pad_obs_kwargs.max_edges:
    the parity default is the fully-connected bound (11,175 edges for 150
    nodes), which drags ~20x dead padding through every GNN forward
    (docs/perf_round2.md). This pins the round-2 lesson."""
    import glob

    import yaml

    def walk(node, found):
        if isinstance(node, dict):
            for key, value in node.items():
                if key == "pad_obs_kwargs" and isinstance(value, dict):
                    found.append(value)
                else:
                    walk(value, found)
        elif isinstance(node, list):
            for item in node:
                walk(item, found)

    checked = 0
    for cfg_path in glob.glob(os.path.join(REPO, "scripts", "*_configs",
                                           "**", "*.yaml"), recursive=True):
        with open(cfg_path) as f:
            cfg = yaml.safe_load(f)
        blocks: list = []
        walk(cfg, blocks)
        for block in blocks:
            checked += 1
            assert block.get("max_edges"), (
                f"{cfg_path}: pad_obs_kwargs must set max_edges (the "
                "fully-connected default is a ~20x perf trap)")
    assert checked >= 4, "expected to find padded env configs to check"


def test_shipped_load32_configs_keep_binding_regime():
    """docs/results_round3 hangs off env_load32's loaded regime; an edit
    that quietly relaxes the load (longer interarrivals, fewer jobs, the
    1e6 horizon) would turn the headline experiment's env back into the
    ceiling regime where every policy ties. Pin the load parameters."""
    import glob
    import os

    import yaml

    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    paths = glob.glob(os.path.join(
        root, "ramp_job_*_configs", "env_config", "env_load32.yaml"))
    assert len(paths) == 2, paths  # partitioning + shaping trees
    for path in sorted(paths):
        with open(path) as f:
            cfg = yaml.safe_load(f)
        jobs = cfg["jobs_config"]
        ia = jobs["job_interarrival_time_dist"]
        assert float(ia["val"]) <= 120, (path, ia)
        assert int(jobs["num_training_steps"]) == 20, path
        assert int(jobs["replication_factor"]) == 60, path
        assert float(cfg["max_simulation_run_time"]) == 2e4, path
        assert cfg["node_config"]["type_1"]["num_nodes"] == 32, path
