"""Ape-X DQN stack tests: n-step folding, prioritised replay, the jitted
double/dueling update on the 8-device mesh, and the full epoch loop
(reference counterpart: RLlib ApexTrainer through
scripts/ramp_job_partitioning_configs/algo/apex_dqn.yaml)."""
import numpy as np
import pytest

import jax

from ddls_tpu.rl.dqn import (ApexDQNLearner, DQNConfig,
                             PrioritizedReplayBuffer, nstep_transitions,
                             per_worker_epsilons)


def _step(obs_id, reward, done=False):
    return {"obs": {"x": np.float32(obs_id)},
            "action": obs_id % 3, "reward": float(reward), "done": done,
            "next_obs": {"x": np.float32(obs_id + 1)}}


class TestNStep:
    def test_three_step_return(self):
        steps = [_step(0, 1.0), _step(1, 2.0), _step(2, 4.0), _step(3, 8.0)]
        out = nstep_transitions(steps, n_step=3, gamma=0.5, flush=False)
        # only t=0 and t=1 have 3 future steps available
        assert len(out) == 2
        assert out[0]["reward"] == pytest.approx(1 + 0.5 * 2 + 0.25 * 4)
        assert out[0]["discount"] == pytest.approx(0.5 ** 3)
        assert out[0]["next_obs"]["x"] == 3.0  # obs after step t=2
        # consumed entries removed, the unfinished tail stays queued
        assert len(steps) == 2

    def test_done_truncates_and_zeroes_discount(self):
        steps = [_step(0, 1.0), _step(1, 2.0, done=True), _step(2, 4.0)]
        out = nstep_transitions(steps, n_step=3, gamma=0.5, flush=False)
        assert out[0]["reward"] == pytest.approx(1 + 0.5 * 2)
        assert out[0]["discount"] == 0.0

    def test_flush_emits_short_horizons(self):
        steps = [_step(0, 1.0), _step(1, 2.0, done=True)]
        out = nstep_transitions(steps, n_step=3, gamma=0.5, flush=True)
        assert len(out) == 2
        assert steps == []
        assert out[1]["reward"] == pytest.approx(2.0)
        assert out[1]["discount"] == 0.0


class TestReplay:
    def test_ring_and_proportional_sampling(self):
        buf = PrioritizedReplayBuffer(capacity=4, alpha=1.0, beta=0.5,
                                      eps=1e-6, seed=0)
        for i in range(6):  # wraps: holds 2,3,4,5
            buf.add({"v": np.float32(i)})
        assert buf.size == 4
        batch, idx, w = buf.sample(32)
        assert set(np.asarray(batch["v"]).astype(int)) <= {2, 3, 4, 5}
        assert w.shape == (32,) and w.max() == pytest.approx(1.0)

    def test_priority_update_biases_sampling(self):
        buf = PrioritizedReplayBuffer(capacity=8, alpha=1.0, beta=0.4,
                                      eps=1e-6, seed=0)
        for i in range(8):
            buf.add({"v": np.float32(i)})
        buf.update_priorities(np.arange(8),
                              np.array([100.0] + [1e-3] * 7))
        batch, _, _ = buf.sample(256)
        frac0 = float(np.mean(np.asarray(batch["v"]) == 0))
        assert frac0 > 0.8


def _tiny_obs(rng, B, n_actions=5):
    mask = np.ones((B, n_actions), np.int32)
    mask[:, -1] = 0  # last action always invalid
    return {"x": rng.rand(B, 4).astype(np.float32),
            "action_mask": mask}


def _mlp_apply(params, obs):
    h = jax.numpy.tanh(obs["x"] @ params["w1"])
    return h @ params["w2"], (h @ params["w3"])[:, 0]


def _mlp_params(rng, n_actions=5):
    return {"w1": rng.randn(4, 8).astype(np.float32),
            "w2": rng.randn(8, n_actions).astype(np.float32),
            "w3": rng.randn(8, 1).astype(np.float32)}


class TestLearner:
    def _make(self, **over):
        from ddls_tpu.parallel.mesh import make_mesh

        base = dict(lr=1e-2, train_batch_size=16,
                    target_network_update_freq=64, grad_clip=1.0)
        base.update(over)
        cfg = DQNConfig(**base)
        mesh = make_mesh(8)
        return ApexDQNLearner(_mlp_apply, cfg, mesh), cfg

    def test_masked_epsilon_greedy_never_picks_invalid(self):
        learner, _ = self._make()
        rng = np.random.RandomState(0)
        params = _mlp_params(rng)
        obs = _tiny_obs(rng, 16)
        for eps in (0.0, 1.0):
            acts = np.asarray(learner.sample_actions(
                params, obs, jax.random.PRNGKey(1),
                np.full(16, eps, np.float32)))
            assert acts.shape == (16,)
            assert (acts < 4).all()  # action 4 is masked out

    def test_train_step_moves_params_and_returns_td(self):
        learner, cfg = self._make()
        rng = np.random.RandomState(0)
        params = _mlp_params(rng)
        state = learner.init_state(params)
        batch = {
            "obs": _tiny_obs(rng, 16),
            "actions": rng.randint(0, 4, 16).astype(np.int32),
            "rewards": rng.randn(16).astype(np.float32),
            "next_obs": _tiny_obs(rng, 16),
            "discounts": np.full(16, 0.999 ** 3, np.float32),
            "weights": np.ones(16, np.float32),
        }
        state2, metrics, td = learner.train_step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert td.shape == (16,) and np.isfinite(td).all()
        assert int(state2.step) == 1
        moved = jax.tree_util.tree_map(
            lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
            jax.device_get(state2.params), params)
        assert max(jax.tree_util.tree_leaves(moved)) > 0
        # target params stay at init until the sync step
        tdiff = jax.tree_util.tree_map(
            lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
            jax.device_get(state2.target_params), params)
        assert max(jax.tree_util.tree_leaves(tdiff)) == 0

    def test_target_sync_cadence(self):
        learner, cfg = self._make(target_network_update_freq=32)
        # sync every 32/16 = 2 learner steps
        rng = np.random.RandomState(0)
        state = learner.init_state(_mlp_params(rng))
        batch = {
            "obs": _tiny_obs(rng, 16),
            "actions": rng.randint(0, 4, 16).astype(np.int32),
            "rewards": rng.randn(16).astype(np.float32),
            "next_obs": _tiny_obs(rng, 16),
            "discounts": np.zeros(16, np.float32),
            "weights": np.ones(16, np.float32),
        }
        state, _, _ = learner.train_step(state, batch)
        state, _, _ = learner.train_step(state, batch)
        sync = jax.tree_util.tree_map(
            lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
            jax.device_get(state.target_params),
            jax.device_get(state.params))
        assert max(jax.tree_util.tree_leaves(sync)) == 0

    def test_epsilon_schedule(self):
        cfg = DQNConfig(initial_epsilon=1.0, final_epsilon=0.05,
                        epsilon_timesteps=100)
        e0 = per_worker_epsilons(4, 0, cfg)
        assert e0 == pytest.approx(np.ones(4))
        eT = per_worker_epsilons(4, 100, cfg)
        assert eT[0] == pytest.approx(0.05)
        assert (np.diff(eT) < 0).all()  # later workers explore less


class TestEpochLoop:
    def test_apex_dqn_trains_on_env(self, dataset_dir):
        from ddls_tpu.train import make_epoch_loop

        loop = make_epoch_loop(
            "apex_dqn",
            path_to_env_cls=("ddls_tpu.envs.partitioning_env."
                             "RampJobPartitioningEnvironment"),
            env_config=_env_config(dataset_dir),
            model={"fcnet_hiddens": [16],
                   "custom_model_config": {"out_features_msg": 4,
                                           "out_features_hidden": 8,
                                           "out_features_node": 4,
                                           "out_features_graph": 4}},
            algo_config={"gamma": 0.99, "lr": 1e-3, "n_step": 2,
                         "train_batch_size": 16, "num_workers": 2,
                         "replay_buffer_config": {
                             "capacity": 256, "learning_starts": 16},
                         "target_network_update_freq": 64,
                         "exploration_config": {"epsilon_timesteps": 100}},
            num_envs=2, rollout_length=10, n_devices=8,
            use_parallel_envs=False, evaluation_interval=2,
            evaluation_duration=1, seed=0)
        r1 = loop.run()
        assert r1["env_steps_this_iter"] == 20
        assert r1["learner"]["replay_size"] > 0
        r2 = loop.run()  # second epoch: replay warm, updates happen + eval
        assert r2["learner"]["num_updates"] >= 1
        assert np.isfinite(r2["learner"]["loss"])
        assert "evaluation" in r2
        assert "episode_reward_mean" in r2["evaluation"]
        loop.close()

    def test_unknown_algo_hard_errors(self):
        from ddls_tpu.train import make_epoch_loop

        with pytest.raises(ValueError, match="unknown algo_name"):
            make_epoch_loop("impala_typo")

    def test_dqn_config_translation(self):
        from ddls_tpu.train import dqn_config_from_rllib

        base = {
            "gamma": 0.999, "lr": 4.121e-7, "n_step": 3,
            "train_batch_size": 512, "target_network_update_freq": 100000,
            "replay_buffer_config": {"capacity": 100000,
                                     "prioritized_replay_alpha": 0.9,
                                     "learning_starts": 10000},
            "exploration_config": {"final_epsilon": 0.05,
                                   "epsilon_timesteps": 1000000},
        }
        cfg = dqn_config_from_rllib(base)
        assert cfg.gamma == 0.999
        assert cfg.lr == 4.121e-7
        assert cfg.buffer_capacity == 100000
        assert cfg.prioritized_replay_alpha == 0.9
        # ray-only plumbing keys are rejected loudly, never silently no-oped
        with pytest.raises(ValueError, match="not consumed"):
            dqn_config_from_rllib(
                dict(base, max_requests_in_flight_per_sampler_worker=2))
        assert cfg.final_epsilon == 0.05


def _env_config(dataset_dir):
    return dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 4,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 2},
        max_partitions_per_op=4,
        reward_function="job_acceptance",
        max_simulation_run_time=5e4,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})
