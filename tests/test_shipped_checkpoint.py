"""The shipped price-feature checkpoint restores and acts sensibly.

Pins the product promise of checkpoints/README.md: a user can restore
`checkpoints/ppo_price_mixed` onto the `env_load32_price_mixed` surface
and get a working greedy policy. The return floor is deliberately loose
(the policy's held-out per-decision mean at ia-50 is ~0.25; random-range
policies score deeply negative in the loaded regime), so the test fails
on a broken restore or a garbage policy, not on eval noise."""
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

CKPT = os.path.join(REPO, "checkpoints", "ppo_price_mixed")


def test_shipped_price_checkpoint_restores_and_scores():
    from ddls_tpu.config import load_config
    from ddls_tpu.train import RLEvalLoop, make_epoch_loop
    from train_from_config import build_epoch_loop_kwargs

    cfg = load_config(os.path.join(REPO, "scripts",
                                   "ramp_job_partitioning_configs"),
                      "rllib_config",
                      ["env_config=env_load32_price_mixed",
                       # fixed moderate load keeps the assertion stable
                       ("env_config.jobs_config.job_interarrival_time_"
                        "dist._target_="
                        "ddls_tpu.demands.distributions.Fixed"),
                       "env_config.jobs_config.job_interarrival_time_"
                       "dist.val=80.0"])
    kwargs = build_epoch_loop_kwargs(cfg)
    kwargs["num_envs"] = 1
    kwargs["rollout_length"] = 1
    kwargs["evaluation_interval"] = None
    loop = make_epoch_loop("ppo", **kwargs)
    ev = RLEvalLoop(loop)
    r = ev.run(checkpoint_path=CKPT, seed=7005)
    rec = r["episode"]
    loop.close()
    # held-out ia-80 per-decision mean is ~0.68 for this checkpoint;
    # anything positive clears random (~-0.2 here) by a wide margin
    per_decision = rec["episode_return"] / max(rec["episode_length"], 1)
    assert np.isfinite(per_decision)
    assert per_decision > 0.2, (rec["episode_return"],
                                rec["episode_length"])
