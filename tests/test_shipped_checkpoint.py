"""The shipped checkpoints restore and act sensibly.

Pins the product promise of checkpoints/README.md: a user can restore
`checkpoints/ppo_price_mixed` onto the `env_load32_price_mixed` surface
and get a working greedy policy. The return floor is deliberately loose
(the policy's held-out per-decision mean at ia-50 is ~0.25; random-range
policies score deeply negative in the loaded regime), so the test fails
on a broken restore or a garbage policy, not on eval noise."""
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

CKPT = os.path.join(REPO, "checkpoints", "ppo_price_mixed")


def _make_eval_loop(extra_overrides):
    from ddls_tpu.config import load_config
    from ddls_tpu.train import make_epoch_loop
    from train_from_config import build_epoch_loop_kwargs

    cfg = load_config(os.path.join(REPO, "scripts",
                                   "ramp_job_partitioning_configs"),
                      "rllib_config",
                      ["env_config=env_load32_price_mixed",
                       *extra_overrides])
    kwargs = build_epoch_loop_kwargs(cfg)
    kwargs["num_envs"] = 1
    kwargs["rollout_length"] = 1
    kwargs["evaluation_interval"] = None
    return make_epoch_loop("ppo", **kwargs)


def test_shipped_price_checkpoint_restores_and_scores():
    from ddls_tpu.train import RLEvalLoop

    loop = _make_eval_loop([
        # fixed moderate load keeps the assertion stable
        ("env_config.jobs_config.job_interarrival_time_dist._target_="
         "ddls_tpu.demands.distributions.Fixed"),
        "env_config.jobs_config.job_interarrival_time_dist.val=80.0",
    ])
    try:
        ev = RLEvalLoop(loop)
        r = ev.run(checkpoint_path=CKPT, seed=7005)
        rec = r["episode"]
    finally:
        loop.close()
    # held-out ia-80 per-decision mean is ~0.68 for this checkpoint;
    # anything positive clears random (~-0.2 here) by a wide margin
    per_decision = rec["episode_return"] / max(rec["episode_length"], 1)
    assert np.isfinite(per_decision)
    assert per_decision > 0.2, (rec["episode_return"],
                                rec["episode_length"])


import pytest


@pytest.mark.parametrize("name,cg,rk,sr,n", [
    ("ppo_price_ft8", 2, 2, 2, 8),
    ("ppo_price_ft72", 6, 6, 2, 72),
    ("ppo_price_ft128", 8, 8, 2, 128),
])
def test_shipped_per_size_checkpoints_restore(name, cg, rk, sr, n):
    """Each per-size fine-tune restores onto its documented env surface
    (full-episode scoring lives in the results artifact — a priced
    multi-hundred-decision episode per size is too heavy for the
    suite; this pins the restore path and parameter compatibility)."""
    import jax

    loop = _make_eval_loop([
        f"env_config.topology_config.kwargs"
        f".num_communication_groups={cg}",
        f"env_config.topology_config.kwargs"
        f".num_racks_per_communication_group={rk}",
        f"env_config.topology_config.kwargs.num_servers_per_rack={sr}",
        f"env_config.node_config.type_1.num_nodes={n}",
    ])
    try:
        before = jax.device_get(loop.state.params)
        loop.load_agent_checkpoint(os.path.join(REPO, "checkpoints",
                                                name))
        after = jax.device_get(loop.state.params)
    finally:
        loop.close()
    moved = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        before, after)
    assert max(jax.tree_util.tree_leaves(moved)) > 0


def test_shipped_jct_checkpoint_restores():
    """The second-objective (JCT-blocking) checkpoint restores onto the
    fixed-load price-feature surface it was trained on."""
    import jax

    loop = _make_eval_loop([
        ("env_config.jobs_config.job_interarrival_time_dist._target_="
         "ddls_tpu.demands.distributions.Fixed"),
        "env_config.jobs_config.job_interarrival_time_dist.val=50.0",
        "env_config.reward_function=multi_objective_jct_blocking",
        "env_config.reward_function_kwargs.fail_reward=null",
        "env_config.reward_function_kwargs.success_reward=null",
    ])
    try:
        before = jax.device_get(loop.state.params)
        loop.load_agent_checkpoint(os.path.join(REPO, "checkpoints",
                                                "ppo_jct_blocking"))
        after = jax.device_get(loop.state.params)
    finally:
        loop.close()
    moved = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        before, after)
    assert max(jax.tree_util.tree_leaves(moved)) > 0


def test_shipped_device_trained_checkpoint_restores_and_scores():
    """The attribution-control checkpoint (plain obs, device-collected)
    restores onto the plain env_load32 surface and clears the same
    sanity floor as the price policy."""
    from ddls_tpu.config import load_config
    from ddls_tpu.train import RLEvalLoop, make_epoch_loop
    from train_from_config import build_epoch_loop_kwargs

    cfg = load_config(os.path.join(REPO, "scripts",
                                   "ramp_job_partitioning_configs"),
                      "rllib_config",
                      ["env_config=env_load32",
                       "env_config.jobs_config.job_interarrival_time_"
                       "dist.val=80.0"])
    kwargs = build_epoch_loop_kwargs(cfg)
    kwargs["num_envs"] = 1
    kwargs["rollout_length"] = 1
    kwargs["evaluation_interval"] = None
    loop = make_epoch_loop("ppo", **kwargs)
    try:
        ev = RLEvalLoop(loop)
        r = ev.run(checkpoint_path=os.path.join(
            REPO, "checkpoints", "ppo_device_trained"), seed=7005)
        rec = r["episode"]
    finally:
        loop.close()
    per_decision = rec["episode_return"] / max(rec["episode_length"], 1)
    assert np.isfinite(per_decision)
    assert per_decision > 0.2, (rec["episode_return"],
                                rec["episode_length"])


def test_device_trained_policy_is_fixed_degree_packing():
    """Pins the round-5 rule extraction (VERDICT r4 item 1): the shipped
    obs-only device-collected policy's greedy decisions are EXACTLY
    FixedDegreePacking(8) — partition degree 8 when an 8-block is free,
    decline otherwise (docs/results_round5/rule_extraction.md; 12,672
    dumped decisions agree at 100%). One held-out episode suffices to
    catch a drifted checkpoint or a broken actor."""
    from ddls_tpu.config import load_config
    from ddls_tpu.envs.baselines import FixedDegreePacking
    from ddls_tpu.rl.rollout import stack_obs
    from ddls_tpu.train import make_epoch_loop
    from train_from_config import build_epoch_loop_kwargs

    cfg = load_config(os.path.join(REPO, "scripts",
                                   "ramp_job_partitioning_configs"),
                      "rllib_config",
                      ["env_config=env_load32",
                       ("env_config.jobs_config.job_interarrival_time_dist"
                        "._target_=ddls_tpu.demands.distributions.Fixed"),
                       ("env_config.jobs_config."
                        "job_interarrival_time_dist.val=80.0")])
    kwargs = build_epoch_loop_kwargs(cfg)
    kwargs["num_envs"] = 1
    kwargs["rollout_length"] = 1
    kwargs["evaluation_interval"] = None
    loop = make_epoch_loop("ppo", **kwargs)
    actor = FixedDegreePacking(degree=8)
    try:
        loop.load_agent_checkpoint(os.path.join(REPO, "checkpoints",
                                                "ppo_device_trained"))
        env = loop.make_eval_env()
        obs = env.reset(seed=7009)
        done, checked = False, 0
        while not done:
            a_pol = int(loop._greedy_actions(stack_obs([obs]))[0])
            a_rule = actor.compute_action(obs)
            assert a_pol == a_rule, (checked, a_pol, a_rule)
            obs, _, done, _ = env.step(a_pol)
            checked += 1
    finally:
        loop.close()
    assert checked > 100
