"""Scenario subsystem (ISSUE 16): fingerprinted specs, deterministic
failure schedules, the loadgen arrival bridge, and the backend-
conformance harness.

Tier-1 scope: the fast conformance legs (host_native episodes, golden
stats, lint) run IN-process; the full five-leg run (x64 jax/jitted
parity) is the ``slow``-marked subprocess test + the manual
``python scripts/conformance.py`` acceptance run.
"""
import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ddls_tpu.scenarios import (REGISTRY, ScenarioError, ScenarioSpec,
                                canonical_spec, failures_spec, get_spec,
                                multi_channel_spec, resolve_failure_windows,
                                spec_fingerprint, validate_spec)
from ddls_tpu.scenarios.failures import inflate_duration

pytestmark = pytest.mark.scenario

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ spec basics
def test_fingerprint_roundtrip():
    for factory in REGISTRY.values():
        spec = factory()
        validate_spec(spec)
        again = ScenarioSpec.from_json(spec.to_json())
        assert again == spec
        assert spec_fingerprint(again) == spec_fingerprint(spec)


def test_fingerprint_sensitive_to_every_value():
    base = spec_fingerprint(canonical_spec())
    edited = canonical_spec()
    edited.topology["kwargs"]["num_channels"] = 2
    assert spec_fingerprint(edited) != base
    edited = canonical_spec()
    edited.seed = 1
    assert spec_fingerprint(edited) != base


def test_registry_names_and_file_resolution(tmp_path):
    assert sorted(REGISTRY) == ["canonical", "failures", "multi_channel"]
    spec = failures_spec()
    path = tmp_path / "spec.json"
    path.write_text(spec.to_json())
    assert get_spec(str(path)) == spec
    with pytest.raises(ScenarioError, match="unknown scenario"):
        get_spec("no_such_scenario")


def test_from_json_rejects_unknown_fields():
    data = json.loads(canonical_spec().to_json())
    data["surprise"] = 1
    with pytest.raises(ScenarioError, match="unknown ScenarioSpec"):
        ScenarioSpec.from_json(json.dumps(data))


@pytest.mark.parametrize("mutate, match", [
    (lambda s: s.arrival.update(kind="bursty"), "arrival.kind"),
    (lambda s: s.sla.update(kind="exotic"), "sla.kind"),
    (lambda s: setattr(s, "job_sampling_mode", "remove_twice"),
     "job_sampling_mode"),
    (lambda s: s.device_speeds.update({"0-0-0": 0.0}), "must be > 0"),
    (lambda s: s.failures.update({"n_preempt": 1, "surprise": 2}),
     "unknown failures keys"),
    (lambda s: s.failures.update(
        {"windows": [{"kind": "meteor", "resource": 0,
                      "t0": 1.0, "t1": 2.0}]}), "window kind"),
    (lambda s: s.failures.update(
        {"windows": [{"kind": "worker_preempt", "resource": 0,
                      "t0": 5.0, "t1": 2.0}]}), "t0 < t1"),
])
def test_validator_rejections(mutate, match):
    spec = canonical_spec()
    mutate(spec)
    with pytest.raises(ScenarioError, match=match):
        validate_spec(spec)


# ------------------------------------------------------- failure schedule
def test_failure_schedule_bit_reproducible():
    spec = failures_spec()
    a = resolve_failure_windows(spec, n_servers=8, n_channels=28)
    b = resolve_failure_windows(copy.deepcopy(spec), n_servers=8,
                                n_channels=28)
    assert a == b  # exact, including every float bit
    assert len(a) == 4
    for w, nxt in zip(a, a[1:]):
        assert w["t1"] <= nxt["t0"]  # globally non-overlapping
    # any spec edit re-keys the schedule (the rng seed includes the
    # fingerprint)
    rekeyed = failures_spec()
    rekeyed.seed = 2
    assert resolve_failure_windows(rekeyed, 8, 28) != a


def test_explicit_overlapping_windows_rejected():
    spec = canonical_spec()
    spec.failures = {"windows": [
        {"kind": "worker_preempt", "resource": 0, "t0": 10.0, "t1": 50.0},
        {"kind": "channel_straggle", "resource": 1, "t0": 40.0,
         "t1": 80.0, "slowdown": 2.0}]}
    with pytest.raises(ScenarioError, match="non-overlapping"):
        resolve_failure_windows(spec, 8, 28)


# ------------------------------------------------------- loadgen arrivals
def test_loadgen_interarrival_deterministic():
    from ddls_tpu.demands.distributions import LoadgenInterarrival

    kw = dict(n_requests=64, base_rps=1.0, seed=7, time_scale=600.0)
    a, b = LoadgenInterarrival(**kw), LoadgenInterarrival(**kw)
    assert a.trace_fingerprint == b.trace_fingerprint
    ga = [a.sample() for _ in range(130)]  # cycles past n_requests
    gb = [b.sample() for _ in range(130)]
    assert ga == gb
    assert all(g >= 0.0 for g in ga)
    assert LoadgenInterarrival(**{**kw, "seed": 8}).trace_fingerprint \
        != a.trace_fingerprint


# ------------------------------------------------------ inflation kernels
def test_inflate_duration_hand_computed():
    t0 = np.asarray([10.0]); t1 = np.asarray([20.0])
    # full preemption (rate 0): work stops for the overlap, resumes after
    rate = np.asarray([0.0])
    assert inflate_duration(0.0, 15.0, 1.0, t0, t1, rate,
                            [True]) == pytest.approx(25.0)
    # window misses the op entirely: nominal
    assert inflate_duration(0.0, 5.0, 1.0, t0, t1, rate, [True]) == 5.0
    # not-affected resource: nominal
    assert inflate_duration(0.0, 15.0, 1.0, t0, t1, rate, [False]) == 15.0
    # straggler at rate 0.5: remaining work inside the window takes 2x;
    # 10s of work left at t=10, window capacity 10*0.5=5 -> 5s spill
    rate = np.asarray([0.5])
    assert inflate_duration(0.0, 20.0, 1.0, t0, t1, rate,
                            [True]) == pytest.approx(25.0)
    # slow device (r0=0.5) doubles everything before windows apply
    assert inflate_duration(0.0, 4.0, 0.5, t0[:0], t1[:0], rate[:0],
                            []) == pytest.approx(8.0)


def test_inflate_duration_host_vs_jax_agree():
    import jax.numpy as jnp

    from ddls_tpu.scenarios.failures import inflate_duration_jax

    rng = np.random.default_rng(3)
    t0 = np.sort(rng.uniform(0.0, 100.0, 4))
    t1 = t0 + rng.uniform(1.0, 10.0, 4)
    rate = np.asarray([0.0, 0.5, 0.25, 0.0])
    for _ in range(25):
        t_start = float(rng.uniform(0.0, 90.0))
        nominal = float(rng.uniform(0.1, 50.0))
        r0 = float(rng.choice([0.5, 0.8, 1.0, 1.25]))
        affects = [bool(b) for b in rng.integers(0, 2, 4)]
        host = inflate_duration(t_start, nominal, r0, t0, t1, rate,
                                affects)
        dev = inflate_duration_jax(
            jnp.asarray(t_start), jnp.asarray(nominal), jnp.asarray(r0),
            jnp.asarray(t0), jnp.asarray(t1), jnp.asarray(rate),
            [jnp.asarray(b) for b in affects])
        # f32 under the test mesh (no x64): compare at f32 resolution
        assert float(dev) == pytest.approx(host, rel=1e-5)


# ------------------------------------------------- episodes + conformance
def _run_failure_episode(max_decisions=40):
    from ddls_tpu.scenarios.conformance import (build_env,
                                                run_recorded_episode)

    env = build_env(failures_spec(), "host")
    events, actions = run_recorded_episode(env, seed=0,
                                           max_decisions=max_decisions)
    return events, actions


def test_failure_events_deterministic_and_adjusted():
    events_a, actions_a = _run_failure_episode()
    events_b, actions_b = _run_failure_episode()
    assert actions_a == actions_b
    fails_a = [e for e in events_a
               if e["kind"] in ("worker_preempted", "channel_degraded")]
    fails_b = [e for e in events_b
               if e["kind"] in ("worker_preempted", "channel_degraded")]
    assert fails_a and fails_a == fails_b
    # emitted t IS the window's t0 — the pure-(seed, spec) schedule
    spec = failures_spec()
    windows = resolve_failure_windows(spec, 8, 28)
    by_t0 = {w["t0"]: w for w in windows}
    for e in fails_a:
        w = by_t0[e["t0"]]
        assert e["t"] == w["t0"] and e["t1"] == w["t1"]
        assert e["rate"] == w["rate"]


def test_conformance_fast_legs_green_on_all_registry_specs():
    """host_native (bit-exact episodes), golden stats, and the lint
    backend-surface rule — in-process; the jax/jitted legs need x64 and
    ride the slow-marked CLI test below."""
    from ddls_tpu.native import native_available
    from ddls_tpu.scenarios.conformance import run_conformance

    for name in sorted(REGISTRY):
        report = run_conformance(get_spec(name), seed=0, max_decisions=30,
                                 legs=("host_native", "golden", "lint"))
        assert report["ok"], report
        statuses = {l["leg"]: l["status"] for l in report["legs"]}
        assert statuses["golden"] == "ok"
        assert statuses["lint"] == "ok"
        if native_available():
            assert statuses["host_native"] == "ok", report


def test_canonical_spec_matches_golden_stats():
    from ddls_tpu.scenarios.conformance import golden_stats_leg

    leg = golden_stats_leg(canonical_spec())
    assert leg["status"] == "ok", leg.get("mismatches")


def test_multi_channel_spec_excludes_jitted_leg_with_reason():
    from ddls_tpu.scenarios.conformance import _jitted_supported

    ok, reason = _jitted_supported(multi_channel_spec())
    assert not ok and "single-channel" in reason
    assert _jitted_supported(canonical_spec()) == (True, None)


@pytest.mark.slow
def test_conformance_cli_full_legs():
    """The acceptance run: scripts/conformance.py (which pins x64 in its
    own process) exits 0 across the whole registry with every leg ok or
    skipped-with-reason."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "conformance.py"),
         "--json", "--max-decisions", "120"],
        capture_output=True, text=True, timeout=2400, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"]
    for rep in doc["specs"]:
        for leg in rep["legs"]:
            assert leg["status"] in ("ok", "skipped", "unavailable"), leg


# ----------------------------------------- training-loop scenario plumbing
_LOOP_ENV_CLS = ("ddls_tpu.envs.partitioning_env."
                 "RampJobPartitioningEnvironment")
_LOOP_TINY_MODEL = {"fcnet_hiddens": [16],
                    "custom_model_config": {"out_features_msg": 4,
                                            "out_features_hidden": 8,
                                            "out_features_node": 4,
                                            "out_features_graph": 4}}


def _loop_overrides(dataset_dir):
    """Tiny-workload env_config overrides: each key REPLACES the spec's
    top-level key wholesale (the loops.py merge contract — never a deep
    merge)."""
    return dict(
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 4,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 2},
        max_partitions_per_op=4,
        max_simulation_run_time=5e4,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})


def _scenario_loop(scenario, dataset_dir):
    from ddls_tpu.train import make_epoch_loop

    return make_epoch_loop(
        "ppo",
        path_to_env_cls=_LOOP_ENV_CLS,
        env_config=_loop_overrides(dataset_dir),
        model=_LOOP_TINY_MODEL,
        algo_config={"train_batch_size": 4, "sgd_minibatch_size": 2,
                     "num_sgd_iter": 1, "num_workers": 2},
        num_envs=2, rollout_length=2, n_devices=1,
        use_parallel_envs=False, evaluation_interval=None, seed=0,
        loop_mode="pipelined", scenario=scenario)


def test_epoch_loop_canonical_scenario_is_byte_identical(dataset_dir):
    """ISSUE 20 satellite: make_epoch_loop(scenario=...) resolves the
    spec into env construction kwargs with explicit env_config keys
    replacing spec keys wholesale, and records the fingerprint. The
    canonical spec builds runtime=None, so the resulting env_config is
    EXACTLY the hand-built dict — no scenario_runtime key, byte-
    identical env path."""
    from ddls_tpu.scenarios import env_kwargs

    spec = canonical_spec()
    loop = _scenario_loop("canonical", dataset_dir)
    try:
        expected = dict(env_kwargs(spec))
        expected.update(_loop_overrides(dataset_dir))
        assert loop.env_config == expected
        assert "scenario_runtime" not in loop.env_config
        assert loop.scenario_fingerprint == spec_fingerprint(spec)
    finally:
        loop.close()


def test_epoch_loop_failure_scenario_carries_runtime(dataset_dir):
    """A failure spec's resolved ScenarioRuntime rides env_config into
    every constructed env (cluster.scenario_runtime), keyed by the spec
    fingerprint; a spec instance is accepted as well as a name."""
    spec = failures_spec()
    loop = _scenario_loop(spec, dataset_dir)
    try:
        rt = loop.env_config["scenario_runtime"]
        assert rt is not None
        assert rt.fingerprint == spec_fingerprint(spec)
        env = loop.vec_env.envs[0]
        assert env.cluster.scenario_runtime is rt
        assert loop.scenario_fingerprint == spec_fingerprint(spec)
    finally:
        loop.close()
