"""Worker for the 4-process REAL-epoch multi-host test.

Each process owns 2 partitioning envs (process-distinct seeds by
RLEpochLoop's built-in offset) and joins a global gloo mesh; two full
collect+update epochs run on the REAL RampJobPartitioningEnvironment in a
loaded, blocking-heavy regime so processes genuinely diverge in what
their envs do (different blocking patterns — the deterministic-gate
hazard class from CLAUDE.md's multi-host rules), while the nominally
replicated parameters must stay BIT-identical on every process.

Prints machine-checkable lines: PARAMS <sha1>, DIVERGE blocked=<n>.
"""
import hashlib
import sys

sys.path.insert(0, sys.argv[4] if len(sys.argv) > 4 else ".")

from ddls_tpu.parallel import initialize_distributed


def main() -> int:
    coordinator, num_processes, process_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
    initialize_distributed(coordinator_address=coordinator,
                           num_processes=num_processes,
                           process_id=process_id, platform="cpu")
    import jax
    import numpy as np

    from ddls_tpu.train.loops import RLEpochLoop

    env_config = {
        "topology_config": {"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        "node_config": {"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        "jobs_config": {
            # deterministic synthetic dataset: identical files on every
            # process, so env CONFIG is process-identical while env
            # BEHAVIOR diverges through the per-process collect seeds
            "synthetic": {"n_cnn": 1, "n_translation": 1, "seed": 6,
                          "min_ops": 6, "max_ops": 8},
            "path_to_files": None,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 40.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.1, "max_val": 0.6, "decimals": 2},
            "replication_factor": 20,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 20},
        "max_partitions_per_op": 4,
        "min_op_run_time_quantum": 0.01,
        "reward_function": "job_acceptance",
        "max_simulation_run_time": 2e3,
        "pad_obs_kwargs": {"max_nodes": 32, "max_edges": 64},
    }
    model = {"fcnet_hiddens": [16], "custom_model_config": {
        "out_features_msg": 4, "out_features_hidden": 8,
        "out_features_node": 4, "out_features_graph": 4}}
    algo_config = {"lr": 1e-3, "num_sgd_iter": 2,
                   "sgd_minibatch_size": 8, "train_batch_size": 16}

    loop = RLEpochLoop(
        path_to_env_cls="ddls_tpu.envs.partitioning_env."
                        "RampJobPartitioningEnvironment",
        env_config=env_config, model=model, algo_config=algo_config,
        num_envs=2, rollout_length=8, use_parallel_envs=False,
        evaluation_interval=None, seed=0)
    for _ in range(2):
        results = loop.run()
    assert results["epoch_counter"] == 2, results

    # process-divergence evidence: per-process env blocking counters
    blocked = sum(int(env.cluster.episode_stats["num_jobs_blocked"])
                  + sum(e.get("num_jobs_blocked", 0)
                        for e in getattr(env, "_episode_records", []))
                  for env in loop.vec_env.envs)
    arrived = sum(int(env.cluster.num_jobs_arrived)
                  for env in loop.vec_env.envs)
    print(f"DIVERGE process={process_id} blocked={blocked} "
          f"arrived={arrived}", flush=True)

    # parameters must be BIT-identical across processes
    leaves = jax.tree_util.tree_leaves(jax.device_get(loop.state.params))
    h = hashlib.sha1()
    for leaf in leaves:
        h.update(np.ascontiguousarray(leaf).tobytes())
    print(f"PARAMS process={process_id} digest={h.hexdigest()}",
          flush=True)
    loop.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
