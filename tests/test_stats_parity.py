"""Stats-engine parity tests: exact step/episode stat values on
hand-computable scenarios, per-job blocking causes, pbtxt reader coverage,
and the cluster's SQLite save backend.

These pin the quantities the reference's paper figures are built from
(reference: ramp_cluster_environment.py:956-1167 stats engine,
actions/action.py:36-48 blocking causes).
"""
import pytest

from ddls_tpu.agents import (FirstFitDepPlacer, RampFirstFitOpPlacer,
                             SRPTDepScheduler, SRPTOpScheduler)
from ddls_tpu.agents.partitioners import build_partition_action
from ddls_tpu.graphs.readers import graph_from_pbtxt, read_graph_file
from ddls_tpu.sim import Action, OpPartition, RampClusterEnvironment
from ddls_tpu.sim.actions import OpPlacement
from ddls_tpu.utils import SqliteDict


def _single_op_profile(tmp_path):
    """One forward op: fwd=2, bwd=4, activation=100, parameter=10.

    Mirrored graph: fwd op "1" (compute 2, memory 110), bwd op "2"
    (compute 4, memory 110), join edge (1, 2) of size 100 (the producer's
    activation). Placed unpartitioned on one worker every dep is a non-flow,
    so per-training-step time is exactly 2 + 4 = 6.
    """
    path = tmp_path / "tiny.txt"
    path.write_text(
        "node1 -- Linear(id=1) -- forward_compute_time=2.0, "
        "backward_compute_time=4.0, activation_size=100.0, "
        "parameter_size=10.0\n")
    return str(tmp_path)


def _make_cluster(**kwargs):
    return RampClusterEnvironment(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        **kwargs)


def _jobs_config(path, steps=5, frac=1.0, mode="remove"):
    return {
        "path_to_files": path,
        "job_interarrival_time_dist": {
            "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 1e6},
        "max_acceptable_job_completion_time_frac_dist": {
            "_target_": "ddls_tpu.demands.distributions.Fixed", "val": frac},
        "replication_factor": 1,
        "num_training_steps": steps,
        "job_sampling_mode": mode,
    }


def _heuristic_action(cluster, max_parts=1):
    action_map = {}
    for job_id, job in cluster.job_queue.jobs.items():
        action_map[job_id] = build_partition_action(
            job.graph, min_op_run_time_quantum=0.01,
            max_partitions_per_op=max_parts)
    op_partition = OpPartition(action_map, cluster=cluster)
    op_placement = RampFirstFitOpPlacer().get(op_partition, cluster)
    op_schedule = SRPTOpScheduler().get(op_partition, op_placement, cluster)
    dep_placement = FirstFitDepPlacer().get(op_partition, op_placement, cluster)
    dep_schedule = SRPTDepScheduler().get(op_partition, dep_placement, cluster)
    return Action(op_partition=op_partition, op_placement=op_placement,
                  op_schedule=op_schedule, dep_placement=dep_placement,
                  dep_schedule=dep_schedule)


# ------------------------------------------------------------- pinned values
def test_single_job_episode_stats_exact(tmp_path):
    """Every headline stat on a one-op job placed on one worker, where each
    quantity is computable by hand:

    per-step time = 2 + 4 = 6; JCT = 6 * 5 steps = 30; total op memory
    cost = 2 * (100 + 10) = 220; total dep size = 100 (join edge); all
    deps are non-flows.
    """
    cluster = _make_cluster()
    cluster.reset(_jobs_config(_single_op_profile(tmp_path), steps=5),
                  max_simulation_run_time=None, seed=0)
    cluster.step(_heuristic_action(cluster))
    assert cluster.is_done()

    e = cluster.episode_stats
    assert e["num_jobs_arrived"] == 1
    assert e["num_jobs_completed"] == 1
    assert e["num_jobs_blocked"] == 0
    assert e["blocking_rate"] == 0.0
    assert e["acceptance_rate"] == 1.0
    assert e["job_completion_time"] == [pytest.approx(30.0)]
    assert e["job_completion_time_speedup"] == [pytest.approx(1.0)]
    assert e["job_communication_overhead_time"] == [pytest.approx(0.0)]
    assert e["job_computation_overhead_time"] == [pytest.approx(30.0)]
    assert e["jobs_completed_num_nodes"] == [2]
    assert e["jobs_completed_num_edges"] == [1]
    assert e["jobs_completed_total_operation_memory_cost"] == (
        [pytest.approx(220.0)])
    # the partition transform re-bases edge sizes on the producer's memory
    # cost (activation + parameter = 110), matching the reference's
    # data_split_node semantics
    assert e["jobs_completed_total_dependency_size"] == [pytest.approx(110.0)]
    assert e["jobs_completed_num_mounted_workers"] == [1]
    assert e["jobs_completed_num_mounted_channels"] == [0]
    # the single mounted worker is busy for the whole JCT
    assert e["jobs_completed_mean_mounted_worker_utilisation_frac"] == (
        [pytest.approx(1.0)])

    assert e["episode_time"] == pytest.approx(30.0)
    assert e["compute_info_processed"] == pytest.approx(220.0)
    assert e["dep_info_processed"] == pytest.approx(110.0)
    assert e["flow_info_processed"] == pytest.approx(0.0)
    assert e["cluster_info_processed"] == pytest.approx(330.0)
    assert e["mean_compute_throughput"] == pytest.approx(220.0 / 30.0)
    assert e["mean_cluster_throughput"] == pytest.approx(330.0 / 30.0)
    # original (pre-rebase) demand: 220 memory + 100 activation-sized dep
    assert e["demand_total_info_processed"] == pytest.approx(320.0)
    assert e["mean_demand_total_throughput"] == pytest.approx(320.0 / 30.0)

    assert e["mean_num_jobs_running"] == pytest.approx(1.0)
    assert e["mean_num_mounted_workers"] == pytest.approx(1.0)
    assert e["mean_mounted_worker_utilisation_frac"] == pytest.approx(1.0)
    # 1 of 8 workers mounted, fully utilised
    assert e["mean_cluster_worker_utilisation_frac"] == pytest.approx(1 / 8)
    assert e["mean_compute_overhead_frac"] == pytest.approx(1.0)
    assert e["mean_communication_overhead_frac"] == pytest.approx(0.0)

    # step-level mirror of the same quantities
    s = cluster.steps_log
    assert s["step_time"] == [pytest.approx(30.0)]
    assert s["mean_compute_throughput"] == [pytest.approx(220.0 / 30.0)]
    assert s["job_queue_length"] == [0]


# ------------------------------------------------------------ blocking causes
def test_blocked_cause_sla(tmp_path):
    cluster = _make_cluster()
    cluster.reset(_jobs_config(_single_op_profile(tmp_path), steps=5,
                               frac=0.001), seed=0)
    cluster.step(_heuristic_action(cluster))
    assert cluster.episode_stats["num_jobs_blocked"] == 1
    assert cluster.episode_stats[
        "jobs_blocked_cause_of_unsuccessful_handling"] == (
        ["max_acceptable_job_completion_time_exceeded"])


def test_blocked_cause_sub_action(tmp_path):
    """A job handled by op_partition but dropped by op_placement records
    op_placement as its blocking cause (reference: action.py:36-48)."""
    cluster = _make_cluster()
    cluster.reset(_jobs_config(_single_op_profile(tmp_path)), seed=0)
    job_id = next(iter(cluster.job_queue.jobs))
    op_partition = OpPartition({job_id: {}}, cluster=cluster)
    op_placement = OpPlacement({}, op_partition, cluster)  # placer failed
    action = Action(op_partition=op_partition, op_placement=op_placement)
    assert action.job_id_to_cause_of_unsuccessful_handling == {
        job_id: "op_placement"}
    cluster.step(action)
    assert cluster.episode_stats[
        "jobs_blocked_cause_of_unsuccessful_handling"] == ["op_placement"]


def test_blocked_cause_queue_full(tmp_path):
    cluster = _make_cluster()
    cfg = _jobs_config(_single_op_profile(tmp_path))
    cfg["replication_factor"] = 2
    cfg["job_interarrival_time_dist"] = {
        "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 1.0}
    cluster.reset(cfg, seed=0)
    cluster.job_queue.queue_capacity = 0  # force the overflow path
    cluster.step(Action())
    causes = cluster.episode_stats[
        "jobs_blocked_cause_of_unsuccessful_handling"]
    assert causes[0] == "not_handled"       # queued job, empty action
    assert "job_queue_full" in causes       # second arrival cannot fit


# ------------------------------------------------------------------- sqlite
def test_cluster_sqlite_save(tmp_path):
    cluster = _make_cluster(path_to_save=str(tmp_path / "out"),
                            use_sqlite_database=True)
    cluster.reset(_jobs_config(_single_op_profile(tmp_path)),
                  max_simulation_run_time=None, seed=0)
    cluster.step(_heuristic_action(cluster))
    assert cluster.is_done()
    dbs = list((tmp_path / "out").rglob("*.sqlite"))
    assert {p.name for p in dbs} == {"steps_log.sqlite",
                                     "episode_stats.sqlite"}
    db = SqliteDict(str([p for p in dbs if p.name ==
                         "episode_stats.sqlite"][0]))
    try:
        assert db["num_jobs_completed"] == 1
        assert db["job_completion_time"] == [pytest.approx(30.0)]
    finally:
        db.close()


# -------------------------------------------------------------- pbtxt reader
PBTXT = """node {
  name: "op_a"
  id: 1
  output_info {
    size: 64
  }
  compute_cost: 5
}
node {
  name: "op_b"
  id: 3
  input_info {
    preceding_node: 1
  }
  output_info {
    size: 32
  }
  compute_cost: 7
}
node {
  name: "op_c"
  id: 7
  input_info {
    preceding_node: 3
  }
  control_input: 1
  output_info {
    size: 16
  }
  compute_cost: 2
}
"""


def test_pbtxt_reader(tmp_path):
    path = tmp_path / "g.pbtxt"
    path.write_text(PBTXT)
    g = graph_from_pbtxt(str(path), mirror=False)

    # sparse ids 1, 3, 7 remapped to contiguous "1", "2", "3"
    assert set(g.op_ids) == {"1", "2", "3"}
    assert g.compute_cost("1") == 5.0
    assert g.compute_cost("2") == 7.0
    assert g.compute_cost("3") == 2.0
    assert g.memory_cost("1") == 64.0

    # data edges sized by the producer's (single) output size; control
    # edges sized 0
    assert g.edge_size("1", "2") == 64.0
    assert g.edge_size("2", "3") == 32.0
    assert g.edge_size("1", "3") == 0.0
    assert g.n_deps == 3


def test_pbtxt_reader_mirrored(tmp_path):
    path = tmp_path / "g.pbtxt"
    path.write_text(PBTXT)
    g = graph_from_pbtxt(str(path), mirror=True)

    # 3 forward + 3 mirrored backward ops; bwd id = 2n - (fwd - 1)
    assert set(g.op_ids) == {"1", "2", "3", "4", "5", "6"}
    assert g.compute_cost("6") == 5.0   # bwd of op 1
    assert g.compute_cost("4") == 2.0   # bwd of op 3
    # reflected backward edge for (1, 2) is (5, 6)
    assert g.has_edge("5", "6")
    # join edge from last fwd op to first bwd op
    assert g.has_edge("3", "4")

    # dispatch by extension
    g2 = read_graph_file(str(path))
    assert set(g2.op_ids) == set(g.op_ids)
