"""Sweep runner: space expansion + a real 4-config heuristic sweep."""
import importlib

import numpy as np
import pytest
import yaml

run_sweep_mod = importlib.import_module("scripts.run_sweep")


def test_grid_expansion():
    space = {
        "a.b": {"values": [1, 2]},
        "c": {"values": ["x", "y", "z"]},
    }
    combos = run_sweep_mod.expand_parameter_space(space, method="grid")
    assert len(combos) == 6
    assert {"a.b": 1, "c": "x"} in combos
    assert {"a.b": 2, "c": "z"} in combos


def test_random_expansion():
    space = {
        "lr": {"distribution": "log_uniform", "min": 1e-6, "max": 1e-3},
        "gamma": {"values": [0.99, 0.999]},
        "layers": {"distribution": "int_uniform", "min": 1, "max": 3},
    }
    combos = run_sweep_mod.expand_parameter_space(
        space, method="random", num_runs=16, seed=0)
    assert len(combos) == 16
    for combo in combos:
        assert 1e-6 <= combo["lr"] <= 1e-3
        assert combo["gamma"] in (0.99, 0.999)
        assert combo["layers"] in (1, 2, 3)
    # seeded reproducibility
    again = run_sweep_mod.expand_parameter_space(
        space, method="random", num_runs=16, seed=0)
    assert combos == again


def test_grid_requires_values():
    with pytest.raises(ValueError, match="values"):
        run_sweep_mod.expand_parameter_space(
            {"lr": {"distribution": "uniform", "min": 0, "max": 1}},
            method="grid")


def test_heuristic_sweep_end_to_end(tmp_path):
    """A real 4-actor sweep over a shrunken episode produces per-run
    results and a sweep comparison table."""
    sweep_cfg = {
        "name": "test_sweep",
        "program": "test_heuristic_from_config.py",
        "config_path": "ramp_job_partitioning_configs",
        "config_name": "heuristic_config",
        "method": "grid",
        "max_parallel": 2,
        "stagger_seconds": 0.0,
        "run_timeout_seconds": 240,
        "overrides": [
            "experiment.seed=0",
            "eval_loop.env.jobs_config.replication_factor=2",
            "eval_loop.env.jobs_config.job_sampling_mode=remove",
            "eval_loop.env.jobs_config.synthetic.n_cnn=1",
            "eval_loop.env.jobs_config.synthetic.n_translation=1",
            "eval_loop.env.jobs_config.job_interarrival_time_dist.val=100",
        ],
        "parameters": {
            "eval_loop.actor._target_": {"values": [
                "ddls_tpu.envs.baselines.AcceptableJCT",
                "ddls_tpu.envs.baselines.SiPML",
                "ddls_tpu.envs.baselines.MaxParallelism",
                "ddls_tpu.envs.baselines.NoParallelism",
            ]},
        },
    }
    cfg_path = tmp_path / "sweep.yaml"
    cfg_path.write_text(yaml.safe_dump(sweep_cfg))

    rc = run_sweep_mod.main(["--sweep-config", str(cfg_path),
                             "--out", str(tmp_path / "sweep_out")])
    assert rc == 0
    summary = tmp_path / "sweep_out" / "sweep_summary.csv"
    assert summary.exists()
    import pandas as pd

    table = pd.read_csv(summary)
    assert len(table) == 4
    assert set(table["run"]) == {
        "_target_=AcceptableJCT", "_target_=SiPML",
        "_target_=MaxParallelism", "_target_=NoParallelism"}
    # every run handled the same 4-job workload
    assert (table["num_jobs_arrived"] == table["num_jobs_arrived"].iloc[0]).all()
    assert (tmp_path / "sweep_out" / "analysis" / "comparison.png").exists()
