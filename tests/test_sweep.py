"""Sweep runner: space expansion + a real 4-config heuristic sweep."""
import importlib

import numpy as np
import pytest
import yaml

run_sweep_mod = importlib.import_module("scripts.run_sweep")


def test_grid_expansion():
    space = {
        "a.b": {"values": [1, 2]},
        "c": {"values": ["x", "y", "z"]},
    }
    combos = run_sweep_mod.expand_parameter_space(space, method="grid")
    assert len(combos) == 6
    assert {"a.b": 1, "c": "x"} in combos
    assert {"a.b": 2, "c": "z"} in combos


def test_random_expansion():
    space = {
        "lr": {"distribution": "log_uniform", "min": 1e-6, "max": 1e-3},
        "gamma": {"values": [0.99, 0.999]},
        "layers": {"distribution": "int_uniform", "min": 1, "max": 3},
    }
    combos = run_sweep_mod.expand_parameter_space(
        space, method="random", num_runs=16, seed=0)
    assert len(combos) == 16
    for combo in combos:
        assert 1e-6 <= combo["lr"] <= 1e-3
        assert combo["gamma"] in (0.99, 0.999)
        assert combo["layers"] in (1, 2, 3)
    # seeded reproducibility
    again = run_sweep_mod.expand_parameter_space(
        space, method="random", num_runs=16, seed=0)
    assert combos == again


def test_grid_requires_values():
    with pytest.raises(ValueError, match="values"):
        run_sweep_mod.expand_parameter_space(
            {"lr": {"distribution": "uniform", "min": 0, "max": 1}},
            method="grid")


def test_bayes_codec_roundtrip():
    space = {
        "lr": {"distribution": "log_uniform", "min": 1e-6, "max": 1e-3},
        "gamma": {"values": [0.99, 0.999]},
        "layers": {"distribution": "int_uniform", "min": 1, "max": 3},
        "frac": {"distribution": "uniform", "min": 0.25, "max": 0.75},
    }
    keys, decoders = run_sweep_mod._param_codec(space)
    assert keys == sorted(space)
    rng = np.random.default_rng(0)
    for _ in range(64):
        a = run_sweep_mod._decode_point(rng.uniform(size=4), keys, decoders)
        assert 1e-6 <= a["lr"] <= 1e-3
        assert a["gamma"] in (0.99, 0.999)
        assert a["layers"] in (1, 2, 3)
        assert 0.25 <= a["frac"] <= 0.75
    # unit-interval endpoints decode to the space's endpoints, not beyond
    lo = run_sweep_mod._decode_point(np.zeros(4), keys, decoders)
    hi = run_sweep_mod._decode_point(np.ones(4) - 1e-9, keys, decoders)
    assert lo["layers"] == 1 and hi["layers"] == 3
    assert lo["gamma"] == 0.99 and hi["gamma"] == 0.999
    # negative int ranges stay uniform (floor, not truncate-toward-zero)
    nkeys, ndecs = run_sweep_mod._param_codec(
        {"n": {"distribution": "int_uniform", "min": -3, "max": -1}})
    vals = [run_sweep_mod._decode_point(np.array([u]), nkeys, ndecs)["n"]
            for u in np.linspace(0, 0.999, 300)]
    counts = {v: vals.count(v) for v in (-3, -2, -1)}
    assert all(80 <= c <= 120 for c in counts.values()), counts


def test_gp_ei_concentrates_near_optimum():
    """On a smooth 1-D objective the GP-EI proposer's queries must
    outperform random search: after a random warm start, proposals should
    cluster near the optimum."""
    rng = np.random.default_rng(1)

    def objective(u):  # max at u = 0.3
        return -(u - 0.3) ** 2

    X = [np.array([u]) for u in rng.uniform(size=4)]
    y = [objective(x[0]) for x in X]
    proposals = []
    for _ in range(10):
        u = run_sweep_mod.gp_ei_propose(np.stack(X), np.asarray(y), 1, rng)
        proposals.append(float(u[0]))
        X.append(u)
        y.append(objective(u[0]))
    # the last proposals should be near the optimum
    tail = proposals[-4:]
    assert max(abs(u - 0.3) for u in tail) < 0.1, (proposals, tail)
    best = X[int(np.argmax(y))][0]
    assert abs(best - 0.3) < 0.05


def test_bayes_sweep_end_to_end(tmp_path):
    """A real (tiny) bayes sweep: heuristic episodes whose return depends
    monotonically on the swept max-JCT fraction; the GP must find a
    near-top assignment and the history file must record proposal
    sources."""
    sweep_cfg = {
        "name": "bayes_sweep",
        "program": "test_heuristic_from_config.py",
        "config_path": "ramp_job_partitioning_configs",
        "config_name": "heuristic_config",
        "method": "bayes",
        "num_runs": 5,
        "num_initial": 2,
        "metric": "episode_return",
        "goal": "maximise",
        "seed": 0,
        "run_timeout_seconds": 240,
        "overrides": [
            "experiment.seed=0",
            "eval_loop.env.jobs_config.replication_factor=2",
            "eval_loop.env.jobs_config.job_sampling_mode=remove",
            "eval_loop.env.jobs_config.synthetic.n_cnn=1",
            "eval_loop.env.jobs_config.synthetic.n_translation=1",
            "eval_loop.env.jobs_config.job_interarrival_time_dist.val=100",
        ],
        "parameters": {
            ("eval_loop.env.jobs_config."
             "max_acceptable_job_completion_time_frac_dist.min_val"): {
                "distribution": "uniform", "min": 0.05, "max": 0.9},
        },
    }
    cfg_path = tmp_path / "sweep.yaml"
    cfg_path.write_text(yaml.safe_dump(sweep_cfg))
    out = tmp_path / "out"
    rc = run_sweep_mod.main(["--sweep-config", str(cfg_path),
                             "--out", str(out)])
    assert rc == 0
    history = yaml.safe_load((out / "bayes_history.yaml").read_text())
    assert len(history) == 5
    assert history[0]["proposal_source"] == "random-init"
    assert any(h["proposal_source"] == "gp-ei" for h in history)
    assert all("objective" in h for h in history)
    assert (out / "sweep_summary.csv").exists()


def test_heuristic_sweep_end_to_end(tmp_path):
    """A real 4-actor sweep over a shrunken episode produces per-run
    results and a sweep comparison table."""
    sweep_cfg = {
        "name": "test_sweep",
        "program": "test_heuristic_from_config.py",
        "config_path": "ramp_job_partitioning_configs",
        "config_name": "heuristic_config",
        "method": "grid",
        "max_parallel": 2,
        "stagger_seconds": 0.0,
        "run_timeout_seconds": 240,
        "overrides": [
            "experiment.seed=0",
            "eval_loop.env.jobs_config.replication_factor=2",
            "eval_loop.env.jobs_config.job_sampling_mode=remove",
            "eval_loop.env.jobs_config.synthetic.n_cnn=1",
            "eval_loop.env.jobs_config.synthetic.n_translation=1",
            "eval_loop.env.jobs_config.job_interarrival_time_dist.val=100",
        ],
        "parameters": {
            "eval_loop.actor._target_": {"values": [
                "ddls_tpu.envs.baselines.AcceptableJCT",
                "ddls_tpu.envs.baselines.SiPML",
                "ddls_tpu.envs.baselines.MaxParallelism",
                "ddls_tpu.envs.baselines.NoParallelism",
            ]},
        },
    }
    cfg_path = tmp_path / "sweep.yaml"
    cfg_path.write_text(yaml.safe_dump(sweep_cfg))

    rc = run_sweep_mod.main(["--sweep-config", str(cfg_path),
                             "--out", str(tmp_path / "sweep_out")])
    assert rc == 0
    summary = tmp_path / "sweep_out" / "sweep_summary.csv"
    assert summary.exists()
    import pandas as pd

    table = pd.read_csv(summary)
    assert len(table) == 4
    assert set(table["run"]) == {
        "_target_=AcceptableJCT", "_target_=SiPML",
        "_target_=MaxParallelism", "_target_=NoParallelism"}
    # every run handled the same 4-job workload
    assert (table["num_jobs_arrived"] == table["num_jobs_arrived"].iloc[0]).all()
    assert (tmp_path / "sweep_out" / "analysis" / "comparison.png").exists()
