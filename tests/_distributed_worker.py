"""Worker process for the multi-host mesh test.

Launched twice by tests/test_distributed.py with JAX_PLATFORMS=cpu and a
2-device virtual host each, forming a 2-process x 2-device global mesh.
Runs a learner-shaped update: params replicated, batch assembled from
process-local shards, gradient all-reduced by XLA from the sharding
annotations alone. Prints one machine-checkable line per assertion.
"""
import sys

sys.path.insert(0, sys.argv[4] if len(sys.argv) > 4 else ".")

from ddls_tpu.parallel import (distributed_info, initialize_distributed,
                               is_primary, make_mesh, replicated_sharding,
                               shard_batch)


def main() -> int:
    coordinator, num_processes, process_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
    info = initialize_distributed(coordinator_address=coordinator,
                                  num_processes=num_processes,
                                  process_id=process_id,
                                  platform="cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    assert info["process_count"] == num_processes, info
    assert info["num_global_devices"] == 2 * num_processes, info
    assert is_primary() == (process_id == 0)
    print(f"TOPOLOGY process={info['process_index']} "
          f"global_devices={info['num_global_devices']}", flush=True)

    mesh = make_mesh()  # spans the global device set
    assert mesh.devices.size == 2 * num_processes, mesh.shape

    # global batch = concat of per-process shards; every process holds a
    # distinct slice, so a wrong assembly changes the loss value
    local_batch = np.arange(4, dtype=np.float32) + 4.0 * process_id
    x = shard_batch(mesh, {"x": local_batch})["x"]
    assert x.shape == (8,), x.shape

    params = jax.device_put(jnp.float32(2.0), replicated_sharding(mesh))

    @jax.jit
    def update(w, batch):
        # d/dw mean((w * b)^2) = mean(2 w b^2); XLA inserts the cross-host
        # all-reduce for the mean over the sharded batch
        grad = jax.grad(lambda w: jnp.mean((w * batch) ** 2))(w)
        return w - 0.01 * grad

    new_w = update(params, x)
    # batch is globally 0..7 -> mean(b^2) = 17.5, grad = 2*2*17.5 = 70
    expected = 2.0 - 0.01 * 70.0
    got = float(jax.device_get(new_w))
    assert abs(got - expected) < 1e-5, (got, expected)
    print(f"UPDATE process={process_id} w={got:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
