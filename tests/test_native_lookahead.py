"""Parity tests for the C++ lookahead engine (ddls_tpu/native).

Contract: bit-exact f64 agreement with the host tick engine
(cluster._run_lookahead) — identical semantics AND identical arithmetic
order — so the native path can be enabled by default ("auto") without
perturbing the golden stats tests.
"""
import numpy as np
import pytest

from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.native import native_available, run_lookahead

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable")


def _env_kwargs(tmp_path, **overrides):
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    dataset = str(tmp_path / "graphs")
    generate_pipedream_txt_files(dataset, n_cnn=2, n_translation=1, seed=0,
                                 min_ops=8, max_ops=14)
    kwargs = dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 2,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 500.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.3, "max_val": 1.0, "decimals": 2},
            "replication_factor": 20,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 10},
        max_partitions_per_op=8,
        min_op_run_time_quantum=0.01,
        reward_function="job_acceptance",
        reward_function_kwargs={"fail_reward": -1, "success_reward": 1},
        max_simulation_run_time=1e6,
        pad_obs_kwargs={"max_nodes": 150, "max_edges": 512})
    kwargs.update(overrides)
    return kwargs


def test_native_bit_exact_with_host_engine(tmp_path):
    """Every cache-miss lookahead of real episodes agrees bit-for-bit."""
    env = RampJobPartitioningEnvironment(
        **_env_kwargs(tmp_path, use_native_lookahead=False))
    cluster = env.cluster
    host_engine = cluster._run_lookahead
    compared = []

    def spy(job):
        host = host_engine(job)
        native = cluster._run_native_lookahead(job)
        compared.append((host, native, job.graph.n_ops, job.graph.n_deps))
        return host

    cluster._run_lookahead = spy
    obs = env.reset(seed=0)
    rng = np.random.RandomState(0)
    for i in range(80):
        valid = np.nonzero(np.asarray(obs["action_mask"]))[0]
        obs, _, done, _ = env.step(int(rng.choice(valid)))
        if done:
            obs = env.reset(seed=100 + i)
            # caches persist across resets; clear so later episodes keep
            # producing cache-miss lookaheads for the spy to compare
            cluster.lookahead_cache.clear()

    assert len(compared) >= 5, "episodes produced too few cache-miss lookaheads"
    for host, native, n_ops, n_deps in compared:
        assert native is not None, f"native bailed on n={n_ops} m={n_deps}"
        # bit-exact: the native engine replicates the host's f64 arithmetic
        assert tuple(host) == tuple(native)


def test_full_episode_outcomes_identical(tmp_path):
    """A full episode with the native path auto-enabled reproduces the
    pure-host episode exactly (JCTs, rewards, blocking)."""
    outcomes = []
    for use_native in (False, True):
        env = RampJobPartitioningEnvironment(
            **_env_kwargs(tmp_path, use_native_lookahead=use_native))
        obs = env.reset(seed=3)
        rng = np.random.RandomState(3)
        rewards, done, steps = [], False, 0
        while not done and steps < 200:
            valid = np.nonzero(np.asarray(obs["action_mask"]))[0]
            obs, r, done, _ = env.step(int(rng.choice(valid)))
            rewards.append(r)
            steps += 1
        stats = env.cluster.episode_stats
        outcomes.append((rewards,
                         stats["num_jobs_completed"],
                         stats["num_jobs_blocked"],
                         tuple(stats.get("job_completion_time", []))))
    assert outcomes[0] == outcomes[1]


def test_native_bails_to_none_on_livelock():
    """A non-flow dep with positive remaining can never finish (the host
    engine raises); the native engine must return None (fall back)."""
    from ddls_tpu.sim.jax_lookahead import LookaheadArrays

    arrays = LookaheadArrays(
        op_remaining=np.array([1.0], np.float64),
        op_valid=np.array([True]),
        op_worker=np.array([0], np.int32),
        op_score=np.array([1.0], np.float64),
        num_parents=np.array([0], np.int32),
        dep_remaining=np.array([5.0], np.float64),
        dep_valid=np.array([True]),
        dep_src=np.array([0], np.int32),
        dep_dst=np.array([0], np.int32),
        dep_mutual=np.array([True]),
        dep_is_flow=np.array([False]),
        dep_score=np.array([1.0], np.float64),
        dep_channel=np.full((1, 1), -1, np.int32),
        num_workers=1, num_channels=1)
    assert run_lookahead(arrays) is None


def test_auto_flag_enables_native(tmp_path):
    env = RampJobPartitioningEnvironment(**_env_kwargs(tmp_path))
    assert env.cluster.use_native_lookahead is True
