"""Legacy simulator path: dynamic-tick ClusterEnvironment, manager-style
agents, the job-placing env, and the run_sim demo (reference counterparts:
ddls/environments/cluster/cluster_environment.py:28, ddls/managers/,
ddls/environments/job_placing/, scripts/run_sim.py)."""
import importlib

import numpy as np
import pytest

from ddls_tpu.agents.managers import (AllReduceJobCommunicator,
                                      FIFOJobScheduler, RandomJobPlacer,
                                      SRPTJobPrioritiser, SRPTJobScheduler)
from ddls_tpu.envs.job_placing_env import JobPlacingAllNodesEnvironment
from ddls_tpu.sim.legacy_cluster import ClusterEnvironment


def _profile(tmp_path, name, fwd, bwd):
    path = tmp_path / f"{name}.txt"
    path.write_text(
        f"node1 -- Linear(id=1) -- forward_compute_time={fwd:.1f}, "
        f"backward_compute_time={bwd:.1f}, activation_size=100.0, "
        f"parameter_size=10.0\n")
    return str(path)


def _make_cluster(workers_per_node=1, dims=(2, 2), **kwargs):
    return ClusterEnvironment(
        topology_config={"type": "torus", "kwargs": {
            "x_dims": dims[0], "y_dims": dims[1]}},
        node_config={"type_1": {"num_nodes": dims[0] * dims[1],
                                "workers_config": [
            {"num_workers": workers_per_node, "worker": "A100"}]}},
        **kwargs)


def _jobs_config(path, steps=1, interarrival=1e6, replication=1):
    return {
        "path_to_files": path,
        "job_interarrival_time_dist": {
            "_target_": "ddls_tpu.demands.distributions.Fixed",
            "val": interarrival},
        "replication_factor": replication,
        "job_sampling_mode": "remove",
        "shuffle_files": False,
        "num_training_steps": steps,
    }


def _place_first_job(cluster, worker_id, scheduler):
    job = list(cluster.job_queue.jobs.values())[0]
    placement = {job.job_id: {op: worker_id for op in job.graph.op_ids}}
    schedule = scheduler.get_schedule(new_placements=placement,
                                     cluster=cluster)
    cluster.step({"job_placement": placement, "job_schedule": schedule})
    return job


def _drain(cluster, max_steps=50):
    steps = 0
    while not cluster.is_done() and steps < max_steps:
        cluster.step({"job_placement": {}, "job_schedule": {}})
        steps += 1
    assert cluster.is_done()


def test_single_job_completes_in_sequential_time(tmp_path):
    """Deps are free in the legacy engine, so one job on one worker takes
    exactly its sequential compute time per training step."""
    _profile(tmp_path, "a", fwd=2.0, bwd=4.0)
    cluster = _make_cluster()
    cluster.reset(_jobs_config(str(tmp_path), steps=3), seed=0)
    worker_id = next(iter(cluster.topology.workers))
    _place_first_job(cluster, worker_id, FIFOJobScheduler())
    assert cluster.is_done()
    assert len(cluster.jobs_completed) == 1
    assert cluster.sim_log["job_completion_time"] == [pytest.approx(18.0)]
    # worker freed
    assert not cluster.topology.workers[worker_id].mounted_job_idx_to_ops


def test_workers_hold_multiple_jobs_and_srpt_orders_them(tmp_path):
    """Two jobs share one worker (no RAMP exclusivity); SRPT runs the
    shorter job to completion first."""
    _profile(tmp_path, "a_short", fwd=1.0, bwd=1.0)   # seq 2
    _profile(tmp_path, "b_long", fwd=3.0, bwd=3.0)    # seq 6
    cluster = _make_cluster()
    cluster.reset(_jobs_config(str(tmp_path), steps=1, interarrival=0.0),
                  seed=0)
    worker_id = next(iter(cluster.topology.workers))
    scheduler = SRPTJobScheduler()
    job1 = _place_first_job(cluster, worker_id, scheduler)  # admits job 2
    assert len(cluster.job_queue) == 1
    job2 = _place_first_job(cluster, worker_id, scheduler)
    _drain(cluster)
    jcts = {job.details["model"]:
            job.details["time_completed"] - job.details["time_arrived"]
            for job in cluster.jobs_completed.values()}
    # shorter job runs first: 2; longer finishes at 8
    assert jcts["a_short"] == pytest.approx(2.0)
    assert jcts["b_long"] == pytest.approx(8.0)


def test_fifo_orders_by_arrival(tmp_path):
    _profile(tmp_path, "a_first", fwd=3.0, bwd=3.0)   # arrives first, seq 6
    _profile(tmp_path, "b_second", fwd=1.0, bwd=1.0)  # seq 2
    cluster = _make_cluster()
    cluster.reset(_jobs_config(str(tmp_path), steps=1, interarrival=0.0),
                  seed=0)
    worker_id = next(iter(cluster.topology.workers))
    scheduler = FIFOJobScheduler()
    _place_first_job(cluster, worker_id, scheduler)
    _place_first_job(cluster, worker_id, scheduler)
    _drain(cluster)
    jcts = {job.details["model"]:
            job.details["time_completed"] - job.details["time_arrived"]
            for job in cluster.jobs_completed.values()}
    # first-arrived (long) job runs first despite being longer
    assert jcts["a_first"] == pytest.approx(6.0)
    assert jcts["b_second"] == pytest.approx(8.0)


def test_random_job_placer_respects_memory(tmp_path):
    _profile(tmp_path, "a", fwd=1.0, bwd=1.0)
    cluster = _make_cluster()
    cluster.reset(_jobs_config(str(tmp_path)), seed=0)
    placement = RandomJobPlacer().get_placement(cluster)
    assert len(placement) == 1
    job = list(cluster.job_queue.jobs.values())[0]
    ops = placement[job.job_id]
    assert set(ops) == set(job.graph.op_ids)
    assert all(w in cluster.topology.workers for w in ops.values())


def test_step_returns_when_nothing_can_progress(tmp_path):
    """A queued job left unplaced after the generator drains must hand
    control back to the caller, not spin forever."""
    _profile(tmp_path, "a", fwd=1.0, bwd=1.0)
    cluster = _make_cluster()
    cluster.reset(_jobs_config(str(tmp_path)), seed=0)
    cluster.step({"job_placement": {}, "job_schedule": {}})  # must return
    assert not cluster.is_done()
    assert len(cluster.job_queue) == 1


def test_random_job_partitioner(tmp_path):
    from ddls_tpu.agents import RandomJobPartitioner
    from ddls_tpu.graphs.readers import graph_from_pipedream_txt

    g = graph_from_pipedream_txt(_profile(tmp_path, "a", fwd=4.0, bwd=4.0))
    pg = RandomJobPartitioner(max_partitions_per_op=4).get_partitioned_graph(g)
    assert pg.n_ops >= g.n_ops


def test_prioritiser_and_communicator_stub(tmp_path):
    _profile(tmp_path, "a", fwd=1.0, bwd=1.0)
    cluster = _make_cluster()
    cluster.reset(_jobs_config(str(tmp_path)), seed=0)
    pris = SRPTJobPrioritiser().get_priorities(cluster)
    assert len(pris) == 1
    with pytest.raises(NotImplementedError):
        AllReduceJobCommunicator().communicate(cluster)


def test_run_sim_script():
    mod = importlib.import_module("scripts.run_sim")
    assert mod.main(["--scheduler", "srpt", "--num-jobs", "5",
                     "--dataset-dir", "/tmp/ddls_tpu/test_run_sim"]) == 0


def test_job_placing_env_episode(tmp_path):
    """Full episode of the legacy placing MDP: valid actions place jobs on
    a+1 random workers; every arrived job is completed or blocked."""
    _profile(tmp_path, "a", fwd=1.0, bwd=2.0)
    _profile(tmp_path, "b", fwd=2.0, bwd=3.0)
    env = JobPlacingAllNodesEnvironment(
        topology_config={"type": "torus", "kwargs": {"x_dims": 2,
                                                     "y_dims": 2}},
        node_config={"type_1": {"num_nodes": 4, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config=_jobs_config(str(tmp_path), steps=2, interarrival=5.0,
                                 replication=3),
        reward_function="worker_compute_utilisation",
        pad_obs_kwargs={"max_nodes": 8})
    obs = env.reset(seed=0)
    assert obs["node_features"].shape == (8, 2)
    assert obs["action_mask"].any()
    assert env.action_space.n == 4

    done, steps, rewards = False, 0, []
    while not done and steps < 50:
        valid = np.flatnonzero(obs["action_mask"])
        action = int(valid[steps % len(valid)])
        obs, reward, done, _ = env.step(action)
        rewards.append(reward)
        steps += 1
    assert done
    total = (len(env.cluster.jobs_completed)
             + len(env.cluster.jobs_blocked))
    assert total == env.cluster.num_jobs_arrived == 6
    assert all(0.0 <= r <= 1.0 for r in rewards)


def test_job_placing_env_jct_reward(tmp_path):
    _profile(tmp_path, "a", fwd=1.0, bwd=2.0)
    env = JobPlacingAllNodesEnvironment(
        topology_config={"type": "torus", "kwargs": {"x_dims": 2,
                                                     "y_dims": 2}},
        node_config={"type_1": {"num_nodes": 4, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config=_jobs_config(str(tmp_path), steps=1),
        reward_function="mean_job_completion_time",
        pad_obs_kwargs={"max_nodes": 8})
    obs = env.reset(seed=0)
    obs, reward, done, _ = env.step(0)  # 1 worker
    assert done
    # JCT = 3 -> reward = -log10(3 + 1)
    assert reward == pytest.approx(-np.log10(4.0))


def test_continuous_action_mode(tmp_path):
    _profile(tmp_path, "a", fwd=1.0, bwd=2.0)
    env = JobPlacingAllNodesEnvironment(
        topology_config={"type": "torus", "kwargs": {"x_dims": 2,
                                                     "y_dims": 2}},
        node_config={"type_1": {"num_nodes": 4, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config=_jobs_config(str(tmp_path), steps=1),
        continuous_action_mode=True,
        pad_obs_kwargs={"max_nodes": 8})
    env.reset(seed=0)
    _, _, done, _ = env.step(0.5)  # half the cluster = 2 workers
    assert done
    assert len(env.cluster.jobs_completed) == 1
