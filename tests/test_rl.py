"""RL stack tests: GAE, sharded PPO update, rollout collection.

The SPMD invariant test (8-device mesh == 1-device mesh) is the fake-backend
substitute for multi-chip hardware (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddls_tpu.models.policy import GNNPolicy, batched_policy_apply
from ddls_tpu.parallel import make_mesh
from ddls_tpu.rl import (ParallelVectorEnv, PPOConfig, PPOLearner,
                         RolloutCollector, VectorEnv)
from ddls_tpu.rl.ppo import compute_gae


def _ref_gae(rewards, values, dones, last_values, gamma, lam):
    T, B = rewards.shape
    advs = np.zeros((T, B))
    next_adv = np.zeros(B)
    for t in reversed(range(T)):
        nv = last_values if t == T - 1 else values[t + 1]
        nd = 1.0 - dones[t]
        delta = rewards[t] + gamma * nv * nd - values[t]
        next_adv = delta + gamma * lam * nd * next_adv
        advs[t] = next_adv
    return advs, advs + values


def test_gae_matches_reference_loop():
    rng = np.random.RandomState(0)
    T, B = 7, 3
    rewards = rng.randn(T, B).astype(np.float32)
    values = rng.randn(T, B).astype(np.float32)
    dones = (rng.rand(T, B) < 0.3).astype(np.float32)
    last_values = rng.randn(B).astype(np.float32)
    advs, targets = compute_gae(jnp.asarray(rewards), jnp.asarray(values),
                                jnp.asarray(dones), jnp.asarray(last_values),
                                gamma=0.97, lam=0.95)
    ref_advs, ref_targets = _ref_gae(rewards, values, dones, last_values,
                                     0.97, 0.95)
    np.testing.assert_allclose(np.asarray(advs), ref_advs, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(targets), ref_targets, rtol=1e-5)


N_ACTIONS = 5
MAX_NODES = 6
MAX_EDGES = MAX_NODES * (MAX_NODES - 1) // 2


def _fake_obs(rng, batch_shape):
    """Random padded-graph observation batch with valid masks."""
    B = int(np.prod(batch_shape))
    n_nodes = rng.randint(2, MAX_NODES + 1, size=B)
    n_edges = np.minimum(n_nodes - 1, MAX_EDGES)
    obs = {
        "node_features": rng.rand(B, MAX_NODES, 5).astype(np.float32),
        "edge_features": rng.rand(B, MAX_EDGES, 2).astype(np.float32),
        "graph_features": rng.rand(
            B, 17 + N_ACTIONS).astype(np.float32),
        "edges_src": rng.randint(0, 2, size=(B, MAX_EDGES)).astype(np.int32),
        "edges_dst": rng.randint(0, 2, size=(B, MAX_EDGES)).astype(np.int32),
        "node_split": n_nodes[:, None].astype(np.int32),
        "edge_split": n_edges[:, None].astype(np.int32),
        "action_mask": np.concatenate(
            [np.ones((B, 2), np.int32),
             rng.randint(0, 2, size=(B, N_ACTIONS - 2)).astype(np.int32)],
            axis=1),
    }
    return {k: v.reshape(batch_shape + v.shape[1:]) for k, v in obs.items()}


def _fake_traj(rng, T, B):
    obs = _fake_obs(rng, (T, B))
    return {
        "obs": obs,
        "actions": rng.randint(0, 2, size=(T, B)).astype(np.int32),
        "logp": np.log(np.full((T, B), 0.3, np.float32)),
        "values": rng.randn(T, B).astype(np.float32),
        "rewards": rng.randn(T, B).astype(np.float32),
        "dones": (rng.rand(T, B) < 0.2),
    }


def _make_learner(mesh, model):
    cfg = PPOConfig(num_sgd_iter=2, sgd_minibatch_size=8,
                    grad_clip=0.5)
    return PPOLearner(lambda p, o: batched_policy_apply(model, p, o),
                      cfg, mesh)


@pytest.fixture(scope="module")
def model_and_params():
    model = GNNPolicy(n_actions=N_ACTIONS, out_features_msg=4,
                      out_features_hidden=8, out_features_node=4,
                      out_features_graph=4, fcnet_hiddens=(16,))
    rng = np.random.RandomState(1)
    single = jax.tree_util.tree_map(lambda x: x[0], _fake_obs(rng, (1,)))
    params = model.init(jax.random.PRNGKey(0), single)
    return model, params


def test_train_step_runs_and_updates(model_and_params):
    model, params = model_and_params
    mesh = make_mesh(8)
    learner = _make_learner(mesh, model)
    state = learner.init_state(params)
    rng = np.random.RandomState(2)
    traj = _fake_traj(rng, T=4, B=16)
    last_values = rng.randn(16).astype(np.float32)
    straj, slv = learner.shard_traj(traj, last_values)
    new_state, metrics = learner.train_step(state, straj, slv,
                                            jax.random.PRNGKey(3))
    assert int(new_state.step) == 2 * 8  # epochs x minibatches
    for key in ("policy_loss", "vf_loss", "kl", "entropy", "total_loss",
                "clip_frac", "kl_coeff"):
        assert np.isfinite(float(metrics[key])), key
    # params actually moved (compare against the host-side originals;
    # `state` itself was donated into train_step and its buffers deleted)
    diff = jax.tree_util.tree_reduce(
        lambda acc, leaf: acc + float(jnp.abs(leaf).sum()),
        jax.tree_util.tree_map(lambda a, b: a - b, new_state.params,
                               params), 0.0)
    assert diff > 0.0


def test_sharded_update_matches_single_device(model_and_params):
    """The dp-sharded update must be numerically the same program as the
    single-device update — sharding is layout, not semantics.

    Uses sgd_minibatch_size >= T*B so every minibatch is the full batch:
    minibatch *composition* is deliberately device-count-dependent (the
    shuffle is per-shard to avoid cross-ICI gathers), but the full-batch
    gradient math must agree exactly across mesh sizes."""
    model, params = model_and_params
    rng = np.random.RandomState(4)
    traj = _fake_traj(rng, T=4, B=16)
    last_values = rng.randn(16).astype(np.float32)

    results = []
    for n_dev in (1, 8):
        mesh = make_mesh(n_dev)
        learner = PPOLearner(
            lambda p, o: batched_policy_apply(model, p, o),
            PPOConfig(num_sgd_iter=2, sgd_minibatch_size=64, grad_clip=0.5),
            mesh)
        state = learner.init_state(params)
        straj, slv = learner.shard_traj(traj, last_values)
        new_state, metrics = learner.train_step(state, straj, slv,
                                                jax.random.PRNGKey(5))
        results.append((jax.device_get(new_state.params),
                        jax.device_get(metrics)))
    p1, m1 = results[0]
    p8, m8 = results[1]
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        p1, p8)
    for k in m1:
        np.testing.assert_allclose(m1[k], m8[k], rtol=2e-4, atol=2e-5)


def test_tp_sharded_update_matches_single_device(model_and_params):
    """PPO on a 2-D (dp, mp) mesh with tensor-parallel parameter shardings
    (mp_tree_shardings) is the same program as the single-device update:
    with full-batch minibatches the composition trick of the dp test
    applies, so (4,2) must agree with 1 device numerically."""
    from ddls_tpu.parallel.mesh import mp_tree_shardings

    model, params = model_and_params
    rng = np.random.RandomState(4)
    traj = _fake_traj(rng, T=4, B=16)
    last_values = rng.randn(16).astype(np.float32)

    results = []
    for n_dev, axes, shape, tp in ((1, ("dp",), None, None),
                                   (8, ("dp", "mp"), (4, 2), "mp")):
        mesh = make_mesh(n_dev, axes, shape=shape)
        learner = PPOLearner(
            lambda p, o: batched_policy_apply(model, p, o),
            PPOConfig(num_sgd_iter=2, sgd_minibatch_size=64, grad_clip=0.5),
            mesh, shard_params_axis=tp)
        state = learner.init_state(params)
        if tp is not None:
            specs = [str(getattr(x.sharding, "spec", ""))
                     for x in jax.tree_util.tree_leaves(state.params)]
            assert any("mp" in s for s in specs), specs
        straj, slv = learner.shard_traj(traj, last_values)
        new_state, metrics = learner.train_step(state, straj, slv,
                                                jax.random.PRNGKey(5))
        results.append((jax.device_get(new_state.params),
                        jax.device_get(metrics)))
    p1, m1 = results[0]
    ptp, mtp = results[1]
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        p1, ptp)
    for k in m1:
        np.testing.assert_allclose(m1[k], mtp[k], rtol=2e-4, atol=2e-5)


def test_mesh_explicit_shape_and_mp_rule():
    from ddls_tpu.parallel.mesh import mp_tree_shardings

    mesh = make_mesh(8, ("dp", "mp"), shape=(4, 2))
    assert mesh.shape["dp"] == 4 and mesh.shape["mp"] == 2
    with pytest.raises(ValueError, match="factor"):
        make_mesh(8, ("dp", "mp"), shape=(3, 2))
    tree = {"kernel": np.zeros((6, 4)), "bias": np.zeros((4,)),
            "odd": np.zeros((5, 3)), "scalar": np.zeros(())}
    specs = mp_tree_shardings(mesh, tree, axis_name="mp")
    assert "mp" in str(specs["kernel"].spec)
    assert str(specs["bias"].spec) == str(specs["scalar"].spec)
    assert "mp" not in str(specs["odd"].spec)  # 3 not divisible by 2


def test_masked_actions_never_sampled(model_and_params):
    model, params = model_and_params
    mesh = make_mesh(1)
    learner = _make_learner(mesh, model)
    rng = np.random.RandomState(6)
    obs = _fake_obs(rng, (32,))
    obs["action_mask"][:, 3:] = 0
    actions, logp, values = learner.sample_actions(
        params, obs, jax.random.PRNGKey(7))
    assert np.asarray(actions).max() < 3
    assert np.all(np.isfinite(np.asarray(logp)))


class _ToyEnv:
    """3-step episodes with a fake cluster-stats surface."""

    def __init__(self):
        self.t = 0

    def reset(self, seed=None):
        self.t = 0
        return self._obs()

    def _obs(self):
        rng = np.random.RandomState(self.t)
        return jax.tree_util.tree_map(lambda x: x[0], _fake_obs(rng, (1,)))

    def step(self, action):
        self.t += 1
        done = self.t >= 3
        return self._obs(), 1.0, done, {}


def test_train_step_nondivisible_minibatch(model_and_params):
    """Remainder samples are dropped per shard when the per-device sample
    count is not a multiple of the per-device minibatch size."""
    model, params = model_and_params
    mesh = make_mesh(8)
    learner = PPOLearner(
        lambda p, o: batched_policy_apply(model, p, o),
        PPOConfig(num_sgd_iter=2, sgd_minibatch_size=16), mesh)
    state = learner.init_state(params)
    rng = np.random.RandomState(9)
    traj = _fake_traj(rng, T=5, B=8)  # n=40, n_loc=5, mb_loc=2 -> 2 mbs
    last_values = rng.randn(8).astype(np.float32)
    straj, slv = learner.shard_traj(traj, last_values)
    new_state, metrics = learner.train_step(state, straj, slv,
                                            jax.random.PRNGKey(10))
    assert np.isfinite(float(metrics["total_loss"]))
    assert int(new_state.step) == 2 * 2


def test_vector_env_autoreset_and_collect(model_and_params):
    model, params = model_and_params
    mesh = make_mesh(1)
    learner = _make_learner(mesh, model)
    vec = VectorEnv([_ToyEnv for _ in range(4)])
    collector = RolloutCollector(vec, learner, rollout_length=7)
    out = collector.collect(params, jax.random.PRNGKey(8))
    assert out["env_steps"] == 28
    assert out["traj"]["rewards"].shape == (7, 4)
    # 3-step episodes over 7 steps -> 2 completed episodes per env
    assert len(out["episodes"]) == 8
    for ep in out["episodes"]:
        assert ep["episode_return"] == 3.0
        assert ep["episode_length"] == 3
    # dones marked at episode boundaries (t = 2 and 5, 0-indexed)
    assert out["traj"]["dones"][2].all() and out["traj"]["dones"][5].all()
    assert not out["traj"]["dones"][0].any()


def test_parallel_vector_env_matches_serial():
    """ParallelVectorEnv must behave like VectorEnv: same rewards/dones,
    auto-reset, episode harvesting, and seed continuity across reset()."""
    par = ParallelVectorEnv(_ToyEnv, {}, 4, start_method="spawn")
    ser = VectorEnv([_ToyEnv for _ in range(4)])
    par.reset()
    ser.reset()
    for t in range(7):
        actions = np.zeros(4, dtype=np.int32)
        obs_p, rew_p, done_p = par.step(actions)
        obs_s, rew_s, done_s = ser.step(actions)
        np.testing.assert_array_equal(rew_p, rew_s)
        np.testing.assert_array_equal(done_p, done_s)
        for op, os_ in zip(obs_p, obs_s):
            np.testing.assert_allclose(op["node_features"],
                                       os_["node_features"])
    eps_p = par.drain_completed_episodes()
    eps_s = ser.drain_completed_episodes()
    assert len(eps_p) == len(eps_s) == 8
    assert all(ep["episode_return"] == 3.0 for ep in eps_p)
    # a second reset must not raise and must keep stepping fine
    par.reset()
    par.step(np.zeros(4, dtype=np.int32))
    par.close()
