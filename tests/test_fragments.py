"""Cross-host dataflow fragments pins (ISSUE 20, rl/fragments.py,
docs/perf_round14.md).

* frame codec — scatter-gather SEGMENT encode → socket → sink-directed
  recv round-trips bit-exactly; the incremental FrameAssembler survives
  torn prefixes/headers/bodies; desynchronised streams and mismatched
  sinks fail loudly;
* loud rejections — collect_transport='socket' refuses DQN/ES,
  non-pipelined loop modes, the device collector, and an orphaned
  socket_config BEFORE any env construction;
* the acceptance pin — a single-actor-host depth-0 PPO run over the
  socket transport is BIT-exact vs the in-process path (learner
  metrics, episode records content AND order, env_steps, post-training
  params), its steady-state epoch stays transfer-guard-clean with the
  fragment consumer engaged, and killing the actor host surfaces as a
  loud RuntimeError naming the host — with zero /dev/shm or socket-path
  litter after close();
* depth-K staleness — the IMPALA depth-1 socket loop reports
  ``params_age_updates`` exactly as the in-process ring does, with the
  ``segment_transit_s`` sibling riding the same metrics mapping.

Tests needing real POSIX shared memory carry the ``shm`` marker (the
actor host's vec env and the learner ring both slab over /dev/shm).
"""
import os
import socket

import numpy as np
import pytest

from ddls_tpu.rl.fragments import (AckToken, FrameAssembler, PREFIX_BYTES,
                                   T_ACK, T_CONFIG, T_SEGMENT, encode_frame,
                                   frame_nbytes, parse_address, recv_frame,
                                   send_frame)


# ---------------------------------------------------------------- codec
def _segment_fields(rng):
    return {
        "obs:node_features": rng.rand(4, 3, 5).astype(np.float32),
        "actions": rng.randint(0, 7, (4, 3)).astype(np.int32),
        "rewards": rng.rand(4, 3).astype(np.float64),
    }


def _segment_frame(fields, seq=3):
    header = {"seq": seq,
              "fields": [(k, v.shape, v.dtype.str)
                         for k, v in fields.items()],
              "collect_wall_s": 0.125}
    return header, encode_frame(T_SEGMENT, header,
                                [memoryview(v).cast("B")
                                 for v in fields.values()])


def test_segment_roundtrip_with_sink():
    """encode → socketpair → recv_frame: every field lands bit-exact;
    a sink-provided destination (the learner ring-segment view) is
    written IN PLACE — the recv is the lease-time write."""
    rng = np.random.RandomState(0)
    fields = _segment_fields(rng)
    header, parts = _segment_frame(fields)
    a, b = socket.socketpair()
    try:
        a.sendall(b"".join(bytes(p) for p in parts))
        sink_buf = np.empty((4, 3, 5), np.float32)

        def sink(name, shape, dtype):
            return sink_buf if name == "obs:node_features" else None

        ftype, got_header, got = recv_frame(b, field_sink=sink)
    finally:
        a.close()
        b.close()
    assert ftype == T_SEGMENT
    assert got_header["seq"] == header["seq"]
    assert got["obs:node_features"] is sink_buf  # in-place recv
    for k, v in fields.items():
        np.testing.assert_array_equal(got[k], v, err_msg=k)
        assert got[k].dtype == v.dtype, k


def test_send_frame_counts_every_byte():
    a, b = socket.socketpair()
    try:
        n = send_frame(a, T_ACK, {"seq": 9})
        assert n == frame_nbytes(encode_frame(T_ACK, {"seq": 9}))
        ftype, header, fields = recv_frame(b)
        assert (ftype, header, fields) == (T_ACK, {"seq": 9}, {})
    finally:
        a.close()
        b.close()


def test_frame_assembler_torn_frames():
    """Two frames fed in 7-byte chunks: each emerges only once complete
    (torn prefix/header/body all wait), then the buffer drains to 0."""
    fields = _segment_fields(np.random.RandomState(2))
    header, parts = _segment_frame(fields)
    wire = (b"".join(bytes(p)
                     for p in encode_frame(T_CONFIG, {"num_envs": 2}))
            + b"".join(bytes(p) for p in parts))
    asm = FrameAssembler()
    out = []
    for i in range(0, len(wire), 7):
        out.extend(asm.feed(wire[i:i + 7]))
    assert asm.pending_bytes == 0
    assert [(f[0], f[1].get("num_envs"), f[1].get("seq"))
            for f in out] == [(T_CONFIG, 2, None), (T_SEGMENT, None, 3)]
    # the SEGMENT body is the concatenated raw field bytes in table order
    assert out[1][2] == b"".join(v.tobytes() for v in fields.values())


def test_frame_assembler_bad_magic_is_loud():
    asm = FrameAssembler()
    with pytest.raises(ValueError, match="magic"):
        asm.feed(b"XXXX" + b"\0" * PREFIX_BYTES)


def test_recv_frame_sink_mismatch_is_loud():
    fields = _segment_fields(np.random.RandomState(3))
    _, parts = _segment_frame(fields)
    a, b = socket.socketpair()
    try:
        a.sendall(b"".join(bytes(p) for p in parts))
        with pytest.raises(ValueError, match="sink shape/dtype"):
            recv_frame(b, field_sink=lambda *_:
                       np.empty((1, 1), np.float32))
    finally:
        a.close()
        b.close()


def test_body_without_field_table_is_loud():
    parts = encode_frame(T_SEGMENT, {"no": "fields"},
                         [memoryview(b"junkjunk")])
    a, b = socket.socketpair()
    try:
        a.sendall(b"".join(bytes(p) for p in parts))
        with pytest.raises(ValueError, match="no field table"):
            recv_frame(b)
    finally:
        a.close()
        b.close()


def test_parse_address_forms():
    assert parse_address("unix:/tmp/x.sock") == (socket.AF_UNIX,
                                                 "/tmp/x.sock")
    assert parse_address("tcp:127.0.0.1:5001") == (socket.AF_INET,
                                                   ("127.0.0.1", 5001))
    with pytest.raises(ValueError, match="unix:"):
        parse_address("udp:nope")


def test_ack_token_protocol():
    tok = AckToken()
    assert not tok.is_ready()
    tok.set()
    assert tok.is_ready()


# ----------------------------------------------------- loud rejections
_TINY_MODEL = {"fcnet_hiddens": [16],
               "custom_model_config": {"out_features_msg": 4,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}}

ENV_CLS = "ddls_tpu.envs.partitioning_env.RampJobPartitioningEnvironment"


def _env_config(dataset_dir):
    return dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 4,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 2},
        max_partitions_per_op=4,
        reward_function="job_acceptance",
        max_simulation_run_time=5e4,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})


def _loop_kwargs(dataset_dir, **over):
    kw = dict(path_to_env_cls=ENV_CLS,
              env_config=_env_config(dataset_dir),
              model=_TINY_MODEL,
              algo_config={"train_batch_size": 8,
                           "sgd_minibatch_size": 4,
                           "num_sgd_iter": 2, "num_workers": 2},
              num_envs=2, rollout_length=4, n_devices=2,
              use_parallel_envs=True, evaluation_interval=None, seed=0,
              loop_mode="pipelined",
              collect_transport="socket",
              socket_config={"transport": "unix"})
    kw.update(over)
    return kw


@pytest.mark.parametrize("algo,over,match", [
    ("apex_dqn", {"algo_config": {}}, "does not support"),
    ("es", {"algo_config": {}}, "does not support"),
    ("ppo", {"loop_mode": "sequential"}, "requires loop_mode"),
    ("ppo", {"algo_config": {"train_batch_size": 8,
                             "device_collector": True}},
     "device_collector"),
    ("ppo", {"collect_transport": "inprocess"}, "socket_config"),
    ("ppo", {"collect_transport": "carrier-pigeon",
             "socket_config": None}, "collect_transport"),
], ids=["dqn", "es", "sequential", "device-collector",
        "orphan-config", "bad-transport"])
def test_socket_transport_loud_rejections(algo, over, match, dataset_dir):
    """Every unsupported combination is rejected BEFORE env construction
    with a message that says why (the ES/DQN opt-out convention)."""
    from ddls_tpu.train import make_epoch_loop

    with pytest.raises(ValueError, match=match):
        make_epoch_loop(algo, **_loop_kwargs(dataset_dir, **over))


# -------------------------------------------- parity / guard / teardown
def _leaked(names):
    return [n for n in names
            if os.path.exists(os.path.join("/dev/shm", n.lstrip("/")))]


def _epoch_record(r, socket_arm):
    learner = dict(r["learner"])
    if socket_arm:
        # the transport's own metrics ride the mapping; everything else
        # must match the in-process arm bit-for-bit
        assert learner.pop("segment_transit_s") >= 0.0
    return {"learner": learner, "episodes": r["episodes"],
            "env_steps": r["env_steps_this_iter"]}


@pytest.mark.shm
def test_socket_parity_transfer_guard_and_teardown(dataset_dir):
    """The ISSUE 20 acceptance pin, three phases on ONE socket loop:

    1. parity — 3 epochs of single-actor-host depth-0 PPO over the
       socket transport reproduce the in-process arm bit-for-bit
       (metrics, episodes content AND order, env_steps, final params);
       the 3rd socket epoch additionally runs under
       ``jax.transfer_guard("disallow")`` — the steady-state fragment
       epoch performs NO implicit device↔host transfer (params leave
       via the collector's explicit device_get, segments enter via the
       collector's explicit device_put staging);
    2. teardown — SIGTERM on the actor host makes the NEXT collect
       raise a RuntimeError naming the host and its pid (no hang, no
       silent truncation);
    3. litter — after close(), the unix socket path, its tempdir, and
       every learner-ring /dev/shm segment are gone."""
    import jax

    from ddls_tpu.train import make_epoch_loop

    outcomes = {}
    for transport in ("inprocess", "socket"):
        over = ({} if transport == "socket"
                else {"collect_transport": "inprocess",
                      "socket_config": None})
        loop = make_epoch_loop("ppo", **_loop_kwargs(dataset_dir, **over))
        records = []
        for epoch in range(3):
            if transport == "socket" and epoch == 2:
                with jax.transfer_guard("disallow"):
                    r = loop.run()
            else:
                r = loop.run()
            records.append(_epoch_record(r, transport == "socket"))
        loop.sync_metrics()
        params = jax.device_get(loop.state.params)
        if transport == "socket":
            frag = loop.collector
            address = frag.address
            assert address.startswith("unix:")
            sock_path = address[len("unix:"):]
            assert os.path.exists(sock_path)
            shm_names = [n for seg in frag.ring.segments
                         for n in seg.slabs.segment_names()]
            assert shm_names  # the learner ring really slabbed
            stats = frag.stats()
            # the pipelined loop prefetches, so >= epochs consumed —
            # but every received segment must have been acked
            assert stats["segments"] == stats["per_host"]["h0"]["acks"] >= 3
            assert stats["collect_bytes_per_step"] > 0

            # phase 2: kill the actor host — loud, named, no hang
            (proc,) = frag._procs
            proc.terminate()
            proc.wait(timeout=30)
            with pytest.raises(RuntimeError,
                               match=r"actor host 0 \(pid \d+"):
                for _ in range(3):  # a prefetched segment may absorb one
                    loop.run()
            loop.close()
            loop.close()  # idempotent
            # phase 3: zero litter on every surface the learner owns
            assert not os.path.exists(sock_path)
            assert not os.path.exists(os.path.dirname(sock_path))
            assert _leaked(shm_names) == []
        else:
            loop.close()
        outcomes[transport] = (records, params)

    ref_records, ref_params = outcomes["inprocess"]
    soc_records, soc_params = outcomes["socket"]
    for e, (rr, rs) in enumerate(zip(ref_records, soc_records)):
        assert rr["env_steps"] == rs["env_steps"], f"epoch {e}"
        assert rr["learner"] == rs["learner"], f"epoch {e} metrics"
        assert rr["episodes"] == rs["episodes"], f"epoch {e} episodes"
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        ref_params, soc_params)


@pytest.mark.shm
def test_socket_depth1_staleness_counters(dataset_dir):
    """IMPALA depth-K staleness rides the socket transport unchanged:
    the steady-state batch is exactly one update stale
    (``params_age_updates`` — V-trace's lag), with the wire's own cost
    reported beside it (``segment_transit_s``), and the learner ring
    sized depth + 2 like the in-process ledger."""
    from ddls_tpu.train import make_epoch_loop

    loop = make_epoch_loop("impala", **_loop_kwargs(
        dataset_dir,
        algo_config={"lr": 1e-3, "train_batch_size": 8,
                     "num_workers": 2},
        pipeline_depth=1))
    try:
        assert len(loop.collector.ring.segments) == 3  # depth + 2
        metrics = [dict(loop.run()["learner"]) for _ in range(3)]
        loop.sync_metrics()
        assert metrics[0]["params_age_updates"] == 0.0  # warm inline batch
        assert metrics[-1]["params_age_updates"] == 1.0  # steady state
        for m in metrics:
            assert m["segment_transit_s"] >= 0.0
        stats = loop.collector.stats()
        assert stats["num_actor_hosts"] == 1
        assert stats["segments"] >= 3
        assert stats["per_host"]["h0"]["transit_max_s"] >= 0.0
    finally:
        loop.close()
