"""The OracleJCT heuristic running entirely in-kernel: candidate pricing,
the oracle's selection rule, decision, and event clock in one jitted
dispatch — replayed against the host OracleJCT driving the real env with
host candidate pricing. Every action, reward, and counter must match.

x64 subprocess (process-global flag), as the other episode-parity
tests."""
import os
import subprocess
import sys

DRIVER = r"""
import numpy as np
import jax
import jax.numpy as jnp

assert jax.config.read("jax_enable_x64")

import tempfile
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.envs.baselines import OracleJCT
from ddls_tpu.sim.jax_env import (build_episode_tables, build_job_bank,
                                  build_obs_tables,
                                  make_oracle_episode_fn)

d = tempfile.mkdtemp(prefix="jax_oracle_ep_")
generate_pipedream_txt_files(d, n_cnn=2, n_translation=1, seed=5)
env = RampJobPartitioningEnvironment(
    topology_config={"type": "ramp", "kwargs": {
        "num_communication_groups": 4,
        "num_racks_per_communication_group": 4,
        "num_servers_per_rack": 2, "num_channels": 1,
        "total_node_bandwidth": 1.6e12,
        "intra_gpu_propagation_latency": 50e-9,
        "worker_io_latency": 100e-9}},
    node_config={"type_1": {"num_nodes": 32, "workers_config": [
        {"num_workers": 1, "worker": "A100"}]}},
    jobs_config={"path_to_files": d,
        "job_interarrival_time_dist": {
            "_target_": "ddls_tpu.demands.distributions.Fixed",
            "val": 45.0},
        "max_acceptable_job_completion_time_frac_dist": {
            "_target_": "ddls_tpu.demands.distributions.Uniform",
            "min_val": 0.1, "max_val": 1.0, "decimals": 2},
        "replication_factor": 30, "job_sampling_mode": "remove_and_repeat",
        "num_training_steps": 20},
    max_partitions_per_op=8, min_op_run_time_quantum=0.01,
    reward_function="job_acceptance", max_simulation_run_time=4e3,
    pad_obs_kwargs={"max_nodes": 150, "max_edges": 512},
    candidate_pricing="native")

# ---- host episode: OracleJCT with host candidate pricing
obs = env.reset(seed=31)
actor = OracleJCT()
arrivals, actions, rewards = [], [], []
seen = set()
done = False
while not done:
    job = next(iter(env.cluster.job_queue.jobs.values()))
    ji = env.cluster.job_id_to_job_idx[job.job_id]
    if ji not in seen:
        seen.add(ji)
        arrivals.append({"model": job.details["model"],
                         "num_training_steps": job.num_training_steps,
                         "sla_frac": job.max_acceptable_jct_frac,
                         "time_arrived": job.details["time_arrived"]})
    action = int(actor.compute_action(obs, job_to_place=job, env=env))
    actions.append(action)
    obs, reward, done, info = env.step(action)
    rewards.append(reward)
n_arrived = env.cluster.num_jobs_arrived
for ji in range(len(arrivals), n_arrived):
    j = (env.cluster.jobs_running.get(ji)
         or env.cluster.jobs_completed.get(ji)
         or env.cluster.jobs_blocked.get(ji)
         or env.cluster.job_queue.jobs.get(env.cluster.job_idx_to_job_id[ji]))
    j = j.original_job if j.original_job is not j else j
    arrivals.append({"model": j.details["model"],
                     "num_training_steps": j.num_training_steps,
                     "sla_frac": j.max_acceptable_jct_frac,
                     "time_arrived": j.details["time_arrived"]})
host_ret = float(np.sum(rewards))
print(f"host oracle: {len(actions)} decisions, return {host_ret}")

# ---- in-kernel oracle on the same bank
et = build_episode_tables(env)
ot = build_obs_tables(env, et)
bank = {k: jnp.asarray(v) for k, v in build_job_bank(et, arrivals).items()}
fn = make_oracle_episode_fn(et, ot)
out = fn(bank)
a_tr, r_tr, acc_tr, cause_tr, jct_tr, t_tr, has_tr = (
    np.asarray(x) for x in out["trace"])
live = has_tr.nonzero()[0]
assert len(live) == len(actions), (len(live), len(actions))
mismatch = np.nonzero(a_tr[live] != np.array(actions))[0]
if len(mismatch):
    i = mismatch[0]
    print(f"FIRST MISMATCH at decision {i}: host {actions[i]} "
          f"kernel {a_tr[live][i]}")
assert len(mismatch) == 0, f"{len(mismatch)} action mismatches"
assert np.allclose(r_tr[live], np.array(rewards))
assert abs(float(out["ret"]) - host_ret) < 1e-9
print(f"ORACLE_EPISODE_PARITY_OK decisions={len(actions)} ret={host_ret}")
"""


def test_oracle_episode_parity_x64():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    res = subprocess.run([sys.executable, "-c", DRIVER], env=env,
                         capture_output=True, text=True, timeout=1800)
    assert res.returncode == 0, (res.stdout[-4000:], res.stderr[-4000:])
    assert "ORACLE_EPISODE_PARITY_OK" in res.stdout, res.stdout[-2000:]
