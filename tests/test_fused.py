"""Fused on-device collect→update epochs (rl/fused.py, ISSUE 12).

The load-bearing pin is the x64 full-epoch parity driver: the fused
program (ONE jitted lax.scan over U collect→update rounds) must
reproduce the sequential device-collector path — `DevicePPOCollector`
collects, `PPOLearner.train_step` updates — EXACTLY: post-training
params bit-equal, per-update metrics equal, episode records equal, on
the virtual 8-device mesh with lanes sharded over dp. Same subprocess
isolation as tests/test_jax_episode.py (JAX_ENABLE_X64 is
process-global).

In-process (f32): the steady-state fused epoch is transfer-free under
``jax.transfer_guard("disallow")``; DQN/ES reject loop_mode='fused'
loudly before any env construction; the autotuner units (candidate
ranking, size model, cache, probe fallback) and the chip lock run
device-free.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

ENV_CLS = "ddls_tpu.envs.partitioning_env.RampJobPartitioningEnvironment"

_TINY_MODEL = {"fcnet_hiddens": [16],
               "custom_model_config": {"out_features_msg": 4,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}}


def _env_config(dataset_dir, horizon=2e3):
    return dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2, "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 60.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.2, "max_val": 1.0, "decimals": 2},
            "replication_factor": 10,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 10},
        max_partitions_per_op=4, min_op_run_time_quantum=0.01,
        reward_function="job_acceptance", max_simulation_run_time=horizon,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})


def _make_fused_loop(dataset_dir, **kw):
    from ddls_tpu.train import make_epoch_loop

    defaults = dict(
        path_to_env_cls=ENV_CLS,
        env_config=_env_config(dataset_dir),
        model=_TINY_MODEL,
        algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 2, "num_workers": 8},
        num_envs=8, rollout_length=2, n_devices=8,
        use_parallel_envs=False, evaluation_interval=None, seed=0,
        loop_mode="fused", updates_per_epoch=2,
        fused_config={"lanes": 8, "segment_len": 2})
    defaults.update(kw)
    return make_epoch_loop("ppo", **defaults)


# ===================================================== x64 parity driver
# A fused loop of E epochs x U updates must equal U*E sequential
# device-collector epochs: params EXACTLY, per-update metrics (the
# LazyMetrics mean over each fused epoch equals the f64 mean of its
# sequential epochs' metrics), and episode records field-for-field —
# with episodes actually completing (the 6e2 horizon ends one per lane).
PARITY_DRIVER = r"""
import tempfile
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
assert jax.config.read("jax_enable_x64")
assert len(jax.devices()) == 8
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
from ddls_tpu.train import make_epoch_loop

import test_fused as tf

d = tempfile.mkdtemp(prefix="fused_parity_")
generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=9)
algo = {"train_batch_size": 16, "sgd_minibatch_size": 8,
        "num_sgd_iter": 2, "num_workers": 8, "device_collector": True}
kw = dict(path_to_env_cls=tf.ENV_CLS,
          env_config=tf._env_config(d, horizon=6e2),
          model=tf._TINY_MODEL,
          num_envs=8, rollout_length=2, n_devices=8,
          use_parallel_envs=False, evaluation_interval=None, seed=0)

U, E = 2, 3
seq = make_epoch_loop("ppo", algo_config=dict(algo),
                      loop_mode="sequential", **kw)
seq_metrics, seq_episodes = [], []
for _ in range(U * E):
    r = seq.run()
    seq_metrics.append(dict(r["learner"]))
    seq_episodes.extend(r["episodes"])
seq_params = jax.device_get(seq.state.params)
seq.close()

fus = make_epoch_loop("ppo", algo_config=dict(algo), loop_mode="fused",
                      updates_per_epoch=U, metrics_sync_interval=1,
                      fused_config={"lanes": 8, "segment_len": 2}, **kw)
fus_means, fus_episodes = [], []
for _ in range(E):
    r = fus.run()
    assert r["learner"]["num_updates"] == U
    fus_means.append(dict(r["learner"]))
    fus_episodes.extend(r["episodes"])
fus_params = jax.device_get(fus.state.params)
fus.close()

# post-training params: EXACT (bitwise array equality)
jax.tree_util.tree_map(
    lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)),
    seq_params, fus_params)

# LazyMetrics values: each fused epoch's mean equals the f64 mean of
# its U sequential updates' (already-float) metrics
for e in range(E):
    want = {k: float(np.mean([seq_metrics[e * U + u][k]
                              for u in range(U)]))
            for k in seq_metrics[0]}
    got = {k: v for k, v in fus_means[e].items() if k in want}
    assert got == want, (e, got, want)

# episode records: same records, same order, same fields — and
# episodes genuinely completed (the horizon guarantees >= 1 per lane)
assert len(seq_episodes) >= 8, len(seq_episodes)
assert seq_episodes == fus_episodes
print(f"FUSED_PARITY_OK episodes={len(fus_episodes)}")
"""


def test_fused_full_epoch_parity_x64():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO, os.path.dirname(os.path.abspath(__file__))])
    res = subprocess.run([sys.executable, "-c", PARITY_DRIVER], env=env,
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, (res.stdout[-4000:], res.stderr[-4000:])
    assert "FUSED_PARITY_OK" in res.stdout, res.stdout[-2000:]


# =================================================== steady-state guards
@pytest.fixture(scope="module")
def fused_dataset(tmp_path_factory):
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    d = str(tmp_path_factory.mktemp("fused_jobs"))
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=9)
    return d


def test_fused_epoch_transfer_free_then_harvests(fused_dataset):
    """ISSUE 12 acceptance, one loop/compile for both halves: with the
    drain boundary at metrics_sync_interval=3, epoch 2 is a
    steady-state fused epoch performing NO implicit device<->host
    transfer (params, opt state, rng keys, metrics, and episode
    counters all stay on device), and epoch 3 hits the drain boundary —
    params moved, metrics are epoch-mean-shaped, and episode records
    surface with the host record schema."""
    import jax

    loop = _make_fused_loop(
        fused_dataset, metrics_sync_interval=3,
        env_config=_env_config(fused_dataset, horizon=6e2))
    try:
        before = jax.device_get(loop.state.params)
        r1 = loop.run()  # warm: compile + first-use constant transfers
        assert r1["episodes"] == []  # epoch 1: no drain boundary yet
        with jax.transfer_guard("disallow"):
            r2 = loop.run()
        assert r2["episodes"] == []  # still pending on device
        r3 = loop.run()  # epoch 3: the drain boundary
        for r in (r1, r2, r3):
            assert np.isfinite(r["learner"]["total_loss"])
            assert r["learner"]["num_updates"] == 2
            assert r["env_steps_this_iter"] == 2 * 2 * 8  # U * T * B
        assert loop.autotune_result.source == "explicit"
        assert (loop.autotune_result.lanes,
                loop.autotune_result.segment_len) == (8, 2)
        moved = jax.tree_util.tree_map(
            lambda a, b: float(np.abs(np.asarray(a)
                                      - np.asarray(b)).max()),
            before, jax.device_get(loop.state.params))
        assert max(jax.tree_util.tree_leaves(moved)) > 0
        episodes = r3["episodes"]
        assert episodes, "horizon 6e2 must complete episodes by epoch 3"
        for e in episodes:
            assert set(e) >= {"env_index", "episode_return",
                              "episode_length", "num_jobs_arrived",
                              "num_jobs_completed", "num_jobs_blocked",
                              "acceptance_rate", "blocking_rate"}
            assert (e["num_jobs_arrived"]
                    >= e["num_jobs_completed"] + e["num_jobs_blocked"])
    finally:
        loop.close()


# ====================================================== loud rejections
@pytest.mark.parametrize("algo", ["apex_dqn", "es"])
def test_fused_rejected_loudly_without_contract(algo):
    """DQN (host replay insertion) and ES (host population fitness)
    cannot run a fused in-kernel epoch; the rejection fires before any
    env/model construction (env_config={} would explode otherwise)."""
    from ddls_tpu.train import make_epoch_loop

    with pytest.raises(ValueError, match="fused"):
        make_epoch_loop(algo, path_to_env_cls=ENV_CLS, env_config={},
                        loop_mode="fused")


def test_fused_rejects_multiprocess_and_bad_mode():
    from ddls_tpu.train import make_epoch_loop

    with pytest.raises(ValueError, match="loop_mode"):
        make_epoch_loop("ppo", path_to_env_cls=ENV_CLS, env_config={},
                        loop_mode="bogus")


# ====================================================== autotuner units
def test_candidate_configs_rank_and_divide():
    from ddls_tpu.rl.fused import candidate_configs

    # dp=1: every divisor of the batch up to max_lanes, fewest first
    assert candidate_configs(64, 1, 8) == [(1, 64), (2, 32), (4, 16),
                                           (8, 8)]
    # dp=4: lanes must divide over the dp axis
    assert candidate_configs(64, 4, 16) == [(4, 16), (8, 8), (16, 4)]
    # lanes never exceed the requested num_envs
    assert candidate_configs(64, 4, 4) == [(4, 16)]


def test_estimate_monotonic_in_lanes_flat_in_segment():
    from ddls_tpu.rl.fused import estimate_program_bytes

    cells = 10_000
    assert (estimate_program_bytes(1, 64, cells)
            < estimate_program_bytes(8, 8, cells)
            < estimate_program_bytes(64, 1, cells))
    # a lax.scan's program does not grow with its length
    assert (estimate_program_bytes(4, 16, cells)
            == estimate_program_bytes(4, 1024, cells))
    # captured table constants count
    assert (estimate_program_bytes(4, 16, cells)
            < estimate_program_bytes(4, 16, cells * 10))


def test_autotune_cache_roundtrip(tmp_path):
    from ddls_tpu.rl.fused import (load_cached_config,
                                   store_cached_config)

    probe_dir = str(tmp_path / "probe")
    assert load_cached_config(probe_dir, "k") is None
    store_cached_config(probe_dir, "k", {"lanes": 2, "segment_len": 8,
                                         "estimated_bytes": 123,
                                         "actual_bytes": 456})
    got = load_cached_config(probe_dir, "k")
    assert got == {"lanes": 2, "segment_len": 8,
                   "estimated_bytes": 123, "actual_bytes": 456}
    # corrupt cache reads as a miss, never an error
    with open(os.path.join(probe_dir, "fused_autotune.json"), "w") as f:
        f.write("not json")
    assert load_cached_config(probe_dir, "k") is None


class _EtStub:
    def __init__(self):
        from ddls_tpu.sim.jax_env import ConfigPads

        self.pads = ConfigPads(n_ops=4, n_deps=4, n_fwd=2, n_parents=1,
                               max_split=2, n_groups=1, group_edges=1,
                               n_sync=1, n_o2o=1)
        self.n_srv = 8
        self.n_chan = 1
        self.types = ["a"]
        self.degrees = [1, 2]
        self.max_action = 2
        self.tables = {"t": np.zeros((4, 4))}


class _FailingDriver:
    def lower(self, state):
        raise RuntimeError("remote_compile rejected the program")


def test_autotune_fallback_when_nothing_compiles(tmp_path):
    """Every candidate failing to compile returns (None, result) so the
    caller can fall back to loop_mode='pipelined' loudly; every probed
    config and its error ride the result."""
    from ddls_tpu.rl.fused import autotune_fused

    driver, result = autotune_fused(
        lambda lanes, seg: _FailingDriver(), state=None, et=_EtStub(),
        total_steps=8, updates_per_epoch=1, dp=1, max_lanes=2,
        probe_dir=str(tmp_path), probe_timeout_s=5.0)
    assert driver is None
    assert result.source == "failed"
    assert [(l, s) for l, s, _, _ in result.probed] == [(1, 8), (2, 4)]
    assert all(not ok for _, _, ok, _ in result.probed)
    assert all("remote_compile" in err for _, _, _, err in result.probed)
    # nothing cached on failure
    assert not os.path.exists(
        os.path.join(str(tmp_path), "fused_autotune.json"))


def test_autotune_explicit_config_validation(tmp_path):
    from ddls_tpu.rl.fused import autotune_fused

    with pytest.raises(ValueError, match="both lanes and segment_len"):
        autotune_fused(lambda l, s: None, None, _EtStub(), 8, 1, 1, 2,
                       probe_dir=str(tmp_path), lanes=2)
    with pytest.raises(ValueError, match="must equal the per-update"):
        autotune_fused(lambda l, s: None, None, _EtStub(), 8, 1, 1, 2,
                       probe_dir=str(tmp_path), lanes=2, segment_len=2)


def test_autotune_cache_hit_skips_probing(tmp_path):
    """The fused-vs-fallback gate is a pure function of the cached
    config (multihost rule): a cache hit builds the cached config and
    never probe-compiles."""
    from ddls_tpu.rl.fused import (autotune_fused, store_cached_config,
                                   workload_signature)

    et = _EtStub()
    key = workload_signature(et, 8, 1, 1, max_lanes=8, extra="x")
    store_cached_config(str(tmp_path), key,
                        {"lanes": 2, "segment_len": 4,
                         "estimated_bytes": 7, "actual_bytes": 9})
    built = []
    driver, result = autotune_fused(
        lambda lanes, seg: built.append((lanes, seg)) or "driver",
        state=None, et=et, total_steps=8, updates_per_epoch=1, dp=1,
        max_lanes=8, probe_dir=str(tmp_path), signature_extra="x")
    assert driver == "driver"
    assert built == [(2, 4)]
    assert result.source == "cache"
    assert (result.lanes, result.segment_len) == (2, 4)
    assert result.actual_bytes == 9 and result.probed == []


def test_workload_signature_keys_everything(tmp_path):
    from ddls_tpu.rl.fused import workload_signature

    et = _EtStub()
    base = workload_signature(et, 8, 1, 1)
    assert workload_signature(et, 8, 1, 1) == base
    assert workload_signature(et, 16, 1, 1) != base  # batch
    assert workload_signature(et, 8, 2, 1) != base   # updates/epoch
    assert workload_signature(et, 8, 1, 2) != base   # mesh width
    # the lane cap keys too: a cached config can never carry more
    # lanes than the current run's num_envs allows
    assert workload_signature(et, 8, 1, 1, max_lanes=4) != base
    assert workload_signature(et, 8, 1, 1, extra="m") != base


# ========================================================== chip lock
def test_chip_lock_acquire_release(tmp_path, monkeypatch):
    from ddls_tpu.rl.fused import LOCK_OWNER_ENV, chip_lock

    monkeypatch.delenv(LOCK_OWNER_ENV, raising=False)
    probe_dir = str(tmp_path / "probe")
    lock_path = os.path.join(probe_dir, "tpu.lock")
    with chip_lock(probe_dir) as lock:
        assert lock.acquired
        assert os.path.exists(lock_path)
        assert os.environ.get(LOCK_OWNER_ENV) == "1"
        with open(lock_path) as f:
            assert int(f.read().strip()) == os.getpid()
    assert not os.path.exists(lock_path)
    assert LOCK_OWNER_ENV not in os.environ


def test_chip_lock_never_steals_foreign_lock(tmp_path, monkeypatch):
    from ddls_tpu.rl.fused import LOCK_OWNER_ENV, chip_lock

    monkeypatch.delenv(LOCK_OWNER_ENV, raising=False)
    probe_dir = str(tmp_path / "probe")
    os.makedirs(probe_dir)
    lock_path = os.path.join(probe_dir, "tpu.lock")
    live = os.getppid() or 1  # a provably LIVE foreign owner
    with open(lock_path, "w") as f:
        f.write(f"{live}\n")
    with chip_lock(probe_dir) as lock:
        assert not lock.acquired
        assert LOCK_OWNER_ENV not in os.environ  # our probes defer
    assert os.path.exists(lock_path)  # never removed a live foreign lock
    with open(lock_path) as f:
        assert f.read() == f"{live}\n"


def test_chip_lock_reclaims_stale_dead_pid_lock(tmp_path, monkeypatch):
    """Crash fallback: a lock whose recorded owner pid is provably dead
    (a SIGKILLed run cannot unlink its own file) is reclaimed instead of
    diverting every later run's probes to CPU forever; bench's probe
    cache ignores the same stale locks."""
    import bench

    from ddls_tpu.rl.fused import LOCK_OWNER_ENV, chip_lock, lock_is_stale

    monkeypatch.delenv(LOCK_OWNER_ENV, raising=False)
    probe_dir = str(tmp_path / "probe")
    os.makedirs(probe_dir)
    lock_path = os.path.join(probe_dir, "tpu.lock")
    # find a pid that provably does not exist
    dead = 2 ** 22 - 3
    while os.path.exists(f"/proc/{dead}"):
        dead -= 1
    with open(lock_path, "w") as f:
        f.write(f"{dead}\n")
    assert lock_is_stale(lock_path)
    err, reason = bench.consult_probe_state(probe_dir=probe_dir)
    assert reason != "tpu_lock_held"  # stale lock never diverts probes
    with chip_lock(probe_dir) as lock:
        assert lock.acquired  # reclaimed
        with open(lock_path) as f:
            assert int(f.read().strip()) == os.getpid()
    assert not os.path.exists(lock_path)
    # an empty/pid-less lock (external wrapper) stays respected
    with open(lock_path, "w"):
        pass
    assert not lock_is_stale(lock_path)
    err, reason = bench.consult_probe_state(probe_dir=probe_dir)
    assert reason == "tpu_lock_held"


def test_chip_lock_delegated_ownership_under_wrapper(tmp_path,
                                                     monkeypatch):
    """A wrapper above this process that holds the lock and exports
    DDLS_TPU_LOCK_OWNER=1 (the documented convention) delegates chip
    ownership: entry does no file ops, `owned` is True (fused keeps
    running instead of downgrading to pipelined), and exit leaves the
    wrapper's lock alone."""
    from ddls_tpu.rl.fused import LOCK_OWNER_ENV, chip_lock

    probe_dir = str(tmp_path / "probe")
    os.makedirs(probe_dir)
    lock_path = os.path.join(probe_dir, "tpu.lock")
    with open(lock_path, "w") as f:
        f.write(f"{os.getppid() or 1}\n")  # the wrapper's live lock
    monkeypatch.setenv(LOCK_OWNER_ENV, "1")
    with chip_lock(probe_dir) as lock:
        assert lock.delegated and not lock.acquired
        assert lock.owned
    assert os.path.exists(lock_path)  # the wrapper's lock untouched
    assert os.environ.get(LOCK_OWNER_ENV) == "1"


def test_autotune_cache_rejects_tampered_entries(tmp_path):
    """A cached config must satisfy every constraint the prober
    enforces — lane cap, exact batch factorisation, dp divisibility —
    or it is re-probed, never obeyed."""
    from ddls_tpu.rl.fused import (autotune_fused, store_cached_config,
                                   workload_signature)

    et = _EtStub()
    key = workload_signature(et, 8, 1, 1, max_lanes=8, extra="x")
    # segment_len tampered: lanes * segment_len != total_steps
    store_cached_config(str(tmp_path), key,
                        {"lanes": 2, "segment_len": 8,
                         "estimated_bytes": 7, "actual_bytes": 9})
    driver, result = autotune_fused(
        lambda lanes, seg: _FailingDriver(), state=None, et=et,
        total_steps=8, updates_per_epoch=1, dp=1, max_lanes=8,
        probe_dir=str(tmp_path), probe_timeout_s=5.0,
        signature_extra="x")
    # the tampered entry was ignored and probing ran (and failed here)
    assert result.source == "failed"
    assert len(result.probed) >= 1


def test_bench_lock_owner_env_matches_probe_cache():
    # the handshake bench.py's consult_probe_state keys on — a rename on
    # either side would silently divert an owner's probes to CPU
    import bench

    from ddls_tpu.rl.fused import LOCK_OWNER_ENV

    assert bench.PROBE_LOCK_OWNER_ENV == LOCK_OWNER_ENV


# ================================================= LazyMetrics (fused)
def test_lazy_metrics_stacked_dict_mean():
    """The fused epoch shape: one dict of [U]-stacked device arrays,
    reduced as the f64 mean per key (bit-matching the sequential loop's
    python-float mean over its per-update dicts)."""
    import jax.numpy as jnp

    from ddls_tpu.train.metrics import LazyMetrics

    vals = np.asarray([0.1, 0.2, 0.7], np.float32)
    lm = LazyMetrics({"loss": jnp.asarray(vals)}, reduce="mean",
                     extras={"num_updates": 3})
    assert lm.pending
    assert set(lm) == {"loss", "num_updates"}
    want = float(np.mean([float(v) for v in vals]))
    assert lm["loss"] == want
    assert lm["num_updates"] == 3.0
    assert not lm.pending
