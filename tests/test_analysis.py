"""L9 analysis layer: loaders, frames, summary tables, plots, report CLI."""
import numpy as np
import pytest

from ddls_tpu.analysis import (blocked_cause_table, completed_jobs_frame,
                               epochs_frame, load_cluster_save, load_run,
                               load_runs, render_op_graph,
                               save_comparison_report, steps_frame,
                               summary_table)
from ddls_tpu.train.logger import Logger


def _heuristic_results(name, blocking_rate, jcts):
    n = len(jcts)
    return {
        "heuristic_eval": {
            "episode_return": float(100 - blocking_rate * 100),
            "episode_length": n,
            "episode_stats": {
                "num_jobs_arrived": n + 2,
                "num_jobs_completed": n,
                "num_jobs_blocked": 2,
                "blocking_rate": blocking_rate,
                "acceptance_rate": 1.0 - blocking_rate,
                "mean_cluster_throughput": 12.5,
                "job_completion_time": list(jcts),
                "job_completion_time_speedup": [2.0] * n,
                "jobs_completed_num_nodes": [4] * n,
                "jobs_blocked_num_nodes": [6, 8],
                "jobs_blocked_cause_of_unsuccessful_handling": [
                    "op_placement",
                    "max_acceptable_job_completion_time_exceeded"],
            },
            "steps_log": {
                "step_time": [1.0] * 5,
                "mean_cluster_throughput": [10.0] * 5,
            },
        }
    }


def _training_results(n_epochs=4):
    # exactly the shape Launcher.run logs: epoch dicts whose "evaluation"
    # is the flat _episode_summary scalar dict (loops.py:120-143)
    return {
        "epochs": [
            {"episode_reward_mean": float(i),
             "evaluation": {"episode_reward_mean": float(i) + 0.5,
                            "episode_len_mean": 10.0,
                            "custom_metrics/blocking_rate_mean": 0.1,
                            "custom_metrics/acceptance_rate_mean": 0.9,
                            "custom_metrics/mean_job_completion_time_mean":
                                5.5},
             "epoch_time": 1.0}
            for i in range(n_epochs)
        ]
    }


def _rl_eval_results():
    # the shape scripts/test_from_config.py saves under "rl_eval"
    return {
        "rl_eval": [
            {"episode": {"episode_return": 12.0, "episode_length": 9},
             "episode_stats": {
                 "blocking_rate": 0.25,
                 "acceptance_rate": 0.75,
                 "job_completion_time": [2.0, 4.0],
                 "job_completion_time_speedup": [1.5, 2.5],
                 "jobs_completed_num_nodes": [4, 6]},
             "steps_log": {"step_time": [1.0, 2.0]}},
        ]
    }


def _save_run(tmp_path, name, results, sqlite=False):
    d = tmp_path / name
    logger = Logger(path_to_save=str(d), use_sqlite_database=sqlite)
    logger.log(results)
    logger.save(blocking=True)
    return str(d)


def test_load_and_summary(tmp_path):
    h1 = _save_run(tmp_path, "acceptable_jct",
                   _heuristic_results("h1", 0.05, [10.0, 20.0, 30.0]))
    h2 = _save_run(tmp_path, "sipml",
                   _heuristic_results("h2", 0.20, [40.0, 50.0]),
                   sqlite=True)
    t1 = _save_run(tmp_path, "ppo", _training_results())

    runs = load_runs([h1, h2, t1])
    assert [r.kind for r in runs] == ["heuristic", "heuristic", "training"]

    table = summary_table(runs)
    assert list(table["run"]) == ["acceptable_jct", "sipml", "ppo"]
    row = table[table["run"] == "acceptable_jct"].iloc[0]
    assert row["blocking_rate"] == pytest.approx(0.05)
    assert row["mean_job_completion_time"] == pytest.approx(20.0)
    # training run: final eval reward; episode stats re-mapped from the
    # scalar custom_metrics the pipeline actually logs
    row = table[table["run"] == "ppo"].iloc[0]
    assert row["episode_return"] == pytest.approx(3.5)
    assert row["blocking_rate"] == pytest.approx(0.1)
    assert row["mean_job_completion_time"] == pytest.approx(5.5)


def test_rl_eval_run(tmp_path):
    path = _save_run(tmp_path, "rl_eval_run", _rl_eval_results())
    run = load_run(path)
    assert run.kind == "rl_eval"
    table = summary_table([run])
    row = table.iloc[0]
    assert row["episode_return"] == pytest.approx(12.0)
    assert row["blocking_rate"] == pytest.approx(0.25)
    assert row["mean_job_completion_time"] == pytest.approx(3.0)
    jobs = completed_jobs_frame(run)
    assert jobs["job_completion_time"].tolist() == [2.0, 4.0]
    steps = steps_frame(run)
    assert steps["step_time"].tolist() == [1.0, 2.0]


def test_frames(tmp_path):
    path = _save_run(tmp_path, "h",
                     _heuristic_results("h", 0.1, [1.0, 2.0, 4.0]))
    run = load_run(path)
    jobs = completed_jobs_frame(run)
    assert len(jobs) == 3
    assert jobs["job_completion_time"].tolist() == [1.0, 2.0, 4.0]
    assert jobs["num_nodes"].tolist() == [4, 4, 4]

    steps = steps_frame(run)
    assert len(steps) == 5
    assert "mean_cluster_throughput" in steps.columns

    causes = blocked_cause_table([run])
    assert causes.iloc[0]["op_placement"] == 1

    t = load_run(_save_run(tmp_path, "t", _training_results()))
    frame = epochs_frame(t)
    assert len(frame) == 4
    assert frame["evaluation/episode_reward_mean"].tolist() == (
        [0.5, 1.5, 2.5, 3.5])


def test_comparison_report_and_cli(tmp_path):
    paths = [
        _save_run(tmp_path, "a", _heuristic_results("a", 0.1, [5.0, 7.0])),
        _save_run(tmp_path, "b", _heuristic_results("b", 0.3, [9.0])),
        _save_run(tmp_path, "t", _training_results()),
    ]
    runs = load_runs(paths, names=["A", "B", "PPO"])
    out = tmp_path / "report"
    artifacts = save_comparison_report(runs, out)
    for key in ("summary", "comparison", "jct_cdf", "learning_curves",
                "blocked_causes_png"):
        assert key in artifacts
    import pathlib
    for path in artifacts.values():
        assert pathlib.Path(path).exists()

    # CLI end to end
    import importlib
    mod = importlib.import_module("scripts.analyze_results")
    rc = mod.main(paths + ["--names", "A", "B", "PPO",
                           "--out", str(tmp_path / "cli_out")])
    assert rc == 0
    assert (tmp_path / "cli_out" / "summary.csv").exists()


def test_cluster_save_loader(tmp_path):
    # reuse the cluster sqlite save from the stats tests' scenario shape
    from tests.test_stats_parity import (_heuristic_action, _jobs_config,
                                         _make_cluster, _single_op_profile)
    cluster = _make_cluster(path_to_save=str(tmp_path / "sim"))
    cluster.reset(_jobs_config(_single_op_profile(tmp_path)),
                  max_simulation_run_time=None, seed=0)
    cluster.step(_heuristic_action(cluster))
    cluster._save_thread.join()
    save_dir = cluster.path_to_save
    logs = load_cluster_save(save_dir)
    assert logs["episode_stats"]["num_jobs_completed"] == 1
    frame = steps_frame(logs)
    assert len(frame) == 1


def test_render_op_graph(tmp_path):
    from ddls_tpu.graphs.readers import graph_from_pipedream_txt
    profile = tmp_path / "g.txt"
    profile.write_text(
        "node1 -- A(id=1) -- forward_compute_time=1.0, "
        "backward_compute_time=2.0, activation_size=10.0, "
        "parameter_size=1.0\n"
        "node2 -- B(id=2) -- forward_compute_time=2.0, "
        "backward_compute_time=4.0, activation_size=20.0, "
        "parameter_size=2.0\n"
        "node1 -- node2\n")
    g = graph_from_pipedream_txt(str(profile))
    out = tmp_path / "graph.png"
    render_op_graph(g, path=out)
    assert out.exists() and out.stat().st_size > 0
