"""Multi-host mesh path: 2 CPU processes x 2 virtual devices each join one
global mesh; shard_batch assembles per-process rollout shards and the jitted
update all-reduces gradients across hosts (SURVEY.md §5.8 TPU-native
equivalent of the reference's Ray worker topology)."""
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}  # keep the axon hook off jax init
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def test_two_process_global_mesh():
    port = _free_port()
    coordinator = f"localhost:{port}"
    env = _worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coordinator, "2", str(i), REPO],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(2)
    ]
    outputs = []
    for proc in procs:
        try:
            out, _ = proc.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("distributed workers timed out")
        outputs.append(out)
    for i, (proc, out) in enumerate(zip(procs, outputs)):
        assert proc.returncode == 0, f"worker {i} failed:\n{out}"
        assert "global_devices=4" in out, out
        assert f"UPDATE process={i} w=1.300000" in out, out
