"""Multi-host mesh path: 2 CPU processes x 2 virtual devices each join one
global mesh; shard_batch assembles per-process rollout shards and the jitted
update all-reduces gradients across hosts (SURVEY.md §5.8 TPU-native
equivalent of the reference's Ray worker topology)."""
import glob
import os
import socket
import subprocess
import sys
from typing import List

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}  # keep the axon hook off jax init
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


#: output fingerprints of the coordinator/gloo CONNECT race (the
#: documented position-44 tier-1 flake, ISSUE 13): the port picked by
#: ``_free_port`` can be re-bound by another process between selection
#: and the coordinator's bind (TOCTOU), and gloo's connectFullMesh can
#: time out when one worker's jax init outruns the other's. Both are
#: environment races, not code failures — retried once with a FRESH
#: port; anything else still fails immediately.
_CONNECT_RACE_PATTERNS = (
    "Address already in use",
    "Connection refused",
    "Connection reset",
    "connectFullMesh",
    "DEADLINE_EXCEEDED",
    "Timed out waiting",
    "failed to connect",
)


def _looks_like_connect_race(outputs: List[str]) -> bool:
    return any(p in out for out in outputs if out
               for p in _CONNECT_RACE_PATTERNS)


def _run_lockstep(make_argvs, timeout: float, attempts: int = 2):
    """Launch one process per argv in lockstep; returns (procs, outputs).

    ``make_argvs`` is a zero-arg factory returning the argv list — it is
    re-invoked on retry so each attempt picks a FRESH coordinator port
    (the deflake: a recycled port is exactly the race being retried).
    Retries are bounded and only fire for the connect race (a timeout,
    or a nonzero exit whose output carries a connect-race fingerprint);
    deterministic failures surface on the first attempt. On timeout
    every child is killed AND reaped before retrying/failing, so no
    zombies or stale coordinator sockets leak into later tests."""
    env = _worker_env()
    for attempt in range(attempts):
        last = attempt == attempts - 1
        procs = [subprocess.Popen(argv, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True,
                                  env=env)
                 for argv in make_argvs()]
        outputs = []
        timed_out = False
        for proc in procs:
            try:
                out, _ = proc.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                for p in procs:
                    p.kill()
                for p in procs:
                    p.wait()
                timed_out = True
                break
            outputs.append(out)
        if timed_out:
            if last:
                pytest.fail("distributed processes timed out "
                            f"({attempts} attempts, fresh port each)")
            continue
        failed = any(p.returncode != 0 for p in procs)
        if failed and not last and _looks_like_connect_race(outputs):
            continue
        return procs, outputs
    raise AssertionError("unreachable")  # pragma: no cover


def test_two_process_global_mesh():
    def argvs():
        coordinator = f"localhost:{_free_port()}"
        return [[sys.executable, WORKER, coordinator, "2", str(i), REPO]
                for i in range(2)]

    procs, outputs = _run_lockstep(argvs, timeout=180)
    for i, (proc, out) in enumerate(zip(procs, outputs)):
        assert proc.returncode == 0, f"worker {i} failed:\n{out}"
        assert "global_devices=4" in out, out
        assert f"UPDATE process={i} w=1.300000" in out, out


def test_two_process_training_cli(tmp_path):
    """The full multi-host path through the real CLI: 2 CPU processes x 2
    virtual devices train PPO for 1 epoch over one global mesh; only the
    primary writes artifacts."""
    script = os.path.join(REPO, "scripts", "train_from_config.py")

    def argvs():
        overrides = [
            "launcher.num_epochs=1", "epoch_loop.num_envs=2",
            "epoch_loop.rollout_length=4",
            "epoch_loop.use_parallel_envs=false",
            "eval_config.evaluation_interval=null",
            "env_config.jobs_config.replication_factor=2",
            "env_config.jobs_config.job_sampling_mode=remove",
            "env_config.jobs_config.synthetic.n_cnn=1",
            "env_config.jobs_config.synthetic.n_translation=1",
            "env_config.pad_obs_kwargs.max_nodes=32",
            "env_config.pad_obs_kwargs.max_edges=64",
            "algo.algo_config.num_sgd_iter=2",
            f"experiment.path_to_save={tmp_path}",
            "distributed.enabled=true",
            f"distributed.coordinator_address=localhost:{_free_port()}",
            "distributed.num_processes=2", "distributed.platform=cpu",
        ]
        return [[sys.executable, script] + overrides
                + [f"distributed.process_id={i}"] for i in range(2)]

    procs, outputs = _run_lockstep(argvs, timeout=420)
    for i, (proc, out) in enumerate(zip(procs, outputs)):
        assert proc.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
        assert f"process {i}/2" in out
        assert "Run complete: 1 epochs" in out
    # primary-only artifacts
    assert "Experiment save dir" in outputs[0]
    assert "Experiment save dir" not in outputs[1]
    assert glob.glob(str(tmp_path / "**" / "results.*"), recursive=True)


def test_four_process_real_epoch_bit_identical_params():
    """VERDICT r3 next #7: one real collect+update epoch (x2) of the
    actual partitioning env across 4 gloo processes in a blocking-heavy
    regime. Each process's envs diverge (different blocking patterns —
    the deterministic-gate hazard class), yet the replicated parameters
    must end BIT-identical on every process."""
    worker = os.path.join(REPO, "tests", "_distributed_epoch_worker.py")

    def argvs():
        coordinator = f"localhost:{_free_port()}"
        return [[sys.executable, worker, coordinator, "4", str(i), REPO]
                for i in range(4)]

    procs, outputs = _run_lockstep(argvs, timeout=600)
    digests, blocked = [], []
    for i, (proc, out) in enumerate(zip(procs, outputs)):
        assert proc.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith(f"PARAMS process={i} "):
                digests.append(line.split("digest=")[1].strip())
            if line.startswith(f"DIVERGE process={i} "):
                # strip the process id so the set compares only histories
                blocked.append(line.split(" ", 2)[2])
    assert len(digests) == 4, outputs
    assert len(set(digests)) == 1, f"params diverged across hosts: {digests}"
    # the hazard actually exercised: processes saw different env histories
    assert len(set(blocked)) >= 2, f"env histories identical: {blocked}"


def test_two_process_device_collector_bit_identical_params():
    """VERDICT r4 item 6: multi-host x device_collector. Each of 2 gloo
    processes collects fixed-length segments in the jitted env on its
    OWN per-process job banks (banks must differ — asserted), runs the
    sharded update over the global mesh, and the replicated parameters
    must end BIT-identical (in-kernel resets/done gates are the new
    deterministic-gate hazard class)."""
    worker = os.path.join(REPO, "tests", "_distributed_device_worker.py")

    def argvs():
        coordinator = f"localhost:{_free_port()}"
        return [[sys.executable, worker, coordinator, "2", str(i), REPO]
                for i in range(2)]

    procs, outputs = _run_lockstep(argvs, timeout=600)
    params, banks = [], []
    for i, (proc, out) in enumerate(zip(procs, outputs)):
        assert proc.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith(f"PARAMS process={i} "):
                params.append(line.split("digest=")[1].strip())
            if line.startswith(f"BANKS process={i} "):
                banks.append(line.split("digest=")[1].strip())
    assert len(params) == 2, outputs
    assert len(set(params)) == 1, f"params diverged across hosts: {params}"
    assert len(set(banks)) == 2, "per-process banks were identical"
