"""L1 tests: readers, mirroring semantics, OpGraph invariants, Job readiness."""
import numpy as np
import pytest

from ddls_tpu.demands.job import Job
from ddls_tpu.graphs.op_graph import OpGraph
from ddls_tpu.graphs.readers import (backward_op_id, graph_from_pipedream_txt)
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files


def _write_chain_profile(tmp_path, n=3):
    """Hand-written 3-op chain: ids 1..3, known costs."""
    lines = []
    for i in range(1, n + 1):
        lines.append(
            f"node{i} -- Op(id={i}) -- forward_compute_time={float(i):.3f}, "
            f"backward_compute_time={2 * float(i):.3f}, "
            f"activation_size={100.0 * i:.1f}, parameter_size={10.0 * i:.1f}")
    for i in range(1, n):
        lines.append(f"node{i} -- node{i + 1}")
    path = tmp_path / "chain.txt"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_pipedream_mirroring_semantics(tmp_path):
    path = _write_chain_profile(tmp_path, n=3)
    g = graph_from_pipedream_txt(path)

    # 3 fwd + 3 bwd ops; edges: 2 fwd + 2 bwd + 1 join
    assert g.n_ops == 6
    assert g.n_deps == 5

    # backward id arithmetic: bwd(i) = 2n - (i - 1)
    assert backward_op_id(1, 3) == "6"
    assert backward_op_id(3, 3) == "4"
    assert g.counterpart("1") == "6" and g.counterpart("6") == "1"

    # compute costs: fwd = i, bwd = 2i; memory = activation + parameter
    assert g.compute_cost("2") == pytest.approx(2.0)
    assert g.compute_cost(backward_op_id(2, 3)) == pytest.approx(4.0)
    assert g.memory_cost("2") == pytest.approx(220.0)

    # join edge: last fwd (3) -> first bwd (4); size = activation of producer
    assert g.has_edge("3", "4")
    assert g.edge_size("3", "4") == pytest.approx(300.0)
    # backward edges reversed: fwd edge (1,2) -> bwd edge (bwd(2), bwd(1)) = (5,6)
    assert g.has_edge("5", "6")
    assert g.edge_size("5", "6") == pytest.approx(200.0)


def test_depths_and_topo(tmp_path):
    path = _write_chain_profile(tmp_path, n=3)
    g = graph_from_pipedream_txt(path)
    arrays = g.finalize()
    depth = {op: arrays["depth"][arrays["op_index"][op]] for op in g.op_ids}
    assert depth["1"] == 1 and depth["2"] == 2 and depth["3"] == 3
    assert depth["4"] == 4 and depth["5"] == 5 and depth["6"] == 6
    order = g.topo_order()
    assert order.index("1") < order.index("2") < order.index("3")
    assert order.index("3") < order.index("4") < order.index("6")


def test_parents_exclude_mutual_edges():
    g = OpGraph()
    for op in ("a", "b", "c"):
        g.add_op(op, compute=1.0, memory=1.0)
    g.add_edge("a", "b", 1.0)
    g.add_edge("b", "c", 1.0)
    g.add_edge("c", "b", 1.0)  # mutual pair (b <-> c)
    assert g.parents("b") == ["a"]
    assert g.parents("c") == []


def test_exec_state_readiness_cascade(tmp_path):
    path = _write_chain_profile(tmp_path, n=2)
    g = graph_from_pipedream_txt(path)
    job = Job(g, num_training_steps=5, max_acceptable_jct_frac=1.0, job_id=7)
    st = job.reset_training_step()

    # only op '1' is a source
    assert {st.op_ids[i] for i in st.ops_ready} == {"1"}
    # run op 1 to completion -> its out-edges ready
    i1 = st.op_index["1"]
    st.tick_op(i1, g.compute_cost("1"))
    assert st.op_completed[i1]
    assert {st.edge_ids[e] for e in st.deps_ready} == {("1", "2")}
    # completing dep (1,2) readies op 2
    e12 = st.edge_index[("1", "2")]
    st.set_dep_init_run_time(("1", "2"), 0.5)
    st.tick_dep(e12, 0.5)
    assert st.op_index["2"] in st.ops_ready

    # finish everything: op2, join dep, bwd ops/deps
    def run_all():
        for _ in range(100):
            if st.is_training_step_complete():
                return True
            for op in list(st.ops_ready):
                st.tick_op(op, st.remaining_op[op])
            for dep in list(st.deps_ready):
                st.tick_dep(dep, max(st.remaining_dep[dep], 0.0))
        return st.is_training_step_complete()

    assert run_all()


def test_seq_completion_time(tmp_path):
    path = _write_chain_profile(tmp_path, n=3)
    g = graph_from_pipedream_txt(path)
    job = Job(g, num_training_steps=10, max_acceptable_jct_frac=0.5, job_id=1)
    # sum fwd = 1+2+3, sum bwd = 2+4+6 -> 18 per step, x10 steps
    assert job.seq_completion_time == pytest.approx(180.0)
    assert job.max_acceptable_jct == pytest.approx(90.0)


def test_synthetic_files_loadable(tmp_path):
    paths = generate_pipedream_txt_files(str(tmp_path), n_cnn=2,
                                         n_translation=1, seed=3,
                                         min_ops=4, max_ops=8)
    assert len(paths) == 3
    for p in paths:
        g = graph_from_pipedream_txt(p)
        n_fwd = len(g.forward_op_ids())
        assert g.n_ops == 2 * n_fwd
        # graph must be a DAG reaching every node from the source
        assert (g.finalize()["depth"] > 0).all()


def test_jobs_generator(dataset_dir):
    from ddls_tpu.demands.jobs_generator import JobsGenerator

    gen = JobsGenerator(
        path_to_files=dataset_dir,
        job_interarrival_time_dist={
            "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 1000},
        max_acceptable_job_completion_time_frac_dist={
            "_target_": "ddls_tpu.demands.distributions.Uniform",
            "min_val": 0.1, "max_val": 1.0, "decimals": 2},
        replication_factor=3,
        job_sampling_mode="remove_and_repeat",
        num_training_steps=50)
    assert len(gen) == 9
    seen_ids = set()
    for _ in range(12):  # forces a refill past the first 9
        job = gen.sample_job()
        assert job.job_id not in seen_ids
        seen_ids.add(job.job_id)
        assert 0.1 <= job.max_acceptable_jct_frac <= 1.0
    assert gen.sample_interarrival_time() == 1000
    assert gen.jobs_params["max_job_total_num_ops"] >= \
        gen.jobs_params["min_job_total_num_ops"]
