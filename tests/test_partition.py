"""Partition-rule sharded learner (ISSUE 19, parallel/partition.py).

The rule engine (regex over /-joined param-tree paths -> PartitionSpec)
and its three named layouts: `replicated` (today's exact behaviour),
`fsdp` (large Dense kernels + adam moments sharded over the existing dp
axis — ZeRO-3), `tp` (output-feature tensor sharding over a second "mp"
mesh axis). Pins, per the acceptance criteria:

- engine semantics (first-match re.search, scalar leaves always
  replicated, unmatched non-scalar path is a LOUD error) and the
  canonical-path literal's sync with the runtime GNNPolicy tree (the
  lint frozen-param-tree cross-validation trusts that literal);
- x64 post-update parity: fsdp vs replicated on the SAME 1-D dp mesh is
  bitwise-class (<= 1e-12 measured 2.9e-16); tp vs replicated on the
  SAME (dp, mp) mesh is 1e-9-class (measured 5.8e-15). The tp baseline
  MUST share the mesh: PPO stratifies minibatches per dp shard, so a
  different dp width is genuinely different training math, not a layout
  effect. Subprocess-isolated like tests/test_jax_episode.py
  (JAX_ENABLE_X64 is process-global);
- a wide-GNN config whose replicated state exceeds a per-device budget
  trains under fsdp with measured peak live bytes under that budget;
- checkpoint round-trips: shipped checkpoints restore into the
  replicated layout bit-identically with the rule engine active, and a
  sharded state save/restores with its shardings re-applied (no silent
  de-shard);
- loud contract edges before env construction (DQN/ES, sebulba+tp,
  infeasible tp factorisation, layout/mesh mismatch);
- the steady-state fused epoch stays transfer-free under
  ``jax.transfer_guard("disallow")`` with the fsdp layout.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

import test_fused as tf  # noqa: E402
import test_rl as trl  # noqa: E402
from ddls_tpu.models.policy import (GNNPolicy,  # noqa: E402
                                    batched_policy_apply)
from ddls_tpu.parallel import make_mesh, partition as pt  # noqa: E402
from ddls_tpu.rl import PPOConfig, PPOLearner  # noqa: E402


def _tiny_model_and_params():
    model = GNNPolicy(n_actions=trl.N_ACTIONS, out_features_msg=4,
                      out_features_hidden=8, out_features_node=4,
                      out_features_graph=4, fcnet_hiddens=(16,))
    rng = np.random.RandomState(1)
    single = jax.tree_util.tree_map(lambda x: x[0],
                                    trl._fake_obs(rng, (1,)))
    return model, model.init(jax.random.PRNGKey(0), single)


def _ppo(mesh, model, layout, **cfg):
    defaults = dict(num_sgd_iter=2, sgd_minibatch_size=8, grad_clip=0.5)
    defaults.update(cfg)
    return PPOLearner(lambda p, o: batched_policy_apply(model, p, o),
                      PPOConfig(**defaults), mesh, param_sharding=layout)


# ======================================================== engine units
def test_match_first_rule_wins_and_scalars_replicate():
    tree = {"head": {"Dense_0": {"kernel": np.zeros((4, 4)),
                                 "bias": np.zeros(4)}},
            "step": np.zeros(())}
    rules = ((r"Dense_\d+/kernel$", P("dp", None)), (r".*", P()))
    specs = pt.match_partition_rules(rules, tree)
    assert specs["head"]["Dense_0"]["kernel"] == P("dp", None)
    assert specs["head"]["Dense_0"]["bias"] == P()
    # scalar leaves replicate even under a would-match sharding rule
    specs2 = pt.match_partition_rules(((r".*", P("dp")),),
                                      {"step": np.zeros(())})
    assert specs2["step"] == P()


def test_unmatched_path_is_loud():
    with pytest.raises(ValueError, match="partition rule not found"):
        pt.match_partition_rules(((r"kernel$", P()),),
                                 {"head": {"bias": np.zeros(4)}})


def test_canonical_paths_match_runtime_tree():
    """The literal the lint cross-validation trusts == the real default
    GNNPolicy param tree (suffix-relative: learners hold the tree under
    a flax 'params' wrapper and the rules re.search suffixes)."""
    model = GNNPolicy(n_actions=5)
    rng = np.random.RandomState(0)
    single = jax.tree_util.tree_map(lambda x: x[0],
                                    trl._fake_obs(rng, (1,)))
    params = model.init(jax.random.PRNGKey(0), single)
    got = sorted(pt.tree_paths(params["params"]))
    assert got == sorted(pt.CANONICAL_PARAM_PATHS)
    assert set(pt.LARGE_KERNEL_PATHS) <= set(pt.CANONICAL_PARAM_PATHS)
    # every layout fully covers the canonical tree (match raises if not)
    for layout in pt.LAYOUTS:
        specs = pt.match_partition_rules(pt.PARTITION_RULES[layout],
                                         params)
        for lk in pt.LARGE_KERNEL_PATHS:
            node = specs["params"]
            for part in lk.split("/"):
                node = node[part]
            if layout == "replicated":
                assert node == P()
            else:
                assert any(ax is not None for ax in node), (layout, lk)


def test_mesh_for_layout_and_validation():
    m1 = pt.mesh_for_layout(8, "replicated")
    assert m1.axis_names == ("dp",) and m1.shape["dp"] == 8
    assert pt.mesh_for_layout(8, "fsdp").axis_names == ("dp",)
    mtp = pt.mesh_for_layout(8, "tp")
    assert mtp.axis_names == ("dp", "mp")
    assert (mtp.shape["dp"], mtp.shape["mp"]) == (4, 2)
    mtp4 = pt.mesh_for_layout(8, "tp", tp_size=4)
    assert (mtp4.shape["dp"], mtp4.shape["mp"]) == (2, 4)
    with pytest.raises(ValueError, match="tp_size"):
        pt.mesh_for_layout(8, "tp", tp_size=3)
    with pytest.raises(ValueError, match="param_sharding"):
        pt.validate_layout("bogus")
    # tp on a mesh without the mp axis names the fix
    with pytest.raises(ValueError, match="mesh_for_layout"):
        pt.validate_mesh_for_layout(m1, "tp")
    pt.validate_mesh_for_layout(mtp, "tp")
    pt.validate_mesh_for_layout(mtp, "replicated")


def test_divisibility_fallback_replicates_per_leaf():
    """A leaf whose named dim doesn't divide the mesh axis replicates —
    pure in shapes, so canonical checkpoints load under ANY layout."""
    mesh = make_mesh(8)
    tree = {"big": np.zeros((16, 4)), "odd": np.zeros((3, 4))}
    specs = {"big": P("dp", None), "odd": P("dp", None)}
    sh = pt.specs_to_shardings(mesh, tree, specs)
    assert sh["big"].spec == P("dp", None)
    assert sh["odd"].spec == P()


def test_replicated_state_shardings_is_single_object():
    """The default layout returns ONE replicated sharding (same jit
    cache key, same program as pre-ISSUE-19 — the bit-identity claim)."""
    from ddls_tpu.parallel.mesh import replicated_sharding

    mesh = make_mesh(8)
    sh = pt.state_shardings(mesh, {"w": np.zeros((4, 4))}, "replicated")
    assert sh == replicated_sharding(mesh)


# ================================================== learner-level (f32)
def test_fsdp_learner_shards_large_kernels_and_trains():
    model, params = _tiny_model_and_params()
    mesh = pt.mesh_for_layout(8, "fsdp")
    learner = _ppo(mesh, model, "fsdp")
    state = learner.init_state(params)
    big = state.params["params"]["logit_head"]["Dense_0"]["kernel"]
    assert big.sharding.spec == P("dp", None)
    # adam moments follow the params layout (the ZeRO-3 point): every
    # opt-state leaf shaped like the big kernel carries its spec
    mu_specs = [x.sharding.spec for x in jax.tree_util.tree_leaves(
        state.opt_state) if getattr(x, "shape", None) == big.shape]
    assert mu_specs and all(s == P("dp", None) for s in mu_specs)
    rng = np.random.RandomState(2)
    traj = trl._fake_traj(rng, T=4, B=16)
    straj, slv = learner.shard_traj(traj, rng.randn(16).astype(np.float32))
    new_state, metrics = learner.train_step(state, straj, slv,
                                            jax.random.PRNGKey(3))
    assert np.isfinite(float(metrics["total_loss"]))
    nb = new_state.params["params"]["logit_head"]["Dense_0"]["kernel"]
    assert nb.sharding.spec == P("dp", None)  # layout survives the step


def test_wide_gnn_fsdp_fits_per_device_budget():
    """ISSUE 19 acceptance: a wide-GNN config whose replicated state
    exceeds a per-device budget trains under fsdp with lower measured
    peak live bytes (numbers: docs/perf_round13.md / BENCH_r09.json)."""
    BUDGET = 2 * 1024 * 1024  # bytes per device
    model = GNNPolicy(n_actions=trl.N_ACTIONS, out_features_msg=64,
                      out_features_hidden=128, out_features_node=64,
                      out_features_graph=64, fcnet_hiddens=(512, 512))
    rng = np.random.RandomState(1)
    single = jax.tree_util.tree_map(lambda x: x[0],
                                    trl._fake_obs(rng, (1,)))
    params = model.init(jax.random.PRNGKey(0), single)

    repl = _ppo(pt.mesh_for_layout(8, "replicated"), model, "replicated")
    bytes_repl = pt.live_bytes_per_device(repl.init_state(params))
    assert bytes_repl > BUDGET, bytes_repl  # genuinely over budget

    mesh = pt.mesh_for_layout(8, "fsdp")
    learner = _ppo(mesh, model, "fsdp")
    state = learner.init_state(params)
    bytes_fsdp = pt.live_bytes_per_device(state)
    assert bytes_fsdp < BUDGET, bytes_fsdp
    assert bytes_fsdp < bytes_repl / 4  # dp=8 shards the big kernels
    rng = np.random.RandomState(2)
    traj = trl._fake_traj(rng, T=2, B=16)
    straj, slv = learner.shard_traj(traj, rng.randn(16).astype(np.float32))
    new_state, metrics = learner.train_step(state, straj, slv,
                                            jax.random.PRNGKey(3))
    assert np.isfinite(float(metrics["total_loss"]))
    assert pt.live_bytes_per_device(new_state) < BUDGET


# ==================================================== x64 parity driver
PARITY_DRIVER = r"""
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
assert jax.config.read("jax_enable_x64")
assert len(jax.devices()) == 8
import test_rl as trl
from ddls_tpu.models.policy import GNNPolicy, batched_policy_apply
from ddls_tpu.parallel import partition as pt
from ddls_tpu.rl import PPOConfig, PPOLearner

# CANONICAL widths, deliberately: toy widths (4/8-wide Dense) leave
# many near-zero gradients whose adam updates (m / (sqrt(v) + eps) with
# v ~ 0) amplify layout-reassociation dust to ~1e-7 even in f64 — the
# canonical tree measures 2e-15/3e-15 under the same schedule
model = GNNPolicy(n_actions=trl.N_ACTIONS)
rng = np.random.RandomState(1)
single = jax.tree_util.tree_map(lambda x: x[0], trl._fake_obs(rng, (1,)))
params = model.init(jax.random.PRNGKey(0), single)
# f64 state AND f64 trajectory floats: at f32 the loss pipeline rounds
# at f32 and adam's eps/sqrt amplifies layout-reassociation noise to
# ~1e-6 — the parity claim loses its teeth
params = jax.tree_util.tree_map(
    lambda x: np.asarray(x, np.float64), params)
rng2 = np.random.RandomState(2)
traj = trl._fake_traj(rng2, T=4, B=16)
for k in ("logp", "values", "rewards"):
    traj[k] = traj[k].astype(np.float64)
last_values = rng2.randn(16)

def run(mesh, layout, steps=3):
    learner = PPOLearner(
        lambda p, o: batched_policy_apply(model, p, o),
        PPOConfig(num_sgd_iter=2, sgd_minibatch_size=8, grad_clip=0.5),
        mesh, param_sharding=layout)
    state = learner.init_state(params)
    straj, slv = learner.shard_traj(traj, last_values)
    for i in range(steps):
        state, _ = learner.train_step(state, straj, slv,
                                      jax.random.PRNGKey(3 + i))
    return jax.device_get(state.params)

def maxdiff(a, b):
    return max(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda x, y: float(np.abs(np.asarray(x)
                                  - np.asarray(y)).max()), a, b)))

# fsdp rides the SAME 1-D dp mesh as replicated: same minibatch
# stratification, same semantics — only the all-gather/reduce-scatter
# layout differs, so agreement is bitwise-class (measured 2.9e-16)
ref = run(pt.mesh_for_layout(8, "replicated"), "replicated")
d_fsdp = maxdiff(ref, run(pt.mesh_for_layout(8, "fsdp"), "fsdp"))
assert d_fsdp < 1e-12, d_fsdp

# tp changes the mesh geometry (dp 4 x mp 2), and PPO stratifies
# minibatches PER dp shard — so the replicated baseline must run ON
# the same 2-axis mesh or the two runs shuffle different minibatches
# (different training math, not a layout effect). Measured 5.8e-15;
# the pinned 1e-9 class absorbs cross-version reassociation drift.
mesh_tp = pt.mesh_for_layout(8, "tp")
ref_tp = run(mesh_tp, "replicated")
d_tp = maxdiff(ref_tp, run(mesh_tp, "tp"))
assert d_tp < 1e-9, d_tp
print(f"PARTITION_PARITY_OK fsdp={d_fsdp:.3e} tp={d_tp:.3e}")
"""


def test_layout_parity_x64():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO, os.path.dirname(os.path.abspath(__file__))])
    res = subprocess.run([sys.executable, "-c", PARITY_DRIVER], env=env,
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, (res.stdout[-4000:], res.stderr[-4000:])
    assert "PARTITION_PARITY_OK" in res.stdout, res.stdout[-2000:]


# ================================================= checkpoint round-trip
CKPT = os.path.join(REPO, "checkpoints", "ppo_price_mixed")


def test_shipped_checkpoint_replicated_roundtrip():
    """Shipped checkpoints keep loading into the replicated layout
    bit-identically with the rule engine active — and the rule tables
    fully cover the SHIPPED param tree (match raises on a gap)."""
    from ddls_tpu.parallel.mesh import place_state_tree
    from ddls_tpu.train.checkpointer import restore_train_state

    raw = restore_train_state(CKPT)
    params = raw["params"]
    for layout in pt.LAYOUTS:  # full coverage of the shipped tree
        pt.match_partition_rules(pt.PARTITION_RULES[layout], params)
    mesh = pt.mesh_for_layout(8, "replicated")
    specs = pt.match_partition_rules(pt.PARTITION_RULES["replicated"],
                                     params)
    placed = place_state_tree(
        params, pt.specs_to_shardings(mesh, params, specs))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(placed), params)


def test_sharded_state_roundtrips_with_shardings(tmp_path):
    """An fsdp-trained state save/restores through train/checkpointer.py
    with its shardings re-applied — no silent de-shard on restore."""
    from ddls_tpu.train.checkpointer import (restore_train_state,
                                             save_train_state)

    model, params = _tiny_model_and_params()
    mesh = pt.mesh_for_layout(8, "fsdp")
    learner = _ppo(mesh, model, "fsdp")
    state = learner.init_state(params)
    save_train_state(state, str(tmp_path / "ck"))
    restored = restore_train_state(str(tmp_path / "ck"), target=state)
    big = restored.params["params"]["logit_head"]["Dense_0"]["kernel"]
    assert big.sharding.spec == P("dp", None)
    assert pt.live_bytes_per_device(restored) \
        == pt.live_bytes_per_device(state)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        jax.device_get(restored.params), jax.device_get(state.params))


# ===================================================== loud contract edges
def test_learner_rejects_bad_layout_and_mesh():
    model, _ = _tiny_model_and_params()
    with pytest.raises(ValueError, match="param_sharding"):
        _ppo(make_mesh(8), model, "bogus")
    # tp layout on a mesh without the mp axis names the fix
    with pytest.raises(ValueError, match="mesh_for_layout"):
        _ppo(make_mesh(8), model, "tp")
    # the legacy knob and the rule engine cannot both drive the layout
    with pytest.raises(ValueError, match="shard_params_axis"):
        PPOLearner(lambda p, o: None, PPOConfig(), make_mesh(8),
                   shard_params_axis="dp", param_sharding="fsdp")


@pytest.mark.parametrize("algo", ["apex_dqn", "es"])
def test_loop_rejects_dqn_es_before_env_construction(algo):
    from ddls_tpu.train import make_epoch_loop

    with pytest.raises(ValueError, match="param_sharding"):
        make_epoch_loop(algo, path_to_env_cls=tf.ENV_CLS, env_config={},
                        param_sharding="fsdp")


def test_loop_rejects_sebulba_tp_and_bad_tp_size():
    from ddls_tpu.train import make_epoch_loop

    with pytest.raises(ValueError, match="sebulba"):
        make_epoch_loop("ppo", path_to_env_cls=tf.ENV_CLS, env_config={},
                        loop_mode="sebulba", param_sharding="tp")
    with pytest.raises(ValueError, match="tp_size"):
        make_epoch_loop("ppo", path_to_env_cls=tf.ENV_CLS, env_config={},
                        param_sharding="tp", tp_size=3)


def test_learner_ctor_rejects_dqn_es():
    from ddls_tpu.rl.dqn import ApexDQNLearner, DQNConfig
    from ddls_tpu.rl.es import ESConfig, ESLearner

    with pytest.raises(ValueError, match="param_sharding"):
        ApexDQNLearner(lambda p, o: None, DQNConfig(), make_mesh(8),
                       param_sharding="fsdp")
    with pytest.raises(ValueError, match="param_sharding"):
        ESLearner(lambda p, o: None, ESConfig(), make_mesh(8),
                  population=4, param_sharding="tp")


# ============================================ sharded end-to-end epochs
@pytest.fixture(scope="module")
def part_dataset(tmp_path_factory):
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    d = str(tmp_path_factory.mktemp("part_jobs"))
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=9)
    return d


def test_device_collector_epoch_trains_fsdp(part_dataset):
    """The sequential device-collector loop trains under fsdp: the
    collector's forwards consume the learner's layout via explicit
    in_shardings (no implicit per-collect gather at dispatch)."""
    from ddls_tpu.train import make_epoch_loop

    algo = {"train_batch_size": 16, "sgd_minibatch_size": 8,
            "num_sgd_iter": 2, "num_workers": 8,
            "device_collector": True}
    loop = make_epoch_loop(
        "ppo", path_to_env_cls=tf.ENV_CLS,
        env_config=tf._env_config(part_dataset, horizon=6e2),
        model=tf._TINY_MODEL, algo_config=algo, num_envs=8,
        rollout_length=2, n_devices=8, use_parallel_envs=False,
        evaluation_interval=None, seed=0, loop_mode="sequential",
        param_sharding="fsdp")
    try:
        big = loop.state.params["params"]["logit_head"]["Dense_0"]["kernel"]
        assert big.sharding.spec == P("dp", None)
        before = jax.device_get(loop.state.params)
        for _ in range(2):
            r = loop.run()
            assert np.isfinite(r["learner"]["total_loss"])
        after = jax.device_get(loop.state.params)
        moved = jax.tree_util.tree_map(
            lambda a, b: float(np.abs(np.asarray(a)
                                      - np.asarray(b)).max()),
            before, after)
        assert max(jax.tree_util.tree_leaves(moved)) > 0
        nb = loop.state.params["params"]["logit_head"]["Dense_0"]["kernel"]
        assert nb.sharding.spec == P("dp", None)
    finally:
        loop.close()


def test_fused_epoch_transfer_free_fsdp(part_dataset):
    """ISSUE 19 acceptance: the steady-state epoch stays transfer-free
    under ``jax.transfer_guard("disallow")`` with a sharded layout (the
    fused scan carries the fsdp state in its own shardings)."""
    loop = tf._make_fused_loop(
        part_dataset, metrics_sync_interval=3, param_sharding="fsdp",
        env_config=tf._env_config(part_dataset, horizon=6e2))
    try:
        big = loop.state.params["params"]["logit_head"]["Dense_0"]["kernel"]
        assert big.sharding.spec == P("dp", None)
        r1 = loop.run()  # warm: compile + first-use constant transfers
        with jax.transfer_guard("disallow"):
            r2 = loop.run()
        for r in (r1, r2):
            assert np.isfinite(r["learner"]["total_loss"])
        nb = loop.state.params["params"]["logit_head"]["Dense_0"]["kernel"]
        assert nb.sharding.spec == P("dp", None)
    finally:
        loop.close()
