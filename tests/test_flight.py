"""Episode flight recorder tests (ISSUE 6): the recorder-off hot-path
guard (zero event objects created during env stepping — the same
discipline as test_telemetry's ``test_env_hot_loop_disabled_guard``),
trace capture + JSONL round trip through ``scripts/trace_export.py`` and
``scripts/telemetry_report.py``, cross-backend diffing (seeded host vs
C++ identical; a deliberately perturbed backend pinpointed at its first
divergent event), the worker-process trace merge over the rollout close
ack, the ``scripts/check_flight_gated.py`` tier-1 guard, and the bench
probe wedge-state cache satellite."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ddls_tpu.telemetry import flight

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_global_flight():
    """Each test starts and ends with the global recorder disabled and
    empty (it is process-global state, like the telemetry registry)."""
    def clean():
        flight.reset()
        flight.disable()
        flight.recorder().detail = False

    clean()
    yield
    clean()


def _tiny_env(dataset_dir, **overrides):
    from ddls_tpu.envs import RampJobPartitioningEnvironment

    kwargs = dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 1000.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.1, "max_val": 1.0, "decimals": 2},
            "replication_factor": 5,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 50},
        max_partitions_per_op=8,
        min_op_run_time_quantum=0.01,
        reward_function="job_acceptance",
        reward_function_kwargs={"fail_reward": -1, "success_reward": 1},
        max_simulation_run_time=2e4,
        pad_obs_kwargs={"max_nodes": 64, "max_edges": 256})
    kwargs.update(overrides)
    return RampJobPartitioningEnvironment(**kwargs)


def _run_episode(env, seed=0, max_decisions=20):
    obs = env.reset(seed=seed)
    rng = np.random.RandomState(seed)
    actions, done = [], False
    while not done and len(actions) < max_decisions:
        valid = np.flatnonzero(np.asarray(obs["action_mask"]))
        action = int(rng.choice(valid))
        obs, _, done, _ = env.step(action)
        actions.append(action)
    return actions


# ------------------------------------------------------------ off guard
def test_recorder_disabled_guard(dataset_dir, monkeypatch):
    """Acceptance guard: with the recorder disabled, env stepping calls
    the emit path zero times — no event objects, no payload dicts."""
    calls = {"n": 0}
    orig = flight.FlightRecorder.emit

    def counting(self, *a, **k):
        calls["n"] += 1
        return orig(self, *a, **k)

    monkeypatch.setattr(flight.FlightRecorder, "emit", counting)
    monkeypatch.setattr(flight, "emit",
                        lambda *a, **k: counting(flight.recorder(),
                                                 *a, **k))

    env = _tiny_env(dataset_dir)
    _run_episode(env, seed=0, max_decisions=4)
    assert calls["n"] == 0
    assert flight.events() == []

    # flipping the switch makes the SAME loop emit the full vocabulary
    flight.enable()
    _run_episode(env, seed=1, max_decisions=6)
    assert calls["n"] > 0
    kinds = {e["kind"] for e in flight.events()}
    assert {"job_arrived", "action_decided", "tick"} <= kinds, kinds
    # this seed places at least one job: the full placement chain fires
    assert {"partitioned", "placed", "mounted", "lookahead"} <= kinds, \
        kinds


def test_recorder_event_order_and_summary(dataset_dir):
    flight.enable()
    env = _tiny_env(dataset_dir)
    _run_episode(env, seed=3, max_decisions=8)
    events = flight.events()
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    summ = flight.summarize(events)
    assert summ["n_events"] == len(events)
    decided = summ["by_kind"]["action_decided"]
    assert decided == 8 or env.cluster.is_done()
    # every decided job has a lifecycle row with an arrival
    for ji, row in summ["jobs"].items():
        if "decided" in row:
            assert "arrived" in row, (ji, row)


def test_detail_events_only_with_detail_enabled(dataset_dir):
    flight.enable(detail=False)
    env = _tiny_env(dataset_dir)
    _run_episode(env, seed=3, max_decisions=6)
    assert not any(e["kind"] in flight.DETAIL_KINDS
                   for e in flight.events())
    flight.reset()
    flight.enable(detail=True)
    # fresh cluster (fresh lookahead cache), HOST engine — detail events
    # exist only where the host engine ticks the lookahead itself
    env2 = _tiny_env(dataset_dir, use_native_lookahead=False)
    _run_episode(env2, seed=3, max_decisions=6)
    detail = [e for e in flight.events()
              if e["kind"] in flight.DETAIL_KINDS]
    assert detail, "no op/flow completion detail from the host engine"
    assert all("lt" in e and "job_idx" in e for e in detail)


# ------------------------------------------------- round trip + export
def test_jsonl_roundtrip_export_and_report(dataset_dir, tmp_path):
    flight.enable()
    env = _tiny_env(dataset_dir)
    _run_episode(env, seed=3, max_decisions=8)
    events = flight.drain()
    path = str(tmp_path / "trace.jsonl")
    n = flight.save_jsonl(path, events)
    assert n == len(events)
    loaded = flight.load_jsonl(path)
    assert loaded == events

    # trace_export.py: Chrome-trace JSON with slices + markers
    out_json = str(tmp_path / "trace.perfetto.json")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_export.py"),
         path, "-o", out_json],
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr
    trace = json.load(open(out_json))
    phases = [e.get("ph") for e in trace["traceEvents"]]
    assert "X" in phases and "i" in phases and "M" in phases
    assert trace["otherData"]["n_flight_events"] == len(events)

    # telemetry_report.py: the flight-trace summary section
    res = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "telemetry_report.py"), path],
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr
    assert "flight trace" in res.stdout
    assert "action_decided" in res.stdout
    assert "blocked by cause" in res.stdout


def test_export_rejects_empty_input(tmp_path):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_export.py"),
         str(empty)],
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 2


# ------------------------------------------------------- backend diffing
def test_host_vs_native_trace_identical(dataset_dir):
    """Acceptance: a seeded canonical-RAMP episode produces bit-identical
    flight traces on the host and C++ lookahead backends."""
    from ddls_tpu.native import native_available

    if not native_available():
        pytest.skip("C++ lookahead engine unavailable")

    traces = {}
    for backend in ("host", "native"):
        flight.reset()
        flight.enable()
        env = _tiny_env(dataset_dir,
                        use_native_lookahead=(backend == "native"))
        _run_episode(env, seed=7, max_decisions=10)
        traces[backend] = flight.drain()
    a = flight.comparable_events(traces["host"])
    b = flight.comparable_events(traces["native"])
    assert len(a) > 20
    div = flight.first_divergence(a, b)
    assert div is None, flight.format_divergence(div, "host", "native")
    # the context field the diff ignores really did differ: the engines
    # are distinguishable in the raw traces
    assert {e.get("backend") for e in traces["host"]
            if e["kind"] == "lookahead"} <= {"host", "cache"}
    assert "native" in {e.get("backend") for e in traces["native"]
                        if e["kind"] == "lookahead"}


def test_perturbed_backend_first_divergent_event(dataset_dir, tmp_path):
    """Acceptance: a deliberately perturbed lookahead backend is
    pinpointed at its first divergent event — kind, sim-time, payload
    diff — in-process and through scripts/trace_diff.py files mode."""
    flight.enable()
    env_a = _tiny_env(dataset_dir, use_native_lookahead=False)
    actions = _run_episode(env_a, seed=7, max_decisions=10)
    trace_a = flight.drain()

    flight.reset()
    flight.enable()
    env_b = _tiny_env(dataset_dir, use_native_lookahead=False)
    orig = env_b.cluster._run_lookahead

    def perturbed(job):
        jct, comm, comp, busy = orig(job)
        return jct * 1.0001, comm, comp, busy  # the injected bug

    env_b.cluster._run_lookahead = perturbed
    obs = env_b.reset(seed=7)
    for action in actions:
        try:
            obs, _, done, _ = env_b.step(action)
        except ValueError:
            break  # mask diverged post-perturbation
        if done:
            break
    trace_b = flight.drain()

    a = flight.comparable_events(trace_a)
    b = flight.comparable_events(trace_b)
    div = flight.first_divergence(a, b)
    assert div is not None
    assert div["a"]["kind"] == "lookahead"
    assert "jct" in [f[0] for f in div["fields"]]
    text = flight.format_divergence(div, "host", "perturbed")
    assert "lookahead" in text and "jct" in text and "t=" in text

    # the script names the same event from the saved files
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    flight.save_jsonl(pa, trace_a)
    flight.save_jsonl(pb, trace_b)
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "trace_diff.py"),
         "files", pa, pb],
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 1, res.stdout + res.stderr
    assert "first divergence" in res.stdout
    assert "lookahead" in res.stdout and "jct" in res.stdout


def test_summarize_separates_envs_and_episode_generations():
    """Merged worker traces and auto-reset episodes reuse job_idx; the
    lifecycle table must not conflate them (labels carry the env tag and
    an episode generation bumped on each re-arrival)."""
    evts = [
        {"seq": 0, "kind": "job_arrived", "t": 0.0, "job_idx": 0,
         "env": 0},
        {"seq": 1, "kind": "job_blocked", "t": 1.0, "job_idx": 0,
         "env": 0, "cause": "not_handled"},
        # same idx, other worker
        {"seq": 0, "kind": "job_arrived", "t": 0.0, "job_idx": 0,
         "env": 1},
        {"seq": 1, "kind": "job_completed", "t": 5.0, "job_idx": 0,
         "env": 1, "jct": 5.0},
        # same idx again on env 0: a new episode's job 0
        {"seq": 2, "kind": "job_arrived", "t": 0.0, "job_idx": 0,
         "env": 0},
    ]
    jobs = flight.summarize(evts)["jobs"]
    assert set(jobs) == {"e0:j0", "e1:j0", "e0:j0#1"}
    assert "blocked" in jobs["e0:j0"]
    assert "completed" in jobs["e1:j0"]
    assert jobs["e0:j0#1"] == {"arrived": 0.0, "model": None}
    # single-env single-episode traces keep plain numeric labels
    plain = flight.summarize([
        {"seq": 0, "kind": "job_arrived", "t": 0.0, "job_idx": 3}])
    assert set(plain["jobs"]) == {"3"}


def test_first_divergence_length_and_rtol():
    a = [{"kind": "tick", "t": 1.0, "dt": 0.5}]
    assert flight.first_divergence(a, list(a)) is None
    div = flight.first_divergence(a, [])
    assert div["reason"] == "length" and div["index"] == 0
    b = [{"kind": "tick", "t": 1.0, "dt": 0.5 + 1e-12}]
    assert flight.first_divergence(a, b) is not None
    assert flight.first_divergence(a, b, rtol=1e-9) is None


# --------------------------------------------------- worker trace merge
def test_worker_traces_merge_on_close(dataset_dir):
    """Subprocess env workers mirror the parent's recorder switch and
    their traces ride the close ack into the parent, env-tagged."""
    from ddls_tpu.envs import RampJobPartitioningEnvironment
    from ddls_tpu.rl.rollout import ParallelVectorEnv

    flight.enable()
    env_kwargs = dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2, "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 1000.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.1, "max_val": 1.0, "decimals": 2},
            "replication_factor": 5,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 50},
        max_partitions_per_op=8, min_op_run_time_quantum=0.01,
        reward_function="job_acceptance",
        reward_function_kwargs={"fail_reward": -1, "success_reward": 1},
        max_simulation_run_time=2e4,
        pad_obs_kwargs={"max_nodes": 64, "max_edges": 256})
    vec = ParallelVectorEnv(RampJobPartitioningEnvironment, env_kwargs,
                            num_envs=2, backend="pipe")
    try:
        vec.reset()
        for _ in range(3):
            vec.step(np.zeros(2, dtype=np.int64))
    finally:
        vec.close()
    events = flight.events()
    assert events, "no worker events merged on close"
    assert {e.get("env") for e in events} == {0, 1}
    assert {"job_arrived", "action_decided"} <= {e["kind"]
                                                 for e in events}


# ------------------------------------------------------ tier-1 guards
def test_check_flight_gated_clean_tree():
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_flight_gated.py")],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_flight_gated_flags_violations(tmp_path):
    bad = tmp_path / "hot_module.py"
    bad.write_text(
        "from ddls_tpu.telemetry import flight as _flight\n"
        "def step(t):\n"
        "    _flight.emit('tick', t=t)\n"          # ungated
        "    if _flight.enabled():\n"
        "        _flight.emit('ok', t=t)\n"         # gated: fine
        "    _flight.enable()\n")                   # switch: forbidden
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_flight_gated.py"),
         "--paths", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 1
    assert "hot_module.py:3" in out.stdout
    assert "hot_module.py:6" in out.stdout
    assert "hot_module.py:5" not in out.stdout
    assert "enabled" in out.stdout  # the fix pointer


# ------------------------------------- bench probe wedge-state cache
def test_probe_cache_skips_on_recorded_wedge(tmp_path, monkeypatch):
    import time

    import bench

    probe_dir = str(tmp_path / ".probe")
    bench.record_probe_state("timeout", error="init timed out after "
                                              "240s", probe_dir=probe_dir)
    monkeypatch.setattr(bench, "probe_backend",
                        lambda *a, **k: pytest.fail(
                            "probe subprocess ran despite recorded "
                            "wedge"))
    err, reason = bench.probe_backend_cached(240.0, probe_dir=probe_dir)
    assert reason == "recent_probe_timeout"
    assert err is not None and "timed out" in err
    # stale state probes normally again
    state_path = os.path.join(probe_dir, bench.PROBE_STATE_FILE)
    state = json.load(open(state_path))
    state["ts"] = time.time() - 10 * bench.PROBE_STATE_TTL_S
    json.dump(state, open(state_path, "w"))
    monkeypatch.setattr(bench, "probe_backend", lambda *a, **k: None)
    err, reason = bench.probe_backend_cached(240.0, probe_dir=probe_dir)
    assert (err, reason) == (None, None)
    # ... and the fresh success was recorded without enabling a skip
    assert json.load(open(state_path))["outcome"] == "success"
    err, reason = bench.probe_backend_cached(240.0, probe_dir=probe_dir)
    assert (err, reason) == (None, None)


def test_probe_cache_respects_tpu_lock(tmp_path, monkeypatch):
    import bench

    probe_dir = tmp_path / ".probe"
    probe_dir.mkdir()
    (probe_dir / "tpu.lock").touch()
    monkeypatch.setattr(bench, "probe_backend",
                        lambda *a, **k: pytest.fail(
                            "probed while another owner holds the "
                            "chip lock"))
    err, reason = bench.probe_backend_cached(240.0,
                                             probe_dir=str(probe_dir))
    assert reason == "tpu_lock_held"
    assert "tpu.lock" in err
    # ttl 0 disables every skip path (--probe-ttl 0)
    monkeypatch.setattr(bench, "probe_backend", lambda *a, **k: None)
    err, reason = bench.probe_backend_cached(240.0, ttl_s=0,
                                             probe_dir=str(probe_dir))
    assert (err, reason) == (None, None)
