"""Batched candidate-degree pricing: parity with the cluster's own
lookahead, memo-cache prefetching, the jax batched backend, and the
OracleJCT consumer (docs/jax_lookahead_gonogo.md point 2; VERDICT r2 next
#3)."""
import tempfile

import numpy as np
import pytest

from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.envs.baselines import AcceptableJCT, OracleJCT
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files


def _env_kwargs(dataset_dir, **overrides):
    kwargs = dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.2, "max_val": 1.0, "decimals": 2},
            "replication_factor": 15,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 10},
        max_partitions_per_op=8,
        min_op_run_time_quantum=0.01,
        reward_function="job_acceptance",
        max_simulation_run_time=1.5e4,
        pad_obs_kwargs={"max_nodes": 150, "max_edges": 512})
    kwargs.update(overrides)
    return kwargs


@pytest.fixture(scope="module")
def dataset_dir():
    d = tempfile.mkdtemp(prefix="candidate_pricing_")
    generate_pipedream_txt_files(d, n_cnn=2, n_translation=1, seed=11)
    return d


def test_prices_match_step_lookahead_and_prefetch(dataset_dir):
    """For every step of a real episode: the price of the chosen action
    equals the cluster's own lookahead outcome EXACTLY (native backend is
    the same bit-exact C++ engine), and the step's lookahead is served
    from the prefetched memo entry (no engine call)."""
    env = RampJobPartitioningEnvironment(
        **_env_kwargs(dataset_dir, candidate_pricing="native"))
    obs = env.reset(seed=3)
    rng = np.random.RandomState(0)
    checked = 0
    engine_calls = []
    orig = env.cluster._run_native_lookahead

    def spy(job):
        engine_calls.append(job.job_id)
        return orig(job)

    env.cluster._run_native_lookahead = spy
    host_calls = []
    orig_host = env.cluster._run_lookahead
    env.cluster._run_lookahead = lambda job: (host_calls.append(job.job_id)
                                              or orig_host(job))
    for _ in range(25):
        prices = dict(env.candidate_prices)
        decided = None
        if len(env.cluster.job_queue.jobs):
            decided = next(iter(env.cluster.job_queue.jobs.values()))
        valid = np.nonzero(np.asarray(obs["action_mask"]))[0]
        action = int(rng.choice(valid))
        before = len(engine_calls) + len(host_calls)
        obs, reward, done, info = env.step(action)
        if action != 0 and decided is not None \
                and prices.get(action) is not None:
            # the chosen candidate was prefetched: the step ran NO engine
            assert len(engine_calls) + len(host_calls) == before, (
                f"step re-ran the lookahead engine for action {action}")
            # the job just decided carries EXACTLY the predicted JCT (the
            # lookahead detail lives on the PARTITIONED clone the cluster
            # runs, found by job_idx in whichever lifecycle dict holds it)
            ji = decided.details["job_idx"]
            if ji in env.cluster.jobs_blocked:
                # SLA block: the predicted JCT must indeed exceed the limit
                assert prices[action][0] > decided.max_acceptable_jct
            else:
                placed = (env.cluster.jobs_running.get(ji)
                          or env.cluster.jobs_completed.get(ji))
                assert placed is not None
                la = placed.details["lookahead_job_completion_time"]
                assert la == prices[action][0], (la, prices[action][0])
            checked += 1
        if done:
            break
    assert checked >= 5


def test_unplaceable_candidates_price_none(dataset_dir):
    """Degrees the cluster cannot host (no free block) price to None, and
    placeable ones carry finite positive JCTs."""
    env = RampJobPartitioningEnvironment(
        **_env_kwargs(dataset_dir, candidate_pricing="native"))
    env.reset(seed=1)
    prices = env.candidate_prices
    assert prices, "no prices for the first queued job"
    placeable = {a: p for a, p in prices.items() if p is not None}
    assert placeable, "first job on an empty cluster must be placeable"
    for a, (jct, comm, comp, busy) in placeable.items():
        assert np.isfinite(jct) and jct > 0
        assert busy > 0


def test_jax_backend_matches_native_prices(dataset_dir):
    """One vmapped dispatch over all candidates agrees with the bit-exact
    C++ engine to f32 tolerance (the documented jax-engine trade)."""
    env = RampJobPartitioningEnvironment(**_env_kwargs(dataset_dir))
    env.reset(seed=5)
    native = env.price_candidate_degrees(backend="native")
    env2 = RampJobPartitioningEnvironment(**_env_kwargs(dataset_dir))
    env2.reset(seed=5)
    jaxp = env2.price_candidate_degrees(backend="jax")
    assert set(native) == set(jaxp)
    compared = 0
    for a in native:
        if native[a] is None:
            assert jaxp[a] is None
            continue
        for lhs, rhs in zip(native[a][:3], jaxp[a][:3]):
            assert rhs == pytest.approx(lhs, rel=2e-4, abs=1e-5)
        compared += 1
    assert compared >= 3


def test_oracle_jct_respects_sla_better_than_approximation(dataset_dir):
    """Full-episode comparison: OracleJCT (true lookahead prices) must not
    lose to AcceptableJCT (sequential-time approximation) on the
    acceptance reward, and must run the whole episode with candidate
    pricing on."""

    def run(actor, pricing):
        env = RampJobPartitioningEnvironment(
            **_env_kwargs(dataset_dir, candidate_pricing=pricing))
        obs = env.reset(seed=9)
        done, total = False, 0.0
        while not done:
            job = None
            if len(env.cluster.job_queue.jobs):
                job = next(iter(env.cluster.job_queue.jobs.values()))
            a = actor.compute_action(obs, job_to_place=job, env=env)
            obs, r, done, _ = env.step(a)
            total += r
        return total

    oracle = run(OracleJCT(max_partitions_per_op=8), "native")
    approx = run(AcceptableJCT(max_partitions_per_op=8), None)
    assert oracle >= approx, (oracle, approx)


def test_price_features_in_observation(dataset_dir):
    """obs_include_candidate_prices appends one priced-JCT/SLA ratio per
    action, 0.5 at the acceptance boundary, 1.0 for unpriceable, matching
    env.candidate_prices exactly at every decision (prices are computed
    BEFORE the observation so they describe the CURRENT queued job)."""
    env = RampJobPartitioningEnvironment(
        **_env_kwargs(dataset_dir, candidate_pricing="native",
                      obs_include_candidate_prices=True))
    obs = env.reset(seed=5)
    n_actions = env.max_partitions_per_op + 1
    base_dim = env.observation_space["graph_features"].shape[0] - n_actions
    rng = np.random.RandomState(1)
    checked = 0
    for _ in range(12):
        job = next(iter(env.cluster.job_queue.jobs.values()))
        feats = np.asarray(obs["graph_features"])[base_dim:]
        assert feats.shape == (n_actions,)
        limit = job.max_acceptable_jct
        for a in range(n_actions):
            priced = env.candidate_prices.get(a)
            if priced is not None:
                expected = min(priced[0] / max(limit, 1e-30), 2.0) / 2.0
                assert feats[a] == pytest.approx(expected, rel=1e-6), a
                # boundary semantics: <= 0.5 iff the SLA accepts it
                assert (feats[a] <= 0.5 + 1e-9) == (priced[0] <= limit
                                                    or feats[a] == 0.5)
                checked += 1
            else:
                assert feats[a] == 1.0
        valid = np.nonzero(np.asarray(obs["action_mask"]))[0]
        obs, _, done, _ = env.step(int(rng.choice(valid)))
        if done:
            break
    assert checked >= 8


def test_price_features_require_pricing(dataset_dir):
    with pytest.raises(ValueError, match="requires candidate_pricing"):
        RampJobPartitioningEnvironment(
            **_env_kwargs(dataset_dir, obs_include_candidate_prices=True))
