"""Property/fuzz test for the C++ lookahead engine.

Random lookahead instances (random DAGs with mutual sync pairs, random
worker assignment, multi-channel flow routing, permutation priority
scores) are run through the C++ engine and through an independent,
deliberately-naive numpy mirror of the pinned tick semantics
(jax_lookahead.py module docstring). Outcomes must agree exactly in f64:
this exercises the engine's incremental data structures (lazy heaps,
readiness staging, channel nomination) on tie-break and contention
patterns that episode-captured cases may never produce.
"""
import numpy as np
import pytest

from ddls_tpu.native import native_available, run_lookahead
from ddls_tpu.sim.jax_lookahead import LookaheadArrays

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable")


def _numpy_reference(a: LookaheadArrays):
    """Straightforward O(iters x (N+E)) mirror of the host semantics."""
    N = a.op_remaining.shape[0]
    E = a.dep_remaining.shape[0]
    rem_op = a.op_remaining.astype(np.float64).copy()
    rem_dep = a.dep_remaining.astype(np.float64).copy()
    op_done = np.zeros(N, bool)
    dep_done = np.zeros(E, bool)
    parent_done = np.zeros(N, np.int64)
    t = comm = comp = busy = 0.0
    BIG = 1.7e308

    for _ in range(2 * (N + E) + 16):
        if op_done.all() and dep_done.all():
            return t, comm, comp, busy, True
        ops_ready = ~op_done & (parent_done >= a.num_parents)
        deps_ready = ~dep_done & op_done[a.dep_src]
        flow_ready = deps_ready & a.dep_is_flow
        nonflow_ready = deps_ready & ~a.dep_is_flow

        # per-worker best ready op by score
        selected = np.zeros(N, bool)
        for w in range(a.num_workers):
            cand = np.nonzero(ops_ready & (a.op_worker == w))[0]
            if len(cand):
                selected[cand[np.argmax(a.op_score[cand])]] = True
        shortest_op = rem_op[selected].min() if selected.any() else BIG

        if nonflow_ready.any():
            shortest_comm = 0.0
        else:
            shortest_comm = BIG
            for c in range(a.num_channels):
                on_c = np.nonzero(flow_ready
                                  & (a.dep_channel == c).any(axis=1))[0]
                if len(on_c):
                    top = on_c[np.argmax(a.dep_score[on_c])]
                    shortest_comm = min(shortest_comm, rem_dep[top])

        tick = min(shortest_op, shortest_comm)
        if tick >= BIG:
            return t, comm, comp, busy, False

        # advance selected ops (dep readiness was snapshotted above)
        for oi in np.nonzero(selected)[0]:
            rem_op[oi] = rem_op[oi] - min(tick, rem_op[oi])
            if rem_op[oi] == 0.0:
                op_done[oi] = True
        # advance deps from the snapshot
        tick_mask = nonflow_ready if nonflow_ready.any() else flow_ready
        ticked_flows = (not nonflow_ready.any()) and bool(flow_ready.any())
        for ei in np.nonzero(tick_mask)[0]:
            rem_dep[ei] = rem_dep[ei] - min(tick, rem_dep[ei])
            if rem_dep[ei] == 0.0 and not dep_done[ei]:
                dep_done[ei] = True
                if not a.dep_mutual[ei]:
                    parent_done[a.dep_dst[ei]] += 1

        if selected.any() and ticked_flows:
            comm += tick
            comp += tick
        elif ticked_flows:
            comm += tick
        elif selected.any():
            comp += tick
        busy += float(selected.sum()) * tick
        t += tick
    return t, comm, comp, busy, False


def _random_instance(rng: np.random.RandomState) -> LookaheadArrays:
    n = rng.randint(3, 13)
    W = rng.randint(1, min(n, 4) + 1)
    C = rng.randint(1, 4)
    L = rng.randint(1, 3)

    # forward (non-mutual) DAG edges i < j, plus mutual sync pairs
    edges, mutual = [], []
    for j in range(1, n):
        for i in rng.choice(j, size=min(j, rng.randint(1, 3)),
                            replace=False):
            edges.append((int(i), j))
            mutual.append(False)
    for _ in range(rng.randint(0, 3)):
        i, j = rng.choice(n, size=2, replace=False)
        edges.append((int(i), int(j)))
        mutual.append(True)
        edges.append((int(j), int(i)))
        mutual.append(True)
    m = len(edges)

    dep_src = np.array([e[0] for e in edges], np.int32)
    dep_dst = np.array([e[1] for e in edges], np.int32)
    dep_mutual = np.array(mutual)
    num_parents = np.zeros(n, np.int32)
    for (u, v), mu in zip(edges, mutual):
        if not mu:
            num_parents[v] += 1

    dep_is_flow = rng.rand(m) < 0.5
    dep_remaining = np.where(
        dep_is_flow,
        np.round(rng.rand(m) * 10, 2) * (rng.rand(m) < 0.8),
        0.0)
    dep_channel = np.full((m, L), -1, np.int32)
    for ei in np.nonzero(dep_is_flow)[0]:
        k = rng.randint(1, min(L, C) + 1)
        dep_channel[ei, :k] = rng.choice(C, size=k, replace=False)

    return LookaheadArrays(
        op_remaining=np.round(rng.rand(n) * 5, 2) * (rng.rand(n) < 0.9),
        op_valid=np.ones(n, bool),
        op_worker=rng.randint(0, W, size=n).astype(np.int32),
        op_score=(rng.permutation(n) + 1).astype(np.float64),
        num_parents=num_parents,
        dep_remaining=dep_remaining.astype(np.float64),
        dep_valid=np.ones(m, bool),
        dep_src=dep_src, dep_dst=dep_dst,
        dep_mutual=dep_mutual,
        dep_is_flow=dep_is_flow,
        dep_score=(rng.permutation(m) + 1).astype(np.float64),
        dep_channel=dep_channel,
        num_workers=W, num_channels=C)


def test_native_matches_numpy_reference_on_random_instances():
    rng = np.random.RandomState(0)
    solved = 0
    for case in range(300):
        arrays = _random_instance(rng)
        expected = _numpy_reference(arrays)
        got = run_lookahead(arrays)
        if not expected[4]:
            # unfinishable instance: the native engine must bail too
            assert got is None, f"case {case}: native solved a stuck instance"
            continue
        solved += 1
        assert got is not None, f"case {case}: native bailed on solvable"
        assert got == pytest.approx(expected[:4], rel=0, abs=0), \
            f"case {case}: {got} != {expected[:4]}"
    assert solved > 200, f"only {solved} solvable instances generated"


def test_native_block_search_matches_python():
    """The C++ first-fit block search reproduces the Python search
    (shapes -> origins -> cells, first fit) exactly on random snapshots,
    including the diagonal layout and meta-mode whole-extent scans."""
    from ddls_tpu.agents.block_search import (block_shapes_for,
                                              enumerate_block, block_ok,
                                              factor_pairs,
                                              first_fit_block,
                                              _ramp_arrays)
    from ddls_tpu.native import run_first_fit_block

    rng = np.random.RandomState(1)
    for case in range(200):
        ramp_shape = (int(rng.randint(1, 5)), int(rng.randint(1, 5)),
                      int(rng.randint(1, 3)))
        ramp = {}
        for c in range(ramp_shape[0]):
            for r in range(ramp_shape[1]):
                for s in range(ramp_shape[2]):
                    occ = set()
                    if rng.rand() < 0.3:
                        occ.add(int(rng.randint(0, 3)))
                    ramp[(c, r, s)] = {
                        "mem": float(rng.randint(0, 5)),
                        "job_idxs": occ}
        meta_shape = (int(rng.randint(1, ramp_shape[0] + 1)),
                      int(rng.randint(1, ramp_shape[1] + 1)),
                      int(rng.randint(1, ramp_shape[2] + 1)))
        job_idx = int(rng.randint(0, 3))
        num_servers = int(rng.randint(1, 7))
        op_size = float(rng.randint(0, 4))

        shapes = block_shapes_for(factor_pairs(num_servers), meta_shape)
        shapes += [(num_servers, num_servers, -1), (num_servers, 1, 1)]
        expected = first_fit_block(shapes, meta_shape, ramp_shape, ramp,
                                   job_idx, op_size=op_size)
        got = run_first_fit_block(shapes, meta_shape, ramp_shape,
                                  *_ramp_arrays(ramp, ramp_shape, job_idx),
                                  op_size=op_size, meta_scan=False)
        assert got != "unavailable"
        assert (got[0] if got else None) == expected, f"case {case}"

        # meta-mode parity
        expected_meta = None
        for i in range(ramp_shape[0]):
            for j in range(ramp_shape[1]):
                for k in range(ramp_shape[2]):
                    block = enumerate_block(meta_shape, ramp_shape,
                                            (i, j, k))
                    if block_ok(ramp, block, None, job_idx="__meta__"):
                        expected_meta = (block, (i, j, k))
                        break
                if expected_meta:
                    break
            if expected_meta:
                break
        got_meta = run_first_fit_block(
            [meta_shape], meta_shape, ramp_shape,
            *_ramp_arrays(ramp, ramp_shape, "__meta__"),
            op_size=None, meta_scan=True)
        assert got_meta != "unavailable"
        assert got_meta == expected_meta, f"meta case {case}"
