"""L3/L4 tests: partition transforms, collective grouping, placers,
schedulers, and the cluster simulator end to end."""
import numpy as np
import pytest

from ddls_tpu.agents import (FirstFitDepPlacer, RampFirstFitOpPlacer,
                             SRPTDepScheduler, SRPTOpScheduler,
                             sip_ml_num_partitions)
from ddls_tpu.agents.partitioners import build_partition_action
from ddls_tpu.demands.job import Job
from ddls_tpu.graphs.readers import backward_op_id, graph_from_pipedream_txt
from ddls_tpu.sim import (Action, OpPartition, RampClusterEnvironment,
                          partition_graph)
from ddls_tpu.sim.actions import group_collectives


def _chain_profile(tmp_path, n=3):
    lines = []
    for i in range(1, n + 1):
        lines.append(
            f"node{i} -- Op(id={i}) -- forward_compute_time={float(i):.3f}, "
            f"backward_compute_time={2 * float(i):.3f}, "
            f"activation_size={100.0 * i:.1f}, parameter_size={10.0 * i:.1f}")
    for i in range(1, n):
        lines.append(f"node{i} -- node{i + 1}")
    path = tmp_path / "chain.txt"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


# ------------------------------------------------------------------ partition
def test_partition_graph_semantics(tmp_path):
    g = graph_from_pipedream_txt(_chain_profile(tmp_path, n=3))
    # split op 2 (and so its backward op 5) into 2 sub-ops
    pg = partition_graph(g, {"2": 2})

    # ops: 1,3,4,6 unsplit + 2a,2b,5a,5b
    assert set(pg.op_ids) == {"1", "3", "4", "6", "2a", "2b", "5a", "5b"}
    assert pg.compute_cost("2a") == pytest.approx(g.compute_cost("2") / 2)
    assert pg.memory_cost("5b") == pytest.approx(g.memory_cost("5") / 2)

    # data_split re-bases every edge size on the producer's memory cost
    assert pg.edge_size("3", "4") == pytest.approx(g.memory_cost("3"))

    # in-edges to sub-ops: size = parent memory / n
    assert pg.edge_size("1", "2a") == pytest.approx(g.memory_cost("1") / 2)
    # out-edges from sub-ops: size = child memory / n
    assert pg.edge_size("2b", "3") == pytest.approx(g.memory_cost("3") / 2)

    # backward sync clique, both directions, sized at sub-op memory
    assert pg.has_edge("5a", "5b") and pg.has_edge("5b", "5a")
    assert pg.edge_size("5a", "5b") == pytest.approx(g.memory_cost("5") / 2)

    # dep conservation: chain 3 fwd ops had 5 edges; after split of op 2:
    # fwd (1,2a),(1,2b),(2a,3),(2b,3); bwd (4,5a),(4,5b),(5a,6),(5b,6);
    # join (3,4); sync (5a,5b),(5b,5a) -> 11
    assert pg.n_deps == 11


def test_sip_ml_partition_formula():
    # compute 5.0, quantum 1.0 -> ceil(ceil(5)/2)*2 = 6, capped at 4
    assert sip_ml_num_partitions(5.0, 1.0, 8) == 6
    assert sip_ml_num_partitions(5.0, 1.0, 4) == 4
    assert sip_ml_num_partitions(0.5, 1.0, 8) == 2
    assert sip_ml_num_partitions(5.0, 100.0, 8) == 2


def test_group_collectives_conservation(tmp_path):
    g = graph_from_pipedream_txt(_chain_profile(tmp_path, n=3))
    pg = partition_graph(g, {"2": 2})
    orig = Job(g, 1, 1.0, job_id=1, details={"job_idx": 0})
    part = Job(pg, 1, 1.0, job_id=1, details={"job_idx": 0},
               original_job=orig)
    cand, sync, o2o = group_collectives(orig, part, {"2": 2})
    total = sum(len(c) for c in cand) + sum(len(s) for s in sync) + len(o2o)
    assert total == pg.n_deps
    # exactly one sync group with the two directed sync edges
    assert len(sync) == 1
    assert set(sync[0]) == {("5a", "5b"), ("5b", "5a")}


# ------------------------------------------------------- cluster end-to-end
def _make_cluster(**kwargs):
    return RampClusterEnvironment(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        **kwargs)


def _jobs_config(path, steps=5, frac=1.0):
    return {
        "path_to_files": path,
        "job_interarrival_time_dist": {
            "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 1e6},
        "max_acceptable_job_completion_time_frac_dist": {
            "_target_": "ddls_tpu.demands.distributions.Fixed", "val": frac},
        "replication_factor": 1,
        "num_training_steps": steps,
    }


def _heuristic_action(cluster, max_parts):
    """Partition via SiP-ML-style action then run the full heuristic control
    plane, as the PAC-ML env does each step."""
    action_map = {}
    for job_id, job in cluster.job_queue.jobs.items():
        action_map[job_id] = build_partition_action(
            job.graph, min_op_run_time_quantum=0.01,
            max_partitions_per_op=max_parts)
    op_partition = OpPartition(action_map, cluster=cluster)
    op_placement = RampFirstFitOpPlacer().get(op_partition, cluster)
    op_schedule = SRPTOpScheduler().get(op_partition, op_placement, cluster)
    dep_placement = FirstFitDepPlacer().get(op_partition, op_placement, cluster)
    dep_schedule = SRPTDepScheduler().get(op_partition, dep_placement, cluster)
    return Action(op_partition=op_partition, op_placement=op_placement,
                  op_schedule=op_schedule, dep_placement=dep_placement,
                  dep_schedule=dep_schedule)


def test_sequential_placement_matches_seq_jct(tmp_path):
    """Golden invariant: an unpartitioned job placed on one server completes
    in exactly its sequential completion time (all deps are non-flows)."""
    path = str(tmp_path)
    _chain_profile(tmp_path, n=3)
    cluster = _make_cluster()
    cluster.reset(_jobs_config(path, steps=5), max_simulation_run_time=None,
                  seed=0)
    job = list(cluster.job_queue.jobs.values())[0]
    seq = job.seq_completion_time

    action = _heuristic_action(cluster, max_parts=1)
    assert len(action.job_ids) == 1
    cluster.step(action)

    assert len(cluster.jobs_completed) == 1
    done = list(cluster.jobs_completed.values())[0]
    assert done.details["lookahead_job_completion_time"] == pytest.approx(seq)
    assert len(done.details["mounted_workers"]) == 1
    assert cluster.episode_stats["job_completion_time_speedup"][0] == (
        pytest.approx(1.0))


def test_partitioned_job_speedup(tmp_path):
    """Partitioning must speed the job up (compute dominates for these
    profiles) but cost some communication overhead."""
    path = str(tmp_path)
    _chain_profile(tmp_path, n=3)

    cluster = _make_cluster()
    cluster.reset(_jobs_config(path, steps=5), seed=0)
    action = _heuristic_action(cluster, max_parts=4)
    cluster.step(action)
    assert len(cluster.jobs_completed) == 1
    done = list(cluster.jobs_completed.values())[0]
    jct_part = done.details["lookahead_job_completion_time"]
    seq = done.seq_completion_time
    assert jct_part < seq
    assert done.details["communication_overhead_time"] >= 0
    assert len(done.details["mounted_workers"]) > 1


def test_sla_violation_blocks_job(tmp_path):
    """A job whose lookahead JCT exceeds its max acceptable JCT blocks."""
    path = str(tmp_path)
    _chain_profile(tmp_path, n=3)
    cluster = _make_cluster()
    # frac so tight even max partitioning cannot meet it
    cluster.reset(_jobs_config(path, steps=5, frac=0.001), seed=0)
    action = _heuristic_action(cluster, max_parts=2)
    cluster.step(action)
    assert len(cluster.jobs_blocked) == 1
    assert len(cluster.jobs_completed) == 0
    # workers freed again
    assert all(not w.mounted_job_idx_to_ops
               for w in cluster.topology.workers.values())


def test_unhandled_job_blocks(tmp_path):
    path = str(tmp_path)
    _chain_profile(tmp_path, n=3)
    cluster = _make_cluster()
    cluster.reset(_jobs_config(path), seed=0)
    cluster.step(Action())  # empty action handles no jobs
    assert len(cluster.jobs_blocked) == 1


def test_lookahead_memoisation(dataset_dir):
    """Same (model, degree) jobs reuse cached lookahead results."""
    cluster = _make_cluster()
    cfg = _jobs_config(dataset_dir, steps=5)
    cfg["replication_factor"] = 3
    cfg["job_sampling_mode"] = "remove"  # finite pool so the episode ends
    cfg["job_interarrival_time_dist"] = {
        "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 10.0}
    cluster.reset(cfg, max_simulation_run_time=None, seed=0)
    steps = 0
    while not cluster.is_done() and steps < 50:
        if len(cluster.job_queue):
            cluster.step(_heuristic_action(cluster, max_parts=2))
        else:
            cluster.step(Action())
        steps += 1
    assert cluster.is_done()
    n_outcomes = (cluster.episode_stats["num_jobs_completed"]
                  + cluster.episode_stats["num_jobs_blocked"])
    assert n_outcomes == cluster.episode_stats["num_jobs_arrived"] == 9
    # 3 distinct models x 1 degree -> at most 3+ cache entries, far fewer
    # than the 9 jobs simulated
    assert len(cluster.lookahead_cache) <= 6


def test_ramp_rule_one_job_per_worker(tmp_path):
    """Two jobs may never share a worker; the placer must avoid occupied
    servers."""
    path = str(tmp_path)
    _chain_profile(tmp_path, n=3)
    cluster = _make_cluster()
    cfg = _jobs_config(path, steps=10000)
    cfg["replication_factor"] = 2
    cfg["job_interarrival_time_dist"] = {
        "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 1.0}
    cluster.reset(cfg, max_simulation_run_time=None, seed=0)
    # place job 1 on the cluster (long running)
    cluster.step(_heuristic_action(cluster, max_parts=2))
    assert len(cluster.jobs_running) == 1
    occupied_before = {w for w, worker in cluster.topology.workers.items()
                      if worker.mounted_job_idx_to_ops}
    # job 2 arrives; placing it must not reuse occupied workers
    assert len(cluster.job_queue) == 1
    cluster.step(_heuristic_action(cluster, max_parts=2))
    if len(cluster.jobs_running) == 2:
        jobs = list(cluster.jobs_running.values())
        w1 = jobs[0].details["mounted_workers"]
        w2 = jobs[1].details["mounted_workers"]
        assert not (w1 & w2)


def test_memo_caches_persist_across_resets_same_workload(tmp_path):
    """Exact-keyed partition/lookahead memos survive reset() while the
    workload is unchanged (training episodes 2+ reuse them) and are
    dropped when the dataset or num_training_steps changes (which scales
    cached lookahead results)."""
    _chain_profile(tmp_path, n=3)
    path = str(tmp_path)
    cluster = _make_cluster()
    cluster.reset(_jobs_config(path, steps=5), max_simulation_run_time=None,
                  seed=0)
    cluster.step(_heuristic_action(cluster, max_parts=2))
    assert cluster.lookahead_cache, "expected a cached lookahead"
    cached = dict(cluster.lookahead_cache)

    # same workload: caches persist
    cluster.reset(_jobs_config(path, steps=5), max_simulation_run_time=None,
                  seed=1)
    assert cluster.lookahead_cache == cached

    # changed num_training_steps: caches dropped (values scale by steps)
    cluster.reset(_jobs_config(path, steps=7), max_simulation_run_time=None,
                  seed=1)
    assert not cluster.lookahead_cache

    # and outcomes with a warm cache match a cold-cache run exactly
    def episode_outcome(cl):
        cl.step(_heuristic_action(cl, max_parts=2))
        job = next(iter(cl.jobs_running.values()), None)
        if job is None:
            job = next(iter(cl.jobs_completed.values()))
        return job.details["lookahead_job_completion_time"]

    cluster.reset(_jobs_config(path, steps=5), max_simulation_run_time=None,
                  seed=2)
    cold = episode_outcome(cluster)  # steps=5 cache was just dropped
    cluster.reset(_jobs_config(path, steps=5), max_simulation_run_time=None,
                  seed=2)
    warm = episode_outcome(cluster)
    assert warm == cold


def test_pricing_memo_hit_equals_fresh_pricing(dataset_dir):
    """The whole-result pricing memo (partition-cache entry, keyed by the
    per-op server-code bytes) must serve arrays identical to a fresh
    pricing pass — CLAUDE.md's memo-exactness practice for the new cache.
    Partition/pricing caches persist across resets, so episode 2 with the
    same seed replays the same placements as memo HITS."""
    cluster = _make_cluster()
    cfg = _jobs_config(dataset_dir, steps=5)
    cfg["replication_factor"] = 3
    cfg["job_interarrival_time_dist"] = {
        "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 10.0}

    def first_priced_times(seed):
        cluster.reset(cfg, max_simulation_run_time=None, seed=seed)
        cluster.step(_heuristic_action(cluster, max_parts=2))
        job = next(iter(cluster.jobs_running.values()), None) or \
            next(iter(cluster.jobs_completed.values()))
        return np.array(job.dep_init_run_time_arr, copy=True)

    fresh = first_priced_times(seed=0)  # cold: group walk runs
    memos = [e.get("pricing") for e in cluster.partition_cache.values()
             if e.get("pricing")]
    assert memos, "pricing memo never populated"
    assert all(arr.dtype == np.float64
               for memo in memos for arr in memo.values())
    n_entries = sum(len(m) for m in memos)

    hit = first_priced_times(seed=0)  # same seed -> same placement -> hit
    memos2 = [e.get("pricing") for e in cluster.partition_cache.values()
              if e.get("pricing")]
    assert sum(len(m) for m in memos2) == n_entries, (
        "memo grew on a replayed placement: the hit path never fired")
    np.testing.assert_array_equal(hit, fresh)


def test_fast_lookahead_key_matches_dict_walk(dataset_dir):
    """The vectorised code-array key path must produce byte-identical
    tuples to lookahead_key_for's dict walk on real placements (the
    candidate-pricing prefetch relies on exact equality)."""
    cluster = _make_cluster()
    cfg = _jobs_config(dataset_dir, steps=5)
    cfg["replication_factor"] = 3
    cfg["job_interarrival_time_dist"] = {
        "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 10.0}
    cluster.reset(cfg, max_simulation_run_time=None, seed=0)
    checked = 0
    for max_parts in (1, 2, 4):
        if not len(cluster.job_queue):
            cluster.step(Action())
        if cluster.is_done():
            break
        cluster.step(_heuristic_action(cluster, max_parts=max_parts))
        for job_idx, job in list(cluster.jobs_running.items()):
            job_id = cluster.job_idx_to_job_id[job_idx]
            if job_id not in cluster.op_partition.job_id_to_split_forward_ops:
                continue
            split = tuple(sorted(cluster.op_partition
                                 .job_id_to_split_forward_ops[job_id]
                                 .items()))
            fast = cluster._lookahead_cache_key(job, job_id)
            slow = cluster.lookahead_key_for(
                job, split, cluster.job_op_to_worker[job_idx])
            assert fast == slow
            assert cluster.job_server_codes.get(job_idx) is not None
            checked += 1
    assert checked >= 2
