"""Invariant lint engine self-tests (ISSUE 9, ddls_tpu/lint, docs/lint.md).

Per-rule fixture trees — one clean, one violating, one
suppressed-with-reason each — prove every rule fires on its target
pattern and every suppression path works; engine-level tests pin the
mandatory-reason contract, the stale-allowance guard (an unknown-file
allowance entry is itself a lint error), the parse-each-file-exactly-once
budget, and the tier-1 real-tree clean run that replaces the three
separate guard-script invocations with ONE engine call
(``python scripts/lint.py --json``). The legacy shim CLIs stay covered by
their original homes (tests/test_telemetry.py, test_flight.py,
test_shm.py)."""
import ast
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from ddls_tpu.lint import (ALL_RULES, Config, get_rules,  # noqa: E402
                           run_lint)

RULE_IDS = [r.id for r in ALL_RULES]


def lint_tree(tmp_path, files, rule, config=None):
    """Run ONE rule over a synthetic tree rooted (and repo-rooted) at
    ``tmp_path`` — rels in findings/config keys are then bare names."""
    for name, text in files.items():
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(text)
    return run_lint(roots=[str(tmp_path)], repo_root=str(tmp_path),
                    rules=get_rules([rule]),
                    config=Config(config or {}))


def errors_of(result, rule):
    return [f for f in result.errors if f.rule == rule]


# ------------------------------------------------------------ registry
def test_registry_has_all_ten_rules():
    assert RULE_IDS == [
        "bare-timers", "flight-gated", "shm-unlink", "socket-lifecycle",
        "hot-path-transfer", "multihost-deterministic-gates",
        "telemetry-gated", "flow-mask", "frozen-param-tree",
        "backend-surface-parity"]


def test_get_rules_rejects_unknown_id():
    with pytest.raises(ValueError, match="unknown lint rule"):
        get_rules(["bare-timers", "no-such-rule"])


# ---------------------------------------------------------- bare-timers
TIMER_BAD = ("import time\n"
             "t0 = time.perf_counter()\n"
             "dt = time.perf_counter() - t0\n")


def test_bare_timers_fires(tmp_path):
    # one finding PER occurrence beyond the allowance, each on its line
    res = lint_tree(tmp_path, {"hot.py": TIMER_BAD}, "bare-timers")
    found = errors_of(res, "bare-timers")
    assert [(f.rel, f.line) for f in found] == [("hot.py", 2),
                                               ("hot.py", 3)]
    assert "allowance 0" in found[0].message


def test_bare_timers_clean(tmp_path):
    res = lint_tree(tmp_path, {"ok.py": "import time\nx = time.time()\n"},
                    "bare-timers")
    assert res.errors == []


def test_bare_timers_suppressed_with_reason(tmp_path):
    src = ("import time\n"
           "t0 = time.perf_counter()  # ddls-lint: allow(bare-timers) "
           "-- injected default clock, never reported\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "bare-timers")
    assert res.errors == []
    (f,) = [f for f in res.findings if f.suppressed]
    assert f.suppress_reason == "injected default clock, never reported"


def test_bare_timers_config_allowance(tmp_path):
    res = lint_tree(tmp_path, {"hot.py": TIMER_BAD}, "bare-timers",
                    {"bare-timers": {"allow": {"hot.py": 2}}})
    assert res.errors == []


def test_bare_timers_inline_suppression_covers_only_its_line(tmp_path):
    # a suppressed occurrence must not green-light future bare timers
    # elsewhere in the file
    src = ("import time\n"
           "t0 = time.perf_counter()  # ddls-lint: allow(bare-timers) "
           "-- injectable clock default\n"
           "t1 = time.perf_counter()\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "bare-timers")
    (f,) = errors_of(res, "bare-timers")
    assert f.line == 3
    assert any(x.suppressed and x.line == 2 for x in res.findings)


def test_bare_timers_over_allowance_flags_every_line(tmp_path):
    # a count allowance has no line identity: when a NEW timer lands
    # BEFORE the audited occurrence, flagging a positional subset would
    # point at the audited line — every unsuppressed line is flagged
    src = ("import time\n"
           "t_new = time.perf_counter()\n"
           "t_audited = time.perf_counter()\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "bare-timers",
                    {"bare-timers": {"allow": {"hot.py": 1}}})
    assert [f.line for f in errors_of(res, "bare-timers")] == [2, 3]


def test_bare_timers_config_and_inline_mix_is_error(tmp_path):
    # combined, an inline suppression could mask which occurrence is
    # new — the mechanisms are exclusive per file
    src = ("import time\n"
           "t0 = time.perf_counter()  # ddls-lint: allow(bare-timers) "
           "-- injectable clock\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "bare-timers",
                    {"bare-timers": {"allow": {"hot.py": 1}}})
    assert any("mixes" in f.message and "inline suppressions" in f.message
               for f in errors_of(res, "bare-timers"))


def test_bare_timers_non_int_allowance_is_config_error_not_crash(tmp_path):
    # a maintainer copying the hot-path-transfer "path" = "why" shape
    # must get a config finding, not a ValueError traceback
    res = lint_tree(tmp_path, {"hot.py": TIMER_BAD}, "bare-timers",
                    {"bare-timers": {"allow": {"hot.py": "clock param"}}})
    msgs = [f.message for f in errors_of(res, "bare-timers")]
    assert any("must be an integer occurrence count" in m for m in msgs)
    # and the malformed value grants nothing: the occurrences still fire
    assert any("bare perf_counter" in m for m in msgs)


def test_bare_timers_overgranted_allowance_is_stale(tmp_path):
    # an allowance above the file's actual count is green headroom for
    # NEW bare timers — flagged as stale, like a deleted-file entry
    res = lint_tree(tmp_path, {"hot.py": TIMER_BAD}, "bare-timers",
                    {"bare-timers": {"allow": {"hot.py": 5}}})
    (f,) = errors_of(res, "bare-timers")
    assert f.rel == "pyproject.toml"
    assert "stale" in f.message and "grants 5" in f.message


# --------------------------------------------------------- flight-gated
FLIGHT_BAD = ("from ddls_tpu.telemetry import flight as _flight\n"
              "def step(t):\n"
              "    _flight.emit('tick', t=t)\n"
              "    if _flight.enabled():\n"
              "        _flight.emit('ok', t=t)\n"
              "    _flight.enable()\n")


def test_flight_gated_fires(tmp_path):
    res = lint_tree(tmp_path, {"hot.py": FLIGHT_BAD}, "flight-gated")
    lines = [f.line for f in errors_of(res, "flight-gated")]
    assert lines == [3, 6]  # ungated emit + switch; gated emit clean


def test_flight_gated_clean(tmp_path):
    src = ("from ddls_tpu.telemetry import flight as _flight\n"
           "def step(t):\n"
           "    if _flight.enabled():\n"
           "        _flight.emit('tick', t=t)\n")
    res = lint_tree(tmp_path, {"ok.py": src}, "flight-gated")
    assert res.errors == []


def test_flight_gated_inverted_gate_is_not_a_guard(tmp_path):
    # `if not _flight.enabled():` runs its BODY when the recorder is
    # OFF — an emit there is exactly the violation; the ELSE branch is
    # the guarded side
    src = ("from ddls_tpu.telemetry import flight as _flight\n"
           "def step(t):\n"
           "    if not _flight.enabled():\n"
           "        _flight.emit('oops', t=t)\n"
           "    else:\n"
           "        _flight.emit('ok', t=t)\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "flight-gated")
    assert [f.line for f in errors_of(res, "flight-gated")] == [4]


def test_flight_gated_suppressed(tmp_path):
    src = ("from ddls_tpu.telemetry import flight as _flight\n"
           "_flight.emit('boot')  # ddls-lint: allow(flight-gated) "
           "-- module-import one-shot, not a hot path\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "flight-gated")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


# ----------------------------------------------------------- shm-unlink
SHM_BAD = ("from multiprocessing import shared_memory\n"
           "seg = shared_memory.SharedMemory(create=True, size=64)\n")
SHM_GOOD = ("import weakref\n"
            "from multiprocessing import shared_memory\n"
            "seg = shared_memory.SharedMemory(create=True, size=64)\n"
            "weakref.finalize(seg, seg.unlink)\n"
            "seg.unlink()\n")


def test_shm_unlink_fires(tmp_path):
    res = lint_tree(tmp_path, {"leaky.py": SHM_BAD}, "shm-unlink")
    (f,) = errors_of(res, "shm-unlink")
    assert f.line == 2
    assert "unlink" in f.message and "finalizer" in f.message


def test_shm_unlink_clean(tmp_path):
    res = lint_tree(tmp_path, {"ok.py": SHM_GOOD}, "shm-unlink")
    assert res.errors == []


def test_shm_unlink_inline_suppression_covers_only_its_create(tmp_path):
    src = ("from multiprocessing import shared_memory\n"
           "a = shared_memory.SharedMemory(create=True, size=64)  "
           "# ddls-lint: allow(shm-unlink) -- tracker-owned scratch\n"
           "b = shared_memory.SharedMemory(create=True, size=64)\n")
    res = lint_tree(tmp_path, {"leaky.py": src}, "shm-unlink")
    (f,) = errors_of(res, "shm-unlink")
    assert f.line == 3


def test_shm_unlink_overgranted_allowance_is_stale(tmp_path):
    # allowance 2 covers the single create (no violation finding) but
    # the unused grant is itself stale; an exact grant stays clean
    res = lint_tree(tmp_path, {"leaky.py": SHM_BAD}, "shm-unlink",
                    {"shm-unlink": {"allow": {"leaky.py": 2}}})
    (f,) = errors_of(res, "shm-unlink")
    assert f.rel == "pyproject.toml"
    assert "stale" in f.message and "grants 2" in f.message
    res = lint_tree(tmp_path, {"leaky.py": SHM_BAD}, "shm-unlink",
                    {"shm-unlink": {"allow": {"leaky.py": 1}}})
    assert res.errors == []


def test_shm_unlink_multi_segment_triple(tmp_path):
    """ISSUE 15 fixture: a trajectory-ring-shaped file creating THREE
    segments must flag every create line when the pairing is missing,
    and go clean once the unlink + finalizer pair appears (one pairing
    covers all segments of a ring, as SlabSet does per segment)."""
    triple = ("from multiprocessing import shared_memory\n"
              "ring = [shared_memory.SharedMemory(create=True, size=64),\n"
              "        shared_memory.SharedMemory(create=True, size=64),\n"
              "        shared_memory.SharedMemory(create=True, size=64)]\n")
    res = lint_tree(tmp_path, {"ring.py": triple}, "shm-unlink")
    flagged = errors_of(res, "shm-unlink")
    assert [f.line for f in flagged] == [2, 3, 4]
    assert all("3 create(s)" in f.message for f in flagged)

    paired = (triple
              + "import weakref\n"
              + "for seg in ring:\n"
              + "    weakref.finalize(seg, seg.unlink)\n"
              + "    seg.unlink()\n")
    res = lint_tree(tmp_path, {"ring.py": paired}, "shm-unlink")
    assert res.errors == []


def test_shm_unlink_suppressed(tmp_path):
    src = ("from multiprocessing import shared_memory\n"
           "seg = shared_memory.SharedMemory(create=True, size=64)  "
           "# ddls-lint: allow(shm-unlink) -- tracker-owned scratch "
           "segment, unlinked by the resource tracker\n")
    res = lint_tree(tmp_path, {"scratch.py": src}, "shm-unlink")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


# ------------------------------------------------------ socket-lifecycle
SOCK_BAD = ("import socket\n"
            "lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)\n"
            "conn, _ = lst.accept()\n")
SOCK_GOOD = ("import socket\n"
             "import weakref\n"
             "lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)\n"
             "conn, _ = lst.accept()\n"
             "weakref.finalize(lst, lst.close)\n"
             "conn.close()\n")


def test_socket_lifecycle_fires(tmp_path):
    # one finding per create site (socket() AND accept()), each on its
    # line, naming what is missing
    res = lint_tree(tmp_path, {"leaky.py": SOCK_BAD}, "socket-lifecycle")
    found = errors_of(res, "socket-lifecycle")
    assert [(f.rel, f.line) for f in found] == [("leaky.py", 2),
                                               ("leaky.py", 3)]
    assert all("close" in f.message and "finalizer" in f.message
               for f in found)


def test_socket_lifecycle_clean(tmp_path):
    res = lint_tree(tmp_path, {"ok.py": SOCK_GOOD}, "socket-lifecycle")
    assert res.errors == []


def test_socket_lifecycle_import_only_not_flagged(tmp_path):
    # `import socket` for gethostname() creates nothing (runlog.py)
    src = "import socket\nhost = socket.gethostname()\n"
    res = lint_tree(tmp_path, {"host.py": src}, "socket-lifecycle")
    assert res.findings == []


def test_socket_lifecycle_inline_suppression_covers_only_its_create(
        tmp_path):
    src = ("import socket\n"
           "a = socket.socket()  "
           "# ddls-lint: allow(socket-lifecycle) -- caller-owned fd\n"
           "b = socket.socket()\n")
    res = lint_tree(tmp_path, {"leaky.py": src}, "socket-lifecycle")
    (f,) = errors_of(res, "socket-lifecycle")
    assert f.line == 3
    assert any(x.suppressed and x.line == 2 for x in res.findings)


def test_socket_lifecycle_overgranted_allowance_is_stale(tmp_path):
    src = "import socket\ns = socket.socket()\n"
    res = lint_tree(tmp_path, {"leaky.py": src}, "socket-lifecycle",
                    {"socket-lifecycle": {"allow": {"leaky.py": 2}}})
    (f,) = errors_of(res, "socket-lifecycle")
    assert f.rel == "pyproject.toml"
    assert "stale" in f.message and "grants 2" in f.message
    res = lint_tree(tmp_path, {"leaky.py": src}, "socket-lifecycle",
                    {"socket-lifecycle": {"allow": {"leaky.py": 1}}})
    assert res.errors == []


# ---------------------------------------------------- hot-path-transfer
HOT_BAD = ("def drain(metrics):\n"
           "    return {k: float(v) for k, v in metrics.items()}\n"
           "def fetch(arr):\n"
           "    return arr.item()\n")


def test_hot_path_transfer_fires(tmp_path):
    res = lint_tree(tmp_path, {"loops.py": HOT_BAD}, "hot-path-transfer")
    msgs = [f.message for f in errors_of(res, "hot-path-transfer")]
    assert len(msgs) == 2
    assert any("float(...)" in m and "(in drain)" in m for m in msgs)
    assert any(".item()" in m and "(in fetch)" in m for m in msgs)


def test_hot_path_transfer_clean(tmp_path):
    src = ("import jax\n"
           "def drain(metrics):\n"
           "    return jax.device_get(metrics)\n")
    res = lint_tree(tmp_path, {"loops.py": src}, "hot-path-transfer")
    assert res.errors == []


def test_hot_path_transfer_suppressed(tmp_path):
    src = ("def drain(metrics):\n"
           "    return float(metrics)  # ddls-lint: "
           "allow(hot-path-transfer) -- eval boundary, one per epoch\n")
    res = lint_tree(tmp_path, {"loops.py": src}, "hot-path-transfer")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


def test_hot_path_transfer_qualname_allowance(tmp_path):
    cfg = {"hot-path-transfer": {
        "allow": {"loops.py::drain": "sanctioned sync boundary"}}}
    res = lint_tree(tmp_path, {"loops.py": HOT_BAD}, "hot-path-transfer",
                    cfg)
    # drain is allowlisted, fetch still fires
    msgs = [f.message for f in errors_of(res, "hot-path-transfer")]
    assert len(msgs) == 1 and "(in fetch)" in msgs[0]


def test_hot_path_transfer_stale_qualname_allowance_is_error(tmp_path):
    cfg = {"hot-path-transfer": {
        "allow": {"loops.py::gone": "was removed"}}}
    res = lint_tree(tmp_path, {"loops.py": HOT_BAD}, "hot-path-transfer",
                    cfg)
    assert any("no function 'gone'" in f.message
               for f in errors_of(res, "hot-path-transfer"))


def test_hot_path_transfer_fused_driver_shape(tmp_path):
    """PR 8: the fused driver's epoch body must stay coercion-free
    while its drain-boundary harvest (already-fetched numpy) is
    config-allowlisted — the exact pyproject shape rl/fused.py ships
    with."""
    src = ("import numpy as np\n"
           "class FusedEpochDriver:\n"
           "    def fused_epoch(self, state, rngs):\n"
           "        return float(state.step)\n"
           "    def harvest_episodes(self, ep):\n"
           "        return [int(x) for x in np.asarray(ep['done'])]\n")
    cfg = {"hot-path-transfer": {
        "allow": {"fused.py::FusedEpochDriver.harvest_episodes":
                  "records from the already-fetched host trace"}}}
    res = lint_tree(tmp_path, {"fused.py": src}, "hot-path-transfer",
                    cfg)
    msgs = [f.message for f in errors_of(res, "hot-path-transfer")]
    assert len(msgs) == 1
    assert "float(...)" in msgs[0] and "fused_epoch" in msgs[0]


# ------------------------------------------- multihost-deterministic-gates
GATE_BAD = ("import time\n"
            "def run(self, learner, x):\n"
            "    if time.time() % 2 > 1:\n"
            "        learner.train_step(x)\n")
GATE_EARLY_RETURN = ("import os\n"
                     "def run(self, learner, x):\n"
                     "    if os.environ.get('SKIP'):\n"
                     "        return\n"
                     "    learner.train_step(x)\n")


def test_multihost_gates_fires(tmp_path):
    res = lint_tree(tmp_path, {"loop.py": GATE_BAD},
                    "multihost-deterministic-gates")
    (f,) = errors_of(res, "multihost-deterministic-gates")
    assert f.line == 4 and "train_step" in f.message
    assert "time.time" in f.message


def test_multihost_gates_early_return_guard_fires(tmp_path):
    res = lint_tree(tmp_path, {"loop.py": GATE_EARLY_RETURN},
                    "multihost-deterministic-gates")
    (f,) = errors_of(res, "multihost-deterministic-gates")
    assert "os.environ" in f.message


def test_multihost_gates_clean_deterministic(tmp_path):
    src = ("import jax\n"
           "def run(self, learner, x, epoch, rng):\n"
           "    if epoch % self.sync_interval == 0:\n"
           "        learner.train_step(x)\n"
           "    if float(jax.random.uniform(rng)) < 0.5:\n"
           "        learner.update(x)\n")
    res = lint_tree(tmp_path, {"loop.py": src},
                    "multihost-deterministic-gates")
    assert res.errors == []


def test_multihost_gates_sees_inside_match_statements(tmp_path):
    src = ("import time\n"
           "def run(self, learner, x, mode):\n"
           "    match mode:\n"
           "        case 'fast':\n"
           "            if time.time() > self.deadline:\n"
           "                learner.train_step(x)\n")
    res = lint_tree(tmp_path, {"loop.py": src},
                    "multihost-deterministic-gates")
    (f,) = errors_of(res, "multihost-deterministic-gates")
    assert f.line == 6 and "train_step" in f.message


def test_multihost_gates_dict_update_is_not_a_collective(tmp_path):
    # `update` is receiver-qualified: cfg.update(...) is a dict method,
    # learner.update(...) is the sharded call
    src = ("import os\n"
           "def merge(self, cfg, overrides, learner, x):\n"
           "    if os.environ.get('WANDB_MODE'):\n"
           "        cfg.update(overrides)\n"
           "    if os.environ.get('FAST'):\n"
           "        self.learner.update(x)\n")
    res = lint_tree(tmp_path, {"loop.py": src},
                    "multihost-deterministic-gates")
    (f,) = errors_of(res, "multihost-deterministic-gates")
    assert f.line == 6 and "update" in f.message


def test_multihost_gates_covers_fused_epoch_calls(tmp_path):
    """PR 8 coverage: the fused epoch dispatch (rl/fused.py) is a
    guarded call — a nondeterministic gate around it is the same
    desynced-collective hang as one around train_step."""
    src = ("import time\n"
           "def run(self, state, rngs):\n"
           "    if time.time() > self.deadline:\n"
           "        self.fused.fused_epoch(state, rngs)\n")
    res = lint_tree(tmp_path, {"fused.py": src},
                    "multihost-deterministic-gates")
    (f,) = errors_of(res, "multihost-deterministic-gates")
    assert f.line == 4 and "fused_epoch" in f.message


def test_multihost_gates_fused_cached_config_gate_clean(tmp_path):
    # the autotuner fallback contract: the fused-vs-pipelined gate is a
    # pure function of the CACHED config (+ epoch counters) — that
    # shape must lint clean
    src = ("def run(self, state, rngs):\n"
           "    if self.autotune_result.source != 'failed':\n"
           "        self.fused.fused_epoch(state, rngs)\n"
           "    if self.epoch_counter % self.sync_interval == 0:\n"
           "        self.fused.fused_epoch(state, rngs)\n")
    res = lint_tree(tmp_path, {"fused.py": src},
                    "multihost-deterministic-gates")
    assert res.errors == []


def test_multihost_gates_fused_epoch_suppressed(tmp_path):
    src = ("import os\n"
           "def run(self, state, rngs):\n"
           "    if os.environ.get('FORCE_FUSED'):\n"
           "        self.fused.fused_epoch(state, rngs)  # ddls-lint: "
           "allow(multihost-deterministic-gates) -- single-process "
           "debug hook, fused rejects multi-host at build\n")
    res = lint_tree(tmp_path, {"fused.py": src},
                    "multihost-deterministic-gates")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


def test_rules_scope_covers_fused_driver():
    """The PR 8 scope extension itself: rl/fused.py is on the
    hot-path-transfer module list AND inside the multihost rule's
    scan scope (train/ alone no longer bounds the collective surface)."""
    from ddls_tpu.lint.rules.hot_path_transfer import DEFAULT_MODULES
    from ddls_tpu.lint.rules.multihost_gates import (
        DEFAULT_GUARDED_CALLS, MultihostGatesRule)

    assert "ddls_tpu/rl/fused.py" in DEFAULT_MODULES
    assert "fused_epoch" in DEFAULT_GUARDED_CALLS
    rule = MultihostGatesRule()
    assert rule.in_scope("ddls_tpu/rl/fused.py")
    assert rule.in_scope("ddls_tpu/train/loops.py")
    assert not rule.in_scope("ddls_tpu/rl/ppo.py")


def test_multihost_gates_suppressed(tmp_path):
    src = ("import time\n"
           "def run(self, learner, x):\n"
           "    if time.time() > self.deadline:\n"
           "        learner.train_step(x)  # ddls-lint: "
           "allow(multihost-deterministic-gates) -- single-process "
           "tool, never launched multi-host\n")
    res = lint_tree(tmp_path, {"loop.py": src},
                    "multihost-deterministic-gates")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


# ------------------------------------------------------- telemetry-gated
TEL_BAD = ("from ddls_tpu import telemetry\n"
           "def step(sizes):\n"
           "    telemetry.inc('sim.bytes', sum(sizes))\n"
           "    telemetry.enable()\n")


def test_telemetry_gated_fires(tmp_path):
    res = lint_tree(tmp_path, {"hot.py": TEL_BAD}, "telemetry-gated")
    lines = [f.line for f in errors_of(res, "telemetry-gated")]
    assert lines == [3, 4]  # computed-arg inc + switch


def test_telemetry_gated_clean(tmp_path):
    src = ("from ddls_tpu import telemetry\n"
           "def step(n, sizes):\n"
           "    telemetry.inc('sim.steps', n)\n"  # trivial args: legal
           "    if telemetry.enabled():\n"
           "        telemetry.inc('sim.bytes', sum(sizes))\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "telemetry-gated")
    assert res.errors == []


def test_telemetry_gated_suppressed(tmp_path):
    src = ("from ddls_tpu import telemetry\n"
           "def close(self):\n"
           "    telemetry.inc('sim.final', self.a + self.b)  "
           "# ddls-lint: allow(telemetry-gated) -- close() runs once, "
           "not a hot path\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "telemetry-gated")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


def test_telemetry_gated_relative_import_fires(tmp_path):
    # `from .. import telemetry` (the natural in-package refactor of the
    # absolute import) must not silently disable gating enforcement
    src = ("from .. import telemetry\n"
           "def step(sizes):\n"
           "    telemetry.inc('sim.bytes', sum(sizes))\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "telemetry-gated")
    (f,) = errors_of(res, "telemetry-gated")
    assert f.line == 3


def test_telemetry_gated_dotted_import_fires(tmp_path):
    # unaliased `import ddls_tpu.telemetry` reaches the API through the
    # full dotted path — the call target is an Attribute chain, not a
    # bare Name, and must still be resolved
    src = ("import ddls_tpu.telemetry\n"
           "def step(x):\n"
           "    ddls_tpu.telemetry.inc('sim.' + str(x), 1)\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "telemetry-gated")
    (f,) = errors_of(res, "telemetry-gated")
    assert f.line == 3


# ------------------------------------------------------------ flow-mask
FLOW_BAD = ("def pack(size, src, dst):\n"
            "    is_flow = size > 0 and src != dst\n"
            "    return is_flow\n")


def test_flow_mask_fires(tmp_path):
    res = lint_tree(tmp_path, {"packer.py": FLOW_BAD}, "flow-mask")
    (f,) = errors_of(res, "flow-mask")
    assert f.line == 2 and "flow_mask_from_codes" in f.message


def test_flow_mask_fires_on_bitwise_chain(tmp_path):
    src = ("def pack(dep_size, sc_src, sc_dst, valid):\n"
           "    return valid & (dep_size > 0) & (sc_src != sc_dst)\n")
    res = lint_tree(tmp_path, {"packer.py": src}, "flow-mask")
    assert len(errors_of(res, "flow-mask")) == 1


def test_flow_mask_clean_in_defining_module_and_elsewhere(tmp_path):
    # the canonical helper's own body is exempt (defining_module) and a
    # non-flow `and` chain elsewhere does not match the fingerprint
    cfg = {"flow-mask": {"defining_module": "op_graph.py"}}
    res = lint_tree(tmp_path, {
        "op_graph.py": ("def flow_mask_from_codes(size, a, b):\n"
                        "    return (size > 0) & (a != b)\n"),
        "other.py": ("def ready(n, state):\n"
                     "    return n > 0 and state is None\n"),
    }, "flow-mask", cfg)
    assert res.errors == []


def test_flow_mask_suppressed(tmp_path):
    src = ("def traced(dep_size, sc_src, sc_dst):\n"
           "    return (dep_size > 0) & (sc_src != sc_dst)  "
           "# ddls-lint: allow(flow-mask) -- traced mirror, numpy "
           "helper cannot run under jit\n")
    res = lint_tree(tmp_path, {"kernel.py": src}, "flow-mask")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


# ----------------------------------------------------- frozen-param-tree
NET_SRC = ("class Net:\n"
           "    def setup(self):\n"
           "        self.gnn = 1\n"
           "        self.logit_head = 2\n")


def test_frozen_param_tree_unregistered_class_fires(tmp_path):
    res = lint_tree(tmp_path, {"net.py": NET_SRC}, "frozen-param-tree")
    (f,) = errors_of(res, "frozen-param-tree")
    assert "no frozen-param-tree entry" in f.message


def test_frozen_param_tree_drift_fires(tmp_path):
    cfg = {"frozen-param-tree": {"classes": {
        "net.py::Net": ["gnn", "value_head"]}}}
    res = lint_tree(tmp_path, {"net.py": NET_SRC}, "frozen-param-tree",
                    cfg)
    (f,) = errors_of(res, "frozen-param-tree")
    assert "unexpected ['logit_head']" in f.message
    assert "missing ['value_head']" in f.message


def test_frozen_param_tree_clean(tmp_path):
    cfg = {"frozen-param-tree": {"classes": {
        "net.py::Net": ["gnn", "logit_head"]}}}
    res = lint_tree(tmp_path, {"net.py": NET_SRC}, "frozen-param-tree",
                    cfg)
    assert res.errors == []


def test_frozen_param_tree_stale_class_entry_is_error(tmp_path):
    cfg = {"frozen-param-tree": {"classes": {
        "net.py::Gone": ["gnn"]}}}
    res = lint_tree(tmp_path, {"net.py": NET_SRC}, "frozen-param-tree",
                    cfg)
    assert any("no class 'Gone'" in f.message
               for f in errors_of(res, "frozen-param-tree"))


def test_frozen_param_tree_suppressed(tmp_path):
    src = ("class Probe:\n"
           "    def setup(self):  # ddls-lint: allow(frozen-param-tree) "
           "-- test-only module, no shipped checkpoint\n"
           "        self.head = 1\n")
    res = lint_tree(tmp_path, {"probe.py": src}, "frozen-param-tree")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


# partition-rule table cross-validation (same rule id, ISSUE 19): the
# fixture module is only PARSED — P/PartitionSpec need not resolve.
def _part_src(fsdp_rules):
    return (
        'FSDP_AXIS = "dp"\n'
        "CANONICAL_PARAM_PATHS = (\n"
        '    "gnn/Dense_0/kernel",\n'
        '    "gnn/Dense_0/bias",\n'
        '    "logit_head/Dense_0/kernel",\n'
        ")\n"
        'LARGE_KERNEL_PATHS = ("logit_head/Dense_0/kernel",)\n'
        "PARTITION_RULES = {\n"
        '    "replicated": ((r".*", P()),),\n'
        '    "fsdp": (\n'
        + fsdp_rules +
        "    ),\n"
        "}\n")


def test_partition_table_clean(tmp_path):
    src = _part_src(
        '        (r"Dense_\\d+/kernel$", P(FSDP_AXIS, None)),\n'
        '        (r"Dense_\\d+/bias$", P()),\n')
    res = lint_tree(tmp_path, {"partition.py": src}, "frozen-param-tree")
    assert res.errors == []


def test_partition_table_stale_rule_fires(tmp_path):
    src = _part_src(
        '        (r"decoder/Dense_\\d+/kernel$", P(FSDP_AXIS, None)),\n'
        '        (r"Dense_\\d+/kernel$", P(FSDP_AXIS, None)),\n'
        '        (r"Dense_\\d+/bias$", P()),\n')
    res = lint_tree(tmp_path, {"partition.py": src}, "frozen-param-tree")
    (f,) = errors_of(res, "frozen-param-tree")
    assert "matches no CANONICAL_PARAM_PATHS entry" in f.message
    assert "decoder" in f.message


def test_partition_table_uncovered_path_fires(tmp_path):
    # no bias rule: gnn/Dense_0/bias would raise in match_partition_rules
    src = _part_src(
        '        (r"Dense_\\d+/kernel$", P(FSDP_AXIS, None)),\n')
    res = lint_tree(tmp_path, {"partition.py": src}, "frozen-param-tree")
    (f,) = errors_of(res, "frozen-param-tree")
    assert "covers no rule for canonical path 'gnn/Dense_0/bias'" \
        in f.message


def test_partition_table_unsharded_large_leaf_fires(tmp_path):
    # a replicate catch-all shadows the sharding rule for the big kernel
    src = _part_src(
        '        (r"kernel$", P()),\n'
        '        (r"Dense_\\d+/kernel$", P(FSDP_AXIS, None)),\n'
        '        (r"Dense_\\d+/bias$", P()),\n')
    res = lint_tree(tmp_path, {"partition.py": src}, "frozen-param-tree")
    msgs = [f.message for f in errors_of(res, "frozen-param-tree")]
    assert any("first-matches the replicate rule" in m for m in msgs)


def test_partition_table_missing_canonical_paths_fires(tmp_path):
    src = 'PARTITION_RULES = {"replicated": ((r".*", P()),)}\n'
    res = lint_tree(tmp_path, {"partition.py": src}, "frozen-param-tree")
    (f,) = errors_of(res, "frozen-param-tree")
    assert "cannot be cross-validated" in f.message


def test_partition_table_suppressed(tmp_path):
    src = _part_src(
        '        (r"decoder/.*", P(FSDP_AXIS, None)),  '
        "# ddls-lint: allow(frozen-param-tree) -- fixture stale rule\n"
        '        (r"Dense_\\d+/kernel$", P(FSDP_AXIS, None)),\n'
        '        (r"Dense_\\d+/bias$", P()),\n')
    res = lint_tree(tmp_path, {"partition.py": src}, "frozen-param-tree")
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


def test_partition_table_real_tree_clean():
    """The shipped rule table in ddls_tpu/parallel/partition.py passes
    its own cross-validation (and the canonical-path literal there stays
    in sync with the runtime tree — tests/test_partition.py pins that
    side)."""
    from ddls_tpu.lint import run_lint as _run
    res = _run(rules=get_rules(["frozen-param-tree"]))
    assert [f for f in res.errors
            if "partition" in f.rel.lower()] == []


# ------------------------------------------------ backend-surface-parity
def parity_files(jax_env_extra="", host_strings=("'queue_full'",
                                                 "'mounted'"),
                 ppo_extra="", harvest_keys=("'env_index'", "'ret'"),
                 host_key_fns=("lookahead_key_for",
                               "_assemble_lookahead_key"),
                 memo_surface=("'lookahead_key_for'",
                               "'_assemble_lookahead_key'"),
                 memo_trace_keys=("'memo_hits'",),
                 memo_extra="",
                 wide_probe=("'jax_lookahead'", "'skip'"),
                 lookahead_src=("def jax_lookahead(x, *, skip=None):\n"
                                "    pass\n"),
                 forward_call=("def run_lookahead(skip=None):\n"
                               "    jax_lookahead(1, skip=skip)\n"),
                 failure_map=("FAILURE_PREEMPT: 'worker_preempted', "
                              "FAILURE_STRAGGLE: 'channel_degraded'"),
                 flight_kinds=("'worker_preempted'",
                               "'channel_degraded'"),
                 host_emits=("'worker_preempted'",
                             "'channel_degraded'")):
    jax_env = (
        "CAUSE_QUEUE_FULL = 0\n"
        "CAUSE_MOUNTED = 1\n"
        "CAUSE_CODE_TO_STR = {CAUSE_QUEUE_FULL: 'queue_full', "
        "CAUSE_MOUNTED: 'mounted'}\n"
        + jax_env_extra +
        "def make_segment_fn():\n"
        "    trace = {'ep_ret': 0, 'action': 1, 'memo_hits': 2}\n"
        + forward_call)
    host = ("HOST_CAUSES = (" + ", ".join(host_strings) + ")\n"
            "HOST_EMITS = (" + ", ".join(host_emits) + ",)\n"
            + "".join(f"def {fn}():\n    pass\n" for fn in host_key_fns))
    ppo = ("def collect(trace):\n"
           "    r = trace['ep_ret']\n"
           + ppo_extra +
           "def _harvest_episodes(trace):\n"
           "    return [{" + ": 1, ".join(harvest_keys) + ": 2}]\n")
    rollout = ("def harvest_episode_record(env):\n"
               "    return {'env_index': 0, 'ret': 1.0}\n")
    memo = ("HOST_KEY_SURFACE = (" + ", ".join(memo_surface) + ",)\n"
            "MEMO_TRACE_KEYS = (" + ", ".join(memo_trace_keys) + ",)\n"
            "WIDE_PROBE_SURFACE = (" + ", ".join(wide_probe) + ",)\n"
            + memo_extra)
    failures = ("FAILURE_PREEMPT = 0\n"
                "FAILURE_STRAGGLE = 1\n"
                "FAILURE_KIND_TO_EVENT = {" + failure_map + "}\n")
    flight = "EVENT_KINDS = (" + ", ".join(flight_kinds) + ",)\n"
    return {"jax_env.py": jax_env, "cluster.py": host, "ppo.py": ppo,
            "rollout.py": rollout, "jax_memo.py": memo,
            "jax_lookahead.py": lookahead_src,
            "failures.py": failures, "flight.py": flight}


PARITY_CFG = {"backend-surface-parity": {
    "jax_env": "jax_env.py", "ppo_device": "ppo.py",
    "rollout": "rollout.py", "jax_memo": "jax_memo.py",
    "jax_lookahead": "jax_lookahead.py",
    "failures": "failures.py", "flight": "flight.py",
    "host_cause_files": ["cluster.py"],
    "jitted_only_causes": []}}


def test_backend_parity_clean(tmp_path):
    res = lint_tree(tmp_path, parity_files(), "backend-surface-parity",
                    PARITY_CFG)
    assert res.errors == []


def test_backend_parity_nonbijective_table_fires(tmp_path):
    files = parity_files(jax_env_extra="CAUSE_NEW = 2\n")
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("not a bijection" in f.message and "CAUSE_NEW" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_missing_host_cause_fires(tmp_path):
    files = parity_files(host_strings=("'queue_full'",))  # no 'mounted'
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'mounted'" in f.message and "drifted" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_untraced_counter_fires(tmp_path):
    files = parity_files(ppo_extra="    b = trace['ep_blocked']\n")
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'ep_blocked'" in f.message
               and "make_segment_fn does not trace" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_unknown_episode_key_fires(tmp_path):
    files = parity_files(harvest_keys=("'env_index'", "'novel_key'"))
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'novel_key'" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_docstring_mention_does_not_mask_drift(tmp_path):
    # the host vocabulary is CODE strings only: a cause word surviving
    # in a docstring must not keep the drift check green
    files = parity_files(host_strings=("'queue_full'",))
    files["cluster.py"] = ('"""The mounted state is documented here."""\n'
                          + files["cluster.py"])
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'mounted'" in f.message and "drifted" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_missing_host_file_is_flagged(tmp_path):
    # a typo'd host_cause_files path must fail loudly, not silently
    # shrink the host vocabulary the causes are checked against
    files = parity_files()
    del files["cluster.py"]
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("cannot read 'cluster.py'" in f.message
               for f in errors_of(res, "backend-surface-parity"))
    # and the half-vocabulary drift compare is skipped (no noise)
    assert not any("drifted" in f.message
                   for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_memo_missing_host_key_builder_fires(tmp_path):
    # the memo mirrors the host memo-key builders (ISSUE 13): renaming
    # one in cluster.py without updating the in-kernel mirror must fail
    # at lint time, not at the first stale-memo debugging session
    files = parity_files(host_key_fns=("lookahead_key_for",))
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'_assemble_lookahead_key'" in f.message
               and "host memo-key builders moved" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_memo_untraced_counter_key_fires(tmp_path):
    files = parity_files(memo_trace_keys=("'memo_hits'",
                                          "'memo_evictions'"))
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'memo_evictions'" in f.message
               and "would not drain" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_memo_counter_via_emitter_is_clean(tmp_path):
    # the real tree's shape: make_segment_fn emits the counters through
    # jax_memo.memo_trace_counters (one naming home) — keys literal in
    # that helper count as traced
    files = parity_files(
        memo_trace_keys=("'memo_hits'", "'memo_misses'"),
        memo_extra=("def memo_trace_counters(memo):\n"
                    "    return {'memo_misses': memo}\n"))
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert res.errors == []


def test_backend_parity_memo_surface_moved_fires(tmp_path):
    files = parity_files()
    files["jax_memo.py"] = "def memo_init():\n    pass\n"
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    msgs = [f.message for f in errors_of(res, "backend-surface-parity")]
    assert any("HOST_KEY_SURFACE" in m and "moved" in m for m in msgs)
    assert any("MEMO_TRACE_KEYS" in m and "moved" in m for m in msgs)


def test_backend_parity_wide_probe_missing_entry_fn_fires(tmp_path):
    # the batched probe's masking surface (ISSUE 17): renaming the
    # lookahead entry point without the memo mirror must fail at lint —
    # an unmasked probe is correct but inert, so no parity test catches
    # the drift
    files = parity_files(
        lookahead_src="def jax_lookahead_v2(x, *, skip=None):\n    pass\n")
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'jax_lookahead'" in f.message
               and "entry point moved" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_wide_probe_missing_keyword_fires(tmp_path):
    files = parity_files(
        lookahead_src="def jax_lookahead(x):\n    pass\n")
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'skip'" in f.message and "nothing to bind" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_wide_probe_not_forwarded_fires(tmp_path):
    files = parity_files(
        forward_call="def run_lookahead():\n    jax_lookahead(1)\n")
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("forwards skip=" in f.message and "inert" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_wide_probe_surface_moved_fires(tmp_path):
    files = parity_files()
    files["jax_memo.py"] = (
        "HOST_KEY_SURFACE = ('lookahead_key_for', "
        "'_assemble_lookahead_key',)\n"
        "MEMO_TRACE_KEYS = ('memo_hits',)\n")
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("WIDE_PROBE_SURFACE" in f.message and "moved" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_failure_map_nonbijective_fires(tmp_path):
    # a FAILURE_* kind code with no event mapping (ISSUE 16): adding a
    # failure kind without naming its flight event must fail at lint
    files = parity_files(
        failure_map="FAILURE_PREEMPT: 'worker_preempted'")
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("FAILURE_KIND_TO_EVENT is not a bijection" in f.message
               and "FAILURE_STRAGGLE" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_failure_event_not_in_flight_kinds_fires(tmp_path):
    files = parity_files(flight_kinds=("'worker_preempted'",))
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'channel_degraded'" in f.message
               and "EVENT_KINDS" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_failure_event_no_host_emission_fires(tmp_path):
    files = parity_files(host_emits=("'worker_preempted'",))
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("'channel_degraded'" in f.message
               and "no host emission site" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_missing_memo_file_is_flagged(tmp_path):
    files = parity_files()
    del files["jax_memo.py"]
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert any("cannot read 'jax_memo.py'" in f.message
               for f in errors_of(res, "backend-surface-parity"))


def test_backend_parity_suppressed(tmp_path):
    files = parity_files(host_strings=("'queue_full'",))
    files["jax_env.py"] = files["jax_env.py"].replace(
        "CAUSE_MOUNTED: 'mounted'}\n",
        "CAUSE_MOUNTED: 'mounted'}  # ddls-lint: "
        "allow(backend-surface-parity) -- fixture: host side pending\n")
    res = lint_tree(tmp_path, files, "backend-surface-parity",
                    PARITY_CFG)
    assert res.errors == []
    assert any(f.suppressed for f in res.findings)


# ----------------------------------------------- suppression / allowance
def test_suppression_without_reason_is_error(tmp_path):
    src = ("import time\n"
           "t0 = time.perf_counter()  # ddls-lint: allow(bare-timers)\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "bare-timers")
    rules = {f.rule for f in res.errors}
    # the bare allow() is rejected AND does not suppress the finding
    assert "lint-suppression" in rules and "bare-timers" in rules


def test_suppression_for_wrong_rule_does_not_suppress(tmp_path):
    src = ("import time\n"
           "t0 = time.perf_counter()  # ddls-lint: allow(flow-mask) "
           "-- wrong rule id on purpose\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "bare-timers")
    assert errors_of(res, "bare-timers")


def test_unknown_file_allowance_is_lint_error(tmp_path):
    cfg = {"bare-timers": {"allow": {"no/such/file.py": 1}}}
    res = lint_tree(tmp_path, {"ok.py": "x = 1\n"}, "bare-timers", cfg)
    (f,) = errors_of(res, "bare-timers")
    assert f.rel == "pyproject.toml"
    assert "stale" in f.message and "no/such/file.py" in f.message


def test_parse_error_is_reported(tmp_path):
    res = lint_tree(tmp_path, {"broken.py": "def f(:\n"}, "bare-timers")
    assert any(f.rule == "parse-error" for f in res.errors)


def test_unknown_suppression_rule_id_is_error_in_every_run(tmp_path):
    """A typo'd rule id suppresses nothing — flagged even by restricted
    (shim) runs, mirroring get_rules raising on unknown --rules ids."""
    src = ("import time\n"
           "t0 = time.perf_counter()  # ddls-lint: allow(baretimers) "
           "-- typo'd rule id\n")
    res = lint_tree(tmp_path, {"hot.py": src}, "shm-unlink")
    (f,) = res.errors
    assert f.rule == "lint-suppression"
    assert "unknown rule id 'baretimers'" in f.message
    # and the typo'd comment does not suppress the real finding
    res = lint_tree(tmp_path, {"hot.py": src}, "bare-timers")
    assert {f.rule for f in res.errors} == {"lint-suppression",
                                            "bare-timers"}


def test_restricted_run_skips_other_rules_bad_suppressions(tmp_path):
    """Shim parity: a single-rule run (the legacy-shim surface) must not
    fail on another rule's reasonless suppression — that finding belongs
    to the rule the comment names. A suppression naming NO rule is
    engine-level garbage and fails every run."""
    src = ("x = 1  # ddls-lint: allow(flow-mask)\n")
    res = lint_tree(tmp_path, {"mod.py": src}, "shm-unlink")
    assert res.errors == []
    res = lint_tree(tmp_path, {"mod.py": src}, "flow-mask")
    assert [f.rule for f in res.errors] == ["lint-suppression"]
    res = lint_tree(tmp_path, {"mod.py": "x = 1  # ddls-lint: allow()\n"},
                    "shm-unlink")
    assert [f.rule for f in res.errors] == ["lint-suppression"]


# ------------------------------------------------- whole-tree / tier-1
def expected_tree_files():
    # the default run's scan surface: ddls_tpu/ plus the bare-timers
    # rule's extra_roots ("scripts" — every other rule is gated off
    # those files, but they are parsed once like any other)
    out = []
    for root in ("ddls_tpu", "scripts"):
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(REPO, root)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                rel = os.path.relpath(os.path.join(dirpath, fn), REPO)
                rel = rel.replace(os.sep, "/")
                if fn.endswith(".py") and not rel.startswith(
                        "ddls_tpu/lint/"):
                    out.append(rel)
    return out


def test_real_tree_clean_one_engine_call_and_parse_once(monkeypatch):
    """THE tier-1 guard: one engine call covers what the three legacy
    script invocations covered (plus the six new rules), the tree is
    clean, every suppression carries a reason, and every file is parsed
    exactly ONCE for the full 9-rule run."""
    from ddls_tpu.lint import core

    parse_calls = []
    real_parse = ast.parse

    def counting_parse(source, *args, **kwargs):
        parse_calls.append(1)
        return real_parse(source, *args, **kwargs)

    monkeypatch.setattr(core.ast, "parse", counting_parse)
    result = run_lint(repo_root=REPO)
    assert result.errors == [], "\n".join(str(f) for f in result.errors)
    assert all(f.suppress_reason for f in result.findings if f.suppressed)
    # one ast.parse per tree file; the backend-parity cross-file reads
    # reuse the same cache (its targets all live under ddls_tpu/)
    assert len(parse_calls) == len(expected_tree_files())


def test_cli_json_real_tree():
    """`scripts/lint.py --json` over the real tree: rc 0, machine-
    readable findings with rule id, file, line, message, suppression
    state (the bench/report-tooling surface)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--json"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["returncode"] == 0
    assert payload["counts"]["errors"] == 0
    for f in payload["findings"]:
        assert {"rule", "file", "line", "message",
                "suppressed"} <= set(f)
        assert f["suppressed"] and f["suppress_reason"]


def test_cli_unknown_rule_id_fails_clean(tmp_path):
    """A typo'd --rules id fails loud but clean: rc 2, no traceback,
    and --json keeps its machine-readable contract."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--rules", "nosuchrule", "--paths", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 2
    assert "unknown lint rule" in out.stdout
    assert "Traceback" not in out.stderr
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--rules", "nosuchrule", "--json", "--paths", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 2
    payload = json.loads(out.stdout)
    assert payload["returncode"] == 2 and "unknown" in payload["error"]


def test_cli_rules_restriction(tmp_path):
    """--rules runs only the named rules (the shim surface): a tree that
    violates bare-timers passes a flow-mask-only run."""
    bad = tmp_path / "hot.py"
    bad.write_text(TIMER_BAD)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--rules", "flow-mask", "--paths", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--rules", "bare-timers", "--paths", str(tmp_path)],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 1
    assert "hot.py" in out.stdout and "bare-timers" in out.stdout
