"""Policy-in-the-loop jitted episodes: the in-kernel observation must
equal the host encoder bit-for-bit (f32), and a greedy GNN policy rolled
out INSIDE the jitted episode must reproduce the host env driven by the
same policy — actions, rewards, counters.

x64 subprocess (same isolation as tests/test_jax_episode.py): the
simulator side runs f64 for exact decision parity while the policy side
is f32 on both paths."""
import os
import subprocess
import sys

DRIVER = r"""
import os
import numpy as np
import jax
import jax.numpy as jnp

assert jax.config.read("jax_enable_x64")
USE_PRICES = bool(int(os.environ.get("DRIVER_PRICES", "0")))

import tempfile
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.models.policy import GNNPolicy
from ddls_tpu.sim.jax_env import (build_episode_tables, build_job_bank,
                                  build_obs_tables, _kernel_obs,
                                  make_policy_episode_fn)

d = tempfile.mkdtemp(prefix="jax_pol_ep_")
generate_pipedream_txt_files(d, n_cnn=2, n_translation=1, seed=5)

def make_env():
    return RampJobPartitioningEnvironment(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 4,
            "num_racks_per_communication_group": 4,
            "num_servers_per_rack": 2, "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 32, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={"path_to_files": d,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 40.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.1, "max_val": 1.0, "decimals": 2},
            "replication_factor": 30, "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 20},
        max_partitions_per_op=8, min_op_run_time_quantum=0.01,
        reward_function="job_acceptance", max_simulation_run_time=4e3,
        pad_obs_kwargs={"max_nodes": 150, "max_edges": 512},
        candidate_pricing="native" if USE_PRICES else None,
        obs_include_candidate_prices=USE_PRICES)

env = make_env()
obs = env.reset(seed=17)
et = build_episode_tables(env)
ot = build_obs_tables(env, et)

model = GNNPolicy(n_actions=env.max_partitions_per_op + 1,
                  out_features_msg=8, out_features_hidden=16,
                  out_features_node=8, out_features_graph=4,
                  fcnet_hiddens=(32,))
params = model.init(jax.random.PRNGKey(3),
                    jax.tree_util.tree_map(jnp.asarray, obs))

# ---- host episode driven by the greedy policy, recording everything
rng = np.random.RandomState(0)
arrivals, actions, rewards = [], [], []
seen = set()
obs_checked = 0
done = False
while not done:
    job = next(iter(env.cluster.job_queue.jobs.values()))
    ji = env.cluster.job_id_to_job_idx[job.job_id]
    if ji not in seen:
        seen.add(ji)
        arrivals.append({"model": job.details["model"],
                         "num_training_steps": job.num_training_steps,
                         "sla_frac": job.max_acceptable_jct_frac,
                         "time_arrived": job.details["time_arrived"]})
    if not USE_PRICES:
        # in-kernel obs parity vs the host encoder at THIS live state
        # (the price block needs the kernel's own pricing state, so the
        # price variant is proven through trace parity instead: the
        # greedy policy CONSUMES the price block, so any divergence in it
        # changes the action trace)
        jtype = et.types.index(job.details["model"])
        kobs = _kernel_obs(ot, et, jnp.int32(jtype),
                           jnp.float64(job.max_acceptable_jct_frac),
                           jnp.float64(job.num_training_steps),
                           jnp.int32(len(env.cluster.mounted_workers)),
                           jnp.int32(len(env.cluster.jobs_running)))
        for key in obs:
            a = np.asarray(kobs[key])
            b = np.asarray(obs[key])
            assert a.dtype == b.dtype or key in ("action_mask",), (
                key, a.dtype, b.dtype)
            assert np.array_equal(a.astype(b.dtype), b), (
                f"obs field {key} diverged at decision {len(actions)}:"
                f" {a} vs {b}")
        obs_checked += 1

    logits, value = model.apply(params, jax.tree_util.tree_map(
        jnp.asarray, obs))
    action = int(np.argmax(np.asarray(logits)))
    actions.append(action)
    obs, reward, done, info = env.step(action)
    rewards.append(reward)

n_arrived = env.cluster.num_jobs_arrived
for ji in range(len(arrivals), n_arrived):
    j = (env.cluster.jobs_running.get(ji)
         or env.cluster.jobs_completed.get(ji)
         or env.cluster.jobs_blocked.get(ji)
         or env.cluster.job_queue.jobs.get(env.cluster.job_idx_to_job_id[ji]))
    j = j.original_job if j.original_job is not j else j
    arrivals.append({"model": j.details["model"],
                     "num_training_steps": j.num_training_steps,
                     "sla_frac": j.max_acceptable_jct_frac,
                     "time_arrived": j.details["time_arrived"]})
print(f"host: {len(actions)} decisions, obs checked {obs_checked}")

# ---- jitted policy episode on the same bank
bank = {k: jnp.asarray(v) for k, v in build_job_bank(et, arrivals).items()}
episode_fn = make_policy_episode_fn(et, ot, model, greedy=True)
out = episode_fn(bank, params, jax.random.PRNGKey(0))
(a_tr, logp_tr, v_tr, r_tr, acc_tr, cause_tr, jct_tr, t_tr,
 has_tr) = (np.asarray(x) for x in out["trace"])
n = int(has_tr.sum())
assert n == len(actions), (n, len(actions))
live = has_tr.nonzero()[0]
assert (a_tr[live] == np.array(actions)).all(), "action trace diverged"
assert np.allclose(r_tr[live], np.array(rewards)), "reward trace diverged"
assert int(out["accepted"]) + int(out["blocked"]) == len(actions)
host_ret = float(np.sum(rewards))
assert abs(float(out["ret"]) - host_ret) < 1e-9, (out["ret"], host_ret)

# ---- episode-record parity: the kernel counters must reproduce the host
# cluster's episode stats EXACTLY, including the arrival denominator the
# device collector's harvested rates divide by and the host finalisation
# that blocks jobs still running at simulation end (VERDICT r4 item 5)
er = env.cluster.episode_stats
assert int(out["arrived"]) == n_arrived == er["num_jobs_arrived"], (
    int(out["arrived"]), n_arrived, er["num_jobs_arrived"])
assert int(out["completed"]) == er["num_jobs_completed"]
assert int(out["blocked_total"]) == er["num_jobs_blocked"], (
    int(out["blocked_total"]), int(out["blocked"]), er["num_jobs_blocked"])
still_running = int(out["blocked_total"]) - int(out["blocked"])
arr = int(out["arrived"])
k_acc = int(out["completed"]) / arr if arr else 0.0
k_blk = int(out["blocked_total"]) / arr if arr else 0.0
assert k_acc == er["acceptance_rate"], (k_acc, er["acceptance_rate"])
assert k_blk == er["blocking_rate"], (k_blk, er["blocking_rate"])
print(f"POLICY_EPISODE_PARITY_OK decisions={n} ret={host_ret} "
      f"still_running_at_end={still_running}")
"""


def _run_driver(prices: bool):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["DRIVER_PRICES"] = "1" if prices else "0"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    res = subprocess.run([sys.executable, "-c", DRIVER], env=env,
                         capture_output=True, text=True, timeout=1800)
    assert res.returncode == 0, (res.stdout[-4000:], res.stderr[-4000:])
    assert "POLICY_EPISODE_PARITY_OK" in res.stdout, res.stdout[-2000:]


def test_policy_episode_parity_x64():
    _run_driver(prices=False)


def test_policy_episode_parity_with_price_features_x64():
    """The price-informed policy runs on device too: in-kernel candidate
    pricing feeds the observation's price block and the greedy rollout
    reproduces the host env's full action/reward trace. (The price block
    is checked THROUGH the trace — the greedy policy consumes it, so a
    feature divergence big enough to change any decision fails the test;
    per-field bit-equality is pinned for the non-price obs by the other
    variant and for the price values by test_jax_oracle_episode.py's
    pricing parity.)"""
    _run_driver(prices=True)
