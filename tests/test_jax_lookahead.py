"""The jitted array lookahead must reproduce the host tick engine's
JCT/overhead outputs on real mounted jobs (SURVEY.md §7.4.1: build the
host oracle first, then property-test the array engine against it)."""
import numpy as np
import pytest

from ddls_tpu.envs.partitioning_env import RampJobPartitioningEnvironment


def _make_env(dataset_dir, max_partitions=4):
    # the C++ engine (auto-enabled) would absorb every cache-miss lookahead
    # before the host/jax engines under test here ever ran
    return RampJobPartitioningEnvironment(
        use_native_lookahead=False,
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 4,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 3},
        max_partitions_per_op=max_partitions,
        reward_function="job_acceptance",
        max_simulation_run_time=1e5,
        pad_obs_kwargs={"max_nodes": 64, "max_edges": 256})


def _collect_cases(env, actions, n_cases):
    """Step the env with the given action sequence, capturing
    (host lookahead outputs, padded arrays) per successfully placed job."""
    from ddls_tpu.sim.jax_lookahead import build_lookahead_arrays

    cases = []
    obs = env.reset(seed=0)
    rng = np.random.RandomState(0)
    cluster = env.cluster
    orig = cluster._run_lookahead

    def spy(job):
        jct, comm, comp, busy = orig(job)
        steps = job.num_training_steps
        arrays = build_lookahead_arrays(cluster, job, pad_ops=160,
                                        pad_deps=520, pad_links=2)
        cases.append({"host": (jct / steps, comm / steps, comp / steps),
                      "host_busy": busy,
                      "arrays": arrays})
        return jct, comm, comp, busy

    cluster._run_lookahead = spy
    try:
        i = 0
        while len(cases) < n_cases:
            mask = np.asarray(obs["action_mask"])
            valid = np.nonzero(mask)[0]
            if actions == "max":
                a = int(valid[-1])
            elif actions == "min":
                a = int(valid[0])
            else:
                a = int(rng.choice(valid))
            obs, _, done, _ = env.step(a)
            i += 1
            if done or i > 200:
                obs = env.reset(seed=i)
                # memo caches persist across resets (same workload); clear
                # so repeated episodes keep producing cache-miss lookaheads
                # for the spy to capture
                cluster.lookahead_cache.clear()
    finally:
        cluster._run_lookahead = orig
    return cases


@pytest.mark.parametrize("actions", ["max", "random"])
def test_matches_host_engine(dataset_dir, actions):
    from ddls_tpu.sim.jax_lookahead import arrays_as_args, lookahead_fn

    env = _make_env(dataset_dir)
    cases = _collect_cases(env, actions, n_cases=6)
    assert cases, "no lookahead cases captured"

    fns = {}
    for case in cases:
        a = case["arrays"]
        key = (a.num_workers, a.num_channels)
        fn = fns.setdefault(key, lookahead_fn(*key))
        t, comm, comp, busy, ok = fn(*arrays_as_args(a))
        assert bool(ok), "array engine failed to converge"
        host_t, host_comm, host_comp = case["host"]
        assert float(t) == pytest.approx(host_t, rel=1e-4), \
            f"jct mismatch: jax {float(t)} vs host {host_t}"
        assert float(comm) == pytest.approx(host_comm, rel=1e-4, abs=1e-6)
        assert float(comp) == pytest.approx(host_comp, rel=1e-4, abs=1e-6)
        assert float(busy) == pytest.approx(case["host_busy"], rel=1e-4,
                                            abs=1e-6)


def test_vmapped_batch(dataset_dir):
    """vmap over a batch of jobs padded to common shapes."""
    from ddls_tpu.sim.jax_lookahead import (arrays_as_args,
                                            batched_lookahead_fn)

    env = _make_env(dataset_dir)
    cases = _collect_cases(env, "random", n_cases=4)
    # pad worker/channel statics to the max across the batch
    W = max(c["arrays"].num_workers for c in cases)
    C = max(c["arrays"].num_channels for c in cases)
    fn = batched_lookahead_fn(W, C)
    batch = [np.stack([arrays_as_args(c["arrays"])[k] for c in cases])
             for k in range(13)]
    t, comm, comp, busy, ok = fn(*batch)
    assert bool(np.all(ok))
    for bi, case in enumerate(cases):
        assert float(t[bi]) == pytest.approx(case["host"][0], rel=1e-4)


def test_cluster_opt_in_backend_matches_host(dataset_dir):
    """use_jax_lookahead=True: a full episode's outcomes (JCTs, blocking,
    overheads, utilisation) match the host engine's episode to f32
    precision (docs/jax_lookahead_gonogo.md integration)."""
    episodes = {}
    for use_jax in (False, True):
        env = _make_env(dataset_dir)
        env.cluster.use_jax_lookahead = use_jax
        obs = env.reset(seed=0)
        done, steps = False, 0
        while not done and steps < 60:
            mask = np.asarray(obs["action_mask"])
            a = int(np.nonzero(mask)[0][-1])  # max parallelism: misses cache
            obs, _, done, _ = env.step(a)
            steps += 1
        episodes[use_jax] = env.cluster.episode_stats

    host, jaxe = episodes[False], episodes[True]
    assert jaxe["num_jobs_completed"] == host["num_jobs_completed"]
    assert jaxe["num_jobs_blocked"] == host["num_jobs_blocked"]
    assert jaxe["job_completion_time"] == pytest.approx(
        host["job_completion_time"], rel=1e-4)
    assert jaxe["job_communication_overhead_time"] == pytest.approx(
        host["job_communication_overhead_time"], rel=1e-4, abs=1e-6)
    assert jaxe["jobs_completed_mean_mounted_worker_utilisation_frac"] == (
        pytest.approx(
            host["jobs_completed_mean_mounted_worker_utilisation_frac"],
            rel=1e-4))
