"""Scan-ified allocate_job vs the host placer: randomized full-job parity
(the placer-side step beyond test_jax_block_search's single-search fuzz;
VERDICT r3 next #2).

Graph memory values are dyadic integers so the kernel's f32 arithmetic is
exact and any mismatch is a semantics bug, not rounding."""
import os
import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

from ddls_tpu.agents.partitioners import build_partition_action
from ddls_tpu.agents.placers import allocate_job
from ddls_tpu.graphs.readers import read_graph_file
from ddls_tpu.sim.jax_env import (build_shape_tables, config_tables_for,
                                  jax_allocate_job, stack_config_tables)


def _write_profile(path, n_fwd, rng):
    """A chain-with-skips pipedream profile with integer dyadic sizes."""
    lines = []
    for i in range(1, n_fwd + 1):
        act = int(rng.randint(1, 20)) * 4
        par = int(rng.randint(0, 10)) * 4
        fwd = int(rng.randint(1, 50))
        bwd = int(rng.randint(1, 50))
        lines.append(
            f"node{i} -- Op(x) -- forward_compute_time={fwd}, "
            f"backward_compute_time={bwd}, activation_size={act}, "
            f"parameter_size={par}")
    for i in range(1, n_fwd):
        lines.append(f"node{i} -- node{i + 1}")
        if i + 2 <= n_fwd and rng.rand() < 0.4:
            lines.append(f"node{i} -- node{i + 2}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


@pytest.fixture(scope="module", params=[(2, 2, 2), (4, 4, 2)])
def setup(request):
    ramp_shape = request.param
    n_srv = int(np.prod(ramp_shape))
    max_split = min(16, n_srv)
    rng = np.random.RandomState(sum(ramp_shape))
    d = tempfile.mkdtemp(prefix="jax_placer_")
    graphs = []
    for gi, n_fwd in enumerate([4, 7, 10]):
        path = os.path.join(d, f"g{gi}.txt")
        _write_profile(path, n_fwd, rng)
        graphs.append(read_graph_file(path))

    degrees = [dg for dg in (1, 2, 4, 8, 16) if dg <= max_split]
    st = build_shape_tables(ramp_shape, max_split)
    cfgs = []
    cfg_meta = []  # (graph index, degree)
    for gi, g in enumerate(graphs):
        for dg in degrees:
            cfgs.append(config_tables_for(g, dg, 0.01))
            cfg_meta.append((gi, dg))
    tables, pads = stack_config_tables(cfgs, st)
    jtables = {k: jnp.asarray(v) for k, v in tables.items()}
    return ramp_shape, graphs, st, jtables, pads, cfg_meta


def _random_state(rng, ramp_shape, occupancy_p):
    n_srv = int(np.prod(ramp_shape))
    mem = (rng.randint(50, 1200, size=n_srv)).astype(np.float64)
    other = rng.rand(n_srv) < occupancy_p
    ramp = {}
    codes = []
    for c in range(ramp_shape[0]):
        for r in range(ramp_shape[1]):
            for s in range(ramp_shape[2]):
                codes.append((c, r, s))
    for i, coord in enumerate(codes):
        ramp[coord] = {"mem": float(mem[i]),
                       "job_idxs": {77} if other[i] else set()}
    return mem, ~other, ramp, codes


def test_full_job_parity_randomized(setup):
    ramp_shape, graphs, st, jtables, pads, cfg_meta = setup
    import jax

    fn = jax.jit(lambda mem, free, cfg: jax_allocate_job(
        mem, free, cfg, jtables, st, pads))

    rng = np.random.RandomState(0)
    n_checked_placed = 0
    for trial in range(40):
        cfg = int(rng.randint(0, len(cfg_meta)))
        gi, degree = cfg_meta[cfg]
        graph = graphs[gi]
        mem, other_free, ramp, codes = _random_state(
            rng, ramp_shape, rng.choice([0.0, 0.25, 0.6]))

        action = build_partition_action(graph, 0.01, degree)
        split_fwd = {op: n for op, n in action.items()
                     if n > 1 and graph.is_forward(op)}
        forward_graph = graph.forward_view()
        meta_servers = set(codes)
        host = allocate_job(dict((k, dict(mem=v["mem"],
                                          job_idxs=set(v["job_idxs"])))
                                 for k, v in ramp.items()),
                            ramp_shape, forward_graph, graph, split_fwd,
                            meta_servers, ramp_shape, job_idx=1)

        ots, new_mem, ok = fn(jnp.asarray(mem, jnp.float32),
                              jnp.asarray(other_free), cfg)
        ots = np.asarray(ots)
        ok = bool(ok)

        if host is None:
            assert not ok, (trial, cfg_meta[cfg])
            continue
        assert ok, (trial, cfg_meta[cfg])
        n_checked_placed += 1

        # host placed dict -> server codes, compared op by op
        from ddls_tpu.sim.partition import partition_graph

        pgraph = partition_graph(graph, action)
        op_index = pgraph.finalize()["op_index"]
        R, S = ramp_shape[1], ramp_shape[2]
        assert len(host) == pgraph.n_ops
        for op_id, coord in host.items():
            code = (coord[0] * R + coord[1]) * S + coord[2]
            assert ots[op_index[op_id]] == code, (
                trial, cfg_meta[cfg], op_id, coord, ots[op_index[op_id]])
        # all padded slots beyond the real ops stay unassigned
        assert (ots[pgraph.n_ops:] == -1).all()
    assert n_checked_placed >= 8


def test_memory_accounting_matches_host(setup):
    """New free-memory grid equals the host's mutated snapshot after a
    successful allocation (placement deducts fwd+bwd pair memory)."""
    ramp_shape, graphs, st, jtables, pads, cfg_meta = setup
    import jax

    fn = jax.jit(lambda mem, free, cfg: jax_allocate_job(
        mem, free, cfg, jtables, st, pads))
    rng = np.random.RandomState(7)
    checked = 0
    for trial in range(30):
        cfg = int(rng.randint(0, len(cfg_meta)))
        gi, degree = cfg_meta[cfg]
        graph = graphs[gi]
        mem, other_free, ramp, codes = _random_state(rng, ramp_shape, 0.2)
        action = build_partition_action(graph, 0.01, degree)
        split_fwd = {op: n for op, n in action.items()
                     if n > 1 and graph.is_forward(op)}
        host_ramp = {k: dict(mem=v["mem"], job_idxs=set(v["job_idxs"]))
                     for k, v in ramp.items()}
        host = allocate_job(host_ramp, ramp_shape, graph.forward_view(),
                            graph, split_fwd, set(codes), ramp_shape,
                            job_idx=1)
        if host is None:
            continue
        _, new_mem, ok = fn(jnp.asarray(mem, jnp.float32),
                            jnp.asarray(other_free), cfg)
        assert bool(ok)
        new_mem = np.asarray(new_mem)
        for i, coord in enumerate(codes):
            assert new_mem[i] == pytest.approx(host_ramp[coord]["mem"],
                                               abs=1e-4), (trial, coord)
        checked += 1
    assert checked >= 5
