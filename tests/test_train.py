"""L7 train-stack tests: logger backends, launcher control flow, the
RLEpochLoop end-to-end on a tiny config, checkpoint round-trip, and the
shipped heuristic config driving an EvalLoop."""
import os

import numpy as np
import pytest

from ddls_tpu.config import instantiate, load_config
from ddls_tpu.train import (Checkpointer, Launcher, Logger, RLEpochLoop,
                            RLEvalLoop, ppo_config_from_rllib)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIGS = os.path.join(REPO, "scripts", "ramp_job_partitioning_configs")


def test_logger_gzip_round_trip(tmp_path):
    logger = Logger(path_to_save=str(tmp_path))
    logger.log({"epochs": [{"a": 1}], "scalar": 5})
    logger.log({"epochs": [{"a": 2}], "scalar": 6})
    logger.save(blocking=True)
    back = Logger.load(str(tmp_path / "results.pkl.gz"))
    assert back["epochs"] == [{"a": 1}, {"a": 2}]  # lists extend
    assert back["scalar"] == 6  # scalars overwrite


def test_logger_sqlite_accumulates_across_flushes(tmp_path):
    logger = Logger(path_to_save=str(tmp_path), use_sqlite_database=True)
    logger.log({"epochs": [{"a": 1}]})
    logger.save(blocking=True)
    assert logger.results == {}  # cleared after sqlite flush
    logger.log({"epochs": [{"a": 2}]})
    logger.save(blocking=True)
    back = Logger.load(str(tmp_path / "results.sqlite"))
    assert back["epochs"] == [{"a": 1}, {"a": 2}]


def test_ppo_config_from_rllib_maps_keys():
    cfg = ppo_config_from_rllib({
        "lr": 1e-3, "gamma": 0.9, "lambda": 0.95, "clip_param": 0.3,
        "train_batch_size": 128, "grad_clip": 2.0})
    assert cfg.lr == 1e-3
    assert cfg.gae_lambda == 0.95
    assert cfg.clip_param == 0.3
    assert cfg.train_batch_size == 128
    assert cfg.grad_clip == 2.0
    # unknown keys are rejected loudly, never silently no-oped
    with pytest.raises(ValueError, match="not consumed"):
        ppo_config_from_rllib({"lr": 1e-3, "unknown_key": 1})


class _CountingEpochLoop:
    def __init__(self):
        self.runs = 0
        self.checkpoints = []
        self.best_checkpoint_path = None
        self.best_metric_value = None

    def run(self):
        self.runs += 1
        return {"episodes_this_iter": 2, "env_steps_this_iter": 10,
                "episode_reward_mean": float(self.runs)}

    def log(self, results):
        pass

    def save_agent_checkpoint(self, path):
        self.checkpoints.append(path)

    def register_checkpoint(self, path, results):
        self.best_checkpoint_path = path


def test_launcher_stop_conditions_and_checkpoint_cadence(tmp_path):
    loop = _CountingEpochLoop()
    launcher = Launcher(epoch_loop=loop, num_epochs=5, verbose=False)
    ckpt = Checkpointer(path_to_save=str(tmp_path), epoch_checkpoint_freq=2)
    summary = launcher.run(checkpointer=ckpt)
    assert loop.runs == 5
    assert summary["epochs_run"] == 5
    assert summary["episodes_run"] == 10
    assert summary["actor_steps_run"] == 50
    # initial checkpoint + epochs 2 and 4
    assert len(loop.checkpoints) == 3

    loop = _CountingEpochLoop()
    launcher = Launcher(epoch_loop=loop, num_actor_steps=25, verbose=False)
    launcher.run()
    assert loop.runs == 3  # 10 steps/epoch -> stops after 3rd

    with pytest.raises(ValueError):
        Launcher(epoch_loop=loop)


def _tiny_epoch_loop(dataset_dir, tmp_path, **kwargs):
    env_config = dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 1000.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.1, "max_val": 1.0, "decimals": 2},
            "replication_factor": 5,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 50},
        max_partitions_per_op=8,
        min_op_run_time_quantum=0.01,
        reward_function="job_acceptance",
        reward_function_kwargs={"fail_reward": -1, "success_reward": 1},
        max_simulation_run_time=2e4,
        pad_obs_kwargs={"max_nodes": 64, "max_edges": 256})
    defaults = dict(
        path_to_env_cls=("ddls_tpu.envs.partitioning_env."
                         "RampJobPartitioningEnvironment"),
        env_config=env_config,
        model={"fcnet_hiddens": [32],
               "custom_model_config": {"out_features_msg": 8,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}},
        algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 2, "num_workers": 2},
        num_envs=2, rollout_length=4, n_devices=2,
        evaluation_interval=None, seed=0)
    defaults.update(kwargs)
    return RLEpochLoop(**defaults)


def test_rl_epoch_loop_end_to_end(dataset_dir, tmp_path):
    loop = _tiny_epoch_loop(dataset_dir, tmp_path)
    r1 = loop.run()
    assert r1["env_steps_this_iter"] == 8
    assert np.isfinite(r1["learner"]["total_loss"])
    # per-update phase spans land in the global telemetry registry when
    # enabled (ISSUE 3) — and stay absent while it is disabled (r1 above)
    from ddls_tpu import telemetry

    assert "train.collect" not in telemetry.span_summaries()
    telemetry.reset()
    telemetry.enable()
    try:
        r2 = loop.run()
        spans = telemetry.span_summaries()
        assert {"train.collect", "train.device_transfer",
                "train.train_step"} <= set(spans)
        # pipelined default (PR 4): metrics stay device futures — no
        # per-update host_sync; the update's device wall is carried by
        # the monitor-thread span instead, and an explicit sync drains
        # the ring under exactly one host_sync span
        assert "train.host_sync" not in spans
        loop.sync_metrics()
        if loop._watch_executor is not None:  # settle the monitor span
            loop._watch_executor.shutdown(wait=True)
            loop._watch_executor = None
        spans = telemetry.span_summaries()
        assert spans["train.host_sync"]["count"] == 1
        assert "train.update_device" in spans
        assert all(s["count"] == 1 for s in spans.values())
    finally:
        telemetry.reset()
        telemetry.disable()
    assert r2["total_env_steps"] == 16

    # greedy evaluation produces cluster stats
    ev = loop.evaluate(num_episodes=1, seed=123)
    assert "episode_reward_mean" in ev
    assert ev["episodes_this_iter"] == 1

    # checkpoint round-trip restores params exactly (host copy: the live
    # state is donated into the next train_step and its buffers deleted)
    import jax

    path = str(tmp_path / "ckpt")
    loop.save_agent_checkpoint(path)
    params_before = jax.device_get(loop.state.params)
    loop.run()  # moves params
    loop.load_agent_checkpoint(path)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        loop.state.params, params_before)
    loop.close()


def test_rl_eval_loop_from_checkpoint(dataset_dir, tmp_path):
    loop = _tiny_epoch_loop(dataset_dir, tmp_path)
    path = str(tmp_path / "ckpt2")
    loop.save_agent_checkpoint(path)
    eval_loop = RLEvalLoop(loop)
    results = eval_loop.run(checkpoint_path=path, seed=7)
    assert results["episode"]["episode_length"] > 0
    stats = results["episode_stats"]
    assert stats["num_jobs_arrived"] >= (stats["num_jobs_completed"]
                                         + stats["num_jobs_blocked"])
    loop.close()


def test_shipped_heuristic_config_runs(dataset_dir):
    cfg = load_config(CONFIGS, "heuristic_config", overrides=[
        "eval_loop.env.jobs_config.path_to_files=" + dataset_dir,
        "eval_loop.env.jobs_config.synthetic=null",
        "eval_loop.env.jobs_config.replication_factor=3",
        "eval_loop.env.max_simulation_run_time=2e4",
        "eval_loop.env.pad_obs_kwargs.max_nodes=64",
        "eval_loop.env.pad_obs_kwargs.max_edges=256",
    ])
    eval_loop = instantiate(cfg["eval_loop"])
    results = eval_loop.run(seed=0)
    stats = results["episode_stats"]
    assert results["episode_length"] > 0
    assert stats["num_jobs_arrived"] > 0
    assert "steps_log" in results


def test_evaluate_preserves_global_rng(dataset_dir, tmp_path):
    """Periodic evaluation must not leak its fixed test seed into the
    process-global RNG that training workload sampling draws from."""
    loop = _tiny_epoch_loop(dataset_dir, tmp_path, test_seed=1799)
    np.random.seed(12345)
    expected = np.random.RandomState(12345).rand(3)  # what should come next
    loop.evaluate(num_episodes=1)
    np.testing.assert_allclose(np.random.rand(3), expected)
    loop.close()


def test_metric_lookup_handles_slash_keys():
    results = {"evaluation": {"custom_metrics/blocking_rate_mean": 0.25,
                              "episode_reward_mean": 3.0}}
    assert RLEpochLoop._lookup_metric(
        results, "evaluation/custom_metrics/blocking_rate_mean") == 0.25
    assert RLEpochLoop._lookup_metric(
        results, "evaluation/episode_reward_mean") == 3.0
    assert RLEpochLoop._lookup_metric(results, "evaluation/missing") is None


def test_launcher_eval_overrides_wire_to_epoch_loop():
    loop = _CountingEpochLoop()
    loop.evaluation_interval = 1
    loop.evaluation_duration = 3
    Launcher(epoch_loop=loop, num_epochs=1, eval_freq=5,
             num_eval_episodes=7, verbose=False)
    assert loop.evaluation_interval == 5
    assert loop.evaluation_duration == 7


def test_batched_evaluation_runs_all_episodes(dataset_dir, tmp_path):
    """evaluation_duration > 1 drives parallel eval envs with one jitted
    greedy call per step (reference's parallel eval workers)."""
    loop = _tiny_epoch_loop(dataset_dir, tmp_path,
                            evaluation_interval=None)
    results = loop.evaluate(3)
    assert results["episodes_this_iter"] == 3
    assert np.isfinite(results["episode_reward_mean"])

    # per-episode RNG isolation: episode i consumes exactly the stream
    # seeded by base_seed + i, so the first episode of a 3-env batch is
    # bit-identical to a 1-env evaluation at the same seed, and repeated
    # evaluations reproduce exactly
    solo = loop._run_greedy_episodes_batched(1, base_seed=123)
    batch = loop._run_greedy_episodes_batched(3, base_seed=123)
    assert solo[0]["episode_return"] == batch[0]["episode_return"]
    assert solo[0]["episode_length"] == batch[0]["episode_length"]
    again = loop._run_greedy_episodes_batched(3, base_seed=123)
    assert [r["episode_return"] for r in batch] == (
        [r["episode_return"] for r in again])
    loop.close()


def test_device_collector_mesh_gate_falls_back(dataset_dir, tmp_path):
    """ADVICE r5 item 1: the device-collector gate must check
    divisibility by the mesh's dp axis (what DevicePPOCollector actually
    validates), not the local device count. n_devices=3 with num_envs=8
    divides the 8 local devices but not the dp=3 mesh — previously this
    passed the gate and raised ValueError in the collector; now it warns
    and collects on one device."""
    import warnings

    from ddls_tpu.rl.ppo_device import DevicePPOCollector

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        loop = _tiny_epoch_loop(
            dataset_dir, tmp_path, n_devices=3, num_envs=8,
            algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                         "num_sgd_iter": 2, "num_workers": 2,
                         "device_collector": True})
    assert isinstance(loop.collector, DevicePPOCollector)
    assert loop.collector.mesh is None
    assert any("mesh dp axis" in str(w.message) for w in caught)
    # collection itself runs single-device — the crash was at collector
    # construction. (A full loop.run() stays impossible for this config
    # with ANY collector: the learner's dp=3 mesh cannot shard B=8 in
    # shard_traj — a pre-existing training-side constraint, not the
    # collector gate's concern.)
    out = loop.collector.collect(loop.state.params,
                                 loop._split_collect_rng())
    assert out["traj"]["actions"].shape == (loop.rollout_length, 8)
    loop.close()


def test_device_collector_shards_smaller_mesh(dataset_dir, tmp_path):
    """The flip side of the dp-axis gate: n_devices=3 with num_envs=6
    failed the old local-device-count check (6 % 8 != 0) and silently
    collected on ONE device; the dp check (6 % 3 == 0) shards lanes over
    the configured mesh and the full epoch trains end-to-end."""
    loop = _tiny_epoch_loop(
        dataset_dir, tmp_path, n_devices=3, num_envs=6,
        algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 2, "num_workers": 2,
                     "device_collector": True})
    assert loop.collector.mesh is not None
    assert int(loop.collector.mesh.shape["dp"]) == 3
    r = loop.run()
    assert r["env_steps_this_iter"] == 24
    assert np.isfinite(r["learner"]["total_loss"])
    loop.close()


def test_device_collector_epoch_loop(dataset_dir, tmp_path):
    """algo_config device_collector=true: collection runs in the jitted
    env (rl/ppo_device.py) while eval/checkpointing stay on the host
    surface — the PPO-on-device product path."""
    loop = _tiny_epoch_loop(
        dataset_dir, tmp_path,
        algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 2, "num_workers": 2,
                     "device_collector": True})
    from ddls_tpu.rl.ppo_device import DevicePPOCollector

    assert isinstance(loop.collector, DevicePPOCollector)
    r1 = loop.run()
    assert r1["env_steps_this_iter"] == 8
    assert np.isfinite(r1["learner"]["total_loss"])
    # banks are per-lane distinct (sampled from the env's own workload
    # machinery with lane-offset seeds; arrival times are Fixed here, so
    # distinctness shows in the sampled job-type sequences)
    b = loop.collector.banks
    assert not np.array_equal(np.asarray(b["type"][0]),
                              np.asarray(b["type"][1]))
    # episodes eventually complete in-kernel and surface as records
    n_eps = 0
    for _ in range(60):
        r = loop.run()
        n_eps += len(r.get("episodes") or [])
        if n_eps:
            break
    assert n_eps >= 1
    # host evaluation surface still works alongside device collection
    ev = loop.evaluate(num_episodes=1, seed=5)
    assert "episode_reward_mean" in ev
    loop.close()
