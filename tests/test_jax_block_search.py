"""Jittable block search vs the host first-fit oracle: randomized
equivalence over occupancy grids, shape lists, and meta shapes (the
placement-side counterpart of the lookahead engine's parity fuzz)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddls_tpu.agents.block_search import (block_shapes_for, factor_pairs,
                                          first_fit_block)
from ddls_tpu.sim.jax_block_search import (block_cells,
                                           first_fit_block_jax,
                                           free_grid_from_ramp,
                                           jitted_first_fit)


def _random_ramp(rng, ramp_shape, occupancy_p, job_idx):
    ramp = {}
    for c in range(ramp_shape[0]):
        for r in range(ramp_shape[1]):
            for s in range(ramp_shape[2]):
                occ = set()
                if rng.rand() < occupancy_p:
                    occ.add(int(rng.randint(0, 5)))
                ramp[(c, r, s)] = {"mem": float(rng.rand() * 100),
                                   "job_idxs": occ}
    return ramp


@pytest.mark.parametrize("ramp_shape", [(4, 4, 2), (2, 2, 2), (3, 2, 4)])
def test_matches_host_first_fit_randomized(ramp_shape):
    rng = np.random.RandomState(hash(ramp_shape) % 2**31)
    job_idx = 1
    for trial in range(60):
        ramp = _random_ramp(rng, ramp_shape, rng.choice([0.2, 0.5, 0.8]),
                            job_idx)
        n = int(rng.choice([1, 2, 4, 8]))
        shapes = [s for s in block_shapes_for(factor_pairs(n), ramp_shape)
                  if -1 not in s]  # diagonal layout stays host-side
        if not shapes:
            continue
        op_size = float(rng.rand() * 80) if rng.rand() < 0.5 else None

        host = first_fit_block(shapes, ramp_shape, ramp_shape, ramp,
                               job_idx, op_size=op_size)
        free = free_grid_from_ramp(ramp, ramp_shape, job_idx,
                                   op_size=op_size)
        si, i, j, k, found = first_fit_block_jax(
            jnp.asarray(free), tuple(shapes), ramp_shape)
        if host is None:
            assert not bool(found), (trial, shapes)
            continue
        assert bool(found), (trial, shapes)
        cells = block_cells(shapes[int(si)], (int(i), int(j), int(k)),
                            ramp_shape)
        assert cells == host, (trial, shapes[int(si)],
                               (int(i), int(j), int(k)), host)


def test_jitted_and_vmapped_batch():
    """One compiled search serves a batch of occupancy grids (the
    multi-env use case for device-resident placement)."""
    ramp_shape = (4, 4, 2)
    shapes = tuple(s for s in block_shapes_for(factor_pairs(4), ramp_shape)
                   if -1 not in s)
    fn = jitted_first_fit(shapes, ramp_shape)
    rng = np.random.RandomState(0)
    grids = rng.rand(8, *ramp_shape) > 0.5
    batched = jax.vmap(fn)(jnp.asarray(grids))
    si, i, j, k, found = (np.asarray(x) for x in batched)
    assert found.shape == (8,)
    for b in range(8):
        ramp = {(c, r, s): {"mem": 1.0,
                            "job_idxs": set() if grids[b, c, r, s]
                            else {9}}
                for c in range(4) for r in range(4) for s in range(2)}
        host = first_fit_block(list(shapes), ramp_shape, ramp_shape, ramp,
                               1, op_size=None)
        assert bool(found[b]) == (host is not None)
        if host is not None:
            cells = block_cells(shapes[int(si[b])],
                                (int(i[b]), int(j[b]), int(k[b])),
                                ramp_shape)
            assert cells == host
