"""Test harness config.

Force JAX onto a virtual 8-device CPU mesh before jax initialises, so all
sharding/pjit/psum code paths are exercised without TPU hardware (the standard
JAX substitute for a fake multi-chip backend; see SURVEY.md §4).
"""
import os
import re

os.environ["JAX_PLATFORMS"] = "cpu"
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache, shared across the whole test run AND
# the subprocess drivers (x64 parity episodes, multi-host smoke, shim
# CLIs): the env vars are set BEFORE jax imports so every child python
# inherits them via os.environ. The suite re-compiles the same episode
# kernels dozens of times across processes; a warm cache turns each
# ~1.8 s compile into ~0.2 s (measured, jax 0.4.37 CPU). Keyed by jax
# version inside a stable tmp dir, so version bumps never serve stale
# binaries and repeat runs on one box reuse the cache.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    import tempfile

    _cache = os.path.join(
        tempfile.gettempdir(),
        f"ddls_tpu_xla_cache_{os.environ.get('USER', 'ci')}")
    os.makedirs(_cache, exist_ok=True)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# Site hooks may have imported (and pinned) jax onto an accelerator backend
# before this conftest runs; jax.config.update re-pins the platform as long
# as no backend has been initialised yet.
import jax

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"expected the virtual 8-device CPU mesh, got {jax.devices()}")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import random

    np.random.seed(0)
    random.seed(0)


@pytest.fixture(scope="session")
def dataset_dir(tmp_path_factory):
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    out = tmp_path_factory.mktemp("small_graphs")
    generate_pipedream_txt_files(str(out), n_cnn=2, n_translation=1, seed=0,
                                 min_ops=4, max_ops=6)
    return str(out)


def pytest_collection_modifyitems(config, items):
    """Auto-skip ``shm``-marked tests where POSIX shared memory is not
    usable (no /dev/shm, sandboxed CI): the shm rollout backend itself
    falls back to pipe on such platforms, so skipping — not failing —
    is the correct signal there."""
    from ddls_tpu.rl.shm import shm_available

    if shm_available():
        return
    skip = pytest.mark.skip(
        reason="POSIX shared memory unavailable on this platform")
    for item in items:
        if "shm" in item.keywords:
            item.add_marker(skip)
