"""Test harness config.

Force JAX onto a virtual 8-device CPU mesh before jax initialises, so all
sharding/pjit/psum code paths are exercised without TPU hardware (the standard
JAX substitute for a fake multi-chip backend; see SURVEY.md §4).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed():
    import random

    np.random.seed(0)
    random.seed(0)


@pytest.fixture(scope="session")
def dataset_dir(tmp_path_factory):
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    out = tmp_path_factory.mktemp("small_graphs")
    generate_pipedream_txt_files(str(out), n_cnn=2, n_translation=1, seed=0,
                                 min_ops=4, max_ops=6)
    return str(out)
