"""Zero-copy shared-memory rollout collection pins (docs/perf_round7.md).

* ``pad_obs_to(..., out=)`` / ``write_obs_into`` — the encode-into-
  destination API is bit-identical to the allocating path (fuzzed over
  random graph sizes/dtypes, mask rows included);
* pipe-vs-shm backend parity — same stacked obs, rewards/dones, episode
  records (content AND order) stepping the same seeds, and bit-exact
  post-training params for PPO and IMPALA epoch loops on the virtual
  CPU mesh (the full-collect acceptance pin);
* slab-trajectory contract — the deferred-fetch collector's traj rows
  ARE the slab (row t = obs before step t);
* trajectory-ring contract (rl/ring.py, ISSUE 15) — K independently-
  owned segments behind the same pipe-ack protocol: lease → publish →
  token-driven release, zero-copy traj views protected by ownership
  (never rewritten before release), stalls counted when the learner
  gates collection, zero /dev/shm litter on every exit path;
* lifecycle hardening — a killed worker raises a clear error instead of
  hanging, ``close()`` is idempotent, and no ``/dev/shm`` segment
  outlives the env (kill path included);
* ``scripts/check_shm_unlink.py`` tier-1 guard (clean tree passes, a
  synthetic unpaired create is flagged);
* serve arena reuse — ``ObsBucketer(reuse_arenas=True)`` output equals
  the allocating bucketer and recycles released arenas.

Tests needing real POSIX shared memory carry the ``shm`` marker
(conftest auto-skips them where /dev/shm is unavailable).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ACTIONS = 5
MAX_N, MAX_E = 6, 15


class ZeroPadToyEnv:
    """3-step episodes with encoder-faithful observations: fixed padded
    shapes and ZERO dead-pad bytes, exactly what ``envs/obs.py`` encode
    emits — so pipe and shm transports agree bit-for-bit (the shm write
    normalises the dead region through the masked-pad policy)."""

    def __init__(self):
        self.t = 0
        self.base = 0

    def reset(self, seed=None):
        self.t = 0
        self.base = 0 if seed is None else int(seed)
        return self._obs()

    def _obs(self):
        rng = np.random.RandomState(self.base * 977 + self.t)
        n, m = 4, 3
        obs = {
            "node_features": np.zeros((MAX_N, 5), np.float32),
            "edge_features": np.zeros((MAX_E, 2), np.float32),
            "graph_features": rng.rand(17 + N_ACTIONS).astype(np.float32),
            "edges_src": np.zeros(MAX_E, np.int32),
            "edges_dst": np.zeros(MAX_E, np.int32),
            "node_split": np.array([n], np.int32),
            "edge_split": np.array([m], np.int32),
            "action_mask": np.ones(N_ACTIONS, np.int32),
            "action_set": np.arange(N_ACTIONS, dtype=np.int32),
        }
        obs["node_features"][:n] = rng.rand(n, 5)
        obs["edge_features"][:m] = rng.rand(m, 2)
        obs["edges_src"][:m] = rng.randint(0, n, m)
        obs["edges_dst"][:m] = rng.randint(0, n, m)
        return obs

    def step(self, action):
        self.t += 1
        done = self.t % 3 == 0
        return self._obs(), float(1 + int(action)), done, {}


def _random_encoded_obs(rng, pad_n, pad_e, src_dtype=np.int32):
    """A random encoded-contract obs padded to (pad_n, pad_e); the dead
    region carries GARBAGE on purpose — pad_obs_to must mask it out
    identically on both paths."""
    n = int(rng.randint(1, pad_n + 1))
    m = int(rng.randint(0, pad_e + 1))
    obs = {
        "node_features": rng.rand(pad_n, 5).astype(np.float32),
        "edge_features": rng.rand(pad_e, 2).astype(np.float32),
        "graph_features": rng.rand(22).astype(np.float32),
        "edges_src": rng.randint(0, n, pad_e).astype(src_dtype),
        "edges_dst": rng.randint(0, n, pad_e).astype(src_dtype),
        "node_split": np.array([n], np.int32),
        "edge_split": np.array([m], np.int32),
        "action_mask": rng.randint(0, 2, N_ACTIONS).astype(np.int32),
        "action_set": np.arange(N_ACTIONS, dtype=np.int32),
    }
    return obs, n, m


# ------------------------------------------------- encode-into-destination
def test_pad_obs_to_out_fuzz():
    """out= writes must equal the allocating path EXACTLY — every key,
    every dtype, dead/mask rows included — over random sizes, source
    dtypes, and stale destination contents."""
    from ddls_tpu.envs.obs import pad_obs_to

    rng = np.random.RandomState(0)
    for trial in range(40):
        pad_n = int(rng.randint(2, 12))
        pad_e = int(rng.randint(1, 20))
        src_dtype = [np.int32, np.int64][trial % 2]
        obs, n, m = _random_encoded_obs(rng, pad_n, pad_e, src_dtype)
        to_n = int(rng.randint(n, n + 8))
        to_e = int(rng.randint(m, m + 12))
        ref = pad_obs_to(obs, to_n, to_e)
        # destinations pre-filled with garbage: the masked-pad write must
        # zero the dead region, not inherit stale bytes
        out = {
            "node_features": rng.rand(to_n, 5).astype(np.float32),
            "edge_features": rng.rand(to_e, 2).astype(np.float32),
            "edges_src": rng.randint(0, 99, to_e).astype(np.int32),
            "edges_dst": rng.randint(0, 99, to_e).astype(np.int32),
            "node_split": np.array([77], np.int32),
            "edge_split": np.array([77], np.int32),
            "graph_features": np.empty(22, np.float32),
            "action_mask": np.empty(N_ACTIONS, np.int32),
            "action_set": np.empty(N_ACTIONS, np.int32),
        }
        got = pad_obs_to(obs, to_n, to_e, out=out)
        assert set(got) == set(ref)
        for k in ref:
            np.testing.assert_array_equal(
                np.asarray(got[k]), np.asarray(ref[k]), err_msg=k)
            assert np.asarray(got[k]).dtype == np.asarray(ref[k]).dtype, k
        # the written fields alias the caller's arrays (that is the point)
        assert got["node_features"] is out["node_features"]


def test_pad_obs_to_out_rejects_mismatched_rows():
    from ddls_tpu.envs.obs import pad_obs_to

    rng = np.random.RandomState(1)
    obs, n, m = _random_encoded_obs(rng, 6, 10)
    out = {"node_features": np.zeros((4, 5), np.float32),
           "edge_features": np.zeros((12, 2), np.float32),
           "edges_src": np.zeros(12, np.int32),
           "edges_dst": np.zeros(12, np.int32),
           "node_split": np.zeros(1, np.int32),
           "edge_split": np.zeros(1, np.int32)}
    with pytest.raises(ValueError, match="rows"):
        pad_obs_to(obs, 8, 12, out=out)  # node dest has 4 rows, target 8


def test_write_obs_into_and_writer_roundtrip():
    """write_obs_into infers the pad target from the destination; the
    result reproduces the source obs bit-for-bit when shapes match (the
    worker-slab write) because encode's own pad region is zero."""
    from ddls_tpu.envs.obs import ObsWriter, write_obs_into

    env = ZeroPadToyEnv()
    obs = env.reset(seed=3)
    out = {k: np.empty_like(np.asarray(v)) for k, v in obs.items()}
    got = write_obs_into(obs, out)
    for k in obs:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(obs[k]), err_msg=k)
    writer = ObsWriter(MAX_N, MAX_E)
    got2 = writer.write(obs, out)
    for k in obs:
        np.testing.assert_array_equal(np.asarray(got2[k]),
                                      np.asarray(obs[k]), err_msg=k)


# ------------------------------------------------ VectorEnv cached stacking
def test_vector_env_stacked_obs_cached_buffer():
    """The in-process stacked_obs reuses ONE preallocated buffer across
    calls, bit-identical to stack_obs (the single-process half of the
    copy tax)."""
    from ddls_tpu.rl.rollout import VectorEnv, stack_obs

    vec = VectorEnv([ZeroPadToyEnv for _ in range(3)])
    vec.reset()
    first = vec.stacked_obs()
    ref = stack_obs(vec.obs)
    for k in ref:
        np.testing.assert_array_equal(first[k], ref[k], err_msg=k)
    vec.step(np.zeros(3, np.int32))
    second = vec.stacked_obs()
    ref2 = stack_obs(vec.obs)
    for k in ref2:
        np.testing.assert_array_equal(second[k], ref2[k], err_msg=k)
        assert second[k] is first[k], f"{k}: buffer not reused"
    vec.close()


# --------------------------------------------------- pipe-vs-shm stepping
def _leaked(names):
    return [n for n in names
            if os.path.exists(os.path.join("/dev/shm", n.lstrip("/")))]


@pytest.mark.shm
def test_shm_backend_matches_pipe_stepping():
    """Same seeds, same actions: stacked obs, per-env obs, rewards,
    dones, and episode records (content and order) are bit-identical
    across transports; slab segments unlink on close."""
    from ddls_tpu.rl.rollout import ParallelVectorEnv

    shm = ParallelVectorEnv(ZeroPadToyEnv, {}, 4, backend="shm")
    pipe = ParallelVectorEnv(ZeroPadToyEnv, {}, 4, backend="pipe")
    try:
        shm.reset()
        pipe.reset()
        assert shm.backend == "shm" and shm._slabs is not None
        names = list(shm._slabs.segment_names())
        for t in range(8):
            actions = np.arange(4, dtype=np.int32) % 3
            obs_a, rew_a, done_a = shm.step(actions)
            obs_b, rew_b, done_b = pipe.step(actions)
            np.testing.assert_array_equal(rew_a, rew_b)
            np.testing.assert_array_equal(done_a, done_b)
            sa, sb = shm.stacked_obs(), pipe.stacked_obs()
            for k in sb:
                np.testing.assert_array_equal(sa[k], sb[k], err_msg=k)
            for oa, ob in zip(obs_a, obs_b):
                for k in ob:
                    np.testing.assert_array_equal(
                        np.asarray(oa[k]), np.asarray(ob[k]), err_msg=k)
        assert (shm.drain_completed_episodes()
                == pipe.drain_completed_episodes())
        # a mid-run restart keeps both transports in lockstep
        shm.restart_episodes()
        pipe.restart_episodes()
        sa, sb = shm.stacked_obs(), pipe.stacked_obs()
        for k in sb:
            np.testing.assert_array_equal(sa[k], sb[k], err_msg=k)
    finally:
        shm.close()
        pipe.close()
    assert not _leaked(names)


@pytest.mark.shm
def test_shm_traj_slab_rows_are_the_trajectory():
    """ensure_traj_rows + rebase_row0: row t holds the obs BEFORE step t
    and the final row holds the bootstrap obs — the deferred-fetch
    collector's zero-copy trajectory contract."""
    from ddls_tpu.rl.rollout import OBS_KEYS, ParallelVectorEnv

    T = 5
    vec = ParallelVectorEnv(ZeroPadToyEnv, {}, 2, backend="shm")
    try:
        vec.reset()
        assert vec.ensure_traj_rows(T + 1)
        assert vec.ensure_traj_rows(T + 1)  # idempotent fast path
        for segment in range(2):
            vec.rebase_row0()
            expected = []
            for t in range(T):
                expected.append({k: np.copy(v) for k, v in
                                 vec.stacked_obs().items()})
                vec.step(np.zeros(2, np.int32))
            final = {k: np.copy(v) for k, v in vec.stacked_obs().items()}
            views = vec.traj_obs_views(T)
            for t in range(T):
                for k in OBS_KEYS:
                    np.testing.assert_array_equal(
                        views[k][t], expected[t][k],
                        err_msg=f"segment {segment} row {t} {k}")
            for k in OBS_KEYS:
                np.testing.assert_array_equal(
                    vec._slabs.views[k][T], final[k], err_msg=k)
    finally:
        vec.close()


def _toy_collector(vec, rollout_length=4, n_devices=1, **collector_kw):
    """A tiny PPO learner + deferred-fetch collector over ``vec`` (the
    shared scaffolding of the slab/ring aliasing pins)."""
    import jax

    from ddls_tpu.models.policy import GNNPolicy, batched_policy_apply
    from ddls_tpu.parallel import make_mesh
    from ddls_tpu.rl import PPOConfig, PPOLearner, RolloutCollector

    model = GNNPolicy(n_actions=N_ACTIONS)
    obs0 = jax.tree_util.tree_map(np.asarray, vec.obs[0])
    params = model.init(jax.random.PRNGKey(0), obs0)
    learner = PPOLearner(
        lambda p, o: batched_policy_apply(model, p, o),
        PPOConfig(num_sgd_iter=1, sgd_minibatch_size=2,
                  train_batch_size=8), make_mesh(n_devices))
    collector = RolloutCollector(vec, learner, rollout_length,
                                 deferred_fetch=True, **collector_kw)
    collector._needs_reset = False
    return learner, learner.init_state(params), collector


@pytest.mark.shm
def test_deferred_collect_traj_never_aliases_the_slab():
    """Regression pin for the zero-copy-aliasing hazard on the LEGACY
    single-slab path (``ring_segments=0``): jax's CPU client zero-copy
    aliases page-aligned host buffers (shm mmaps are) when no layout
    change is needed — e.g. on a 1-device mesh — so the trajectory
    handed to the async update MUST be a fresh buffer, never slab
    views, or the next segment's worker writes would rewrite the
    update's training data in flight. (The trajectory ring retires the
    copy by ownership instead — see the ring pins below.)"""
    import jax

    from ddls_tpu.rl.rollout import OBS_KEYS, ParallelVectorEnv

    vec = ParallelVectorEnv(ZeroPadToyEnv, {}, 2, backend="shm")
    try:
        vec.reset()
        learner, state, collector = _toy_collector(vec, ring_segments=0)
        out = collector.collect(state.params, jax.random.PRNGKey(1))
        assert vec._slabs is not None and vec._slabs.rows == 5
        assert vec.traj_ring is None
        snapshot = {k: np.copy(out["traj"]["obs"][k]) for k in OBS_KEYS}
        for k in OBS_KEYS:
            assert not np.shares_memory(out["traj"]["obs"][k],
                                        vec._slabs.views[k]), k
        # a second segment rewrites every slab row; the first segment's
        # trajectory must not move
        collector.collect(state.params, jax.random.PRNGKey(2))
        for k in OBS_KEYS:
            np.testing.assert_array_equal(out["traj"]["obs"][k],
                                          snapshot[k], err_msg=k)
    finally:
        vec.close()


# ------------------------------------------------------- trajectory ring
def test_traj_ring_ledger_stall_and_timeout():
    """Ring ledger unit pins (no workers involved): round-robin lease
    order, publish-before-release enforcement, stall counting + bounded
    timeout when every segment is unreleased, and token-driven release
    (an object without the ``is_ready`` protocol counts as ready)."""
    from ddls_tpu.rl.ring import TrajRing

    fields = {"x": ((3,), np.dtype(np.float32))}
    ring = TrajRing(fields, rows=2, num_envs=2, segments=2)
    try:
        a = ring.lease()
        with pytest.raises(RuntimeError, match="leased"):
            ring.publish(ring.segments[1])  # never leased
        ring.publish(a)
        b = ring.lease()
        ring.publish(b)
        # every segment published, no release token anywhere: the next
        # lease must stall and surface a clear timeout, never hang
        with pytest.raises(RuntimeError, match="ring lease timed out"):
            ring.lease(timeout_s=0.2)
        assert ring.stalls == 1
        ring.set_release_token(a, object())  # no is_ready -> ready
        c = ring.lease(timeout_s=5.0)
        assert c is a and c.state == "leased"
        assert ring.releases == 1
        stats = ring.stats()
        assert stats["segments"] == 2 and stats["leases"] == 3
        assert stats["stalls"] == 1
        assert sum(stats["occupancy_counts"]) == stats["leases"] + 1
        # generation fencing: a SLOW consumer's late token (quoting an
        # older lease) must not release the segment's new batch
        ring.publish(c)
        ring.set_release_token(c, object(), generation=c.generation - 1)
        assert c.release_token is None  # stale token ignored
        ring.set_release_token(c, object(), generation=c.generation)
        assert c.release_token is not None
    finally:
        ring.close()


@pytest.mark.shm
def test_ring_traj_views_owned_until_release():
    """The ISSUE 15 aliasing pin, per segment: the deferred collector's
    ring trajectory IS the leased segment (``np.shares_memory`` TRUE —
    the PR 4 bulk defensive copy is gone), and a segment staged into
    the async update is never rewritten before its release token
    reports ready — collection rotates to other segments and only
    reuses this one after release."""
    import jax

    from ddls_tpu.rl.rollout import OBS_KEYS, ParallelVectorEnv

    vec = ParallelVectorEnv(ZeroPadToyEnv, {}, 2, backend="shm")
    try:
        vec.reset()
        learner, state, collector = _toy_collector(vec, ring_segments=2)
        out = collector.collect(state.params, jax.random.PRNGKey(1))
        ring, seg = out["ring"], out["ring_segment"]
        assert ring is vec.traj_ring and seg.state == "published"
        # zero-copy contract: the trajectory is the segment's rows
        for k in OBS_KEYS:
            assert np.shares_memory(out["traj"]["obs"][k],
                                    seg.views[k]), k
        # stage into the update exactly as the loop does, and take the
        # lease-time alias verdict: on a 1-device CPU mesh device_put
        # zero-copy aliases the shm segment
        straj, slv = learner.shard_traj(out["traj"], out["last_values"])
        from ddls_tpu.rl.ring import staged_aliases

        seg.aliased = staged_aliases(straj["obs"], seg.views)
        assert seg.aliased is True
        snapshot = {k: np.copy(v) for k, v in out["traj"]["obs"].items()}
        # the next collect must take the OTHER segment and leave this
        # one's bytes untouched (it is published, not released)
        out2 = collector.collect(state.params, jax.random.PRNGKey(2))
        assert out2["ring_segment"] is not seg
        for k in OBS_KEYS:
            np.testing.assert_array_equal(out["traj"]["obs"][k],
                                          snapshot[k], err_msg=k)
        # consume the staged batch, attach the update token -> the
        # segment becomes reusable and a third collect leases it again
        state2, metrics = learner.train_step(state, straj, slv,
                                             jax.random.PRNGKey(3))
        ring.set_release_token(seg, metrics["total_loss"])
        ring.set_release_token(out2["ring_segment"], object())
        # make the update token provably ready so the next sweep's
        # round-robin deterministically hands segment 0 back
        jax.block_until_ready(metrics["total_loss"])
        out3 = collector.collect(state.params, jax.random.PRNGKey(4))
        assert out3["ring_segment"] is seg
        assert ring.stats()["leases"] == 3
    finally:
        vec.close()


@pytest.mark.shm
def test_ring_multi_device_staging_does_not_alias():
    """The other half of the lease-time verdict: a multi-device mesh's
    strided batch shards force real copies, so the staged tree shares
    no memory with the segment and the segment may release as soon as
    staging lands (token = the staged tree itself)."""
    import jax

    from ddls_tpu.rl.rollout import ParallelVectorEnv

    vec = ParallelVectorEnv(ZeroPadToyEnv, {}, 2, backend="shm")
    try:
        vec.reset()
        learner, state, collector = _toy_collector(vec, n_devices=2,
                                                   ring_segments=2)
        out = collector.collect(state.params, jax.random.PRNGKey(1))
        seg = out["ring_segment"]
        straj, _ = learner.shard_traj(out["traj"], out["last_values"])
        from ddls_tpu.rl.ring import staged_aliases

        assert staged_aliases(straj["obs"], seg.views) is False
        # staged-tree token: ready once the copies complete — make
        # that deterministic, then pin that the next lease's sweep
        # actually RELEASES the segment on this token (the copy-path
        # release, no update output involved)
        out["ring"].set_release_token(seg, straj)
        jax.block_until_ready(straj)
        collector.collect(state.params, jax.random.PRNGKey(2))
        assert out["ring"].stats()["releases"] == 1
        assert seg.state == "free"
    finally:
        vec.close()


@pytest.mark.shm
def test_ring_kill_and_crash_paths_leave_no_litter():
    """ISSUE 15 hardening pin for K segments: a killed worker still
    surfaces as a clear error, ``close()`` unlinks EVERY ring segment
    (kill path included), and a garbage-collected ring unlinks through
    the per-segment finalizers even when close() never ran."""
    import gc

    import jax

    from ddls_tpu.rl.ring import TrajRing
    from ddls_tpu.rl.rollout import ParallelVectorEnv

    vec = ParallelVectorEnv(ZeroPadToyEnv, {}, 2, backend="shm")
    vec.reset()
    _, state, collector = _toy_collector(vec, ring_segments=3)
    out = collector.collect(state.params, jax.random.PRNGKey(1))
    names = list(vec.traj_ring.segment_names())
    assert len(names) == 3 * len(vec._slabs.views)  # 3 segments worth
    # a direct step on the PUBLISHED segment is a loud ledger violation
    with pytest.raises(RuntimeError, match="PUBLISHED"):
        vec.step(np.zeros(2, np.int32))
    out["ring"].release(out["ring_segment"])  # hand it back, then step
    vec._procs[1].kill()
    vec._procs[1].join(timeout=10)
    with pytest.raises(RuntimeError, match="died"):
        for _ in range(3):
            vec.step(np.zeros(2, np.int32))
    vec.close()  # idempotent after the error path's close
    assert not _leaked(names)

    # crash path: no close() at all — the SlabSet finalizers fire on gc
    ring = TrajRing({"x": ((3,), np.dtype(np.float32))}, rows=2,
                    num_envs=2, segments=3)
    names = ring.segment_names()
    assert _leaked(names) == names
    del ring
    gc.collect()
    assert not _leaked(names)


@pytest.mark.shm
def test_killed_worker_raises_clear_error_and_unlinks():
    """ISSUE 5 hardening pin: a worker killed mid-episode surfaces as a
    RuntimeError naming the worker (never a hang), close() is
    idempotent, and no segment survives in /dev/shm."""
    from ddls_tpu.rl.rollout import ParallelVectorEnv

    vec = ParallelVectorEnv(ZeroPadToyEnv, {}, 2, backend="shm")
    vec.reset()
    names = list(vec._slabs.segment_names())
    vec.step(np.zeros(2, np.int32))
    vec._procs[1].kill()
    vec._procs[1].join(timeout=10)
    with pytest.raises(RuntimeError, match="died"):
        for _ in range(3):  # EOF lands on this or the next dispatch
            vec.step(np.zeros(2, np.int32))
    vec.close()  # idempotent after the error path's close
    assert not _leaked(names)


@pytest.mark.shm
def test_slabset_finalizer_unlinks_without_close():
    """Crash-path leak-proofing: a SlabSet that is garbage-collected (or
    reaped at interpreter exit) unlinks its segments even though close()
    never ran."""
    import gc

    from ddls_tpu.rl.shm import SlabSet

    slabs = SlabSet({"x": ((3,), np.dtype(np.float32))}, rows=2,
                    num_envs=2)
    names = slabs.segment_names()
    assert _leaked(names) == names  # alive while the set is
    del slabs
    gc.collect()
    assert not _leaked(names)


def test_backend_auto_falls_back_without_shm(monkeypatch):
    """backend='auto' resolves to pipe when POSIX shm is unavailable
    (the parity default on such platforms)."""
    from ddls_tpu.rl import shm as shm_mod
    from ddls_tpu.rl.rollout import ParallelVectorEnv

    monkeypatch.setattr(shm_mod, "_AVAILABLE", False)
    vec = ParallelVectorEnv(ZeroPadToyEnv, {}, 2, backend="auto")
    try:
        assert vec.backend == "pipe"
        vec.reset()
        vec.step(np.zeros(2, np.int32))
    finally:
        vec.close()


def test_backend_rejects_unknown():
    from ddls_tpu.rl.rollout import ParallelVectorEnv

    with pytest.raises(ValueError, match="backend"):
        ParallelVectorEnv(ZeroPadToyEnv, {}, 1, backend="carrier-pigeon")


# --------------------------------------------- full-collect parity (loops)
_TINY_MODEL = {"fcnet_hiddens": [16],
               "custom_model_config": {"out_features_msg": 4,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}}

ENV_CLS = "ddls_tpu.envs.partitioning_env.RampJobPartitioningEnvironment"


def _env_config(dataset_dir):
    return dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 4,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 2},
        max_partitions_per_op=4,
        reward_function="job_acceptance",
        max_simulation_run_time=5e4,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})


@pytest.mark.shm
@pytest.mark.parametrize("algo,algo_config,depth", [
    ("ppo", {"train_batch_size": 8, "sgd_minibatch_size": 4,
             "num_sgd_iter": 2, "num_workers": 2}, 0),
    ("impala", {"lr": 1e-3, "train_batch_size": 8, "num_workers": 2}, 0),
    ("impala", {"lr": 1e-3, "train_batch_size": 8, "num_workers": 2}, 1),
], ids=["ppo", "impala", "impala-depth1"])
def test_full_collect_parity_pipe_vs_shm(algo, algo_config, depth,
                                         dataset_dir):
    """The ISSUE 5 acceptance pin, extended by ISSUE 15 to the
    trajectory ring: identical post-training params, episode records,
    and learner metrics for the same seeds under the pipe and shm
    transports — at depth 0 AND at depth 1, where the shm side rides
    the multi-segment ring (ownership-protected zero-copy views) while
    pipe uses fresh per-collect buffers. The ring must be a pure
    transport swap below the training math."""
    import jax

    from ddls_tpu.train import make_epoch_loop

    outcomes = {}
    for backend in ("pipe", "shm"):
        loop = make_epoch_loop(
            algo,
            path_to_env_cls=ENV_CLS,
            env_config=_env_config(dataset_dir),
            model=_TINY_MODEL,
            algo_config=dict(algo_config),
            num_envs=2, rollout_length=4, n_devices=2,
            use_parallel_envs=True, vec_env_backend=backend,
            evaluation_interval=None, seed=0, loop_mode="pipelined",
            pipeline_depth=depth)
        assert loop.vec_env.backend == backend
        records = []
        for _ in range(2 if depth == 0 else 3):
            r = loop.run()
            records.append({"learner": dict(r["learner"]),
                            "episodes": r["episodes"],
                            "env_steps": r["env_steps_this_iter"]})
        loop.sync_metrics()
        params = jax.device_get(loop.state.params)
        if backend == "shm":
            # the shm side actually exercised the ring (depth + 2
            # segments) — the parity below is about the ring, not a
            # silent fallback
            assert loop.vec_env.traj_ring is not None
            assert (len(loop.vec_env.traj_ring.segments)
                    == loop.pipeline_depth + 2)
        loop.close()
        outcomes[backend] = (records, params)

    pipe_records, pipe_params = outcomes["pipe"]
    shm_records, shm_params = outcomes["shm"]
    for e, (rp, rs) in enumerate(zip(pipe_records, shm_records)):
        assert rp["env_steps"] == rs["env_steps"], f"epoch {e}"
        assert rp["learner"] == rs["learner"], f"epoch {e} metrics"
        assert rp["episodes"] == rs["episodes"], f"epoch {e} episodes"
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        pipe_params, shm_params)


@pytest.mark.shm
def test_shm_epoch_stays_transfer_free(dataset_dir):
    """The slab-trajectory epoch keeps the round-6 pin: a steady-state
    collect→update epoch performs NO implicit device↔host transfer —
    slab views enter the device only through the collector's explicit
    device_put staging."""
    import jax

    from ddls_tpu.train import make_epoch_loop

    loop = make_epoch_loop(
        "ppo",
        path_to_env_cls=ENV_CLS,
        env_config=_env_config(dataset_dir),
        model=_TINY_MODEL,
        algo_config={"train_batch_size": 8, "sgd_minibatch_size": 4,
                     "num_sgd_iter": 2, "num_workers": 2},
        num_envs=2, rollout_length=4, n_devices=2,
        use_parallel_envs=True, vec_env_backend="shm",
        evaluation_interval=None, seed=0, loop_mode="pipelined",
        metrics_sync_interval=1000)
    try:
        assert loop.vec_env.backend == "shm"
        loop.run()  # warm epoch: compiles + first-use constant transfers
        loop.run()  # second ring segment's first staging (alias probe)
        with jax.transfer_guard("disallow"):
            r = loop.run()
        assert np.isfinite(r["learner"]["total_loss"])
    finally:
        loop.close()


@pytest.mark.shm
def test_ring_depth2_epoch_stays_transfer_free(dataset_dir):
    """ISSUE 15 transfer-guard pin: the steady-state depth-2 epoch adds
    no implicit device↔host transfer on the main thread — ring lease
    sweeps are pointer/readiness checks, release tokens attach without
    fetching, params-age metrics are host ints. Warmup covers every
    segment's one-time alias probe (depth + 2 segments)."""
    import jax

    from ddls_tpu.train import make_epoch_loop

    loop = make_epoch_loop(
        "impala",
        path_to_env_cls=ENV_CLS,
        env_config=_env_config(dataset_dir),
        model=_TINY_MODEL,
        algo_config={"lr": 1e-3, "train_batch_size": 8,
                     "num_workers": 2},
        num_envs=2, rollout_length=4, n_devices=2,
        use_parallel_envs=True, vec_env_backend="shm",
        evaluation_interval=None, seed=0, loop_mode="pipelined",
        pipeline_depth=2, metrics_sync_interval=1000)
    try:
        assert loop.vec_env.backend == "shm"
        for _ in range(4):  # every segment staged at least once
            loop.run()
        with jax.transfer_guard("disallow"):
            r = loop.run()
        assert np.isfinite(r["learner"]["total_loss"])
        assert r["learner"]["params_age_updates"] == 2.0
        stats = loop.ring_stats()
        assert stats is not None and stats["segments"] == 4
        assert stats["leases"] >= 5
        assert stats["mean_params_age"] is not None
    finally:
        loop.close()


# ------------------------------------------------------- serve arena reuse
def test_serve_bucketer_arena_reuse_bit_equal():
    """reuse_arenas output equals the allocating bucketer for every
    field, and a released arena is recycled for the next same-bucket
    lease (no fresh allocation)."""
    from ddls_tpu.serve.bucketing import ObsBucketer

    buckets = [(4, 6), (8, 12)]
    plain = ObsBucketer(buckets)
    reuse = ObsBucketer(buckets, reuse_arenas=True)
    rng = np.random.RandomState(7)
    leased = []
    for _ in range(6):
        obs, n, m = _random_encoded_obs(rng, 8, 12)
        i_p, padded_p = plain.bucket_obs(obs)
        i_r, padded_r = reuse.bucket_obs(obs)
        assert i_p == i_r
        for k in padded_p:
            np.testing.assert_array_equal(
                np.asarray(padded_r[k]), np.asarray(padded_p[k]),
                err_msg=k)
        leased.append((i_r, padded_r))
    for idx, padded in leased:
        reuse.release(idx, padded)
    # the next lease in a released bucket must come from the pool
    idx0, padded0 = leased[-1]
    pool_sizes = [len(p) for p in reuse._pools]
    obs, n, m = _random_encoded_obs(rng, 8, 12)
    i_new, _ = reuse.bucket_obs(obs)
    assert len(reuse._pools[i_new]) == pool_sizes[i_new] - 1


def test_serve_bucketer_pooled_arena_key_mismatch_gets_fresh_arena():
    """Regression pin: an arena pooled from an obs with an EXTRA field
    must not be handed to a later request lacking it (pad_obs_to(out=)
    copies every out entry from the obs — a stale key would KeyError
    mid-request); key-set mismatches lease a fresh arena instead."""
    from ddls_tpu.serve.bucketing import ObsBucketer

    reuse = ObsBucketer([(8, 12)], reuse_arenas=True)
    rng = np.random.RandomState(11)
    rich, _, _ = _random_encoded_obs(rng, 8, 12)
    rich["client_tag"] = np.array([1.0], np.float32)  # extra field
    idx, padded_rich = reuse.bucket_obs(rich)
    reuse.release(idx, padded_rich)
    plain, _, _ = _random_encoded_obs(rng, 8, 12)  # no client_tag
    idx2, padded_plain = reuse.bucket_obs(plain)  # must not raise
    assert "client_tag" not in padded_plain
    # and the reverse direction: plain arena pooled, rich obs next
    reuse.release(idx2, padded_plain)
    rich2, _, _ = _random_encoded_obs(rng, 8, 12)
    rich2["client_tag"] = np.array([2.0], np.float32)
    _, padded_rich2 = reuse.bucket_obs(rich2)
    np.testing.assert_array_equal(padded_rich2["client_tag"],
                                  rich2["client_tag"])


# ------------------------------------------------------------ tier-1 guard
def test_check_shm_unlink_clean_tree():
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_shm_unlink.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_check_shm_unlink_flags_unpaired_create(tmp_path):
    bad = tmp_path / "leaky.py"
    bad.write_text(
        "from multiprocessing import shared_memory\n"
        "seg = shared_memory.SharedMemory(create=True, size=64)\n")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_shm_unlink.py"),
         "--paths", str(tmp_path)],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "leaky.py" in out.stdout

    good = tmp_path / "leaky.py"
    good.write_text(
        "import weakref\n"
        "from multiprocessing import shared_memory\n"
        "seg = shared_memory.SharedMemory(create=True, size=64)\n"
        "weakref.finalize(seg, seg.unlink)\n"
        "# seg.unlink() on close\n")
    out = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "check_shm_unlink.py"),
         "--paths", str(tmp_path)],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout
