"""Verbatim reference-config compatibility (VERDICT r3 next #5 /
BASELINE "the existing ramp_job_partitioning_configs run unchanged").

Points load_config at the reference's own config trees — unmodified on
disk — applies the compat shim, and builds + runs a real epoch loop.
Only machine-specific dataset paths and run-length knobs are overridden
via the normal CLI-override mechanism (that is usage, not modification).
"""
import os

import pytest

from ddls_tpu.config import instantiate, load_config
from ddls_tpu.train import make_epoch_loop
from ddls_tpu.train.compat import apply_reference_compat

REF = "/root/reference/scripts"
pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference tree not present")


@pytest.fixture(scope="module")
def dataset_dir(tmp_path_factory):
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    d = str(tmp_path_factory.mktemp("ref_compat_jobs"))
    generate_pipedream_txt_files(d, n_cnn=2, n_translation=1, seed=2)
    return d


def _compose(tree, name, overrides):
    cfg = load_config(os.path.join(REF, tree), name, overrides)
    with pytest.warns(UserWarning, match="reference-config compat"):
        apply_reference_compat(cfg)
    return cfg


@pytest.mark.parametrize("algo,expected", [
    ("apex_dqn", "apex_dqn"), ("ppo", "ppo"), ("impala", "impala"),
    ("pg", "pg"), ("es", "es")])
def test_partitioning_tree_composes_for_every_algo(algo, expected,
                                                   dataset_dir):
    cfg = _compose(
        "ramp_job_partitioning_configs", "rllib_config",
        [f"algo={algo}",
         f"env_config.jobs_config.path_to_files={dataset_dir}"])
    assert cfg["algo"]["algo_name"] == expected
    assert "path_to_rllib_trainer_cls" not in cfg["algo"]
    # every ddls.* path translated
    def no_ref_paths(node):
        if isinstance(node, dict):
            return all(no_ref_paths(v) for v in node.values())
        if isinstance(node, list):
            return all(no_ref_paths(v) for v in node)
        return not (isinstance(node, str) and node.startswith("ddls."))
    assert no_ref_paths(cfg)


def test_partitioning_tree_runs_an_epoch(dataset_dir):
    """The reference tree (apex_dqn default) drives a REAL collect+update
    epoch end-to-end on the TPU stack."""
    cfg = _compose(
        "ramp_job_partitioning_configs", "rllib_config",
        [f"env_config.jobs_config.path_to_files={dataset_dir}",
         "env_config.jobs_config.replication_factor=2",
         "env_config.max_simulation_run_time=1e5",
         "launcher.num_epochs=1"])
    from scripts.train_from_config import build_epoch_loop_kwargs

    kwargs = build_epoch_loop_kwargs(cfg)
    kwargs["num_envs"] = 2
    kwargs["rollout_length"] = 4
    loop = make_epoch_loop(cfg["algo"]["algo_name"], **kwargs)
    results = loop.run()
    assert results["epoch_counter"] == 1
    assert results["env_steps_this_iter"] == 8
    loop.close()


def test_shaping_tree_composes_and_heuristic_runs(dataset_dir):
    """The placement-shaping tree's heuristic config instantiates its
    FirstFit shaper actor + env and steps an episode."""
    cfg = _compose(
        "ramp_job_placement_shaping_configs", "heuristic_config",
        [f"eval_loop.env.jobs_config.path_to_files={dataset_dir}",
         "eval_loop.env.jobs_config.replication_factor=2",
         "eval_loop.env.max_simulation_run_time=1e5"])
    loop_cfg = cfg["eval_loop"]
    env = instantiate(loop_cfg["env"])
    actor = instantiate(loop_cfg["actor"])
    from ddls_tpu.train.loops import EvalLoop

    loop = EvalLoop(env=env, actor=actor)
    result = loop.run(seed=0)
    assert result["episode_length"] >= 1
