"""L6 tests: segment ops + GNN policy (forward shapes, masking, padding
invariance, jit/vmap)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddls_tpu.models import GNN, GNNPolicy, batched_policy_apply
from ddls_tpu.ops import masked_mean, masked_segment_mean, masked_segment_sum

N_ACTIONS = 9
MAX_NODES = 12
MAX_EDGES = (MAX_NODES * (MAX_NODES - 1)) // 2


def _rand_obs(rng, n=5, m=6, max_nodes=MAX_NODES, max_edges=MAX_EDGES):
    node_features = np.zeros((max_nodes, 5), np.float32)
    node_features[:n] = rng.uniform(0, 1, (n, 5))
    edge_features = np.zeros((max_edges, 2), np.float32)
    edge_features[:m] = rng.uniform(0, 1, (m, 2))
    src = np.zeros(max_edges, np.int32)
    dst = np.zeros(max_edges, np.int32)
    src[:m] = rng.integers(0, n, m)
    dst[:m] = rng.integers(0, n, m)
    mask = np.ones(N_ACTIONS, np.int32)
    mask[5] = 0
    return {
        "action_set": np.arange(N_ACTIONS, dtype=np.int32),
        "action_mask": mask,
        "node_features": node_features,
        "edge_features": edge_features,
        "graph_features": rng.uniform(0, 1, (17 + N_ACTIONS,)).astype(
            np.float32),
        "edges_src": src,
        "edges_dst": dst,
        "node_split": np.array([n], np.int32),
        "edge_split": np.array([m], np.int32),
    }


class TestSegmentOps:
    def test_masked_segment_sum(self):
        data = jnp.array([[1.0], [2.0], [4.0], [100.0]])
        seg = jnp.array([0, 0, 1, 0])
        mask = jnp.array([True, True, True, False])
        out = masked_segment_sum(data, seg, mask, 3)
        np.testing.assert_allclose(out, [[3.0], [4.0], [0.0]])

    def test_masked_segment_mean_with_self(self):
        data = jnp.array([[2.0], [4.0]])
        seg = jnp.array([0, 0])
        mask = jnp.array([True, True])
        extra = jnp.array([[6.0], [5.0]])
        out = masked_segment_mean(data, seg, mask, 2, extra=extra)
        # node 0: mean(6, 2, 4) = 4; node 1: mean(5) = 5 (no in-edges)
        np.testing.assert_allclose(out, [[4.0], [5.0]])

    def test_masked_mean(self):
        data = jnp.array([[1.0, 2.0], [3.0, 4.0], [99.0, 99.0]])
        mask = jnp.array([True, True, False])
        np.testing.assert_allclose(masked_mean(data, mask), [2.0, 3.0])


class TestGNN:
    def test_forward_shape_and_padding_mask(self):
        rng = np.random.default_rng(0)
        obs = _rand_obs(rng, n=4, m=5)
        model = GNN()
        params = model.init(
            jax.random.PRNGKey(0),
            jnp.asarray(obs["node_features"]),
            jnp.asarray(obs["edge_features"]),
            jnp.asarray(obs["edges_src"]), jnp.asarray(obs["edges_dst"]),
            jnp.arange(MAX_NODES) < 4, jnp.arange(MAX_EDGES) < 5)
        out = model.apply(params,
                          jnp.asarray(obs["node_features"]),
                          jnp.asarray(obs["edge_features"]),
                          jnp.asarray(obs["edges_src"]),
                          jnp.asarray(obs["edges_dst"]),
                          jnp.arange(MAX_NODES) < 4,
                          jnp.arange(MAX_EDGES) < 5)
        assert out.shape == (MAX_NODES, 16)
        # padded nodes produce exactly zero embeddings
        np.testing.assert_allclose(out[4:], 0.0)

    def test_padding_invariance(self):
        """Growing the pad region must not change real-node embeddings."""
        rng = np.random.default_rng(1)
        small = _rand_obs(rng, n=4, m=5, max_nodes=8, max_edges=10)
        model = GNN()
        args_small = (jnp.asarray(small["node_features"]),
                      jnp.asarray(small["edge_features"]),
                      jnp.asarray(small["edges_src"]),
                      jnp.asarray(small["edges_dst"]),
                      jnp.arange(8) < 4, jnp.arange(10) < 5)
        params = model.init(jax.random.PRNGKey(0), *args_small)
        out_small = model.apply(params, *args_small)

        big = {k: np.copy(v) for k, v in small.items()}
        big["node_features"] = np.zeros((20, 5), np.float32)
        big["node_features"][:8] = small["node_features"]
        big["edge_features"] = np.zeros((40, 2), np.float32)
        big["edge_features"][:10] = small["edge_features"]
        for k in ("edges_src", "edges_dst"):
            arr = np.zeros(40, np.int32)
            arr[:10] = small[k]
            big[k] = arr
        out_big = model.apply(params,
                              jnp.asarray(big["node_features"]),
                              jnp.asarray(big["edge_features"]),
                              jnp.asarray(big["edges_src"]),
                              jnp.asarray(big["edges_dst"]),
                              jnp.arange(20) < 4, jnp.arange(40) < 5)
        np.testing.assert_allclose(out_small[:4], out_big[:4], atol=1e-5)


class TestGNNPolicy:
    @pytest.fixture(scope="class")
    def model_params(self):
        rng = np.random.default_rng(2)
        obs = _rand_obs(rng)
        model = GNNPolicy(n_actions=N_ACTIONS)
        params = model.init(jax.random.PRNGKey(0),
                            jax.tree.map(jnp.asarray, obs))
        return model, params

    def test_forward_shapes(self, model_params):
        model, params = model_params
        obs = _rand_obs(np.random.default_rng(3))
        logits, value = model.apply(params, jax.tree.map(jnp.asarray, obs))
        assert logits.shape == (N_ACTIONS,)
        assert value.shape == ()

    def test_action_masking(self, model_params):
        model, params = model_params
        obs = _rand_obs(np.random.default_rng(4))
        logits, _ = model.apply(params, jax.tree.map(jnp.asarray, obs))
        assert logits[5] <= jnp.finfo(jnp.float32).min / 2
        probs = jax.nn.softmax(logits)
        assert probs[5] == 0.0
        assert np.isfinite(np.asarray(logits[np.asarray(
            obs["action_mask"], bool)])).all()

    def test_batched_apply_jit(self, model_params):
        model, params = model_params
        rng = np.random.default_rng(5)
        batch = [_rand_obs(rng, n=int(rng.integers(2, 8))) for _ in range(4)]
        stacked = {k: jnp.stack([jnp.asarray(o[k]) for o in batch])
                   for k in batch[0]}
        fn = jax.jit(lambda p, o: batched_policy_apply(model, p, o))
        logits, values = fn(params, stacked)
        assert logits.shape == (4, N_ACTIONS)
        assert values.shape == (4,)
        # batching must agree with per-sample application (loose tolerance:
        # jit+vmap lowers the segment ops differently, reassociating f32 sums)
        solo_logits, solo_value = model.apply(
            params, jax.tree.map(jnp.asarray, batch[2]))
        np.testing.assert_allclose(logits[2], solo_logits, atol=5e-3)
        np.testing.assert_allclose(values[2], solo_value, atol=5e-3)

    def test_flat_batched_matches_vmapped(self, model_params):
        """batched_policy_apply runs the flattened mega-graph forward; it
        computes the same sums as vmapping the single-sample __call__
        (every parameterised op is row-wise; segment sums keep per-node
        edge order), so outputs agree to f32 reassociation tolerance — XLA
        may tile the row-wise matmuls differently per shape, so exact
        bitwise equality only holds at some shapes. Masked (-inf) entries
        must agree exactly."""
        from ddls_tpu.models.policy import vmapped_policy_apply

        model, params = model_params
        rng = np.random.default_rng(7)
        batch = [_rand_obs(rng, n=int(rng.integers(2, 8))) for _ in range(6)]
        stacked = {k: jnp.stack([jnp.asarray(o[k]) for o in batch])
                   for k in batch[0]}
        lo_f, va_f = jax.jit(
            lambda p, o: batched_policy_apply(model, p, o))(params, stacked)
        lo_v, va_v = jax.jit(
            lambda p, o: vmapped_policy_apply(model, p, o))(params, stacked)
        assert bool(jnp.all(jnp.isfinite(lo_f) == jnp.isfinite(lo_v)))
        np.testing.assert_allclose(
            np.where(np.isfinite(lo_f), lo_f, 0.0),
            np.where(np.isfinite(lo_v), lo_v, 0.0), atol=1e-5)
        np.testing.assert_allclose(va_f, va_v, atol=1e-5)

    def test_grads_flow(self, model_params):
        model, params = model_params
        obs = jax.tree.map(jnp.asarray, _rand_obs(np.random.default_rng(6)))

        def loss(p):
            logits, value = model.apply(p, obs)
            return jnp.sum(jax.nn.log_softmax(logits)[0]) + value ** 2

        grads = jax.grad(loss)(params)
        leaves = jax.tree.leaves(grads)
        assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
        assert any(np.abs(np.asarray(g)).sum() > 0 for g in leaves)
