"""jax_price_and_score vs the host pricing/scheduling pipeline: for every
job placed during a real episode, the kernel's dep run times, flow mask,
channel assignment, and SRPT lookahead scores must match the host's
(assign_dep_run_times + SRPT schedulers + build_native_lookahead_arrays).

The full-precision comparison runs in a subprocess with JAX_ENABLE_X64=1
(x64 is a process-global jax flag; the main pytest process stays f32), the
way tests/test_distributed.py isolates its gloo processes."""
import os
import subprocess
import sys

DRIVER = r"""
import numpy as np
import jax
import jax.numpy as jnp

assert jax.config.read("jax_enable_x64"), "driver needs JAX_ENABLE_X64=1"

import tempfile
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.sim.jax_lookahead import build_native_lookahead_arrays
from ddls_tpu.sim.jax_env import (build_shape_tables, config_tables_for,
                                  jax_price_and_score, stack_config_tables)

d = tempfile.mkdtemp(prefix="jax_pricing_")
generate_pipedream_txt_files(d, n_cnn=2, n_translation=1, seed=3)
env = RampJobPartitioningEnvironment(
    topology_config={"type": "ramp", "kwargs": {
        "num_communication_groups": 4,
        "num_racks_per_communication_group": 4,
        "num_servers_per_rack": 2, "num_channels": 1,
        "total_node_bandwidth": 1.6e12,
        "intra_gpu_propagation_latency": 50e-9,
        "worker_io_latency": 100e-9}},
    node_config={"type_1": {"num_nodes": 32, "workers_config": [
        {"num_workers": 1, "worker": "A100"}]}},
    jobs_config={"path_to_files": d,
        "job_interarrival_time_dist": {
            "_target_": "ddls_tpu.demands.distributions.Fixed", "val": 50.0},
        "max_acceptable_job_completion_time_frac_dist": {
            "_target_": "ddls_tpu.demands.distributions.Uniform",
            "min_val": 0.3, "max_val": 1.0, "decimals": 2},
        "replication_factor": 12, "job_sampling_mode": "remove_and_repeat",
        "num_training_steps": 20},
    max_partitions_per_op=8, min_op_run_time_quantum=0.01,
    reward_function="job_acceptance", max_simulation_run_time=1.5e4,
    pad_obs_kwargs={"max_nodes": 150, "max_edges": 512})
obs = env.reset(seed=11)

topo = env.cluster.topology
records = []
rng = np.random.RandomState(2)
for _ in range(40):
    job = next(iter(env.cluster.job_queue.jobs.values()))
    valid = np.nonzero(np.asarray(obs["action_mask"]))[0]
    prefer = [a for a in valid if a > 0]
    action = int(rng.choice(prefer)) if prefer else 0
    obs, reward, done, info = env.step(action)
    ji = env.cluster.job_id_to_job_idx[job.job_id]
    if action > 0 and ji in env.cluster.jobs_running:
        placed = env.cluster.jobs_running[ji]
        native = build_native_lookahead_arrays(env.cluster, placed)
        payload = env.cluster.job_dep_arrays[ji]
        records.append({
            "model": placed.details["model"],
            "graph": job.graph,              # original profile graph
            "degree": action,
            "sc": env.cluster.job_server_codes[ji].copy(),
            "times": placed.dep_init_run_time_arr.copy(),
            "chan": payload.chan.copy(),
            "op_score": native.op_score.copy(),
            "dep_score": native.dep_score.copy(),
            "is_flow": native.dep_is_flow.copy(),
        })
    if done:
        break

assert len(records) >= 6, f"only {len(records)} placements recorded"

ramp_shape = topo.shape
st = build_shape_tables(ramp_shape, 8)
keys, cfgs = [], []
for r in records:
    key = (r["model"], r["degree"])
    if key not in keys:
        keys.append(key)
        cfgs.append(config_tables_for(r["graph"], r["degree"], 0.01))
tables, pads = stack_config_tables(cfgs, st)
jt = {k: jnp.asarray(v) for k, v in tables.items()}
pair_channel = jnp.asarray(topo.dense_tables()["pair_channel"])
comm = {"x": topo.num_communication_groups,
        "rate": topo.channel_bandwidth,
        "prop": topo.intra_gpu_propagation_latency,
        "io": topo.worker_io_latency}
fn = jax.jit(lambda sc, cfg: jax_price_and_score(
    sc, cfg, jt, st, pads, comm, pair_channel))

checked = 0
for r in records:
    cfg = keys.index((r["model"], r["degree"]))
    n = len(r["sc"])
    m = len(r["times"])
    sc = np.full(pads.n_ops, -1, np.int64)
    sc[:n] = r["sc"]
    times, is_flow, chan, op_score, dep_score, finite_ok = (
        np.asarray(x) for x in fn(jnp.asarray(sc), cfg))
    assert finite_ok
    np.testing.assert_allclose(times[:m], r["times"], rtol=1e-12, atol=0,
        err_msg=f"dep times mismatch {r['model']} deg {r['degree']}")
    assert (times[m:] == 0).all()
    assert (is_flow[:m] == r["is_flow"]).all(), "flow mask mismatch"
    assert (chan[:m] == r["chan"]).all(), "channel assignment mismatch"
    np.testing.assert_allclose(op_score[:n], r["op_score"], rtol=0, atol=0,
        err_msg=f"op_score mismatch {r['model']} deg {r['degree']}")
    np.testing.assert_allclose(dep_score[:m], r["dep_score"], rtol=0,
        atol=0,
        err_msg=f"dep_score mismatch {r['model']} deg {r['degree']}")
    checked += 1
print(f"PRICING_PARITY_OK checked={checked}")
"""


def test_pricing_and_scores_match_host_x64():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    res = subprocess.run([sys.executable, "-c", DRIVER], env=env,
                         capture_output=True, text=True, timeout=900)
    assert res.returncode == 0, (res.stdout[-4000:], res.stderr[-4000:])
    assert "PRICING_PARITY_OK" in res.stdout, res.stdout[-2000:]
