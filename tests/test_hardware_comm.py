"""L2 tests: topologies, devices, RAMP analytical communication model."""
import math

import numpy as np
import pytest

from ddls_tpu.hardware import A100, RampTopology, TorusTopology, build_topology
from ddls_tpu.sim.comm_model import (collective_span, effective_transceivers,
                                     one_to_one_time, parallel_add_time,
                                     ramp_all_reduce_time)


def _node_config(n, worker="A100"):
    return {"type_1": {"num_nodes": n,
                       "workers_config": [{"num_workers": 1, "worker": worker}]}}


def test_ramp_topology_structure():
    topo = RampTopology(num_communication_groups=2,
                        num_racks_per_communication_group=2,
                        num_servers_per_rack=2,
                        num_channels=1,
                        total_node_bandwidth=1.6e12)
    assert topo.num_servers == 8
    assert topo.channel_bandwidth == pytest.approx(0.8e12)
    # full mesh: C(8,2)=28 links, one channel per direction
    assert len(topo.links) == 28
    assert len(topo.channel_id_to_channel) == 56
    # one-hop shortest paths
    assert topo.shortest_paths["0-0-0"]["1-1-1"] == [["0-0-0", "1-1-1"]]

    topo.populate_workers(_node_config(8))
    assert topo.num_workers == 8
    assert topo.worker_types == {"A100"}
    assert topo.worker_to_server["node_0-1-0_worker_0"] == "0-1-0"


def test_ramp_rejects_invalid_shape_and_node_count():
    with pytest.raises(ValueError):
        RampTopology(num_communication_groups=2,
                     num_racks_per_communication_group=4,
                     num_servers_per_rack=1)
    topo = RampTopology(2, 2, 2)
    with pytest.raises(ValueError):
        topo.populate_workers(_node_config(5))


def test_build_topology_from_config():
    topo = build_topology({"type": "ramp", "kwargs": {
        "num_communication_groups": 4,
        "num_racks_per_communication_group": 4,
        "num_servers_per_rack": 2,
        "num_channels": 1,
        "total_node_bandwidth": 1.6e12,
        "intra_gpu_propagation_latency": 50e-9,
        "worker_io_latency": 100e-9}})
    assert topo.num_servers == 32
    assert topo.channel_bandwidth == pytest.approx(0.4e12)


def test_torus_topology():
    topo = TorusTopology(x_dims=3, y_dims=3)
    assert topo.num_servers == 9
    # 2D torus: 2 links per node, each counted once -> 18 links
    assert len(topo.links) == 18
    path = topo.shortest_paths["0-0"]["2-0"][0]
    assert len(path) == 2  # wrap-around neighbour


def test_worker_mount_memory_accounting(dataset_dir):
    import glob

    from ddls_tpu.demands.job import Job
    from ddls_tpu.graphs.readers import graph_from_pipedream_txt

    g = graph_from_pipedream_txt(sorted(glob.glob(dataset_dir + "/*.txt"))[0])
    job = Job(g, 1, 1.0, job_id=1, details={"job_idx": 0})
    w = A100(processor_id="w0")
    op = g.op_ids[0]
    w.mount(job, op)
    assert w.memory_occupied == pytest.approx(g.memory_cost(op))
    assert w.mounted_job_idx_to_ops[0] == {op}
    w.unmount(job, op)
    assert w.memory_occupied == pytest.approx(0.0)
    assert 0 not in w.mounted_job_idx_to_ops


def test_one_to_one_closed_form():
    t = one_to_one_time(1e9, data_rate=4e11, propagation_latency=50e-9,
                        io_latency=100e-9)
    assert t == pytest.approx(50e-9 + 200e-9 + 1e9 / 4e11)


def test_effective_transceivers():
    assert effective_transceivers(4, 1) == 0.0
    # d=2, J=1: 1 + min(4, 4) - 1 = 4
    assert effective_transceivers(4, 2, 1) == 4.0
    # d=5, J=1: 1 + min(4, 1) - 1 = 1
    assert effective_transceivers(4, 5, 1) == 1.0


def test_parallel_add_roofline():
    # devices=2: n_op=1, n_bytes=6, AI=1/6, ops=data/4
    t = parallel_add_time(1000.0, 2, mem_frequency=2e12, peak_flops=130e12)
    expected = (1 * (1000.0 / 2) / 2) / min(2e12 / 6, 130e12)
    assert t == pytest.approx(expected)


def test_ramp_all_reduce_against_manual_expansion():
    """Independently expand the documented reduce-scatter+all-gather formula
    and check the implementation reproduces it step by step."""
    kwargs = dict(message_size=1e9, num_servers=2, num_racks=2,
                  num_comm_groups=2, network_comm_groups=4,
                  data_rate=4e11, propagation_latency=50e-9,
                  io_latency=100e-9)
    got = ramp_all_reduce_time(**kwargs)

    x, rate = 4, 4e11
    data_per_tx = rate / x
    subs = [2, 2, 2, math.ceil(2 / 4)]
    msgs = [math.ceil(1e9 / 2)]
    for s in subs[1:]:
        msgs.append(math.ceil(msgs[-1] / s))
    comm = comp = 0.0
    for step, s in enumerate(subs):
        if s > 1:
            comp += parallel_add_time(msgs[step] * s, s)
            bw = effective_transceivers(x, s, 1) * data_per_tx
            comm += 50e-9 + 2 * 100e-9 + msgs[step] / bw
    assert got == pytest.approx(2 * comm + comp)
    assert got > 0


def test_all_reduce_monotonic_in_message_size():
    base = dict(num_servers=4, num_racks=2, num_comm_groups=2,
                network_comm_groups=4, data_rate=4e11)
    t1 = ramp_all_reduce_time(message_size=1e8, **base)
    t2 = ramp_all_reduce_time(message_size=1e9, **base)
    assert t2 > t1


def test_collective_span():
    cgs, racks, servers, full = collective_span(
        ["0-0-0", "0-1-0", "1-0-1", "1-0-0"])
    assert (cgs, racks, servers, full) == (2, 2, 2, 4)
