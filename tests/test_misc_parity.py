"""Misc component parity: information functions, profiling hooks, generic
GPU device, and the job-scheduling stub."""
import os

import numpy as np
import pytest

from ddls_tpu.envs import (DDLSInformationFunction, JobSchedulingEnvironment,
                           RampJobPartitioningEnvironment)
from ddls_tpu.envs.interfaces import make_information_function
from ddls_tpu.hardware.devices import DEVICE_TYPES, GPU
from ddls_tpu.utils import enable_xla_dump, jax_profiler_trace


def _env_config(dataset_dir, **over):
    cfg = dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 2,
            "job_sampling_mode": "remove",
            "num_training_steps": 2},
        max_partitions_per_op=4,
        reward_function="job_acceptance",
        max_simulation_run_time=5e4,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})
    cfg.update(over)
    return cfg


def test_information_function_episode_stats(dataset_dir):
    env = RampJobPartitioningEnvironment(
        **_env_config(dataset_dir, information_function="episode_stats"))
    obs = env.reset(seed=0)
    _, _, _, info = env.step(int(np.flatnonzero(obs["action_mask"])[0]))
    assert set(info) == {"num_jobs_arrived", "num_jobs_completed",
                         "num_jobs_blocked"}
    assert info["num_jobs_arrived"] >= 1


def test_information_function_default_and_unknown(dataset_dir):
    env = RampJobPartitioningEnvironment(**_env_config(dataset_dir))
    obs = env.reset(seed=0)
    _, _, _, info = env.step(int(np.flatnonzero(obs["action_mask"])[0]))
    assert info == {}
    with pytest.raises(ValueError, match="information_function"):
        make_information_function("nope")
    assert isinstance(make_information_function("default"),
                      DDLSInformationFunction)


def test_generic_gpu_device():
    assert "GPU" in DEVICE_TYPES
    gpu = GPU(processor_id="g0", memory_capacity=8e9)
    assert gpu.memory_capacity == int(8e9)
    assert GPU(processor_id="g1").memory_capacity == int(32e9)


def test_job_scheduling_stub():
    with pytest.raises(NotImplementedError):
        JobSchedulingEnvironment()


def test_jax_profiler_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    trace_dir = tmp_path / "trace"
    with jax_profiler_trace(str(trace_dir)):
        jax.block_until_ready(jnp.ones(8) * 2)
    files = list(trace_dir.rglob("*"))
    assert files, "trace produced no artifacts"
    # disabled -> no-op
    with jax_profiler_trace(None):
        pass


def test_enable_xla_dump(monkeypatch, tmp_path):
    monkeypatch.setenv("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    enable_xla_dump(str(tmp_path / "dump"))
    flags = os.environ["XLA_FLAGS"]
    assert "--xla_force_host_platform_device_count=8" in flags
    assert f"--xla_dump_to={tmp_path / 'dump'}" in flags
    enable_xla_dump(str(tmp_path / "dump"))  # idempotent
    assert flags == os.environ["XLA_FLAGS"]
