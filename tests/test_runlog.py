"""Run ledger + unified timeline tests (ISSUE 18).

Covers the RunLedger lifecycle (manifest/result/snapshot files, the
save→swap→restore of global telemetry state), the Perfetto timeline
builder over synthetic run dirs (span slices, transfer flow arrows,
ring lifecycle async slices, counter tracks), the end-to-end acceptance
path — ledger-enabled pipelined AND sebulba training runs merged into
one trace — and the ``scripts/perf_history.py --check --json`` tier-1
smoke (structural gate over the committed BENCH artifacts; no bench
execution).
"""
import json
import os
import subprocess
import sys
import time

import pytest

from ddls_tpu import telemetry

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from test_fused import ENV_CLS, _TINY_MODEL, _env_config  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_global_telemetry():
    def clean():
        telemetry.reset()
        telemetry.disable()
        reg = telemetry.registry()
        reg.sink = None
        reg.clock = time.perf_counter
        reg.record_intervals = False

    clean()
    yield
    clean()


@pytest.fixture(scope="module")
def runlog_dataset(tmp_path_factory):
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    d = str(tmp_path_factory.mktemp("runlog_jobs"))
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=9)
    return d


# ------------------------------------------------------------ RunLedger
def test_run_ledger_roundtrip(tmp_path):
    from ddls_tpu.telemetry.runlog import RunLedger, load_run_dir

    run_dir = tmp_path / "run"
    ledger = RunLedger(str(run_dir), kind="bench:sim",
                       argv=["bench.py", "--mode", "sim"],
                       config={"num_envs": 4},
                       scenario_fingerprint="abc123")
    assert not telemetry.enabled()
    ledger.open()
    # open() flipped the global registry on with the run-dir sink
    assert telemetry.enabled()
    assert telemetry.registry().sink is not None
    with telemetry.span("bench.run"):
        pass
    with telemetry.transfer("stage.traj", "h2d") as tr:
        tr.add({"x": b""})
    ledger.update_config({"warmed": True})
    ledger.record_result({"metric": "env_steps_per_sec", "value": 42.0})
    ledger.finalize(blocks={"ring": {"stalls": 0}})
    # finalize() restored the prior (disabled, sinkless) state
    assert not telemetry.enabled()
    assert telemetry.registry().sink is None

    run = load_run_dir(str(run_dir))
    man = run["manifest"]
    assert man["kind"] == "bench:sim"
    assert man["argv"] == ["bench.py", "--mode", "sim"]
    assert man["config"]["num_envs"] == 4
    assert man["config"]["warmed"] is True  # update_config rewrote it
    assert man["scenario_fingerprint"] == "abc123"
    assert {"unix", "perf"} <= set(man["clock"])
    assert man["process"] == {"index": 0, "count": 1}
    assert "devices" in man and "git" in man and "host" in man
    assert run["results"] == [{"metric": "env_steps_per_sec",
                               "value": 42.0}]
    snap = run["snapshot"]
    assert snap["blocks"]["ring"] == {"stalls": 0}
    assert snap["snapshot"]["spans"]["bench.run"]["count"] == 1
    assert snap["snapshot"]["counters"]["transfer.stage.traj.calls"] == 1
    # sink records made it to disk (span + transfer at least)
    types = {r.get("type") for r in run["records"]}
    assert {"span", "transfer"} <= types


def test_run_ledger_preserves_active_sink(tmp_path):
    """A ledger opened inside an existing telemetry window (bench.py's
    save/enable/restore) must put the PRIOR sink back on finalize, not
    leave its own."""
    from ddls_tpu.telemetry import JsonlSink
    from ddls_tpu.telemetry.runlog import RunLedger

    prior_path = str(tmp_path / "prior.jsonl")
    telemetry.enable(sink_path=prior_path)
    prior_sink = telemetry.registry().sink
    ledger = RunLedger(str(tmp_path / "run"), kind="test").open()
    assert telemetry.registry().sink is not prior_sink
    ledger.finalize()
    assert telemetry.registry().sink is prior_sink
    assert telemetry.enabled()  # prior state was enabled
    prior_sink.close()
    assert isinstance(prior_sink, JsonlSink)


def test_load_run_dir_tolerates_partial(tmp_path):
    from ddls_tpu.telemetry.runlog import load_run_dir

    d = tmp_path / "partial"
    d.mkdir()
    # torn sink line + no manifest/snapshot/result
    (d / "telemetry.jsonl").write_text(
        json.dumps({"type": "span", "name": "s", "dur_s": 0.1,
                    "ts": 5.0}) + "\n{torn")
    run = load_run_dir(str(d))
    # missing pieces stay ABSENT (not empty) — consumers .get() them
    assert "manifest" not in run and "results" not in run
    assert [r["name"] for r in run["records"]] == ["s"]


# ----------------------------------------------------- timeline builder
def _synthetic_run(tmp_path, name="runA", kind="train:pipelined"):
    """A run dir written through the real ledger, with one of every
    record family the timeline renders."""
    from ddls_tpu.telemetry.runlog import RunLedger

    ledger = RunLedger(str(tmp_path / name), kind=kind).open()
    with telemetry.span("train.collect"):
        time.sleep(0.002)
    with telemetry.transfer("sebulba.params", "l2a") as tr:
        tr.add({"w": memoryview(bytes(64))})
    telemetry.record_event("ring_segment", phase="lease", segment=0,
                           generation=1)
    telemetry.record_event("ring_segment", phase="publish", segment=0,
                           generation=1)
    telemetry.record_event("ring_segment", phase="release", segment=0,
                           generation=1)
    telemetry.record_event("ring_segment", phase="stall", segment=None,
                           occupied=3)
    telemetry.record_event("memo_counters", hits=30, misses=10, evicts=1)
    telemetry.record_event("params_age", value=2)
    ledger.finalize()
    return str(tmp_path / name)


def test_timeline_renders_every_track_family(tmp_path):
    from ddls_tpu.telemetry.timeline import write_timeline

    runs = [_synthetic_run(tmp_path, "runA", "train:pipelined"),
            _synthetic_run(tmp_path, "runB", "train:sebulba")]
    out = tmp_path / "timeline.json"
    doc = write_timeline(runs, str(out))
    assert out.exists()
    ev = doc["traceEvents"]
    # two processes, labelled kind:dirname
    procs = {e["args"]["name"] for e in ev
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"train:pipelined:runA", "train:sebulba:runB"}
    # span duration slice
    spans = [e for e in ev if e.get("ph") == "X"
             and e["name"] == "train.collect"]
    assert len(spans) == 2 and all(e["dur"] >= 2e3 for e in spans)
    # transfer slice with bytes + flow arrows to the destination track
    hops = [e for e in ev if e.get("ph") == "X"
            and e["name"] == "sebulba.params"]
    assert len(hops) == 2
    assert all(e["args"]["bytes"] == 64 for e in hops)
    assert any(e.get("ph") == "s" and e.get("cat") == "transfer"
               for e in ev)
    assert any(e.get("ph") == "f" and e.get("cat") == "transfer"
               for e in ev)
    # ring lifecycle async pair + publish instant + flagged stall
    assert any(e.get("ph") == "b" and e.get("cat") == "ring" for e in ev)
    assert any(e.get("ph") == "e" and e.get("cat") == "ring" for e in ev)
    assert any(e.get("ph") == "i" and e["name"] == "RING STALL"
               for e in ev)
    # counter tracks
    memo = [e for e in ev if e.get("ph") == "C"
            and e["name"] == "memo hit rate"]
    assert memo and memo[0]["args"]["hit_rate"] == 0.75
    assert any(e.get("ph") == "C" and e["name"] == "params_age_updates"
               for e in ev)
    # all timestamps share the non-negative global origin
    assert all(e.get("ts", 0) >= 0 for e in ev)
    # otherData carries run manifest correlation keys
    assert [r["pid"] for r in doc["otherData"]["runs"]] == [1, 2]
    assert doc["otherData"]["runs"][0]["memo_counters"]["hits"] == 30


def test_timeline_cli_and_report_delegation(tmp_path):
    run = _synthetic_run(tmp_path, "runC")
    out1 = tmp_path / "t1.json"
    rc = subprocess.run(
        [sys.executable, "-m", "ddls_tpu.telemetry.timeline", run,
         "-o", str(out1)],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert rc.returncode == 0, rc.stdout + rc.stderr
    assert json.load(open(out1))["traceEvents"]
    out2 = tmp_path / "t2.json"
    rc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "scripts", "telemetry_report.py"),
         "--timeline", run, "-o", str(out2)],
        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stdout + rc.stderr
    assert json.load(open(out2))["traceEvents"]


# ------------------------------------- end-to-end: train runs → timeline
def _make_loop(dataset_dir, loop_mode, ledger, **kw):
    from ddls_tpu.train import make_epoch_loop

    defaults = dict(
        path_to_env_cls=ENV_CLS,
        env_config=_env_config(dataset_dir, horizon=6e2),
        model=_TINY_MODEL,
        algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 2, "num_workers": 8,
                     "device_collector": True},
        num_envs=8, rollout_length=2, n_devices=8,
        use_parallel_envs=False, evaluation_interval=None, seed=0,
        loop_mode=loop_mode, metrics_sync_interval=1,
        run_ledger=ledger)
    if loop_mode == "sebulba":
        defaults["sebulba_config"] = {"actor_devices": 4}
    defaults.update(kw)
    return make_epoch_loop("ppo", **defaults)


def test_end_to_end_train_ledgers_to_one_timeline(tmp_path,
                                                  runlog_dataset):
    """THE acceptance path: a ledger-enabled pipelined run and a
    ledger-enabled sebulba run, merged by one command into one Perfetto
    trace with span tracks, ring lifecycle slices, and cross-mesh hops
    carrying byte sizes."""
    from ddls_tpu.telemetry.runlog import RunLedger, load_run_dir
    from ddls_tpu.telemetry.timeline import write_timeline

    run_dirs = []
    for mode in ("pipelined", "sebulba"):
        run_dir = str(tmp_path / f"run_{mode}")
        loop = _make_loop(runlog_dataset, mode,
                          RunLedger(run_dir, kind=f"train:{mode}"))
        if mode == "sebulba":
            assert loop.loop_mode == "sebulba", \
                "split must not have fallen back"
        try:
            for _ in range(3):
                loop.run()
        finally:
            loop.close()
        run_dirs.append(run_dir)
        # ledger restored the disabled default between runs
        assert not telemetry.enabled()
        man = load_run_dir(run_dir)["manifest"]
        assert man["config"]["loop_mode"] == mode
        assert man["config"]["algo"] == "ppo"
        blocks = load_run_dir(run_dir)["snapshot"]["blocks"]
        assert blocks["train"]["epochs"] == 3

    doc = write_timeline(run_dirs, str(tmp_path / "timeline.json"))
    ev = doc["traceEvents"]
    by_pid_names = {}
    for e in ev:
        if e.get("ph") == "X":
            by_pid_names.setdefault(e["pid"], set()).add(e["name"])
    # both runs contributed span tracks from the training loop
    assert len(by_pid_names) == 2
    for names in by_pid_names.values():
        assert "train.collect" in names
    # the sebulba run's cross-mesh hops carry real byte sizes
    hops = [e for e in ev if e.get("ph") == "X"
            and e["name"] in ("sebulba.params", "stage.traj")
            and (e.get("args") or {}).get("bytes")]
    assert hops, "no cross-mesh hop slices with bytes in the trace"
    assert all(e["args"]["bytes"] > 0 for e in hops)
    directions = {e["args"]["direction"] for e in hops}
    assert "l2a" in directions and "a2l" in directions
    # the sebulba device-mode ring left lease→release lifecycles
    assert any(e.get("ph") == "b" and e.get("cat") == "ring" for e in ev)
    assert any(e.get("ph") == "e" and e.get("cat") == "ring" for e in ev)
    # flow arrows pair up (every dispatch has an arrival)
    s_ids = {e["id"] for e in ev if e.get("ph") == "s"}
    f_ids = {e["id"] for e in ev if e.get("ph") == "f"}
    assert s_ids and s_ids == f_ids


def test_pipelined_transfer_free_pin_survives_ledger(runlog_dataset,
                                                     tmp_path):
    """The ledger compiles into the loop but stays a no-op unless its
    run is enabled: with NO ledger and telemetry off, the steady-state
    pipelined epoch stays transfer-free under jax.transfer_guard (the
    ISSUE 18 hot-path contract; mirrors test_train_pipeline's pin with
    the new instrumentation in place)."""
    import jax

    # the canonical pin's shape (test_train_pipeline): host collection,
    # sync interval beyond the run so no drain fires inside the guard
    loop = _make_loop(
        runlog_dataset, "pipelined", None,
        algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 2, "num_workers": 8},
        metrics_sync_interval=1000)
    try:
        loop.run()  # warm epoch: compiles + first-use constant transfers
        with jax.transfer_guard("disallow"):
            loop.run()
    finally:
        loop.close()


# ------------------------------------------------- perf_history (tier-1)
def test_perf_history_check_json_smoke():
    """`perf_history.py --check --json` over the committed BENCH
    artifacts: rc 0, every artifact parses, rows non-empty, rounds
    monotone — the structural regression gate rides tier-1 without
    executing any bench."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "perf_history.py"),
         "--check", "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["ok"] is True
    assert doc["structural_problems"] == []
    assert len(doc["rows"]) >= 10
    assert all(e["error"] is None for e in doc["artifacts"])


def test_perf_history_regression_gate(tmp_path):
    """--fresh compares a fresh bench line against history: within
    tolerance passes, a big drop fails with rc 1."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import perf_history
    finally:
        sys.path.pop(0)
    entries = perf_history.collect_history(sorted(
        __import__("glob").glob(os.path.join(REPO, "BENCH_r*.json"))))
    base = perf_history.latest_value(entries, "ppo_env_steps_per_sec")
    assert base is not None and base["value"] > 0
    ok_line = tmp_path / "fresh_ok.json"
    ok_line.write_text(json.dumps({
        "metric": "ppo_env_steps_per_sec", "value": base["value"]}))
    verdict = perf_history.regression_check(
        entries, str(ok_line), "ppo_env_steps_per_sec", 0.3)
    assert verdict["ok"] is True
    bad_line = tmp_path / "fresh_bad.json"
    bad_line.write_text(json.dumps({
        "metric": "ppo_env_steps_per_sec",
        "value": base["value"] * 0.5}))
    verdict = perf_history.regression_check(
        entries, str(bad_line), "ppo_env_steps_per_sec", 0.3)
    assert verdict["ok"] is False and "regressed" in verdict["reason"]
