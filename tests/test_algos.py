"""IMPALA / PG / ES learners: V-trace and return math, jitted sharded
updates, ES population mechanics, config translation, and epoch-loop smoke
runs on the real env (reference counterpart: RLlib Impala/PG/ES trainers
through scripts/ramp_job_partitioning_configs/algo/*.yaml)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddls_tpu.parallel.mesh import make_mesh
from ddls_tpu.rl.es import ESConfig, ESLearner, centered_ranks
from ddls_tpu.rl.impala import ImpalaConfig, ImpalaLearner, vtrace
from ddls_tpu.rl.pg import PGConfig, PGLearner, reward_to_go


# --------------------------------------------------------------- math units
def test_vtrace_on_policy_hand_computed():
    # T=2, B=1, gamma=0.5, on-policy (rho = c = 1)
    logp = jnp.zeros((2, 1))
    values = jnp.array([[1.0], [2.0]])
    rewards = jnp.array([[1.0], [1.0]])
    dones = jnp.zeros((2, 1))
    last = jnp.array([3.0])
    vs, adv = vtrace(logp, logp, rewards, values, dones, last, gamma=0.5)
    # deltas: [1 + .5*2 - 1, 1 + .5*3 - 2] = [1, 0.5]
    # vs   : [1 + 1 + .5*.5, 2 + .5] = [2.25, 2.5]
    assert np.asarray(vs)[:, 0] == pytest.approx([2.25, 2.5])
    # adv  : [1 + .5*2.5 - 1, 1 + .5*3 - 2] = [1.25, 0.5]
    assert np.asarray(adv)[:, 0] == pytest.approx([1.25, 0.5])


def test_vtrace_clips_importance_weights():
    behavior = jnp.zeros((2, 1))
    target = jnp.full((2, 1), np.log(4.0))  # rho = 4, clipped to 1
    values = jnp.array([[1.0], [2.0]])
    rewards = jnp.array([[1.0], [1.0]])
    dones = jnp.zeros((2, 1))
    last = jnp.array([3.0])
    vs_clip, adv_clip = vtrace(behavior, target, rewards, values, dones,
                               last, gamma=0.5)
    vs_on, adv_on = vtrace(behavior, behavior, rewards, values, dones,
                           last, gamma=0.5)
    # with clip thresholds 1.0 the clipped off-policy result equals the
    # on-policy one
    assert np.asarray(vs_clip) == pytest.approx(np.asarray(vs_on))
    assert np.asarray(adv_clip) == pytest.approx(np.asarray(adv_on))


def test_vtrace_done_cuts_bootstrap():
    logp = jnp.zeros((2, 1))
    values = jnp.array([[1.0], [2.0]])
    rewards = jnp.array([[1.0], [1.0]])
    dones = jnp.array([[1.0], [0.0]])  # episode ends at t=0
    last = jnp.array([3.0])
    vs, _ = vtrace(logp, logp, rewards, values, dones, last, gamma=0.5)
    # t=0: delta = 1 - 1 = 0 and no propagation from t=1 -> vs[0] = 1
    assert float(vs[0, 0]) == pytest.approx(1.0)


def test_reward_to_go():
    rewards = jnp.array([[1.0], [2.0], [4.0]])
    dones = jnp.zeros((3, 1))
    g = reward_to_go(rewards, dones, gamma=0.5)
    assert np.asarray(g)[:, 0] == pytest.approx([3.0, 4.0, 4.0])
    # done at t=1 cuts the tail out of t<=1 returns
    g2 = reward_to_go(rewards, jnp.array([[0.0], [1.0], [0.0]]), 0.5)
    assert np.asarray(g2)[:, 0] == pytest.approx([2.0, 2.0, 4.0])


def test_centered_ranks():
    w = centered_ranks(jnp.array([3.0, 1.0, 2.0]))
    assert np.asarray(w) == pytest.approx([0.5, -0.5, 0.0])


# ------------------------------------------------------------ tiny learners
def _mlp_apply(params, obs):
    h = jnp.tanh(obs["x"] @ params["w1"])
    return h @ params["w2"], (h @ params["w3"])[:, 0]


def _mlp_params(rng, n_actions=5):
    return {"w1": rng.randn(4, 8).astype(np.float32),
            "w2": rng.randn(8, n_actions).astype(np.float32),
            "w3": rng.randn(8, 1).astype(np.float32)}


def _traj(rng, T=4, B=8, n_actions=5):
    return {
        "obs": {"x": rng.rand(T, B, 4).astype(np.float32)},
        "actions": rng.randint(0, n_actions, (T, B)).astype(np.int32),
        "logp": -np.abs(rng.rand(T, B)).astype(np.float32),
        "values": rng.randn(T, B).astype(np.float32),
        "rewards": rng.randn(T, B).astype(np.float32),
        "dones": (rng.rand(T, B) < 0.1),
    }


def _params_moved(before, after):
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        jax.device_get(before), jax.device_get(after))
    return max(jax.tree_util.tree_leaves(diffs))


def test_impala_learner_update():
    mesh = make_mesh(8)
    learner = ImpalaLearner(_mlp_apply, ImpalaConfig(lr=1e-2), mesh)
    rng = np.random.RandomState(0)
    params = _mlp_params(rng)
    state = learner.init_state(params)
    traj, last = learner.shard_traj(_traj(rng),
                                    rng.randn(8).astype(np.float32))
    state2, metrics = learner.train_step(state, traj, last)
    metrics = jax.device_get(metrics)
    for key in ("policy_loss", "vf_loss", "entropy", "total_loss",
                "mean_rho"):
        assert np.isfinite(float(metrics[key])), key
    assert _params_moved(params, state2.params) > 0
    assert int(state2.step) == 1


def test_pg_learner_update():
    mesh = make_mesh(8)
    learner = PGLearner(_mlp_apply, PGConfig(lr=1e-2), mesh)
    rng = np.random.RandomState(0)
    params = _mlp_params(rng)
    state = learner.init_state(params)
    traj, last = learner.shard_traj(_traj(rng),
                                    np.zeros(8, np.float32))
    state2, metrics = learner.train_step(state, traj, last)
    assert np.isfinite(float(jax.device_get(metrics)["policy_loss"]))
    assert _params_moved(params, state2.params) > 0


def test_es_antithetic_perturbations():
    mesh = make_mesh(8)
    learner = ESLearner(_mlp_apply, ESConfig(noise_stdev=0.1), mesh,
                        population=8)
    rng = np.random.RandomState(0)
    params = _mlp_params(rng)
    stacked, eps = learner.perturb(params, jax.random.PRNGKey(0))
    w1 = np.asarray(stacked["w1"])
    assert w1.shape == (8, 4, 8)
    # antithetic: member i and i + P/2 mirror around the mean params
    for i in range(4):
        assert w1[i] + w1[i + 4] == pytest.approx(
            2 * params["w1"], abs=1e-5)


def test_es_update_optimises_quadratic():
    """ES on a pure optimisation problem: fitness = -||theta||^2 must
    drive the parameters toward zero without any gradients."""
    mesh = make_mesh(8)
    learner = ESLearner(_mlp_apply, ESConfig(stepsize=0.05, noise_stdev=0.1,
                                             l2_coeff=0.0), mesh,
                        population=32)
    rng_np = np.random.RandomState(0)
    params = {"w": rng_np.randn(6).astype(np.float32)}
    state = learner.init_state(params)
    rng = jax.random.PRNGKey(1)
    norm0 = float(np.linalg.norm(np.asarray(state.params["w"])))
    for _ in range(60):
        rng, sub = jax.random.split(rng)
        stacked, eps = learner.perturb(state.params, sub)
        fitness = -np.sum(np.asarray(stacked["w"]) ** 2, axis=1)
        state, metrics = learner.update(state, eps, fitness)
    norm_end = float(np.linalg.norm(np.asarray(state.params["w"])))
    assert norm_end < 0.5 * norm0
    assert np.isfinite(float(jax.device_get(metrics)["fitness_mean"]))


def test_es_rejects_odd_population():
    with pytest.raises(ValueError, match="even"):
        ESLearner(_mlp_apply, ESConfig(), make_mesh(8), population=3)


def test_es_action_noise_explores_but_respects_mask():
    """action_noise_std > 0 must change some actions vs the greedy argmax
    (exploration is real), noise_std = 0 must reproduce greedy exactly,
    and -inf-masked actions must never be picked however large the noise."""

    def masked_apply(params, obs):
        logits = obs["x"] @ params["w1"] @ params["w2"][:, :5]
        logits = jnp.where(jnp.arange(5) == 4, -jnp.inf, logits)
        return logits, jnp.zeros(logits.shape[0])

    mesh = make_mesh(8)
    rng = np.random.RandomState(0)
    params = _mlp_params(rng)
    P = 8
    learner_hot = ESLearner(masked_apply,
                            ESConfig(action_noise_std=5.0), mesh,
                            population=P)
    stacked, _ = learner_hot.perturb(params, jax.random.PRNGKey(0))
    obs = {"x": rng.rand(P, 4).astype(np.float32)}

    greedy = np.asarray(learner_hot.pop_actions(
        stacked, obs, jax.random.PRNGKey(1), noise_std=0.0))
    noisy_draws = [np.asarray(learner_hot.pop_actions(
        stacked, obs, jax.random.PRNGKey(k))) for k in range(2, 12)]

    assert (np.asarray(learner_hot.pop_actions(
        stacked, obs, jax.random.PRNGKey(7), noise_std=0.0)) ==
        greedy).all(), "zero noise must be deterministic greedy"
    assert any((d != greedy).any() for d in noisy_draws), (
        "large action noise never changed a single action")
    for d in noisy_draws:
        assert (d != 4).all(), "noise unmasked an invalid (-inf) action"

    # same invariant through the PRODUCTION masking path: GNNPolicy clamps
    # masked logits to finfo.min (not -inf); noise must not bridge that
    # either
    import __graft_entry__ as ge
    from ddls_tpu.models.policy import batched_policy_apply

    n_actions, max_nodes = 5, 6
    model = ge._tiny_model(n_actions)  # apply_action_mask=True
    obs_g = ge._fake_obs(np.random.RandomState(1), (P,), max_nodes,
                         n_actions)
    obs_g["action_mask"] = np.ones((P, n_actions), np.int32)
    obs_g["action_mask"][:, 3] = 0  # action 3 invalid everywhere
    single = jax.tree_util.tree_map(lambda x: x[0], obs_g)
    gparams = model.init(jax.random.PRNGKey(0), single)
    glearner = ESLearner(lambda p, o: batched_policy_apply(model, p, o),
                         ESConfig(action_noise_std=50.0), make_mesh(8),
                         population=P)
    gstacked, _ = glearner.perturb(gparams, jax.random.PRNGKey(2))
    for k in range(3):
        acts = np.asarray(glearner.pop_actions(gstacked, obs_g,
                                               jax.random.PRNGKey(20 + k)))
        assert (acts != 3).all(), (
            "noise unmasked a finfo.min-clamped invalid action")


def test_es_eval_prob_reports_unperturbed_fitness(dataset_dir):
    """eval_prob = 1 -> every epoch also evaluates the unperturbed mean
    params noise-free and reports eval_fitness_mean (never part of the
    gradient — update metrics are computed before the eval window runs)."""
    from ddls_tpu.train import make_epoch_loop

    loop = make_epoch_loop(
        "es",
        path_to_env_cls=("ddls_tpu.envs.partitioning_env."
                         "RampJobPartitioningEnvironment"),
        env_config=_env_config(dataset_dir),
        model=_TINY_MODEL,
        algo_config={"stepsize": 0.01, "noise_stdev": 0.02,
                     "eval_prob": 1.0, "action_noise_std": 0.0,
                     "num_workers": 2},
        num_envs=2, rollout_length=4, n_devices=8,
        use_parallel_envs=False, evaluation_interval=None,
        evaluation_duration=1, seed=0)
    r1 = loop.run()
    assert "eval_fitness_mean" in r1["learner"]
    assert np.isfinite(r1["learner"]["eval_fitness_mean"])
    loop.close()

    loop2 = make_epoch_loop(
        "es",
        path_to_env_cls=("ddls_tpu.envs.partitioning_env."
                         "RampJobPartitioningEnvironment"),
        env_config=_env_config(dataset_dir),
        model=_TINY_MODEL,
        algo_config={"stepsize": 0.01, "noise_stdev": 0.02,
                     "eval_prob": 0.0, "num_workers": 2},
        num_envs=2, rollout_length=4, n_devices=8,
        use_parallel_envs=False, evaluation_interval=None,
        evaluation_duration=1, seed=0)
    r2 = loop2.run()
    assert "eval_fitness_mean" not in r2["learner"]
    loop2.close()


def test_impala_stale_behavior_policy_vtrace_corrects():
    """Replay a trajectory whose behaviour logp is deliberately stale
    (collected several updates ago): V-trace must (a) detect the
    off-policyness (mean_rho clipped below 1) and (b) produce a
    measurably different update than pretending the data is on-policy
    with the same rewards/actions."""
    mesh = make_mesh(8)
    rng = np.random.RandomState(3)
    params = _mlp_params(rng)

    cfg = ImpalaConfig(lr=1e-2, vtrace_clip_rho_threshold=1.0)
    learner = ImpalaLearner(_mlp_apply, cfg, mesh)

    traj = _traj(rng, T=6, B=8)
    # stale behaviour policy: logp far from what the current params assign
    # (e.g. the behaviour policy loved these actions, the target doesn't)
    traj["logp"] = np.full((6, 8), np.log(0.9), np.float32)
    last = rng.randn(8).astype(np.float32)

    state = learner.init_state(params)
    straj, slast = learner.shard_traj(dict(traj), last)
    state_stale, m_stale = learner.train_step(state, straj, slast)
    m_stale = jax.device_get(m_stale)
    # rho = exp(target_logp - behaviour_logp) with behaviour prob 0.9:
    # the average clipped rho must sit measurably below 1
    assert float(m_stale["mean_rho"]) < 0.9

    # control: identical data relabelled as on-policy (behaviour = target)
    import jax.numpy as jnp_  # noqa: F401

    logits, _ = _mlp_apply(params, {
        "x": traj["obs"]["x"].reshape(-1, 4)})
    logp_target = jax.nn.log_softmax(logits, axis=-1)
    on_logp = np.take_along_axis(
        np.asarray(logp_target),
        traj["actions"].reshape(-1, 1).astype(np.int64), axis=1)
    traj_on = dict(traj)
    traj_on["logp"] = on_logp.reshape(6, 8).astype(np.float32)

    state2 = learner.init_state(params)
    straj_on, slast_on = learner.shard_traj(traj_on, last)
    state_on, m_on = learner.train_step(state2, straj_on, slast_on)
    m_on = jax.device_get(m_on)
    assert float(m_on["mean_rho"]) == pytest.approx(1.0, abs=1e-5)

    # the correction changed the update direction/magnitude
    diff = _params_moved(state_stale.params, state_on.params)
    assert diff > 1e-5, (
        "stale-vs-on-policy updates are identical; V-trace correction "
        "is not doing anything measurable")


# ------------------------------------------------------- config translation
def test_impala_config_translation():
    from ddls_tpu.train.loops import impala_config_from_rllib

    cfg = impala_config_from_rllib({
        "vtrace_clip_rho_threshold": 1.0, "grad_clip": 40.0,
        "opt_type": "adam", "vf_loss_coeff": 0.5, "entropy_coeff": 0.01,
        "num_workers": 32})
    assert cfg.grad_clip == 40.0
    assert cfg.entropy_coeff == 0.01
    assert cfg.opt_type == "adam"


def test_algo_translators_reject_unknown_keys():
    """No silently-ignored algo keys anywhere (VERDICT r2 weakness 6): a
    key nothing consumes — including Ray-only plumbing like
    learner_queue_size — must raise, not no-op."""
    from ddls_tpu.train.loops import (dqn_config_from_rllib,
                                      es_config_from_rllib,
                                      impala_config_from_rllib,
                                      pg_config_from_rllib,
                                      ppo_config_from_rllib)

    cases = [
        (ppo_config_from_rllib, {"lr": 1e-3, "rollout_fragment_length": 50}),
        (impala_config_from_rllib, {"lr": 1e-3, "learner_queue_size": 16}),
        (pg_config_from_rllib, {"lr": 1e-3, "batch_mode": "truncate"}),
        (es_config_from_rllib, {"stepsize": 0.01, "noise_size": 2.5e8}),
        (dqn_config_from_rllib,
         {"lr": 1e-3, "timeout_s_sampler_manager": 0.0}),
    ]
    for fn, cfg in cases:
        with pytest.raises(ValueError, match="not consumed"):
            fn(cfg)
        ok = dict(cfg)
        ok.pop(next(k for k in ok if k not in ("lr", "stepsize")))
        fn(ok)  # the remaining known keys still translate


def test_shipped_algo_yamls_have_no_dead_keys():
    """Every algo_config key in the shipped config trees is consumed by
    its translator (the strict check would raise otherwise)."""
    import os

    import yaml

    from ddls_tpu.train.loops import (dqn_config_from_rllib,
                                      es_config_from_rllib,
                                      impala_config_from_rllib,
                                      pg_config_from_rllib,
                                      ppo_config_from_rllib)

    translators = {"ppo": ppo_config_from_rllib,
                   "apex_dqn": dqn_config_from_rllib,
                   "impala": impala_config_from_rllib,
                   "pg": pg_config_from_rllib,
                   "es": es_config_from_rllib}
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    checked = 0
    for tree in ("ramp_job_partitioning_configs",
                 "ramp_job_placement_shaping_configs"):
        algo_dir = os.path.join(root, tree, "algo")
        if not os.path.isdir(algo_dir):
            continue
        for name in sorted(os.listdir(algo_dir)):
            with open(os.path.join(algo_dir, name)) as f:
                cfg = yaml.safe_load(f)
            translators[cfg["algo_name"]](cfg.get("algo_config") or {})
            checked += 1
    assert checked >= 5


def test_es_config_translation_rejects_rllib_only_noise_size():
    from ddls_tpu.train.loops import es_config_from_rllib

    # noise_size configures RLlib's shared noise table; the TPU design has
    # no noise table (perturbations are drawn on device) so it must be
    # rejected loudly rather than carried
    with pytest.raises(ValueError, match="noise_size"):
        es_config_from_rllib({"noise_size": 250000000})


def test_es_config_translation():
    from ddls_tpu.train.loops import es_config_from_rllib

    cfg = es_config_from_rllib({"noise_stdev": 0.02, "stepsize": 0.01,
                                "l2_coeff": 0.005, "eval_prob": 0.5,
                                "action_noise_std": 0.1})
    assert cfg.noise_stdev == 0.02
    assert cfg.stepsize == 0.01
    assert cfg.eval_prob == 0.5
    assert cfg.action_noise_std == 0.1


# ------------------------------------------------------- epoch loop smoke
def _env_config(dataset_dir):
    return dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 4,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 2},
        max_partitions_per_op=4,
        reward_function="job_acceptance",
        max_simulation_run_time=5e4,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})


_TINY_MODEL = {"fcnet_hiddens": [16],
               "custom_model_config": {"out_features_msg": 4,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}}


@pytest.mark.parametrize("algo,algo_config", [
    ("impala", {"lr": 1e-3, "grad_clip": 40.0, "train_batch_size": 20,
                "num_workers": 2}),
    ("pg", {"lr": 1e-3, "gamma": 0.99, "train_batch_size": 20,
            "num_workers": 2}),
])
def test_actor_critic_loops_train_on_env(algo, algo_config, dataset_dir):
    from ddls_tpu.train import make_epoch_loop

    loop = make_epoch_loop(
        algo,
        path_to_env_cls=("ddls_tpu.envs.partitioning_env."
                         "RampJobPartitioningEnvironment"),
        env_config=_env_config(dataset_dir),
        model=_TINY_MODEL,
        algo_config=algo_config,
        num_envs=2, rollout_length=10, n_devices=2,
        use_parallel_envs=False, evaluation_interval=2,
        evaluation_duration=1, seed=0)
    before = jax.device_get(loop.state.params)
    r1 = loop.run()
    assert r1["env_steps_this_iter"] == 20
    assert np.isfinite(r1["learner"]["total_loss"])
    r2 = loop.run()
    assert "evaluation" in r2
    assert _params_moved(before, loop.state.params) > 0
    loop.close()


def test_es_loop_trains_on_env(dataset_dir):
    from ddls_tpu.train import make_epoch_loop

    loop = make_epoch_loop(
        "es",
        path_to_env_cls=("ddls_tpu.envs.partitioning_env."
                         "RampJobPartitioningEnvironment"),
        env_config=_env_config(dataset_dir),
        model=_TINY_MODEL,
        algo_config={"stepsize": 0.01, "noise_stdev": 0.02,
                     "num_workers": 2},
        num_envs=2, rollout_length=8, n_devices=8,
        use_parallel_envs=False, evaluation_interval=2,
        evaluation_duration=1, seed=0)
    assert loop.num_envs == 2  # population
    before = jax.device_get(loop.state.params)
    r1 = loop.run()
    assert r1["env_steps_this_iter"] == 16
    assert np.isfinite(r1["learner"]["fitness_mean"])
    r2 = loop.run()
    assert "evaluation" in r2
    assert _params_moved(before, loop.state.params) > 0
    loop.close()
