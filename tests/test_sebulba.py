"""Sebulba actor/learner device split (rl/sebulba.py, ISSUE 17).

The load-bearing pin is the x64 depth-0 parity driver: the Sebulba loop
(in-kernel collection jitted over a 4-device actor sub-mesh, the
standalone PPO update over the 4-device learner complement, trajectories
handed over a device-mode ring) must reproduce a MANUAL sequential
reference built from the SAME sub-meshes — `DevicePPOCollector` on the
actor mesh, `PPOLearner` on the learner mesh — EXACTLY: post-training
params bit-equal, per-epoch metrics equal, episode records equal.
Matched partitioning is the contract (rl/ppo_device.py: the bootstrap
forward's partitioned accumulation order depends on the dp width), so
the reference is assembled on the split meshes rather than the stock
full-mesh sequential loop.

In-process (f32): the steady-state Sebulba epoch is transfer-free under
``jax.transfer_guard("disallow")`` (every cross-mesh hop is an explicit
device_put); infeasible meshes fall back to pipelined LOUDLY; DQN/ES
and multi-deep explicit splits reject loudly; the device-mode ring's
token protocol is exercised directly.
"""
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from test_fused import ENV_CLS, _TINY_MODEL, _env_config  # noqa: E402


@pytest.fixture(scope="module")
def sebulba_dataset(tmp_path_factory):
    from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files

    d = str(tmp_path_factory.mktemp("sebulba_jobs"))
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=9)
    return d


def _make_sebulba_loop(dataset_dir, algo="ppo", **kw):
    from ddls_tpu.train import make_epoch_loop

    defaults = dict(
        path_to_env_cls=ENV_CLS,
        env_config=_env_config(dataset_dir, horizon=6e2),
        model=_TINY_MODEL,
        algo_config={"train_batch_size": 16, "sgd_minibatch_size": 8,
                     "num_sgd_iter": 2, "num_workers": 8},
        num_envs=8, rollout_length=2, n_devices=8,
        use_parallel_envs=False, evaluation_interval=None, seed=0,
        loop_mode="sebulba",
        sebulba_config={"actor_devices": 4})
    defaults.update(kw)
    return make_epoch_loop(algo, **defaults)


# ===================================================== x64 parity driver
# Depth-0 Sebulba over E epochs must equal E sequential collect→update
# steps on the SAME sub-mesh split: params EXACTLY (bitwise), per-epoch
# metrics equal, episode records field-for-field equal (the 6e2 horizon
# completes episodes).
PARITY_DRIVER = r"""
import tempfile
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
assert jax.config.read("jax_enable_x64")
assert len(jax.devices()) == 8
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
from ddls_tpu.train import make_epoch_loop

import test_fused as tf

d = tempfile.mkdtemp(prefix="sebulba_parity_")
generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=9)
algo = {"train_batch_size": 16, "sgd_minibatch_size": 8,
        "num_sgd_iter": 2, "num_workers": 8, "device_collector": True}
kw = dict(path_to_env_cls=tf.ENV_CLS,
          env_config=tf._env_config(d, horizon=6e2),
          model=tf._TINY_MODEL,
          num_envs=8, rollout_length=2, n_devices=8,
          use_parallel_envs=False, evaluation_interval=None, seed=0)
E = 6

# the MANUAL sequential reference on the SAME sub-mesh split: start
# from a stock sequential device-collector loop, then rebuild its
# learner on the learner sub-mesh and its collector on the actor
# sub-mesh (matched partitioning is the bit-parity contract). The
# loop's own rng bookkeeping (_split_collect_rng/_split_rng) is reused
# unchanged — both loops split the same seeds in the same order.
seq = make_epoch_loop("ppo", algo_config=dict(algo),
                      loop_mode="sequential", **kw)
from ddls_tpu.rl.ppo import PPOLearner
from ddls_tpu.rl.ppo_device import DevicePPOCollector
from ddls_tpu.rl.sebulba import split_meshes

actor_mesh, learner_mesh = split_meshes(
    4, devices=list(seq.mesh.devices.flat))
seq.mesh = learner_mesh
seq.learner = PPOLearner(seq.apply_fn, seq.ppo_cfg, learner_mesh)
seq.state = seq.learner.init_state(seq.params)
env0, et, ot = seq._device_tables()
stacked = seq._stacked_banks(et, env0, seq.num_envs)


class CrossMeshCollector(DevicePPOCollector):
    # the reference needs the SAME explicit learner->actor params hop
    # the Sebulba collector performs (state.params arrive committed to
    # the learner sub-mesh; device_put replication changes no bits)
    def collect(self, params, rng):
        from jax.sharding import NamedSharding, PartitionSpec as P
        params = jax.device_put(params, NamedSharding(self.mesh, P()))
        return super().collect(params, rng)


seq.collector = CrossMeshCollector(
    et, ot, seq.model, stacked, seq.rollout_length, mesh=actor_mesh,
    memo_cfg=seq._memo_knob())

seq_metrics, seq_episodes = [], []
for _ in range(E):
    r = seq.run()
    seq_metrics.append(dict(r["learner"]))
    seq_episodes.extend(r["episodes"])
seq_params = jax.device_get(seq.state.params)
seq.close()

seb = make_epoch_loop("ppo", algo_config=dict(algo),
                      loop_mode="sebulba", metrics_sync_interval=1,
                      sebulba_config={"actor_devices": 4}, **kw)
assert seb.loop_mode == "sebulba", "split must not have fallen back"
seb_metrics, seb_episodes = [], []
for _ in range(E):
    r = seb.run()
    seb_metrics.append(dict(r["learner"]))
    seb_episodes.extend(r["episodes"])
seb_params = jax.device_get(seb.state.params)
memo = seb.collector.memo_counters()
ring = seb.ring_stats()
seb.close()

# post-training params: EXACT (bitwise array equality)
jax.tree_util.tree_map(
    lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)),
    seq_params, seb_params)

# per-epoch learner metrics: the LazyMetrics floats equal the
# sequential loop's blocking-fetch floats exactly (one update each)
for e in range(E):
    got = {k: v for k, v in seb_metrics[e].items() if k in seq_metrics[e]}
    assert got == seq_metrics[e], (e, got, seq_metrics[e])

# episode records: same records, same order, same fields — and
# episodes genuinely completed
assert len(seq_episodes) >= 8, len(seq_episodes)
assert seq_episodes == seb_episodes

# the actor lanes ran with the in-kernel memo (auto = on at 8 lanes)
assert memo is not None and memo["hits"] > 0, memo
# the device ring saw one lease+publish+release per epoch
assert ring["leases"] == E and ring["publishes"] == E, ring
print(f"SEBULBA_PARITY_OK episodes={len(seb_episodes)}")
"""


def test_sebulba_depth0_parity_vs_sequential_x64():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "1"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO, os.path.dirname(os.path.abspath(__file__))])
    res = subprocess.run([sys.executable, "-c", PARITY_DRIVER], env=env,
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, (res.stdout[-4000:], res.stderr[-4000:])
    assert "SEBULBA_PARITY_OK" in res.stdout, res.stdout[-2000:]


# =================================================== steady-state guards
def test_sebulba_epoch_transfer_free_then_harvests(sebulba_dataset):
    """ISSUE 17 acceptance: with the drain boundary at
    metrics_sync_interval=3, epoch 2 is a steady-state Sebulba epoch
    performing NO implicit device<->host transfer (params hop
    learner→actor and trajectories actor→learner via EXPLICIT
    device_put only; metrics and episode counters stay on device), and
    epoch 3 hits the drain boundary — params moved, episode records
    surface with the host record schema."""
    import jax

    loop = _make_sebulba_loop(sebulba_dataset, metrics_sync_interval=3)
    try:
        assert loop.loop_mode == "sebulba"
        assert loop.actor_mesh is not None
        # disjoint silicon: the defining property of the split
        actor = set(loop.actor_mesh.devices.flat)
        learner = set(loop.mesh.devices.flat)
        assert actor and learner and not (actor & learner)
        before = jax.device_get(loop.state.params)
        r1 = loop.run()  # warm: compile + first-use constant transfers
        assert r1["episodes"] == []  # epoch 1: no drain boundary yet
        with jax.transfer_guard("disallow"):
            r2 = loop.run()
        assert r2["episodes"] == []  # still pending on device
        r3 = loop.run()  # epoch 3: the first drain boundary
        for r in (r1, r2, r3):
            assert np.isfinite(r["learner"]["total_loss"])
            assert r["env_steps_this_iter"] == 2 * 8  # T * B
        moved = jax.tree_util.tree_map(
            lambda a, b: float(np.abs(np.asarray(a)
                                      - np.asarray(b)).max()),
            before, jax.device_get(loop.state.params))
        assert max(jax.tree_util.tree_leaves(moved)) > 0
        # one update per epoch at T=2: 12 steps per lane by epoch 6
        # (the second drain boundary) — enough for the 6e2 horizon to
        # complete episodes
        episodes = list(r3["episodes"])
        for _ in range(3):
            episodes.extend(loop.run()["episodes"])
        assert episodes, "horizon 6e2 must complete episodes by epoch 6"
        for e in episodes:
            assert set(e) >= {"env_index", "episode_return",
                              "episode_length", "num_jobs_arrived",
                              "num_jobs_completed", "num_jobs_blocked",
                              "acceptance_rate", "blocking_rate"}
        stats = loop.ring_stats()
        assert stats["leases"] == 6 and stats["publishes"] == 6
        # slab-less segments: every probed alias verdict is "copied"
        # (the staged tree is a real device-to-device transfer)
        assert stats["aliased_segments"] and not any(
            stats["aliased_segments"])
    finally:
        loop.close()


def test_sebulba_impala_depth1_stale_queue(sebulba_dataset):
    """Depth-K rides along: IMPALA at pipeline_depth=1 keeps one batch
    in flight against pre-update params (background actor thread), the
    staleness shows up as ``params_age_updates`` in the metrics, and
    the ring accounts for it."""
    loop = _make_sebulba_loop(
        sebulba_dataset, algo="impala", metrics_sync_interval=1,
        pipeline_depth=1,
        algo_config={"lr": 1e-3, "train_batch_size": 16,
                     "num_workers": 8})
    try:
        assert loop.loop_mode == "sebulba"
        ages = []
        for _ in range(3):
            r = loop.run()
            ages.append(r["learner"]["params_age_updates"])
            assert np.isfinite(r["learner"]["clip_rho_fraction"])
        # batch 1 is collected inline (age 0); later batches come off
        # the depth-1 queue, collected before the preceding update
        assert ages[0] == 0.0 and max(ages[1:]) >= 1.0, ages
        stats = loop.ring_stats()
        assert stats["leases"] >= 3
        assert stats["mean_params_age"] is not None
    finally:
        loop.close()


def test_sebulba_infeasible_mesh_falls_back_loudly(sebulba_dataset):
    """A 1-device mesh cannot split: the loop warns and falls back to
    pipelined device collection instead of dying or silently
    single-meshing (the fused-fallback convention)."""
    from ddls_tpu.rl.ppo_device import DevicePPOCollector

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        loop = _make_sebulba_loop(sebulba_dataset, n_devices=1,
                                  sebulba_config={})
    try:
        assert loop.loop_mode == "pipelined"
        assert isinstance(loop.collector, DevicePPOCollector)
        assert any("sebulba" in str(w.message) for w in caught)
    finally:
        loop.close()


def test_sebulba_explicit_bad_split_rejects(sebulba_dataset):
    """An explicit actor_devices that leaves a sub-mesh empty is a
    config error, not a fallback."""
    with pytest.raises(ValueError, match="sebulba"):
        _make_sebulba_loop(sebulba_dataset,
                           sebulba_config={"actor_devices": 8})


@pytest.mark.parametrize("algo", ["apex_dqn", "es"])
def test_sebulba_rejected_loudly_without_contract(algo):
    """DQN (host replay insertion) and ES (host population fitness)
    cannot collect in-kernel; the rejection fires before any env/model
    construction."""
    from ddls_tpu.train import make_epoch_loop

    with pytest.raises(ValueError, match="sebulba"):
        make_epoch_loop(algo, path_to_env_cls=ENV_CLS, env_config={},
                        loop_mode="sebulba")


def test_sebulba_rejects_depth_on_ppo(sebulba_dataset):
    """pipeline_depth > 0 under sebulba still needs an off-policy
    correction: PPO rejects exactly as in pipelined mode."""
    with pytest.raises(ValueError, match="stale"):
        _make_sebulba_loop(sebulba_dataset, pipeline_depth=1)


# ================================================== device-mode ring
def test_device_ring_token_protocol():
    """Slab-less segments: the alias probe over zero host views
    verdicts 'copied', so note_staged's phase-1 token (the staged
    device tree) releases the segment when ready; worker-attach
    surfaces reject loudly."""
    import jax.numpy as jnp

    from ddls_tpu.rl.ring import TrajRing

    ring = TrajRing(None, rows=3, num_envs=2, segments=2)
    try:
        seg = ring.lease()
        assert seg.views == {}
        ring.publish(seg)
        staged = {"obs": jnp.ones((3, 2))}
        ring.note_staged(seg, staged, generation=seg.generation)
        assert seg.aliased is False
        ring.sweep()  # the staged tree is ready -> released
        assert seg.state == "free"
        # phase 2 on an already-released segment is a harmless no-op
        ring.note_update(seg, jnp.zeros(()), generation=1)
        assert seg.state == "free"
        with pytest.raises(RuntimeError, match="device-mode"):
            ring.specs()
        with pytest.raises(RuntimeError, match="device-mode"):
            ring.segment_names()
    finally:
        ring.close()
