"""Serving-fleet tests (ddls_tpu/serve/{fleet,loadgen,autoscale},
ISSUE 8).

The load-bearing pins, in order of importance:

* **Routing never changes an answer**: for every routing policy, fleet
  answers are bit-equal to a single PolicyServer serving the same
  requests — each replica runs the same fixed-shape compiled program
  over the same params, and the PR-1 invariant (batch composition
  cannot change a request's output rows) extends across replicas.
* **Shed before degrade**: with shedding enabled, overload produces
  explicit ``source="shed"`` refusals and the replica's ``saturated``
  heuristic fallback NEVER fires; with shedding disabled the legacy
  saturation fallback is intact. Quota/shed decisions replay
  identically for a seeded trace.
* **Hot-swap no-drop**: drain-then-swap answers every already-admitted
  request with the OLD params as policy answers (no drops, no degraded
  latch), and requests after the swap serve the NEW params.
* **Autoscaler determinism**: decisions are a pure function of
  (config, cooldown state, snapshot) — a JSON-round-tripped snapshot
  sequence replays to identical decisions.
* **Loadgen schema**: seeded traces fingerprint deterministically and
  the validator rejects malformed traces (the ``--selftest`` surface,
  wired into tier-1 here).

All CPU, tier-1.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_ACTIONS = 9
BUCKETS = [(8, 12), (16, 28)]
MAX_BATCH = 4


def _rand_obs(rng, n, m, max_nodes, max_edges, mask_valid=(0, 1, 2, 4, 8)):
    node_features = np.zeros((max_nodes, 5), np.float32)
    node_features[:n] = rng.uniform(0, 1, (n, 5))
    edge_features = np.zeros((max_edges, 2), np.float32)
    edge_features[:m] = rng.uniform(0, 1, (m, 2))
    src = np.zeros(max_edges, np.int32)
    dst = np.zeros(max_edges, np.int32)
    src[:m] = rng.integers(0, n, m)
    dst[:m] = rng.integers(0, n, m)
    mask = np.zeros(N_ACTIONS, np.int32)
    mask[list(mask_valid)] = 1
    return {
        "action_set": np.arange(N_ACTIONS, dtype=np.int32),
        "action_mask": mask,
        "node_features": node_features,
        "edge_features": edge_features,
        "graph_features": rng.uniform(0, 1, (17 + N_ACTIONS,)).astype(
            np.float32),
        "edges_src": src,
        "edges_dst": dst,
        "node_split": np.array([n], np.int32),
        "edge_split": np.array([m], np.int32),
    }


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _stub_apply(params, obs):
    """Data-independent forward: every request argmaxes to action 0.
    Keeps the admission/lifecycle tests compile-free."""
    import jax.numpy as jnp

    B = obs["node_features"].shape[0]
    return jnp.zeros((B, N_ACTIONS)), jnp.zeros((B,))


@pytest.fixture(scope="module")
def model_params():
    from ddls_tpu.models.policy import GNNPolicy

    model = GNNPolicy(n_actions=N_ACTIONS, out_features_msg=4,
                      out_features_hidden=8, out_features_node=4,
                      out_features_graph=4, fcnet_hiddens=(16,))
    obs = _rand_obs(np.random.default_rng(0), 6, 8, *BUCKETS[-1])
    params = model.init(jax.random.PRNGKey(0),
                        jax.tree_util.tree_map(np.asarray, obs))
    params_b = model.init(jax.random.PRNGKey(1),
                          jax.tree_util.tree_map(np.asarray, obs))
    return model, params, params_b


def _make_fleet(model, params, clock, n_replicas=2, **kwargs):
    from ddls_tpu.serve import build_fleet

    defaults = dict(buckets=BUCKETS, max_batch=MAX_BATCH,
                    deadline_s=0.01)
    defaults.update(kwargs)
    return build_fleet(model, params, n_replicas=n_replicas,
                       clock=clock, **defaults)


def _stub_fleet(clock, n_replicas=2, **kwargs):
    kwargs.setdefault("apply_fn", _stub_apply)
    return _make_fleet(None, {}, clock, n_replicas=n_replicas, **kwargs)


# ------------------------------------------------------------- bucket refit
class TestFitBuckets:
    def test_quantile_ladder_covers_and_is_deterministic(self):
        from ddls_tpu.serve import fit_buckets

        sizes = [(4, 5), (6, 8), (8, 12), (16, 28), (5, 6), (6, 7)]
        specs = fit_buckets(sizes, n_buckets=3)
        assert specs == fit_buckets(list(sizes), n_buckets=3)
        assert specs[-1] == (16, 28)  # top rung covers the observed max
        assert specs == sorted(specs)
        # strictly monotone in BOTH dims (selection needs both to fit)
        for (n0, m0), (n1, m1) in zip(specs, specs[1:]):
            assert n0 < n1 and m0 < m1
        with pytest.raises(ValueError):
            fit_buckets([], n_buckets=3)


# -------------------------------------------------------- routing equality
class TestRoutingBitEquality:
    @pytest.mark.parametrize("routing", ["affinity", "least_loaded",
                                         "round_robin", "hash"])
    def test_fleet_bit_equal_to_single_server(self, model_params,
                                              routing):
        """THE fleet pin (acceptance): whatever the routing policy and
        however requests co-batch on each replica, the fleet's answers
        are bit-equal to one PolicyServer serving the same requests."""
        from ddls_tpu.serve import PolicyServer

        model, params, _ = model_params
        rng = np.random.default_rng(100)
        reqs, tenants = [], []
        for i in range(10):
            bn, be = BUCKETS[i % 2]
            reqs.append(_rand_obs(rng, int(rng.integers(2, bn + 1)),
                                  int(rng.integers(1, be + 1)), bn, be))
            tenants.append(f"tenant-{i % 3}" if i % 2 else None)
        router = _make_fleet(model, params, _FakeClock(), n_replicas=3,
                             routing=routing)
        fids = [router.submit(o, now=0.0, tenant=t)
                for o, t in zip(reqs, tenants)]
        out = {r.request_id: r for r in router.drain(now=0.0)}
        assert sorted(out) == sorted(fids)
        assert all(r.source == "policy" for r in out.values())
        solo = PolicyServer(model, params, buckets=BUCKETS,
                            max_batch=MAX_BATCH, clock=_FakeClock())
        for fid, obs in zip(fids, reqs):
            assert out[fid].action == solo.serve_one(obs).action

    def test_affinity_pins_tenant_to_one_replica(self):
        clock = _FakeClock()
        router = _stub_fleet(clock, n_replicas=3)
        rng = np.random.default_rng(5)
        replicas = set()
        for _ in range(9):
            router.submit(_rand_obs(rng, 5, 6, *BUCKETS[0]), now=0.0,
                          tenant="alice")
            replicas.update(r.replica for r in router.drain(now=0.0))
        assert len(replicas) == 1

    def test_least_loaded_balances_queued_depth(self):
        clock = _FakeClock()
        router = _stub_fleet(clock, n_replicas=3,
                             routing="least_loaded", deadline_s=100.0)
        rng = np.random.default_rng(6)
        for _ in range(9):
            router.submit(_rand_obs(rng, 5, 6, *BUCKETS[0]), now=0.0)
        depths = [rep.server.queued()
                  for rep in router.replica_set.replicas]
        assert max(depths) - min(depths) <= 1
        assert router.drain(now=0.0)  # leave the fleet clean


# ------------------------------------------------------------- quotas/shed
class TestQuotaShed:
    def test_quota_shed_is_deterministic_and_refills(self):
        clock = _FakeClock()
        router = _stub_fleet(clock, n_replicas=2, quota_rps=2.0,
                             quota_burst=2.0, shed_enabled=True)
        rng = np.random.default_rng(7)
        obs = _rand_obs(rng, 5, 6, *BUCKETS[0])
        for _ in range(5):
            router.submit(obs, now=0.0, tenant="t0")
        out = router.drain(now=0.0)
        shed = [r for r in out if r.source == "shed"]
        assert len(shed) == 3  # burst of 2 admitted
        assert all(r.reason == "quota" and r.action is None
                   for r in shed)
        # untenanted traffic is quota-exempt
        fid = router.submit(obs, now=0.0)
        assert any(r.request_id == fid and r.source == "policy"
                   for r in router.drain(now=0.0))
        # tokens refill with (submitted) time: 1 s at 2/s -> 2 tokens
        router.submit(obs, now=1.0, tenant="t0")
        router.submit(obs, now=1.0, tenant="t0")
        third = router.submit(obs, now=1.0, tenant="t0")
        out = router.drain(now=1.0)
        assert [r.source for r in out
                if r.request_id == third] == ["shed"]
        assert sum(1 for r in out if r.source == "policy") == 2

    def test_shed_fires_before_saturated_fallback(self):
        """THE ordering pin (acceptance): shedding replaces the
        replica's `saturated` heuristic fallback — with shed on, the
        fallback counter for `saturated` must stay zero; with shed off
        the legacy fallback path is untouched."""
        clock = _FakeClock()
        rng = np.random.default_rng(8)
        reqs = [_rand_obs(rng, 5, 6, *BUCKETS[0]) for _ in range(8)]

        router = _stub_fleet(clock, n_replicas=1, shed_enabled=True,
                             max_queue=3, deadline_s=100.0)
        for o in reqs:
            router.submit(o, now=0.0)
        out = router.drain(now=0.0)
        shed = [r for r in out if r.source == "shed"]
        assert len(shed) == 5 and all(r.reason == "overload"
                                      for r in shed)
        rep = router.replica_set.replicas[0]
        assert rep.server.stats.fallback_reasons.get("saturated") is None
        assert not any(r.source == "fallback" for r in out)

        legacy = _stub_fleet(clock, n_replicas=1, shed_enabled=False,
                             max_queue=3, deadline_s=100.0)
        for o in reqs:
            legacy.submit(o, now=0.0)
        out = legacy.drain(now=0.0)
        assert not any(r.source == "shed" for r in out)
        saturated = [r for r in out if r.reason == "saturated"]
        assert len(saturated) == 5  # the pre-fleet behaviour, intact

    def test_overload_shed_refunds_quota_token(self):
        """An overload shed must not burn the tenant's admission budget
        (only served requests spend quota — same invariant as the
        data-error refund path)."""
        clock = _FakeClock()
        router = _stub_fleet(clock, n_replicas=1, quota_rps=1e-9,
                             quota_burst=1.0, shed_enabled=True,
                             max_queue=1, deadline_s=100.0)
        rng = np.random.default_rng(16)
        obs = _rand_obs(rng, 5, 6, *BUCKETS[0])
        router.submit(obs, now=0.0)  # saturate the single replica
        fid = router.submit(obs, now=0.0, tenant="t0")
        out = router.poll(now=0.0)
        assert [r.reason for r in out
                if r.request_id == fid] == ["overload"]
        router.drain(now=0.0)  # free the queue
        # with a ~zero refill rate the only way this is admitted is the
        # overload shed having refunded the burst token
        fid2 = router.submit(obs, now=0.0, tenant="t0")
        assert any(r.request_id == fid2 and r.source == "policy"
                   for r in router.drain(now=0.0))

    def test_seeded_trace_replays_to_identical_decisions(self):
        """Quota/shed/routing decisions are pure functions of the
        submitted timestamps: the same seeded trace through two fresh
        fleets produces the identical decision stream."""
        from ddls_tpu.serve import loadgen

        trace = loadgen.generate_trace(n_requests=40, base_rps=50.0,
                                       seed=3, diurnal_period_s=0.4,
                                       burst_period_s=0.2)
        loadgen.validate_trace(trace)
        rng = np.random.default_rng(9)
        obs = _rand_obs(rng, 5, 6, *BUCKETS[0])

        def run():
            clock = _FakeClock()
            router = _stub_fleet(clock, n_replicas=2, quota_rps=20.0,
                                 quota_burst=4.0, shed_enabled=True,
                                 max_queue=4, deadline_s=0.005)
            stream = []
            for t, tenant in zip(trace["arrival_s"], trace["tenant"]):
                clock.t = float(t)
                router.submit(obs, now=float(t), tenant=tenant)
                stream.extend(router.poll(now=float(t)))
            stream.extend(router.drain(now=float(trace["arrival_s"][-1])))
            return [(r.request_id, r.source, r.reason, r.replica,
                     r.action) for r in stream]

        assert run() == run()


# ------------------------------------------------------- live reconfiguration
class TestHotSwapRefit:
    def test_hot_swap_no_drop_no_degrade(self, model_params):
        """Acceptance pin: a swap answers every already-admitted request
        (policy answers under the OLD params — nothing dropped, nothing
        degraded) and later requests serve the NEW params."""
        from ddls_tpu.serve import PolicyServer

        model, params_a, params_b = model_params
        rng = np.random.default_rng(11)
        bn, be = BUCKETS[0]
        reqs = [_rand_obs(rng, int(rng.integers(2, bn + 1)),
                          int(rng.integers(1, be + 1)), bn, be)
                for _ in range(6)]
        router = _make_fleet(model, params_a, _FakeClock(),
                             n_replicas=2, deadline_s=100.0)
        fids = [router.submit(o, now=0.0) for o in reqs]
        assert router.queued() == len(reqs)  # nothing flushed yet
        router.hot_swap(params_b, now=0.0)
        out = {r.request_id: r for r in router.poll(now=0.0)}
        assert sorted(out) == sorted(fids)
        assert all(r.source == "policy" for r in out.values())
        for rep in router.replica_set.replicas:
            assert rep.server.stats.degraded_transitions == 0
            assert not rep.server.degraded and not rep.server.draining
        solo_a = PolicyServer(model, params_a, buckets=BUCKETS,
                              max_batch=MAX_BATCH, clock=_FakeClock())
        for fid, obs in zip(fids, reqs):
            assert out[fid].action == solo_a.serve_one(obs).action
        # post-swap traffic runs the new checkpoint
        solo_b = PolicyServer(model, params_b, buckets=BUCKETS,
                              max_batch=MAX_BATCH, clock=_FakeClock())
        post = _rand_obs(rng, 6, 7, bn, be)
        fid = router.submit(post, now=0.0)
        resp = next(r for r in router.drain(now=0.0)
                    if r.request_id == fid)
        assert resp.action == solo_b.serve_one(post).action

    def test_close_is_drain_aware_and_idempotent(self):
        from ddls_tpu.serve import PolicyServer

        server = PolicyServer(None, {}, buckets=BUCKETS,
                              max_batch=MAX_BATCH, deadline_s=100.0,
                              apply_fn=_stub_apply, clock=_FakeClock())
        rng = np.random.default_rng(12)
        ids = [server.submit(_rand_obs(rng, 5, 6, *BUCKETS[0]), now=0.0)
               for _ in range(2)]
        out = server.close(now=0.0)
        assert sorted(r.request_id for r in out) == sorted(ids)
        assert all(r.source == "policy" for r in out)
        assert server.close(now=0.0) == []  # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            server.submit(_rand_obs(rng, 5, 6, *BUCKETS[0]), now=0.0)

    def test_router_close_latches_like_policy_server(self):
        """Router.close mirrors the PolicyServer contract: idempotent,
        answers everything admitted, and post-close submits RAISE
        instead of being silently recorded as overload sheds."""
        clock = _FakeClock()
        router = _stub_fleet(clock, n_replicas=2, deadline_s=100.0)
        rng = np.random.default_rng(18)
        obs = _rand_obs(rng, 5, 6, *BUCKETS[0])
        fid = router.submit(obs, now=0.0)
        out = router.close(now=0.0)
        assert [r.request_id for r in out] == [fid]
        assert out[0].source == "policy"
        assert router.close(now=0.0) == []  # idempotent
        shed_before = dict(router.registry.counter_items()).get(
            "fleet.shed", 0)
        with pytest.raises(RuntimeError, match="closed"):
            router.submit(obs, now=0.0)
        assert dict(router.registry.counter_items()).get(
            "fleet.shed", 0) == shed_before

    def test_swap_params_drains_under_old_params_first(self,
                                                       model_params):
        from ddls_tpu.serve import PolicyServer

        model, params_a, params_b = model_params
        rng = np.random.default_rng(13)
        obs = _rand_obs(rng, 5, 6, *BUCKETS[0])
        solo_a = PolicyServer(model, params_a, buckets=BUCKETS,
                              max_batch=MAX_BATCH, clock=_FakeClock())
        expected = solo_a.serve_one(obs).action
        server = PolicyServer(model, params_a, buckets=BUCKETS,
                              max_batch=MAX_BATCH, deadline_s=100.0,
                              clock=_FakeClock())
        rid = server.submit(obs, now=0.0)
        server.swap_params(params_b, now=0.0)
        out = server.poll(now=0.0)
        assert [(r.request_id, r.action) for r in out] == [(rid, expected)]

    def test_refit_buckets_from_observed_sizes(self):
        clock = _FakeClock()
        router = _stub_fleet(clock, n_replicas=2, deadline_s=100.0)
        rng = np.random.default_rng(14)
        # the population is small graphs only: the fitted ladder should
        # shrink below the configured (16, 28) top bucket
        fids = [router.submit(_rand_obs(rng, int(rng.integers(3, 7)),
                                        int(rng.integers(3, 9)),
                                        *BUCKETS[0]), now=0.0)
                for _ in range(12)]
        specs = router.refit_buckets(n_buckets=2, now=0.0)
        assert specs[-1][0] <= 8 and specs[-1][1] <= 12
        out = router.poll(now=0.0)  # queued requests answered pre-refit
        assert sorted(r.request_id for r in out) == sorted(fids)
        assert all(r.source == "policy" for r in out)
        for rep in router.replica_set.replicas:
            assert rep.server.bucketer.buckets == specs
        # the new ladder still serves (and overflows past its new top
        # go to the fallback, not a crash)
        fid = router.submit(_rand_obs(rng, 5, 6, *BUCKETS[0]), now=0.0)
        assert any(r.request_id == fid and r.source == "policy"
                   for r in router.drain(now=0.0))


# ----------------------------------------------------------------- autoscale
class TestAutoscale:
    def test_decisions_reproducible_from_counter_snapshots(self):
        """Acceptance pin: decisions replay identically from a fixed
        (JSON round-tripped) snapshot sequence — scaling history is
        reconstructable from a telemetry dump."""
        from ddls_tpu.serve import Autoscaler, AutoscaleConfig

        cfg = AutoscaleConfig(min_replicas=1, max_replicas=3,
                              target_p99_ms=50.0, queue_high=4.0,
                              queue_low=1.0, cooldown=2)
        snaps = [
            {"replicas": 1, "queued_total": 0, "p99_latency_ms": 80.0,
             "batch_occupancy": 0.9},           # p99 breach -> up
            {"replicas": 2, "queued_total": 20, "p99_latency_ms": 20.0,
             "batch_occupancy": 0.9},           # cooldown holds
            {"replicas": 2, "queued_total": 20, "p99_latency_ms": 20.0,
             "batch_occupancy": 0.9},           # cooldown holds
            {"replicas": 2, "queued_total": 20, "p99_latency_ms": 20.0,
             "batch_occupancy": 0.9},           # queue breach -> up
            {"replicas": 3, "queued_total": 30, "p99_latency_ms": 20.0,
             "batch_occupancy": 0.9},           # cooldown
            {"replicas": 3, "queued_total": 0, "p99_latency_ms": 5.0,
             "batch_occupancy": 0.1},           # cooldown
            {"replicas": 3, "queued_total": 0, "p99_latency_ms": 5.0,
             "batch_occupancy": 0.1},           # idle -> down
            {"replicas": 2, "queued_total": 0, "p99_latency_ms": None,
             "batch_occupancy": None},          # cooldown
        ]
        snaps = json.loads(json.dumps(snaps))  # storage round trip

        def run():
            a = Autoscaler(cfg)
            return [tuple(a.decide(s)) for s in snaps]

        first = run()
        assert first == run()
        assert [d[0] for d in first] == [2, 2, 2, 3, 3, 3, 2, 2]
        assert first[0][1] == "up:p99"
        assert first[3][1] == "up:queue"
        assert first[6][1] == "down:idle"
        # out-of-range fleet size snaps back before anything else
        a = Autoscaler(cfg)
        assert a.decide({"replicas": 9, "queued_total": 0}) == (3, "clamp")

    def test_retired_replica_registry_retained_in_aggregate(self):
        """A scale-down must not lose the traffic the retired replica
        served: its final registry snapshot stays in
        ``registry_snapshots()`` and the exact aggregate."""
        clock = _FakeClock()
        router = _stub_fleet(clock, n_replicas=2, routing="round_robin",
                             deadline_s=100.0)
        rng = np.random.default_rng(17)
        obs = _rand_obs(rng, 5, 6, *BUCKETS[0])
        for _ in range(6):
            router.submit(obs, now=0.0)
        router.drain(now=0.0)
        router.scale_to(1, now=0.0)
        snaps = router.registry_snapshots()
        assert "r1" in snaps  # the retired replica's final snapshot
        assert snaps["aggregate"]["counters"]["serve.requests"] == 6
        router.reset_stats()  # fresh window drops retired history
        assert "r1" not in router.registry_snapshots()

    def test_warm_replica_hook_runs_on_initial_and_scale_up(self):
        """The warm hook runs for the initial fleet and for every
        autoscale-added replica BEFORE it joins the routing set (a
        scale-up never serves its first batches cold)."""
        from ddls_tpu.serve import build_fleet

        warmed = []
        router = build_fleet(None, {}, n_replicas=2,
                             warm_replica=warmed.append,
                             clock=_FakeClock(), buckets=BUCKETS,
                             max_batch=MAX_BATCH, deadline_s=0.01,
                             apply_fn=_stub_apply)
        assert len(warmed) == 2
        router.scale_to(3)
        assert len(warmed) == 3
        assert warmed[2] is router.replica_set.replicas[-1].server

    def test_controller_closes_the_loop_on_real_fleet_counters(self):
        from ddls_tpu.serve import (Autoscaler, AutoscaleConfig,
                                    AutoscaleController)

        clock = _FakeClock()
        router = _stub_fleet(clock, n_replicas=1, deadline_s=100.0,
                             max_queue=64)
        ctl = AutoscaleController(router, Autoscaler(AutoscaleConfig(
            min_replicas=1, max_replicas=2, queue_high=4.0,
            queue_low=1.0, occupancy_low=2.0, target_p99_ms=1e9,
            cooldown=1)))
        rng = np.random.default_rng(15)
        fids = [router.submit(_rand_obs(rng, 5, 6, *BUCKETS[0]), now=0.0)
                for _ in range(8)]
        d = ctl.step(now=0.0)  # queue depth 8 > high watermark -> up
        assert d.target == 2 and d.reason == "up:queue"
        assert len(router.replica_set.replicas) == 2
        out = router.drain(now=0.0)
        assert sorted(r.request_id for r in out) == sorted(fids)
        assert ctl.step(now=0.0).reason == "cooldown"
        d = ctl.step(now=0.0)  # drained + idle -> down, replica retired
        assert d.target == 1 and d.reason == "down:idle"
        assert len(router.replica_set.replicas) == 1
        # scaling history rode the router's private registry
        counters = dict(router.registry.counter_items())
        assert counters["fleet.autoscale.up"] == 1
        assert counters["fleet.autoscale.down"] == 1


# ------------------------------------------------------------------ loadgen
class TestLoadgen:
    def test_fingerprint_determinism_and_validation(self):
        from ddls_tpu.serve import loadgen

        kwargs = dict(n_requests=64, base_rps=100.0, seed=5,
                      diurnal_period_s=0.4, burst_period_s=0.2)
        a = loadgen.generate_trace(**kwargs)
        b = loadgen.generate_trace(**kwargs)
        loadgen.validate_trace(a)
        assert loadgen.trace_fingerprint(a) == loadgen.trace_fingerprint(b)
        c = loadgen.generate_trace(**{**kwargs, "seed": 6})
        assert (loadgen.trace_fingerprint(c)
                != loadgen.trace_fingerprint(a))
        with pytest.raises(ValueError, match="non-decreasing"):
            loadgen.validate_trace(
                dict(a, arrival_s=np.asarray(a["arrival_s"])[::-1]))
        with pytest.raises(ValueError, match="size_frac"):
            loadgen.validate_trace(
                dict(a, size_frac=np.asarray(a["size_frac"]) + 1.0))

    def test_slo_summary_coordinated_omission_accounting(self):
        from ddls_tpu.serve import FleetResponse, loadgen

        def resp(latency, source):
            return FleetResponse(request_id=0, action=None
                                 if source == "shed" else 8,
                                 source=source, reason="batched",
                                 replica=0, bucket_idx=0,
                                 latency_s=latency)

        responses = ([resp(0.01, "policy")] * 6
                     + [resp(0.2, "fallback")] * 2
                     + [resp(0.0, "shed")] * 2)
        s = loadgen.slo_summary(responses, slo_s=0.05, duration_s=2.0)
        assert s["n_offered"] == 10 and s["n_decided"] == 8
        # sheds are excluded from the percentiles (their ~0 s refusal
        # must not deflate the tail) but charged as SLO misses
        assert s["p999_latency_ms"] == pytest.approx(200.0)
        assert s["slo_attainment"] == pytest.approx(0.6)
        assert s["goodput_rps"] == pytest.approx(3.0)
        assert s["shed_rate"] == pytest.approx(0.2)
        assert s["degraded_rate"] == pytest.approx(0.2)

    def test_loadgen_selftest_script(self):
        """CI satellite: the trace-schema validator runs as a tier-1
        subprocess (numpy-only — no jax, no TPU probe)."""
        out = subprocess.run(
            [sys.executable, "-m", "ddls_tpu.serve.loadgen",
             "--selftest"],
            capture_output=True, text=True, timeout=300, cwd=REPO)
        assert out.returncode == 0, out.stderr[-2000:]
        payload = json.loads(out.stdout.strip().splitlines()[-1])
        assert payload["selftest"] == "ok"
        assert payload["rejected_malformed"] == 4


# ------------------------------------------------------------------- bench
def test_bench_serve_trace_fleet_smoke(capsys):
    """Acceptance: `bench.py --mode serve --load trace --replicas 2`
    emits one JSON line with coordinated-omission-correct p50/p99/p999,
    SLO attainment + goodput, per-replica occupancy, shed and degraded
    rates, and the (seed, fingerprint, replicas) reproducibility
    triplet."""
    import bench

    rc = bench.main(["--mode", "serve", "--load", "trace",
                     "--replicas", "2", "--serve-requests", "48",
                     "--serve-rps", "400", "--serve-max-batch", "4",
                     "--slo-ms", "100", "--probe-timeout", "120"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert rc == 0, payload
    assert payload["metric"] == "serve_decisions_per_sec"
    assert payload["value"] > 0
    assert payload["p50_latency_ms"] is not None
    assert payload["p99_latency_ms"] >= payload["p50_latency_ms"]
    assert payload["p999_latency_ms"] >= payload["p99_latency_ms"]
    assert 0.0 <= payload["slo_attainment"] <= 1.0
    assert payload["goodput_rps"] >= 0.0
    assert 0.0 <= payload["shed_rate"] <= 1.0
    assert 0.0 <= payload["degraded_rate"] <= 1.0
    assert payload["replicas"] == 2
    assert len(payload["per_replica"]) == 2
    for s in payload["per_replica"].values():
        assert "batch_occupancy" in s and "p99_latency_ms" in s
    load = payload["load"]
    assert load["mode"] == "trace" and load["seed"] == 1
    assert len(load["fingerprint"]) == 16
    # the same seed + knobs must reproduce the same fingerprint
    from ddls_tpu.serve import loadgen

    trace = loadgen.generate_trace(
        n_requests=48, base_rps=400.0, seed=1,
        diurnal_period_s=load["diurnal_period_s"],
        diurnal_amplitude=load["diurnal_amplitude"],
        burst_factor=load["burst_factor"],
        burst_period_s=load["burst_period_s"],
        burst_duty=load["burst_duty"],
        size_tail_alpha=load["size_tail_alpha"],
        n_tenants=load["n_tenants"])
    assert loadgen.trace_fingerprint(trace) == load["fingerprint"]
    # per-replica registries rode the telemetry section, with the exact
    # multi-registry aggregate alongside
    serve_tele = payload["telemetry"]["serve"]
    assert "fleet" in serve_tele and "aggregate" in serve_tele
    replica_keys = [k for k in serve_tele
                    if k.startswith("r") and k[1:].isdigit()]
    assert len(replica_keys) == 2
    agg = serve_tele["aggregate"]["counters"]["serve.requests"]
    assert agg == sum(serve_tele[k]["counters"]["serve.requests"]
                      for k in replica_keys)


def test_bench_serve_poisson_records_reproducibility_triplet(capsys):
    """Satellite: the legacy single-replica Poisson line now names its
    load seed, arrival fingerprint, and resolved replica count."""
    import bench

    rc = bench.main(["--mode", "serve", "--serve-requests", "24",
                     "--serve-rps", "400", "--serve-max-batch", "4",
                     "--probe-timeout", "120"])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert rc == 0, payload
    assert payload["replicas"] == 1
    assert payload["load"]["mode"] == "poisson"
    assert payload["load"]["seed"] == 1
    assert len(payload["load"]["fingerprint"]) == 16
