"""PPO with on-device collection: fixed-length segments from the jitted
env feed the existing PPOLearner.

The load-bearing check is OBSERVATION RECONSTRUCTION: `rebuild_obs_batch`
IS the vmapped kernel obs function, so the rebuilt observations equal the
in-kernel ones by construction; the re-forward's logp/value then match
the recorded ones to a few f32 ulps (the in-scan forward and the
standalone batched apply are separately compiled XLA programs, whose
fusion choices differ at the last bit — a real reconstruction bug would
show up orders of magnitude above the 3e-6 tolerance)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddls_tpu.envs import RampJobPartitioningEnvironment
from ddls_tpu.graphs.synthetic import generate_pipedream_txt_files
from ddls_tpu.models.policy import GNNPolicy, batched_policy_apply
from ddls_tpu.parallel.mesh import make_mesh
from ddls_tpu.rl.ppo import PPOConfig, PPOLearner
from ddls_tpu.rl.ppo_device import DevicePPOCollector
from ddls_tpu.sim.jax_env import (build_episode_tables, build_job_bank,
                                  build_obs_tables)


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("ppo_device_jobs"))
    generate_pipedream_txt_files(d, n_cnn=1, n_translation=1, seed=9)
    env = RampJobPartitioningEnvironment(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2, "num_channels": 1,
            "total_node_bandwidth": 1.6e12,
            "intra_gpu_propagation_latency": 50e-9,
            "worker_io_latency": 100e-9}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={"path_to_files": d,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 60.0},
            "max_acceptable_job_completion_time_frac_dist": {
                "_target_": "ddls_tpu.demands.distributions.Uniform",
                "min_val": 0.2, "max_val": 1.0, "decimals": 2},
            "replication_factor": 10,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 10},
        max_partitions_per_op=4, min_op_run_time_quantum=0.01,
        reward_function="job_acceptance", max_simulation_run_time=2e3,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})
    obs = env.reset(seed=0)
    et = build_episode_tables(env)
    ot = build_obs_tables(env, et)
    model = GNNPolicy(n_actions=5, out_features_msg=4,
                      out_features_hidden=8, out_features_node=4,
                      out_features_graph=4, fcnet_hiddens=(16,))
    params = model.init(jax.random.PRNGKey(1),
                        jax.tree_util.tree_map(jnp.asarray, obs))

    def mk_bank(seed):
        r = np.random.RandomState(seed)
        recs = [{"model": et.types[int(r.randint(0, len(et.types)))],
                 "num_training_steps": 10,
                 "sla_frac": round(float(r.uniform(0.2, 1.0)), 2),
                 "time_arrived": 60.0 * i} for i in range(30)]
        return build_job_bank(et, recs)

    banks = [mk_bank(s) for s in range(2)]
    stacked = {k: jnp.asarray(np.stack([b[k] for b in banks]))
               for k in banks[0]}
    return et, ot, model, params, stacked


def test_rebuilt_obs_reproduces_kernel_forward(setup):
    et, ot, model, params, banks = setup
    collector = DevicePPOCollector(et, ot, model, banks,
                                   rollout_length=12)
    out = collector.collect(params, jax.random.PRNGKey(0))
    traj = out["traj"]
    T, B = traj["actions"].shape
    assert (T, B) == (12, 2)
    flat_obs = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x).reshape((T * B,) + x.shape[2:]),
        traj["obs"])
    logits, values = batched_policy_apply(model, params, flat_obs)
    logp_re = jax.nn.log_softmax(logits)[
        jnp.arange(T * B), traj["actions"].reshape(-1)]
    # the rebuilt obs reproduce the kernel forward up to XLA's
    # cross-compilation f32 fusion variance; batched_policy_apply's flat
    # mega-graph path reassociates sums shape-dependently vs the kernel's
    # single-sample forward, bounded ~1e-5 (tests/test_models.py)
    np.testing.assert_allclose(np.asarray(logp_re).reshape(T, B),
                               traj["logp"], rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(values).reshape(T, B),
                               traj["values"], rtol=1e-5, atol=1e-5)
    # episode boundaries appear as segments chain across collects
    # (~33 arrivals per episode at this horizon; 12 decisions/collect)
    n_dones = int(traj["dones"].sum())
    for i in range(1, 6):
        out_i = collector.collect(params, jax.random.PRNGKey(i))
        assert out_i["traj"]["actions"].shape == (12, 2)
        n_dones += int(out_i["traj"]["dones"].sum())
        if n_dones:
            break
    assert n_dones >= 1


def test_collect_feeds_ppo_learner(setup):
    et, ot, model, params, banks = setup
    collector = DevicePPOCollector(et, ot, model, banks,
                                   rollout_length=8)
    learner = PPOLearner(
        lambda p, o: batched_policy_apply(model, p, o),
        PPOConfig(num_sgd_iter=2, sgd_minibatch_size=8), make_mesh(1))
    state = learner.init_state(params)
    for i in range(2):
        out = collector.collect(state.params, jax.random.PRNGKey(10 + i))
        straj, slv = learner.shard_traj(out["traj"], out["last_values"])
        state, metrics = learner.train_step(
            state, straj, slv, jax.random.PRNGKey(20 + i))
        metrics = {k: float(v) for k, v in metrics.items()}
        assert all(np.isfinite(v) for v in metrics.values()), metrics
    moved = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        params, state.params)
    assert max(jax.tree_util.tree_leaves(moved)) > 0


def test_episode_records_from_traced_counters(setup):
    et, ot, model, params, banks = setup
    collector = DevicePPOCollector(et, ot, model, banks,
                                   rollout_length=24)
    # per-lane host-side accumulators mirroring what the kernel counters
    # should contain at each done boundary
    B = collector.num_envs
    ret_acc = np.zeros(B)
    len_acc = np.zeros(B, np.int64)
    harvested = []
    total_records = 0
    for i in range(6):
        out = collector.collect(params, jax.random.PRNGKey(100 + i))
        traj = out["traj"]
        T = traj["rewards"].shape[0]
        for t in range(T):
            ret_acc += traj["rewards"][t]
            len_acc += 1
            for b in np.nonzero(traj["dones"][t])[0]:
                harvested.append((ret_acc[b], len_acc[b]))
                ret_acc[b] = 0.0
                len_acc[b] = 0
        for e in out["episodes"]:
            assert set(e) >= {"env_index", "episode_return",
                              "episode_length", "num_jobs_arrived",
                              "num_jobs_completed", "num_jobs_blocked",
                              "acceptance_rate", "blocking_rate"}
            assert 0.0 <= e["acceptance_rate"] <= 1.0
            assert 0.0 <= e["blocking_rate"] <= 1.0
            # host denominator semantics (cluster.py:1020-1023): arrived
            # counts queued-undecided jobs too, so it bounds decided+done
            arr = e["num_jobs_arrived"]
            assert arr >= e["num_jobs_completed"] + e["num_jobs_blocked"]
            assert e["acceptance_rate"] == (
                e["num_jobs_completed"] / arr if arr else 0.0)
            assert e["blocking_rate"] == (
                e["num_jobs_blocked"] / arr if arr else 0.0)
        records = [(e["episode_return"], e["episode_length"])
                   for e in out["episodes"]]
        # records appear in the same (t, b) order as the host scan above
        assert len(records) == len(harvested)
        for (r_rec, l_rec), (r_host, l_host) in zip(records, harvested):
            assert l_rec == l_host
            np.testing.assert_allclose(r_rec, r_host, rtol=1e-5,
                                       atol=1e-5)
        total_records += len(records)
        harvested.clear()
    # the comparisons above are only meaningful if episodes actually
    # completed: 6 x 24 decisions vs ~33 arrivals/episode guarantees it
    assert total_records >= 1


def test_mesh_sharded_lane_collection(setup):
    """Lanes sharded over the 8-device dp mesh (the pod collection
    shape): one jitted dispatch runs each device's lanes. Partitioned
    compilation may differ from the single-device program at the last
    f32 ulp, which can flip a sampled action — so the pin is structural
    (lanes genuinely distributed, trajectories valid, episodes
    harvested, the learner consumes the result), not bitwise."""
    et, ot, model, params, _ = setup
    from ddls_tpu.sim.jax_env import build_job_bank

    def mk_bank(seed):
        r = np.random.RandomState(seed)
        recs = [{"model": et.types[int(r.randint(0, len(et.types)))],
                 "num_training_steps": 10,
                 "sla_frac": round(float(r.uniform(0.2, 1.0)), 2),
                 "time_arrived": 60.0 * i} for i in range(30)]
        return build_job_bank(et, recs)

    banks8 = {k: jnp.asarray(np.stack([mk_bank(s)[k] for s in range(8)]))
              for k in mk_bank(0)}
    mesh = make_mesh(8)
    collector = DevicePPOCollector(et, ot, model, banks8,
                                   rollout_length=16, mesh=mesh)
    # lanes genuinely live on 8 devices
    lane_shard = jax.tree_util.tree_leaves(collector.banks)[0].sharding
    assert len(lane_shard.device_set) == 8

    learner = PPOLearner(
        lambda p, o: batched_policy_apply(model, p, o),
        PPOConfig(num_sgd_iter=2, sgd_minibatch_size=16), mesh)
    state = learner.init_state(params)
    n_eps = 0
    for i in range(4):
        out = collector.collect(state.params, jax.random.PRNGKey(40 + i))
        traj = out["traj"]
        assert traj["actions"].shape == (16, 8)
        assert np.isfinite(traj["logp"]).all()
        assert np.isfinite(traj["rewards"]).all()
        n_eps += len(out["episodes"])
        for e in out["episodes"]:
            assert (e["num_jobs_arrived"]
                    >= e["num_jobs_completed"] + e["num_jobs_blocked"])
        straj, slv = learner.shard_traj(out["traj"], out["last_values"])
        state, metrics = learner.train_step(
            state, straj, slv, jax.random.PRNGKey(50 + i))
        assert np.isfinite(float(metrics["total_loss"]))
    assert n_eps >= 1  # 64 decisions/lane vs ~30-arrival banks

    with pytest.raises(ValueError, match="must divide"):
        DevicePPOCollector(et, ot, model, banks8, rollout_length=4,
                           mesh=make_mesh(5))  # 8 lanes % 5 devices
