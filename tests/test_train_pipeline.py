"""Round-6 pipelined training loop pins (docs/perf_round6.md):

* loop-mode parity — the ``pipeline_depth=0`` pipelined loop produces
  BIT-identical params, metrics, and episode records to the sequential
  loop for all five learners (the restructure changes the dispatch/sync
  schedule, never the math);
* host-sync cadence — pipelined mode emits at most one
  ``train.host_sync`` span per ``metrics_sync_interval`` epochs (vs one
  per update sequentially);
* transfer guard — the steady-state collect→update epoch performs NO
  implicit device↔host transfer (every staging/fetch is an explicit
  device_put/device_get);
* ``pipeline_depth`` gating — IMPALA accepts depth 1 (V-trace corrects
  the one-update staleness), every other learner rejects it loudly;
* LazyMetrics + telemetry overlap-accounting units.
"""
import os

import numpy as np
import pytest

import jax

from ddls_tpu.train import make_epoch_loop
from ddls_tpu.train.metrics import (LazyMetrics, as_float,
                                    materialize_results)

ENV_CLS = "ddls_tpu.envs.partitioning_env.RampJobPartitioningEnvironment"

_TINY_MODEL = {"fcnet_hiddens": [16],
               "custom_model_config": {"out_features_msg": 4,
                                       "out_features_hidden": 8,
                                       "out_features_node": 4,
                                       "out_features_graph": 4}}


def _env_config(dataset_dir):
    return dict(
        topology_config={"type": "ramp", "kwargs": {
            "num_communication_groups": 2,
            "num_racks_per_communication_group": 2,
            "num_servers_per_rack": 2,
            "num_channels": 1,
            "total_node_bandwidth": 1.6e12}},
        node_config={"type_1": {"num_nodes": 8, "workers_config": [
            {"num_workers": 1, "worker": "A100"}]}},
        jobs_config={
            "path_to_files": dataset_dir,
            "job_interarrival_time_dist": {
                "_target_": "ddls_tpu.demands.distributions.Fixed",
                "val": 100.0},
            "replication_factor": 4,
            "job_sampling_mode": "remove_and_repeat",
            "num_training_steps": 2},
        max_partitions_per_op=4,
        reward_function="job_acceptance",
        max_simulation_run_time=5e4,
        pad_obs_kwargs={"max_nodes": 32, "max_edges": 64})


def _make_loop(algo, dataset_dir, loop_mode, algo_config, **kw):
    defaults = dict(
        path_to_env_cls=ENV_CLS,
        env_config=_env_config(dataset_dir),
        model=_TINY_MODEL,
        algo_config=algo_config,
        num_envs=2, rollout_length=4, n_devices=2,
        use_parallel_envs=False, evaluation_interval=None,
        seed=0, loop_mode=loop_mode)
    defaults.update(kw)
    return make_epoch_loop(algo, **defaults)


def _run_epochs(loop, n):
    records = []
    for _ in range(n):
        r = loop.run()
        records.append({
            "learner": dict(r["learner"]),  # materialises LazyMetrics
            "episodes": r["episodes"],
            "env_steps": r["env_steps_this_iter"],
        })
    loop.sync_metrics()
    params = jax.device_get(loop.state.params)
    loop.close()
    return records, params


# ----------------------------------------------------------- mode parity
# ppo + impala run on the full virtual 8-device mesh (the ISSUE 4 pin);
# pg/dqn/es cover the remaining epoch-loop run() shapes on a 2-device
# mesh. DQN sizes its replay gate so updates actually fire by epoch 2.
PARITY_CASES = [
    ("ppo", {"train_batch_size": 16, "sgd_minibatch_size": 8,
             "num_sgd_iter": 2, "num_workers": 8},
     {"num_envs": 8, "rollout_length": 2, "n_devices": 8}, 4),
    ("impala", {"lr": 1e-3, "train_batch_size": 16, "num_workers": 8},
     {"num_envs": 8, "rollout_length": 2, "n_devices": 8}, 4),
    ("pg", {"lr": 1e-3, "train_batch_size": 8, "num_workers": 2}, {}, 3),
    ("apex_dqn", {"lr": 1e-3, "train_batch_size": 4, "n_step": 1,
                  "replay_buffer_config": {"learning_starts": 4,
                                           "capacity": 256},
                  "num_workers": 2}, {}, 3),
    ("es", {"stepsize": 0.01, "noise_stdev": 0.02, "eval_prob": 0.5,
            "num_workers": 2}, {}, 3),
]


@pytest.mark.parametrize("algo,algo_config,loop_kw,n_epochs",
                         PARITY_CASES,
                         ids=[c[0] for c in PARITY_CASES])
def test_loop_mode_parity_bit_exact(algo, algo_config, loop_kw, n_epochs,
                                    dataset_dir):
    """pipeline_depth=0 pipelined vs sequential: identical params,
    metrics, and episode records — the restructured schedule must not
    move a single bit of the training math."""
    outcomes = {}
    for mode in ("sequential", "pipelined"):
        loop = _make_loop(algo, dataset_dir, mode, dict(algo_config),
                          **loop_kw)
        outcomes[mode] = _run_epochs(loop, n_epochs)

    seq_records, seq_params = outcomes["sequential"]
    pipe_records, pipe_params = outcomes["pipelined"]
    for e, (rs, rp) in enumerate(zip(seq_records, pipe_records)):
        assert rs["env_steps"] == rp["env_steps"], f"epoch {e}"
        assert rs["learner"] == rp["learner"], f"epoch {e} metrics"
        assert rs["episodes"] == rp["episodes"], f"epoch {e} episodes"
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        seq_params, pipe_params)


# ------------------------------------------------------ host-sync cadence
def test_pipelined_host_sync_cadence(dataset_dir):
    """ISSUE 4 acceptance: host_sync spans drop from 1/update to
    <= 1/metrics_sync_interval, drained in one batched fetch."""
    from ddls_tpu import telemetry

    loop = _make_loop("ppo", dataset_dir, "pipelined",
                      {"train_batch_size": 8, "sgd_minibatch_size": 4,
                       "num_sgd_iter": 2, "num_workers": 2},
                      metrics_sync_interval=2)
    telemetry.reset()
    telemetry.enable()
    try:
        results = [loop.run() for _ in range(4)]
        spans = telemetry.span_summaries()
        assert spans["train.host_sync"]["count"] == 2  # epochs 2 and 4
        assert spans["train.train_step"]["count"] == 4
        assert not loop._metrics_ring  # drained
        # every epoch's metrics materialised by the ring syncs — no
        # device fetch left on item access
        assert all(not r["learner"].pending for r in results)
        assert all(np.isfinite(r["learner"]["total_loss"])
                   for r in results)
    finally:
        telemetry.reset()
        telemetry.disable()
        loop.close()


# ------------------------------------------------------- transfer guard
def test_pipelined_epoch_no_implicit_transfers(dataset_dir):
    """The steady-state hot loop (collect→update) must not sneak an
    implicit device↔host transfer back in: staging is explicit
    device_put, fetches are explicit device_get, metrics stay futures.
    Logging/eval boundaries are excluded (interval gates keep them out
    of the guarded epoch)."""
    loop = _make_loop("ppo", dataset_dir, "pipelined",
                      {"train_batch_size": 8, "sgd_minibatch_size": 4,
                       "num_sgd_iter": 2, "num_workers": 2},
                      metrics_sync_interval=1000)
    loop.run()  # warm epoch: compiles + first-use constant transfers
    with jax.transfer_guard("disallow"):
        r = loop.run()
    # materialisation happens OUTSIDE the guarded epoch (the logging
    # boundary), and still yields finite host scalars
    assert np.isfinite(r["learner"]["total_loss"])
    loop.close()


# -------------------------------------------------- pipeline_depth gates
@pytest.mark.parametrize("algo", ["ppo", "pg", "apex_dqn", "es"])
def test_pipeline_depth_rejected_loudly(algo, dataset_dir):
    """Stale collection is only sound with an off-policy correction:
    everyone but IMPALA must refuse pipeline_depth > 0 (the rejection
    fires before any env/model construction)."""
    with pytest.raises(ValueError, match="pipeline_depth"):
        make_epoch_loop(algo, path_to_env_cls=ENV_CLS, env_config={},
                        pipeline_depth=1)


def test_pipeline_depth_validation(dataset_dir):
    # depth >= 2 is the IMPALA-only ring surface (ISSUE 15): negative
    # depths and non-pipelined modes stay loudly rejected; ppo keeps
    # rejecting ANY depth > 0 (covered by the parametrised test above)
    with pytest.raises(ValueError, match="pipeline_depth"):
        make_epoch_loop("impala", path_to_env_cls=ENV_CLS, env_config={},
                        pipeline_depth=-1)
    with pytest.raises(ValueError, match="pipeline_depth"):
        make_epoch_loop("ppo", path_to_env_cls=ENV_CLS, env_config={},
                        pipeline_depth=2)
    with pytest.raises(ValueError, match="loop_mode"):
        make_epoch_loop("impala", path_to_env_cls=ENV_CLS, env_config={},
                        loop_mode="sequential", pipeline_depth=1)
    with pytest.raises(ValueError, match="loop_mode"):
        make_epoch_loop("impala", path_to_env_cls=ENV_CLS, env_config={},
                        loop_mode="sequential", pipeline_depth=2)
    with pytest.raises(ValueError, match="loop_mode"):
        make_epoch_loop("ppo", path_to_env_cls=ENV_CLS, env_config={},
                        loop_mode="bogus")


def test_impala_stale_pipeline_trains(dataset_dir):
    """pipeline_depth=1: epoch n+1's collection runs on the background
    thread against the pre-update params while the device applies update
    n; the loop keeps training and the prefetch future hands over
    batch after batch."""
    loop = _make_loop("impala", dataset_dir, "pipelined",
                      {"lr": 1e-3, "train_batch_size": 8,
                       "num_workers": 2},
                      pipeline_depth=1)
    before = jax.device_get(loop.state.params)
    r1 = loop.run()
    assert len(loop._collect_futures) == 1  # next batch already cooking
    r2 = loop.run()
    r3 = loop.run()
    for r in (r1, r2, r3):
        assert r["env_steps_this_iter"] == 8
        assert np.isfinite(r["learner"]["total_loss"])
    # steady-state staleness at depth 1 is exactly one update
    assert r1["learner"]["params_age_updates"] == 0.0  # inline first batch
    assert r2["learner"]["params_age_updates"] == 1.0
    assert r3["learner"]["params_age_updates"] == 1.0
    moved = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        before, jax.device_get(loop.state.params))
    assert max(jax.tree_util.tree_leaves(moved)) > 0
    loop.close()
    assert not loop._collect_futures  # drained on close


def test_impala_depth_k_pipeline_trains(dataset_dir):
    """pipeline_depth=2 (the ISSUE 15 depth-K surface): up to two
    collected batches ride ahead of the learner, each consumed with
    the params age V-trace absorbs; the in-process vec env exercises
    the ring-less fallback (fresh per-collect buffers), so depth-K is
    transport-independent."""
    loop = _make_loop("impala", dataset_dir, "pipelined",
                      {"lr": 1e-3, "train_batch_size": 8,
                       "num_workers": 2},
                      pipeline_depth=2)
    results = [loop.run() for _ in range(4)]
    assert len(loop._collect_futures) == 2  # queue topped to depth
    for r in results:
        assert r["env_steps_this_iter"] == 8
        assert np.isfinite(r["learner"]["total_loss"])
        assert np.isfinite(r["learner"]["clip_rho_fraction"])
    ages = [r["learner"]["params_age_updates"] for r in results]
    assert ages[0] == 0.0  # first batch collected inline, fresh params
    assert ages[-1] == 2.0  # steady state: two updates behind
    loop.close()
    assert not loop._collect_futures


# -------------------------------------------- ParallelVectorEnv prefetch
def test_parallel_prefetch_stacked_parity(dataset_dir):
    """Out-of-order reply handling + incremental stacking must be
    bit-identical to the in-order path (obs, rewards, dones, episode
    records, and the stacked batch itself)."""
    from ddls_tpu.envs.partitioning_env import \
        RampJobPartitioningEnvironment
    from ddls_tpu.rl.rollout import ParallelVectorEnv, stack_obs

    kwargs = _env_config(dataset_dir)
    envs = []
    try:
        for prefetch in (False, True):
            vec = ParallelVectorEnv(RampJobPartitioningEnvironment,
                                    kwargs, 2, seeds=[0, 1])
            vec.prefetch_stacked = prefetch
            vec.reset()
            envs.append(vec)
        plain, pre = envs
        for _ in range(6):
            actions = np.array(
                [int(np.flatnonzero(np.asarray(o["action_mask"]))[0])
                 for o in plain.obs])
            obs_a, rew_a, done_a = plain.step(actions)
            obs_b, rew_b, done_b = pre.step(actions)
            np.testing.assert_array_equal(rew_a, rew_b)
            np.testing.assert_array_equal(done_a, done_b)
            stacked = pre.stacked_obs()
            reference = stack_obs(plain.obs)
            for k in reference:
                np.testing.assert_array_equal(stacked[k], reference[k])
        assert (plain.drain_completed_episodes()
                == pre.drain_completed_episodes())
    finally:
        for vec in envs:
            vec.close()


# --------------------------------------------------- LazyMetrics units
def test_lazy_metrics_mapping_and_deferred_fetch():
    import jax.numpy as jnp

    lm = LazyMetrics({"loss": jnp.asarray(1.5)}, extras={"n": 3})
    assert lm.pending
    assert set(lm) == {"loss", "n"}
    assert len(lm) == 2
    assert lm["n"] == 3.0  # extras never touch the device
    assert lm.pending
    assert lm["loss"] == 1.5  # first scalar access materialises
    assert not lm.pending
    lm["extra"] = 7  # host-side extras writable post-materialisation
    assert lm["extra"] == 7
    assert lm == {"loss": 1.5, "n": 3.0, "extra": 7.0}


def test_lazy_metrics_group_and_mean_reduce():
    import jax.numpy as jnp

    group = [LazyMetrics({"a": jnp.asarray(float(i))}) for i in range(3)]
    LazyMetrics.materialize_group(group)
    assert [lm["a"] for lm in group] == [0.0, 1.0, 2.0]
    assert all(not lm.pending for lm in group)

    mean = LazyMetrics([{"a": jnp.asarray(1.0)}, {"a": jnp.asarray(3.0)}],
                       reduce="mean", extras={"num_updates": 2})
    assert mean["a"] == 2.0
    assert mean["num_updates"] == 2.0
    empty = LazyMetrics([], reduce="mean", extras={"num_updates": 0})
    assert not empty.pending
    assert empty["num_updates"] == 0.0


def test_materialize_results_walk():
    import jax.numpy as jnp

    tree = {"learner": LazyMetrics({"x": jnp.asarray(2.0)}),
            "nested": [{"learner": LazyMetrics({"y": jnp.asarray(4.0)})}],
            "plain": 1}
    out = materialize_results(tree)
    assert out["learner"] == {"x": 2.0}
    assert out["nested"][0]["learner"] == {"y": 4.0}
    assert out["plain"] == 1
    assert as_float(jnp.asarray(2.5)) == 2.5


# ---------------------------------------------- overlap accounting units
def test_overlap_summary_math():
    from ddls_tpu.telemetry import overlap_summary

    iv = [("train.a", 0.0, 10.0), ("train.b", 2.0, 4.0),
          ("train.c", 12.0, 14.0), ("other", 0.0, 100.0)]
    ov = overlap_summary(iv, prefix="train.")
    assert ov["n_spans"] == 3
    assert ov["window_s"] == pytest.approx(14.0)
    assert ov["covered_1_s"] == pytest.approx(12.0)
    assert ov["covered_2_s"] == pytest.approx(2.0)
    assert ov["gap_s"] == pytest.approx(2.0)
    assert ov["overlap_fraction"] == pytest.approx(2.0 / 12.0)
    assert ov["largest_gaps"][0]["start"] == pytest.approx(10.0)
    assert ov["largest_gaps"][0]["end"] == pytest.approx(12.0)
    assert overlap_summary([]) == {"n_spans": 0}


def test_registry_records_intervals_and_explicit_spans():
    from ddls_tpu.telemetry import Registry

    t = [0.0]
    reg = Registry(enabled=True, clock=lambda: t[0])
    reg.record_intervals = True
    with reg.span("train.collect"):
        t[0] = 2.0
    reg.record_span("train.update_device", 1.0, 3.0)
    assert reg.span_intervals() == [("train.collect", 0.0, 2.0),
                                    ("train.update_device", 1.0, 3.0)]
    summ = reg.span_summaries()
    assert summ["train.update_device"]["count"] == 1
    assert summ["train.update_device"]["total_s"] == pytest.approx(2.0)


def test_report_script_overlap_section(tmp_path):
    import json
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import telemetry_report

    path = tmp_path / "sink.jsonl"
    with open(path, "w") as f:
        # collect [0, 10]; update_device [8, 12] -> 2s of real overlap
        f.write(json.dumps({"type": "span", "name": "train.collect",
                            "ts": 10.0, "dur_s": 10.0}) + "\n")
        f.write(json.dumps({"type": "span",
                            "name": "train.update_device",
                            "ts": 12.0, "dur_s": 4.0}) + "\n")
    report = "\n".join(telemetry_report.render_report(str(path)))
    assert "== overlap" in report
    assert "overlap_fraction" in report


def test_report_script_ring_section(tmp_path):
    """The trajectory-ring report section (ISSUE 15): lease/stall
    counters, the lease-time occupancy histogram, and mean params-age
    rendered from a snapshot's gated rollout.ring.* metrics."""
    import json
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts"))
    import telemetry_report

    from ddls_tpu import telemetry
    from ddls_tpu.rl.ring import OCCUPANCY_BUCKETS

    telemetry.reset()
    telemetry.enable()
    try:
        for _ in range(3):
            telemetry.inc("rollout.ring.lease")
        telemetry.inc("rollout.ring.stall")
        for occ in (0, 1, 1):
            telemetry.observe("rollout.ring.occupancy", occ,
                              buckets=OCCUPANCY_BUCKETS)
        for age in (1, 2):
            telemetry.observe("rollout.ring.params_age_updates", age,
                              buckets=OCCUPANCY_BUCKETS)
        snapshot = telemetry.snapshot()
    finally:
        telemetry.reset()
        telemetry.disable()
    path = tmp_path / "ring.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"type": "snapshot", "data": snapshot}) + "\n")
    report = "\n".join(telemetry_report.render_report(str(path)))
    assert "== trajectory ring" in report
    assert "stalls" in report and "occupancy at lease" in report
    assert "mean_params_age" in report
